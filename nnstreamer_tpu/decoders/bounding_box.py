"""bounding_box decoder: detection tensors → RGBA overlay video.

Reference: ext/nnstreamer/tensor_decoder/tensordec-boundingbox.c (1771 LoC).
Modes (option1, tensordec-boundingbox.c:143-186):
  - ``mobilenet-ssd``          (priors file + scales, logit threshold)
  - ``mobilenet-ssd-postprocess`` (model-side NMS, 4 tensors + tensor map)
  - ``ov-person-detection`` / ``ov-face-detection`` ([N,7] descriptors)
  - ``yolov5``                 ([N, 5+C], scaled or raw)
  - ``mp-palm-detection``      (anchors generated from option3 scheme)
Options (same scheme as the reference :30-58):
  option1=mode, option2=labels file, option3=mode-specific,
  option4=WIDTH:HEIGHT video output size, option5=WIDTH:HEIGHT model input.

TPU-first split: thresholding/decode/NMS are jitted device ops
(ops/detection.py) producing a fixed [max,6] detections tensor; only the
RGBA rasterization runs on host. The detections tensor also rides in
``frame.meta["detections"]`` so downstream elements (tensor_crop, query
serialization) can consume structured results without re-parsing pixels —
the reference has no such structured path (it only emits pixels).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.decoders import render
from nnstreamer_tpu.elements.base import MediaSpec, NegotiationError
from nnstreamer_tpu.ops import detection as det
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec

_MODES = (
    "mobilenet-ssd",
    "mobilenet-ssd-postprocess",
    "ov-person-detection",
    "ov-face-detection",
    "yolov5",
    "mp-palm-detection",
    # backward-compat aliases (reference OLDNAME_/deprecated modes :150-155)
    "tflite-ssd",
    "tf-ssd",
)
_ALIASES = {"tflite-ssd": "mobilenet-ssd", "tf-ssd": "mobilenet-ssd-postprocess"}


def load_box_priors(path: str) -> np.ndarray:
    """Reference box-priors.txt: 4 lines (ycenter, xcenter, h, w) × N values
    (tensordec-boundingbox.c:195,box_priors load)."""
    rows = []
    with open(path) as f:
        for line in f:
            vals = [float(v) for v in line.replace(",", " ").split()]
            if vals:
                rows.append(vals)
    if len(rows) < 4:
        raise ValueError(f"box priors file needs 4 rows, got {len(rows)}: {path}")
    n = min(len(r) for r in rows[:4])
    return np.asarray([r[:n] for r in rows[:4]], np.float32)


@registry.decoder_plugin("bounding_boxes")
class BoundingBoxDecoder:
    @classmethod
    def device_capable(cls, options: dict) -> bool:
        """Static capability read for nns-lint NNS-W116 (no negotiation,
        no priors load): every bounding-box mode has a device decode."""
        return True

    def __init__(self) -> None:
        self._mode = "mobilenet-ssd"
        self._labels: Optional[List[str]] = None
        self._priors: Optional[np.ndarray] = None
        self._anchors: Optional[np.ndarray] = None
        self._params: dict = {}
        self._out_wh = (640, 480)
        self._in_wh = (300, 300)
        self._tensor_map = (0, 1, 2, 3)
        self._pp_threshold = det.SSD_THRESHOLD

    # -- option parsing (reference scheme, option3 per mode :39-80) -------
    def _parse_options(self, options: dict) -> None:
        mode = options.get("option1", self._mode) or "mobilenet-ssd"
        mode = _ALIASES.get(mode, mode)
        if mode not in _MODES:
            raise NegotiationError(f"bounding_box: unknown mode {mode!r}")
        self._mode = mode
        labels_path = options.get("option2", "")
        if labels_path:
            self._labels = render.load_labels(labels_path)
        if options.get("option4"):
            self._out_wh = render.parse_wh(options["option4"], "bounding_box option4")
        if options.get("option5"):
            self._in_wh = render.parse_wh(options["option5"], "bounding_box option5")
        opt3 = options.get("option3", "")
        if mode == "mobilenet-ssd":
            parts = (opt3 or "").split(":")
            if not parts or not parts[0]:
                raise NegotiationError(
                    "bounding_box: mobilenet-ssd needs option3=box-priors-file[:...]"
                )
            self._priors = load_box_priors(parts[0])
            defaults = [det.SSD_THRESHOLD, det.SSD_Y_SCALE, det.SSD_X_SCALE,
                        det.SSD_H_SCALE, det.SSD_W_SCALE, det.SSD_IOU_THRESHOLD]
            vals = []
            for i, d in enumerate(defaults):
                p = parts[i + 1] if i + 1 < len(parts) else ""
                vals.append(float(p) if p else d)
            self._params = dict(
                threshold=vals[0], y_scale=vals[1], x_scale=vals[2],
                h_scale=vals[3], w_scale=vals[4], iou_threshold=vals[5],
            )
        elif mode == "mobilenet-ssd-postprocess":
            # "%i:%i:%i:%i,%i" — tensor index map + threshold percent (:60-67)
            if opt3:
                head, _, thr = opt3.partition(",")
                idx = [int(v) for v in head.split(":") if v != ""]
                if len(idx) == 4:
                    self._tensor_map = tuple(idx)
                if thr:
                    self._pp_threshold = int(thr) / 100.0
        elif mode == "mp-palm-detection":
            parts = [p for p in (opt3 or "").split(":")]
            score = float(parts[0]) if parts and parts[0] else 0.5
            num_layers = int(parts[1]) if len(parts) > 1 and parts[1] else 4
            min_scale = float(parts[2]) if len(parts) > 2 and parts[2] else 1.0
            max_scale = float(parts[3]) if len(parts) > 3 and parts[3] else 1.0
            x_off = float(parts[4]) if len(parts) > 4 and parts[4] else 0.5
            y_off = float(parts[5]) if len(parts) > 5 and parts[5] else 0.5
            strides = [int(v) for v in parts[6:] if v] or [8, 16, 16, 16]
            self._params = dict(score_threshold=score)
            try:
                self._anchors = det.generate_mp_palm_anchors(
                    num_layers, min_scale, max_scale, x_off, y_off,
                    tuple(strides), input_size=self._in_wh[0],
                )
            except ValueError as exc:
                raise NegotiationError(f"bounding_box: {exc}") from exc
        elif mode == "yolov5":
            # Reference yolov5 has no option3 and expects normalized [0,1]
            # coords (tensordec-boundingbox.c:1675 multiplies by i_width).
            # Extension: option3=CONF[:IOU[:pixel]] — "pixel" marks models
            # emitting pixel-unit coords (normalized by option5 size here).
            parts = (opt3 or "").split(":")
            self._params = dict(
                conf_threshold=float(parts[0]) if parts and parts[0]
                else det.YOLOV5_CONF_THRESHOLD,
                iou_threshold=float(parts[1]) if len(parts) > 1 and parts[1]
                else det.YOLOV5_IOU_THRESHOLD,
                pixel_coords=len(parts) > 2 and parts[2] == "pixel",
            )

    def negotiate(self, in_spec: TensorsSpec, options: dict) -> MediaSpec:
        self._parse_options(options)
        mode = self._mode
        n = in_spec.num_tensors
        need = {
            "mobilenet-ssd": 2, "mobilenet-ssd-postprocess": 4,
            "ov-person-detection": 1, "ov-face-detection": 1,
            "yolov5": 1, "mp-palm-detection": 2,
        }[mode]
        if n != need:
            raise NegotiationError(
                f"bounding_box[{mode}]: expected {need} tensors, got {n}"
            )
        w, h = self._out_wh
        return MediaSpec("video", width=w, height=h, format="RGBA", rate=in_spec.rate)

    # -- device post-processing (tensor_decoder postproc=device) ----------
    def device_decode(self, in_spec: TensorsSpec, options: dict):
        """Traceable decode for every bounding-box mode: the exact math
        of :meth:`_detections` (ops/detection.py — already pure jax),
        emitted as a fused op so the decode runs inside the adjacent
        device segment. Output: ONE float32 [max_out, 6] detections
        tensor (x1, y1, x2, y2, class, score; rows with score 0 empty).
        The RGBA rasterization host tail is dropped — a downstream
        consumer reads structured rows, not pixels."""
        self.negotiate(in_spec, options)  # validates count + options
        mode = self._mode
        max_out = 20 if mode == "mp-palm-detection" else 100
        shapes = [
            tuple(d for d in t.shape if d != 1) for t in in_spec
        ]

        import jax.numpy as jnp

        if mode == "mobilenet-ssd":
            p = dict(self._params)
            priors = jnp.asarray(self._priors)
            # resolve the loc/scores order STATICALLY from the
            # negotiated shapes (the host path probes per frame)
            loc_idx = 0 if (
                len(shapes[0]) == 2 and shapes[0][-1] == 4
            ) else 1

            def fn(tensors):
                loc = tensors[loc_idx].reshape(-1, 4)
                scores = tensors[1 - loc_idx].reshape(loc.shape[0], -1)
                return (det.ssd_postprocess(
                    loc, scores, priors,
                    threshold=p["threshold"],
                    iou_threshold=p["iou_threshold"],
                    y_scale=p["y_scale"], x_scale=p["x_scale"],
                    h_scale=p["h_scale"], w_scale=p["w_scale"],
                ),)

        elif mode == "mobilenet-ssd-postprocess":
            m = self._tensor_map
            thr = self._pp_threshold

            def fn(tensors):
                loc = tensors[m[0]].reshape(-1, 4).astype(jnp.float32)
                cls = tensors[m[1]].reshape(-1).astype(jnp.float32)
                sco = tensors[m[2]].reshape(-1).astype(jnp.float32)
                num = tensors[m[3]].reshape(-1).astype(jnp.float32)[0]
                return (det.ssd_pp_postprocess(
                    loc, cls, sco, num, threshold=thr
                ),)

        elif mode in ("ov-person-detection", "ov-face-detection"):
            def fn(tensors):
                return (det.ov_detection_postprocess(
                    tensors[0].reshape(-1, 7)
                ),)

        elif mode == "yolov5":
            p = dict(self._params)
            iw, ih = self._in_wh
            cols = shapes[0][-1]

            def fn(tensors):
                pred = tensors[0].reshape(-1, cols).astype(jnp.float32)
                if p["pixel_coords"]:
                    norm = jnp.asarray(
                        [iw, ih, iw, ih], jnp.float32
                    )
                    pred = jnp.concatenate(
                        [pred[:, :4] / norm, pred[:, 4:]], axis=-1
                    )
                return (det.yolov5_postprocess(
                    pred, conf_threshold=p["conf_threshold"],
                    iou_threshold=p["iou_threshold"], scaled=True,
                ),)

        elif mode == "mp-palm-detection":
            anchors = jnp.asarray(self._anchors)
            score_thr = self._params["score_threshold"]
            in_size = self._in_wh[0]
            cols = shapes[0][-1]

            def fn(tensors):
                boxes = tensors[0].reshape(-1, cols)
                scores = tensors[1].reshape(-1)
                return (det.mp_palm_postprocess(
                    boxes, scores, anchors,
                    score_threshold=score_thr, input_size=in_size,
                ),)

        else:  # pragma: no cover - _MODES is closed above
            return None
        from nnstreamer_tpu.tensors.spec import DType, TensorSpec

        out = TensorsSpec.of(
            TensorSpec((max_out, 6), DType.FLOAT32, name="detections"),
            rate=in_spec.rate,
        )
        return out, fn

    # -- per-frame decode --------------------------------------------------
    def _detections(self, frame: Frame) -> np.ndarray:
        mode = self._mode
        ts = [np.squeeze(np.asarray(t)) for t in frame.tensors]
        if mode == "mobilenet-ssd":
            loc, scores = ts[0], ts[1]
            if not (loc.ndim == 2 and loc.shape[-1] == 4):
                loc, scores = scores, loc  # tensors may arrive either order
            p = self._params
            loc = loc.reshape(-1, 4)
            return np.asarray(det.ssd_postprocess(
                loc, scores.reshape(loc.shape[0], -1),
                self._priors,
                threshold=p["threshold"], iou_threshold=p["iou_threshold"],
                y_scale=p["y_scale"], x_scale=p["x_scale"],
                h_scale=p["h_scale"], w_scale=p["w_scale"],
            ))
        if mode == "mobilenet-ssd-postprocess":
            m = self._tensor_map
            loc = np.asarray(ts[m[0]], np.float32).reshape(-1, 4)
            cls = np.asarray(ts[m[1]], np.float32).reshape(-1)
            sco = np.asarray(ts[m[2]], np.float32).reshape(-1)
            num = np.asarray(ts[m[3]], np.float32).reshape(-1)[0]
            return np.asarray(det.ssd_pp_postprocess(
                loc, cls, sco, num, threshold=self._pp_threshold
            ))
        if mode in ("ov-person-detection", "ov-face-detection"):
            return np.asarray(det.ov_detection_postprocess(ts[0].reshape(-1, 7)))
        if mode == "yolov5":
            pred = ts[0].reshape(-1, ts[0].shape[-1]).astype(np.float32)
            p = self._params
            if p["pixel_coords"]:  # normalize pixel-unit outputs first
                iw, ih = self._in_wh
                pred = pred.copy()
                pred[:, 0] /= iw
                pred[:, 1] /= ih
                pred[:, 2] /= iw
                pred[:, 3] /= ih
            return np.asarray(det.yolov5_postprocess(
                pred, conf_threshold=p["conf_threshold"],
                iou_threshold=p["iou_threshold"], scaled=True,
            ))
        if mode == "mp-palm-detection":
            boxes = ts[0].reshape(-1, ts[0].shape[-1])
            scores = ts[1].reshape(-1)
            return np.asarray(det.mp_palm_postprocess(
                boxes, scores, self._anchors,
                score_threshold=self._params["score_threshold"],
                input_size=self._in_wh[0],
            ))
        raise NegotiationError(f"bounding_box: unhandled mode {mode}")

    def decode(self, frame: Frame, options: dict) -> Frame:
        d = self._detections(frame)
        w, h = self._out_wh
        canvas = render.render_detections(d, w, h, self._labels)
        valid = d[d[:, 5] > 0]
        return frame.with_tensors((canvas,)).with_meta(
            media_type="video", detections=valid
        )


# Reference registers this decoder under mode name "bounding_boxes"; keep a
# hyphenless alias for pipeline-string convenience.
registry.register(registry.KIND_DECODER, "bounding-boxes", BoundingBoxDecoder)
