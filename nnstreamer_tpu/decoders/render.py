"""Shared host-side rasterization for egress decoders.

Analogue of the reference's tensordecutil.c (label loading, ASCII sprite
rendering via font.c rasters) — but at the host egress boundary only: the
heavy post-processing (thresholding/NMS/argmax) already happened on device
via ops/detection.py and ops/heatmap.py; what remains here is drawing RGBA
overlays, which the reference also does pixel-by-pixel on the CPU.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

# Red 100% in RGBA — the reference's box color (tensordec-boundingbox.c:128)
PIXEL_RGBA = (255, 0, 0, 255)


def load_labels(path: str) -> List[str]:
    """One label per line (tensordecutil.c loadImageLabels)."""
    with open(path) as f:
        return [line.strip() for line in f if line.strip()]


def parse_wh(s: str, what: str) -> Tuple[int, int]:
    """Parse a WIDTH:HEIGHT decoder option (shared by bounding-box/pose)."""
    from nnstreamer_tpu.elements.base import NegotiationError

    parts = s.split(":")
    if len(parts) < 2:
        raise NegotiationError(f"{what} must be WIDTH:HEIGHT, got {s!r}")
    return int(parts[0]), int(parts[1])


def new_canvas(width: int, height: int) -> np.ndarray:
    """Transparent RGBA canvas — the reference decoders draw boxes/poses on
    a transparent background for compositing downstream."""
    return np.zeros((height, width, 4), np.uint8)


def draw_rect(
    canvas: np.ndarray,
    x1: int,
    y1: int,
    x2: int,
    y2: int,
    color: Tuple[int, int, int, int] = PIXEL_RGBA,
    thickness: int = 1,
) -> None:
    h, w = canvas.shape[:2]
    x1, x2 = sorted((int(np.clip(x1, 0, w - 1)), int(np.clip(x2, 0, w - 1))))
    y1, y2 = sorted((int(np.clip(y1, 0, h - 1)), int(np.clip(y2, 0, h - 1))))
    t = max(1, thickness)
    canvas[y1 : y1 + t, x1 : x2 + 1] = color
    canvas[max(y2 - t + 1, 0) : y2 + 1, x1 : x2 + 1] = color
    canvas[y1 : y2 + 1, x1 : x1 + t] = color
    canvas[y1 : y2 + 1, max(x2 - t + 1, 0) : x2 + 1] = color


def draw_line(
    canvas: np.ndarray,
    x1: int,
    y1: int,
    x2: int,
    y2: int,
    color: Tuple[int, int, int, int] = PIXEL_RGBA,
) -> None:
    """Bresenham — pose skeleton edges (tensordec-pose.c draw)."""
    h, w = canvas.shape[:2]
    x1, y1, x2, y2 = int(x1), int(y1), int(x2), int(y2)
    dx, dy = abs(x2 - x1), -abs(y2 - y1)
    sx = 1 if x1 < x2 else -1
    sy = 1 if y1 < y2 else -1
    err = dx + dy
    while True:
        if 0 <= x1 < w and 0 <= y1 < h:
            canvas[y1, x1] = color
        if x1 == x2 and y1 == y2:
            break
        e2 = 2 * err
        if e2 >= dy:
            err += dy
            x1 += sx
        if e2 <= dx:
            err += dx
            y1 += sy


def draw_point(
    canvas: np.ndarray,
    x: int,
    y: int,
    radius: int = 2,
    color: Tuple[int, int, int, int] = PIXEL_RGBA,
) -> None:
    h, w = canvas.shape[:2]
    x, y = int(x), int(y)
    y0, y1 = max(0, y - radius), min(h, y + radius + 1)
    x0, x1 = max(0, x - radius), min(w, x + radius + 1)
    canvas[y0:y1, x0:x1] = color


def draw_text(
    canvas: np.ndarray,
    text: str,
    x: int,
    y: int,
    color: Tuple[int, int, int, int] = PIXEL_RGBA,
) -> None:
    """Rasterize a small label string (reference: 8x13 ASCII sprites from
    font.c; here PIL's built-in bitmap font — same role, no bundled
    bitmap table)."""
    if not text:
        return
    try:
        from PIL import Image, ImageDraw
    except ImportError:  # pragma: no cover - PIL is in the base image
        return
    h, w = canvas.shape[:2]
    img = Image.fromarray(canvas, "RGBA")
    ImageDraw.Draw(img).text((int(x), int(y)), text, fill=tuple(color))
    canvas[:] = np.asarray(img)


def render_detections(
    detections: np.ndarray,
    width: int,
    height: int,
    labels: Optional[Sequence[str]] = None,
) -> np.ndarray:
    """[N,6] (x1,y1,x2,y2,class,score) normalized → RGBA overlay, with the
    label string drawn above each box like the reference's draw_label."""
    canvas = new_canvas(width, height)
    for row in np.asarray(detections, np.float32):
        x1, y1, x2, y2, cls, score = row
        if score <= 0:
            continue
        draw_rect(canvas, x1 * width, y1 * height, x2 * width, y2 * height)
        if labels:
            ci = int(cls)
            name = labels[ci] if 0 <= ci < len(labels) else str(ci)
            draw_text(canvas, name, x1 * width, max(y1 * height - 12, 0))
    return canvas


# Pascal-VOC 21-class colormap — the deeplab palette the reference's
# image-segment decoder assigns per label (tensordec-imagesegment.c sets
# grayscale/random; we use the standard VOC palette for readable output).
def voc_colormap(num_labels: int = 21) -> np.ndarray:
    cmap = np.zeros((num_labels, 3), np.uint8)
    for i in range(num_labels):
        c, r, g, b = i, 0, 0, 0
        for j in range(8):
            r |= ((c >> 0) & 1) << (7 - j)
            g |= ((c >> 1) & 1) << (7 - j)
            b |= ((c >> 2) & 1) << (7 - j)
            c >>= 3
        cmap[i] = (r, g, b)
    return cmap


def render_segmentation(label_map: np.ndarray, num_labels: int = 21) -> np.ndarray:
    """[H,W] uint8 label map → RGBA (label 0 = background = transparent)."""
    cmap = voc_colormap(max(num_labels, int(label_map.max()) + 1))
    rgb = cmap[label_map]
    alpha = np.where(label_map > 0, 255, 0).astype(np.uint8)[..., None]
    return np.concatenate([rgb, alpha], axis=-1)
