"""python3 decoder subplugin: user script decodes tensors → media/tensors.

Reference: ext/nnstreamer/tensor_decoder/tensordec-python3.cc — the script
defines ``CustomDecoder`` with ``decode(tensors) -> tensors`` and
optionally ``negotiate(in_spec, options) -> MediaSpec|TensorsSpec``.
Script path comes from ``option1``.
"""

from __future__ import annotations

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import MediaSpec
from nnstreamer_tpu.script import load_script_object
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec


@registry.decoder_plugin("python3")
class PythonScriptDecoder:
    def __init__(self) -> None:
        self._obj = None

    def _load(self, options: dict):
        if self._obj is None:
            path = options.get("script") or options.get("option1")
            if not path:
                raise ValueError("python3 decoder: option1=/path/to.py required")
            self._obj = load_script_object(
                path, ("CustomDecoder", "decoder_class")
            )
            if not hasattr(self._obj, "decode"):
                raise ValueError("python3 decoder: script has no decode()")
        return self._obj

    def negotiate(self, in_spec: TensorsSpec, options: dict):
        obj = self._load(options)
        if hasattr(obj, "negotiate"):
            return obj.negotiate(in_spec, options)
        return MediaSpec("octet")

    def decode(self, frame: Frame, options: dict) -> Frame:
        out = self._load(options).decode(frame.tensors)
        return frame.with_tensors(tuple(out))
