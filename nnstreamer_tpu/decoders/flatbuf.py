"""flatbuf decoder subplugin: tensors → serialized flatbuffer Tensors.

Reference: ext/nnstreamer/tensor_decoder/tensordec-flatbuf.cc. Inverse of
converters/flatbuf.py (shared codec there).
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.converters.flatbuf import encode_flatbuf
from nnstreamer_tpu.elements.base import MediaSpec
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec


@registry.decoder_plugin("flatbuf")
class FlatbufDecoder:
    def __init__(self) -> None:
        self._rate = None

    def negotiate(self, in_spec: TensorsSpec, options: dict) -> MediaSpec:
        self._rate = in_spec.rate  # stream rate rides in the wire header
        return MediaSpec("octet")

    def decode(self, frame: Frame, options: dict) -> Frame:
        frame = frame.to_host()
        rate = frame.meta.get("rate") or self._rate
        blob = encode_flatbuf(
            frame.tensors,
            rate=(rate.numerator, rate.denominator) if rate else None,
        )
        return frame.with_tensors(
            (np.frombuffer(blob, dtype=np.uint8),)
        ).with_meta(media_type="octet")
