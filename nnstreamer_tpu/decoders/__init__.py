"""Decoder subplugins (reference ext/nnstreamer/tensor_decoder/).

Importing registers the built-ins. Protocol:
    negotiate(in_spec: TensorsSpec, options: dict) -> Spec
    decode(frame: Frame, options: dict) -> Frame
"""

from nnstreamer_tpu.decoders import direct_video  # noqa: F401
from nnstreamer_tpu.decoders import image_labeling  # noqa: F401
from nnstreamer_tpu.decoders import flexbuf  # noqa: F401
