"""Decoder subplugins (reference ext/nnstreamer/tensor_decoder/).

Importing registers the built-ins. Protocol:
    negotiate(in_spec: TensorsSpec, options: dict) -> Spec
    decode(frame: Frame, options: dict) -> Frame
"""

from nnstreamer_tpu.decoders import bounding_box  # noqa: F401
from nnstreamer_tpu.decoders import direct_video  # noqa: F401
from nnstreamer_tpu.decoders import flatbuf  # noqa: F401
from nnstreamer_tpu.decoders import flexbuf  # noqa: F401
from nnstreamer_tpu.decoders import font  # noqa: F401
from nnstreamer_tpu.decoders import image_labeling  # noqa: F401
from nnstreamer_tpu.decoders import image_segment  # noqa: F401
from nnstreamer_tpu.decoders import octet_stream  # noqa: F401
from nnstreamer_tpu.decoders import pose  # noqa: F401
from nnstreamer_tpu.decoders import protobuf  # noqa: F401
from nnstreamer_tpu.decoders import python_script  # noqa: F401
