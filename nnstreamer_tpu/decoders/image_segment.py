"""image_segment decoder: per-pixel class maps → RGBA overlay.

Reference: ext/nnstreamer/tensor_decoder/tensordec-imagesegment.c (660 LoC).
Modes (option1, :118-122): ``tflite-deeplab`` ([1,H,W,C] scores → argmax),
``snpe-deeplab`` ([H,W] already-argmaxed label map), ``snpe-depth``
([H,W] float depth → grayscale). The argmax/normalize runs jitted on device
(ops/heatmap.py); palette application is host egress.
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.decoders import render
from nnstreamer_tpu.elements.base import MediaSpec, NegotiationError
from nnstreamer_tpu.ops import heatmap as hm
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec

_MODES = ("tflite-deeplab", "snpe-deeplab", "snpe-depth")
_DEFAULT_LABELS = 21  # Pascal-VOC classes of deeplab-v3 (reference :95)


@registry.decoder_plugin("image_segment")
class ImageSegmentDecoder:
    @classmethod
    def device_capable(cls, options: dict) -> bool:
        """Static capability read for nns-lint NNS-W116: every segment
        mode decodes on device."""
        return True

    def __init__(self) -> None:
        self._mode = "tflite-deeplab"
        self._num_labels = _DEFAULT_LABELS
        self._wh = None

    def negotiate(self, in_spec: TensorsSpec, options: dict) -> MediaSpec:
        mode = options.get("option1", self._mode) or "tflite-deeplab"
        if mode not in _MODES:
            raise NegotiationError(f"image_segment: unknown mode {mode!r}")
        self._mode = mode
        if options.get("option2"):
            self._num_labels = int(options["option2"])
        if in_spec.num_tensors != 1:
            raise NegotiationError("image_segment: exactly one tensor expected")
        shape = [d for d in in_spec[0].shape if d != 1]
        if mode == "tflite-deeplab":
            if len(shape) != 3:
                raise NegotiationError(
                    f"image_segment[tflite-deeplab]: need [H,W,C], got {in_spec[0]}"
                )
            h, w, c = shape
            self._num_labels = c
        else:
            if len(shape) != 2:
                raise NegotiationError(
                    f"image_segment[{mode}]: need [H,W], got {in_spec[0]}"
                )
            h, w = shape
        self._wh = (w, h)
        return MediaSpec("video", width=w, height=h, format="RGBA", rate=in_spec.rate)

    # -- device post-processing (tensor_decoder postproc=device) ----------
    def device_decode(self, in_spec: TensorsSpec, options: dict):
        """Traceable per-pixel decode: the argmax / normalization as a
        fused op — emits the [H, W] uint8 label (or depth-gray) map,
        exactly ``meta["label_map"]`` of the host path. The palette
        rasterization host tail is dropped."""
        self.negotiate(in_spec, options)
        w, h = self._wh
        mode = self._mode
        num_labels = self._num_labels
        shape = tuple(d for d in in_spec[0].shape if d != 1)

        def fn(tensors):
            arr = tensors[0].reshape(shape)
            if mode == "snpe-depth":
                return (hm.depth_normalize(arr),)
            return (hm.segment_argmax(arr, num_labels=num_labels),)

        from nnstreamer_tpu.tensors.spec import DType, TensorSpec

        out = TensorsSpec.of(
            TensorSpec((h, w), DType.UINT8, name="label_map"),
            rate=in_spec.rate,
        )
        return out, fn

    def decode(self, frame: Frame, options: dict) -> Frame:
        t = frame.tensors[0]
        arr = np.squeeze(np.asarray(t))
        if self._mode == "snpe-depth":
            gray = np.asarray(hm.depth_normalize(arr))
            rgba = np.stack(
                [gray, gray, gray, np.full_like(gray, 255)], axis=-1
            )
            labels = gray
        else:
            labels = np.asarray(hm.segment_argmax(arr, num_labels=self._num_labels))
            rgba = render.render_segmentation(labels, self._num_labels)
        return frame.with_tensors((rgba,)).with_meta(
            media_type="video", label_map=labels
        )
