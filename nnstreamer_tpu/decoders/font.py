"""font decoder: render text tensors as video frames.

Reference: ext/nnstreamer/tensor_decoder/tensordec-font.c (153 LoC) — takes
a uint8 text tensor and rasterizes it onto an RGBA canvas with the built-in
ASCII font. option1 = WIDTH:HEIGHT of the output video (default 640:480).
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.decoders import render
from nnstreamer_tpu.elements.base import MediaSpec, NegotiationError
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec


@registry.decoder_plugin("font")
class FontDecoder:
    def __init__(self) -> None:
        self._out_wh = (640, 480)

    def negotiate(self, in_spec: TensorsSpec, options: dict) -> MediaSpec:
        if options.get("option1"):
            self._out_wh = render.parse_wh(options["option1"], "font option1")
        if in_spec.num_tensors != 1:
            raise NegotiationError(
                f"font: expected 1 text tensor, got {in_spec.num_tensors}"
            )
        w, h = self._out_wh
        return MediaSpec("video", width=w, height=h, format="RGBA", rate=in_spec.rate)

    def decode(self, frame: Frame, options: dict) -> Frame:
        raw = np.asarray(frame.tensors[0]).reshape(-1).astype(np.uint8)
        text = raw.tobytes().split(b"\0", 1)[0].decode("utf-8", "replace")
        w, h = self._out_wh
        canvas = render.new_canvas(w, h)
        # line-wrapped top-left layout (reference draws at a fixed origin)
        line_h = 14
        for i, line in enumerate(text.splitlines() or [""]):
            y = 2 + i * line_h
            if y + line_h > h:
                break
            render.draw_text(canvas, line, 2, y)
        return frame.with_tensors((canvas,)).with_meta(text=text)
