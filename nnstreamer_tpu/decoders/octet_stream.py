"""octet_stream decoder: tensors → raw application/octet-stream bytes.

Reference: ext/nnstreamer/tensor_decoder/tensordec-octetstream.c (130 LoC):
concatenates the raw bytes of all tensors in order.
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import MediaSpec
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec


@registry.decoder_plugin("octet_stream")
class OctetStreamDecoder:
    def negotiate(self, in_spec: TensorsSpec, options: dict) -> MediaSpec:
        return MediaSpec("octet")

    def decode(self, frame: Frame, options: dict) -> Frame:
        frame = frame.to_host()
        blob = b"".join(np.ascontiguousarray(t).tobytes() for t in frame.tensors)
        return frame.with_tensors(
            (np.frombuffer(blob, dtype=np.uint8),)
        ).with_meta(media_type="octet")
