"""image_labeling decoder: logits → argmax class index + label string.

Reference: ext/nnstreamer/tensor_decoder/tensordec-labeling.c (271 LoC):
argmax over the score tensor, label text looked up from the option1 labels
file (one label per line; shared loader tensordecutil.c).

Output: one uint32 tensor [N] of class indices; label strings ride in
frame.meta["labels"] (egress metadata, the analogue of the text overlay the
reference renders with the font decoder).
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import NegotiationError
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import DType, TensorSpec, TensorsSpec


from nnstreamer_tpu.decoders.render import load_labels  # shared loader


@registry.decoder_plugin("image_labeling")
class ImageLabelingDecoder:
    @classmethod
    def device_capable(cls, options: dict) -> bool:
        """Static capability read for nns-lint NNS-W116: the argmax
        decodes on device unless a labels file (option1) pins the
        label-string lookup — a host tail by nature."""
        return not options.get("option1")

    def __init__(self) -> None:
        self._labels: Optional[List[str]] = None

    def negotiate(self, in_spec: TensorsSpec, options: dict) -> TensorsSpec:
        if in_spec.num_tensors != 1:
            raise NegotiationError("image_labeling: exactly one score tensor")
        t = in_spec[0]
        if t.rank < 1:
            raise NegotiationError(f"image_labeling: bad score tensor {t}")
        labels_path = options.get("option1", "")
        if labels_path:
            if not os.path.isfile(labels_path):
                raise NegotiationError(
                    f"image_labeling: labels file not found: {labels_path}"
                )
            self._labels = load_labels(labels_path)
        batch = t.shape[0] if t.rank > 1 else 1
        return TensorsSpec.of(
            TensorSpec((batch,), DType.UINT32, name="label_index"),
            rate=in_spec.rate,
        )

    def make_fn(self, in_spec: TensorsSpec, options: dict):
        """Traceable argmax (tensor_decoder fuses it into the upstream
        filter's XLA program) — available when no labels file is set;
        label-string lookup needs the host, so option1 keeps the host
        path."""
        if self._labels:
            return None
        import jax.numpy as jnp

        def fn(tensors):
            scores = tensors[0]
            if scores.ndim == 1:
                scores = scores[None, :]
            flat = scores.reshape(scores.shape[0], -1)
            return (jnp.argmax(flat, axis=-1).astype(jnp.uint32),)

        return fn

    def device_decode(self, in_spec: TensorsSpec, options: dict):
        """tensor_decoder postproc=device: the argmax fn plus its
        negotiated tensor spec. None when a labels file is set —
        label-string lookup is a host tail by nature."""
        out = self.negotiate(in_spec, options)
        fn = self.make_fn(in_spec, options)
        if fn is None:
            return None
        return out, fn

    def decode(self, frame: Frame, options: dict) -> Frame:
        scores = np.asarray(frame.tensors[0])
        if scores.ndim == 1:
            scores = scores[None, :]
        flat = scores.reshape(scores.shape[0], -1)
        idx = np.argmax(flat, axis=-1).astype(np.uint32)
        out = frame.with_tensors((idx,))
        if self._labels:
            out = out.with_meta(
                labels=[
                    self._labels[i] if i < len(self._labels) else str(i) for i in idx
                ]
            )
        return out
