"""protobuf decoder subplugin: tensors → serialized Tensors message.

Reference: ext/nnstreamer/tensor_decoder/tensordec-protobuf.cc. Inverse of
converters/protobuf.py; output is one uint8 tensor holding the message.
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.converters.protobuf import frame_to_message
from nnstreamer_tpu.elements.base import MediaSpec
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec


@registry.decoder_plugin("protobuf")
class ProtobufDecoder:
    def __init__(self) -> None:
        self._rate = None

    def negotiate(self, in_spec: TensorsSpec, options: dict) -> MediaSpec:
        self._rate = in_spec.rate  # stream rate rides in the wire header
        return MediaSpec("octet")

    def decode(self, frame: Frame, options: dict) -> Frame:
        frame = frame.to_host()
        blob = frame_to_message(frame, rate=self._rate).SerializeToString()
        return frame.with_tensors(
            (np.frombuffer(blob, dtype=np.uint8),)
        ).with_meta(media_type="octet")
