"""pose_estimation decoder: heatmaps → keypoints + RGBA skeleton overlay.

Reference: ext/nnstreamer/tensor_decoder/tensordec-pose.c (824 LoC).
Options (same scheme, :29-60):
  option1=WIDTH:HEIGHT video output size
  option2=WIDTH:HEIGHT model input size
  option3=labels file ("<name> <connected-id>..." per keypoint line)
  option4=mode: ``heatmap-only`` (default) | ``heatmap-offset``

Input: 1 tensor [1,H,W,K] score maps (heatmap-only) or 2 tensors adding
[1,H,W,2K] offsets (posenet convention). The grid argmax + offset gather are
jitted device ops (ops/heatmap.py); skeleton rasterization is host egress.
Keypoints also ride in ``frame.meta["keypoints"]`` as [K,3] (x,y,score) in
output-pixel units.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.decoders import render
from nnstreamer_tpu.elements.base import MediaSpec, NegotiationError
from nnstreamer_tpu.ops import heatmap as hm
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec


def load_pose_labels(path: str) -> Tuple[List[str], List[List[int]]]:
    """"<label> <id> <id>..." per line → (names, connection lists)
    (tensordec-pose.c:31-56 syntax)."""
    names, conns = [], []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            names.append(parts[0])
            conns.append([int(p) for p in parts[1:]])
    return names, conns


@registry.decoder_plugin("pose_estimation")
class PoseDecoder:
    @classmethod
    def device_capable(cls, options: dict) -> bool:
        """Static capability read for nns-lint NNS-W116: both heatmap
        modes decode on device."""
        return True

    def __init__(self) -> None:
        self._out_wh = (640, 480)
        self._in_wh = (257, 257)
        self._names: Optional[List[str]] = None
        self._conns: Optional[List[List[int]]] = None
        self._offset_mode = False
        self._score_threshold = 0.3

    def negotiate(self, in_spec: TensorsSpec, options: dict) -> MediaSpec:
        if options.get("option1"):
            self._out_wh = render.parse_wh(options["option1"], "pose option1")
        if options.get("option2"):
            self._in_wh = render.parse_wh(options["option2"], "pose option2")
        if options.get("option3"):
            self._names, self._conns = load_pose_labels(options["option3"])
        mode = options.get("option4", "heatmap-only") or "heatmap-only"
        if mode not in ("heatmap-only", "heatmap-offset"):
            raise NegotiationError(f"pose_estimation: unknown option4 {mode!r}")
        self._offset_mode = mode == "heatmap-offset"
        need = 2 if self._offset_mode else 1
        if in_spec.num_tensors != need:
            raise NegotiationError(
                f"pose_estimation[{mode}]: expected {need} tensors, got "
                f"{in_spec.num_tensors}"
            )
        w, h = self._out_wh
        return MediaSpec("video", width=w, height=h, format="RGBA", rate=in_spec.rate)

    # -- device post-processing (tensor_decoder postproc=device) ----------
    def device_decode(self, in_spec: TensorsSpec, options: dict):
        """Traceable keypoint decode: grid argmax (+ offset refinement)
        and the output-pixel scaling as fused ops — emits the [K, 3]
        (x, y, score) keypoints tensor in output-pixel units, exactly
        the values :meth:`decode` stamps into ``meta["keypoints"]``.
        The skeleton rasterization host tail is dropped."""
        self.negotiate(in_spec, options)
        grid_shape = tuple(d for d in in_spec[0].shape if d != 1)
        if len(grid_shape) != 3:
            return None
        gh, gw, k = grid_shape
        ow, oh = self._out_wh
        iw, ih = self._in_wh
        offset_mode = self._offset_mode

        import jax.numpy as jnp

        def fn(tensors):
            grid = tensors[0].reshape(gh, gw, k)
            if offset_mode:
                offs = tensors[1].reshape(gh, gw, 2 * k)
                raw = hm.pose_keypoints_with_offsets(grid, offs)
                x_in = raw[:, 0] / max(gw - 1, 1) * (iw - 1) + raw[:, 3]
                y_in = raw[:, 1] / max(gh - 1, 1) * (ih - 1) + raw[:, 4]
                xs = x_in / iw * ow
                ys = y_in / ih * oh
            else:
                raw = hm.pose_keypoints_from_heatmap(grid)
                xs = raw[:, 0] / max(gw - 1, 1) * ow
                ys = raw[:, 1] / max(gh - 1, 1) * oh
            return (
                jnp.stack([xs, ys, raw[:, 2]], axis=-1).astype(
                    jnp.float32
                ),
            )

        from nnstreamer_tpu.tensors.spec import DType, TensorSpec

        out = TensorsSpec.of(
            TensorSpec((k, 3), DType.FLOAT32, name="keypoints"),
            rate=in_spec.rate,
        )
        return out, fn

    def decode(self, frame: Frame, options: dict) -> Frame:
        heat = np.asarray(frame.tensors[0])
        grid = heat.reshape(heat.shape[-3:])  # drop leading batch dims
        gh, gw, k = grid.shape
        ow, oh = self._out_wh
        if self._offset_mode:
            o = np.asarray(frame.tensors[1])
            offs = o.reshape(o.shape[-3:])
            raw = np.asarray(hm.pose_keypoints_with_offsets(grid, offs))
            # posenet: pos = grid_idx/(grid-1)*(input-1) + offset (pixels in
            # model-input units), then scale to output size
            iw, ih = self._in_wh
            x_in = raw[:, 0] / max(gw - 1, 1) * (iw - 1) + raw[:, 3]
            y_in = raw[:, 1] / max(gh - 1, 1) * (ih - 1) + raw[:, 4]
            xs = x_in / iw * ow
            ys = y_in / ih * oh
            score = raw[:, 2]
        else:
            raw = np.asarray(hm.pose_keypoints_from_heatmap(grid))
            xs = raw[:, 0] / max(gw - 1, 1) * ow
            ys = raw[:, 1] / max(gh - 1, 1) * oh
            score = raw[:, 2]
        kpts = np.stack([xs, ys, score], axis=-1).astype(np.float32)

        canvas = render.new_canvas(ow, oh)
        ok = score >= self._score_threshold
        for i in range(k):
            if not ok[i]:
                continue
            render.draw_point(canvas, xs[i], ys[i])
            if self._names and i < len(self._names):
                render.draw_text(canvas, self._names[i], xs[i] + 3, ys[i] + 3)
            for j in (self._conns[i] if self._conns and i < len(self._conns) else ()):
                if 0 <= j < k and ok[j]:
                    render.draw_line(canvas, xs[i], ys[i], xs[j], ys[j])
        return frame.with_tensors((canvas,)).with_meta(
            media_type="video", keypoints=kpts
        )
