"""direct_video decoder: tensor → raw video media.

Reference: ext/nnstreamer/tensor_decoder/tensordec-directvideo.c (377 LoC):
uint8 tensor with canonical (N,H,W,C) layout back to video frames.
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import MediaSpec, NegotiationError
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import DType, TensorsSpec


@registry.decoder_plugin("direct_video")
class DirectVideoDecoder:
    def negotiate(self, in_spec: TensorsSpec, options: dict) -> MediaSpec:
        if in_spec.num_tensors != 1:
            raise NegotiationError("direct_video: exactly one tensor expected")
        t = in_spec[0]
        if t.dtype is not DType.UINT8 or t.rank != 4:
            raise NegotiationError(f"direct_video: need uint8 NHWC, got {t}")
        n, h, w, c = t.shape
        fmt = {1: "GRAY8", 3: "RGB", 4: "RGBA"}.get(c)
        if fmt is None:
            raise NegotiationError(f"direct_video: {c} channels unsupported")
        return MediaSpec("video", width=w, height=h, format=fmt, rate=in_spec.rate)

    def decode(self, frame: Frame, options: dict):
        batch = np.asarray(frame.tensors[0])
        # one media frame per batch element (a batched tensor came from
        # frames-per-tensor aggregation; un-batch on egress)
        out = []
        n = batch.shape[0]
        for i in range(n):
            f = frame.with_tensors((batch[i],)).with_meta(media_type="video")
            if frame.pts is not None and frame.duration is not None and n > 1:
                per = frame.duration // n
                f = f.with_pts(frame.pts + i * per, per)
            out.append(f)
        return out if len(out) > 1 else out[0]
