"""flexbuf decoder subplugin: tensors → serialized flex-tensor bytes.

Reference: ext/nnstreamer/tensor_decoder/tensordec-flexbuf.cc. Inverse of
the flexbuf converter; output is one uint8 tensor holding the serialized
frame (feed to filesink / network sinks).
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import MediaSpec
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.meta import encode_frame_tensors
from nnstreamer_tpu.tensors.spec import TensorsSpec


@registry.decoder_plugin("flexbuf")
class FlexbufDecoder:
    def negotiate(self, in_spec: TensorsSpec, options: dict) -> MediaSpec:
        return MediaSpec("octet")

    def decode(self, frame: Frame, options: dict) -> Frame:
        frame = frame.to_host()
        blob = encode_frame_tensors(frame.tensors)
        return frame.with_tensors(
            (np.frombuffer(blob, dtype=np.uint8),)
        ).with_meta(media_type="octet")
