"""edgesink / edgesrc: publish/subscribe tensor streams between pipelines.

Reference: gst/edge/edge_{sink,src}.c — thin wrappers over nnstreamer-edge
pub/sub (TCP default, port 3000, edge_common.h:36-37). edgesink listens and
broadcasts every rendered frame to all connected subscribers; edgesrc
connects and emits whatever arrives. Unlike the query pair there is no
reply path and no client demux.
"""

from __future__ import annotations

from typing import List, Optional

from nnstreamer_tpu import registry
from nnstreamer_tpu.edge.mqtt import MqttError
from nnstreamer_tpu.edge.shm import MessageTooLarge
from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.edge.serialize import decode_message, encode_message
from nnstreamer_tpu.edge.transport import TransportError, make_transport
from nnstreamer_tpu.elements.base import (
    _parse_bool,
    ElementError,
    NegotiationError,
    PropSpec,
    Sink,
    Source,
    Spec,
)
from nnstreamer_tpu.tensors.frame import EOS, EOS_FRAME, Frame
from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec

DEFAULT_PORT = 3000  # reference edge_common.h:36-37

_log = get_logger("edge.pubsub")


@registry.element("edgesink")
class EdgeSink(Sink):
    """Broadcast frames to all subscribers.

    Props: host (default 127.0.0.1), port (default 3000; 0 = ephemeral,
    read back via ``bound_port``), wait-connection (block first frame until
    a subscriber arrives, default false), connection-timeout (s).
    """

    FACTORY_NAME = "edgesink"

    PROPERTIES = {
        "host": PropSpec("str", "127.0.0.1"),
        "port": PropSpec("int", 3000, desc="0 = ephemeral"),
        "connect-type": PropSpec("enum", "TCP", ("TCP", "MQTT", "SHM")),
        "topic": PropSpec("str", "nns-edge"),
        "wait-connection": PropSpec("bool", False),
        "connection-timeout": PropSpec("float", 10.0),
        "shm-capacity": PropSpec("int", None, desc="SHM ring capacity"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.host = str(self.get_property("host", "127.0.0.1"))
        self.port = int(self.get_property("port", DEFAULT_PORT))
        self.connect_type = str(self.get_property("connect-type", "TCP")).upper()
        # MQTT mode (reference connect-type=MQTT): host/port address the
        # broker, frames publish to ``topic``
        self.topic = str(self.get_property("topic", "nns-edge"))
        self.wait_connection = _parse_bool(
            self.get_property("wait-connection", False)
        )
        self.conn_timeout = float(self.get_property("connection-timeout", 10.0))
        self.bound_port: Optional[int] = None
        self._transport = None
        self._mqtt = None
        if self.connect_type not in ("TCP", "MQTT", "SHM"):
            raise ValueError(
                f"{self.name}: connect-type={self.connect_type} not built in "
                "(reference HYBRID/AITT are broker-vendor specific)"
            )

    def start(self) -> None:
        if self.connect_type == "MQTT":
            from nnstreamer_tpu.edge.mqtt import MqttClient, MqttError

            try:
                self._mqtt = MqttClient(self.host, self.port).connect()
            except (MqttError, OSError) as exc:
                raise ElementError(
                    f"{self.name}: cannot reach MQTT broker "
                    f"{self.host}:{self.port}: {exc}"
                ) from exc
            return
        if self.connect_type == "SHM":
            from nnstreamer_tpu.edge.shm import DEFAULT_CAPACITY, ShmTransport

            cap = int(self.get_property("shm-capacity", DEFAULT_CAPACITY))
            self._transport = ShmTransport(capacity=cap)
        else:
            self._transport = make_transport()
        self.bound_port = self._transport.listen(self.host, self.port)

    def stop(self) -> None:
        if self._mqtt is not None:
            try:
                self._mqtt.publish(self.topic, encode_message(EOS_FRAME))
            except (MqttError, OSError):
                pass  # broker already gone: teardown must not raise
            self._mqtt.close()
            self._mqtt = None
        if self._transport is not None:
            # subscribers see the stream end explicitly
            try:
                self._transport.send(0, encode_message(EOS_FRAME))
            except (TransportError, OSError):
                pass
            self._transport.close()
            self._transport = None

    def render(self, frame: Frame) -> None:
        if self._mqtt is not None:
            try:
                self._mqtt.publish(self.topic, encode_message(frame))
            except (MqttError, OSError) as exc:
                raise ElementError(
                    f"{self.name}: MQTT publish failed: {exc}"
                ) from exc
            return
        if self.wait_connection and self._transport.peer_count() == 0:
            import time

            deadline = time.monotonic() + self.conn_timeout
            while (
                self._transport.peer_count() == 0
                and time.monotonic() < deadline
            ):
                time.sleep(0.01)
            if self._transport.peer_count() == 0:
                raise ElementError(
                    f"{self.name}: no subscriber within {self.conn_timeout}s"
                )
        try:
            self._transport.send(0, encode_message(frame))  # 0 = broadcast
        except (TransportError, OSError) as exc:
            if isinstance(exc, MessageTooLarge):
                # permanent misconfiguration: EVERY frame would drop —
                # fail the pipeline with the remedy, don't warn forever
                raise ElementError(f"{self.name}: {exc}") from exc
            # best-effort: one dead subscriber must not kill the stream —
            # but dropped frames must be visible, not silent
            _log.warning("%s: frame dropped: %s", self.name, exc)

    def on_eos(self) -> None:
        if self._mqtt is not None:
            try:
                self._mqtt.publish(self.topic, encode_message(EOS_FRAME))
            except (MqttError, OSError):
                pass
        if self._transport is not None:
            try:
                self._transport.send(0, encode_message(EOS_FRAME))
            except (TransportError, OSError):
                pass


@registry.element("edgesrc")
class EdgeSrc(Source):
    """Subscribe to an edgesink and emit its frames.

    Props: dest-host (default 127.0.0.1), dest-port (default 3000),
    connect-type=TCP (sockets), MQTT (broker pub/sub via ``topic``), or
    SHM (same-host native shared-memory ring, native/nns_shm.cpp —
    zero-socket fast path; single consumer).
    """

    FACTORY_NAME = "edgesrc"

    PROPERTIES = {
        "dest-host": PropSpec("str", "127.0.0.1"),
        "dest-port": PropSpec("int", 3000),
        "connect-type": PropSpec("enum", "TCP", ("TCP", "MQTT", "SHM")),
        "topic": PropSpec("str", "nns-edge"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.host = str(self.get_property("dest-host", "127.0.0.1"))
        self.port = int(self.get_property("dest-port", DEFAULT_PORT))
        self.connect_type = str(self.get_property("connect-type", "TCP")).upper()
        self.topic = str(self.get_property("topic", "nns-edge"))
        self._transport = None
        self._mqtt = None

    def output_spec(self) -> Spec:
        if self.connect_type not in ("TCP", "MQTT", "SHM"):
            raise NegotiationError(
                f"{self.name}: connect-type={self.connect_type} not built in"
            )
        return TensorsSpec(format=TensorFormat.FLEXIBLE)

    def start(self) -> None:
        if self.connect_type == "MQTT":
            from nnstreamer_tpu.edge.mqtt import MqttClient, MqttError

            try:
                self._mqtt = MqttClient(self.host, self.port).connect()
                self._mqtt.subscribe(self.topic)
            except (MqttError, OSError) as exc:
                raise ElementError(
                    f"{self.name}: cannot reach MQTT broker "
                    f"{self.host}:{self.port}: {exc}"
                ) from exc
            return
        if self.connect_type == "SHM":
            from nnstreamer_tpu.edge.shm import ShmTransport

            self._transport = ShmTransport()
        else:
            self._transport = make_transport()
        try:
            self._transport.connect(self.host, self.port)
        except (TransportError, OSError) as exc:
            raise ElementError(
                f"{self.name}: cannot reach edgesink {self.host}:{self.port}: "
                f"{exc}"
            ) from exc

    def stop(self) -> None:
        if self._mqtt is not None:
            self._mqtt.close()
            self._mqtt = None
        if self._transport is not None:
            self._transport.close()
            self._transport = None

    def generate(self):
        if self._mqtt is not None:
            got = self._mqtt.recv(timeout=0.1)
            if got is None:
                return None
            payload = got[1]
        else:
            got = self._transport.recv(timeout=0.1)
            if got is None:
                return None
            _, payload = got
            if not payload:
                return EOS_FRAME  # publisher went away
        msg = decode_message(payload)
        return EOS_FRAME if isinstance(msg, EOS) else msg
