"""Query elements: request/response pipeline offload across hosts.

Reference: gst/nnstreamer/tensor_query/ —
- ``tensor_query_client`` (tensor_query_client.c:663-735): sink chain
  serializes the frame, sends to the server, blocks for the reply, pushes
  the reply downstream.
- ``tensor_query_serversrc`` (tensor_query_serversrc.c:299-427): push
  source emitting incoming requests tagged with their ``client_id`` meta
  (the GstMetaQuery analogue, tensor_meta.h:26-31).
- ``tensor_query_serversink`` (tensor_query_serversink.c:241-278): reads
  the ``client_id`` meta and sends the result back to that client.
- serversrc/sink pair through a global id table
  (tensor_query_server.c, hdr :25-73) — here :data:`_server_table`.

Transports (``connect-type``, reference tensor_query_common.c:35-42):
``TCP`` (in-tree native C++ edge library, python fallback), ``MQTT``
(request/reply topics over the broker), ``HYBRID`` (MQTT whois discovery
+ raw TCP bulk) — see query_transports.py. AITT stays vendor-gated like
its meson option.
"""

from __future__ import annotations

import itertools
import os
import random
import threading
import time
from typing import Dict, List, Optional

from nnstreamer_tpu import registry, trace
from nnstreamer_tpu.obs import metrics as obs_metrics
from nnstreamer_tpu.edge.serialize import decode_message, encode_message
from nnstreamer_tpu.edge.transport import TransportError, make_transport
from nnstreamer_tpu.elements.base import (
    ElementError,
    HostElement,
    NegotiationError,
    PropSpec,
    Sink,
    Source,
    Spec,
)
from nnstreamer_tpu.tensors.frame import EOS, EOS_FRAME, Frame
from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec

# reference QUERY_DEFAULT_TIMEOUT_SEC (tensor_query_common.h:28) is 10 s
DEFAULT_TIMEOUT = 10.0

# serversrc/serversink pairing: id → shared server transport
_server_table: Dict[str, object] = {}
_server_lock = threading.Lock()


def _register_server(srv_id: str, transport) -> None:
    with _server_lock:
        _server_table[srv_id] = transport


def _get_server(srv_id: str):
    with _server_lock:
        return _server_table.get(srv_id)


def _unregister_server(srv_id: str, transport=None) -> None:
    """Remove the pairing entry — but only if it still belongs to the
    caller (a restarted serversrc may have re-registered the id)."""
    with _server_lock:
        if transport is None or _server_table.get(srv_id) is transport:
            _server_table.pop(srv_id, None)


CONNECT_TYPES = ("TCP", "MQTT", "HYBRID")


def _check_connect_type(elem) -> str:
    """Validate and return connect-type (reference
    tensor_query_common.c:35-42; AITT is vendor-gated like its meson
    option)."""
    ct = str(elem.get_property("connect-type", "TCP")).upper()
    if ct not in CONNECT_TYPES:
        raise NegotiationError(
            f"{elem.name}: connect-type={ct} not built in "
            f"(have {'/'.join(CONNECT_TYPES)}; AITT is vendor-gated)"
        )
    return ct


def _make_client_transport(ct: str, topic: str):
    if ct == "MQTT":
        from nnstreamer_tpu.edge.query_transports import MqttQueryTransport

        return MqttQueryTransport(topic)
    if ct == "HYBRID":
        from nnstreamer_tpu.edge.query_transports import HybridClientTransport

        return HybridClientTransport(topic)
    return make_transport()


def _make_server_transport(ct: str, topic: str, data_host: str, data_port: int):
    if ct == "MQTT":
        from nnstreamer_tpu.edge.query_transports import MqttQueryTransport

        return MqttQueryTransport(topic)
    if ct == "HYBRID":
        from nnstreamer_tpu.edge.query_transports import HybridServerTransport

        return HybridServerTransport(topic, data_host, data_port)
    return make_transport()


@registry.element("tensor_query_client")
class TensorQueryClient(HostElement):
    """Offload frames to a remote pipeline and emit the replies.

    Props: dest-host (default 127.0.0.1), dest-port, timeout (seconds),
    connect-type=TCP|MQTT|HYBRID (MQTT: dest addresses the broker,
    payloads ride <topic>/req|rep topics; HYBRID: MQTT whois discovery +
    raw TCP bulk — reference tensor_query_common.c:35-42), topic
    (default nns-query). Requests are strictly synchronous request/reply
    per frame (the reference's max-request pipelining knob does not
    apply).

    Reconnect-with-backoff (docs/fault-tolerance.md): ``retry-max`` > 0
    makes CONNECT/SEND failures (unreachable server at start, a dead
    connection discovered while sending) reconnect with the fault
    layer's jittered exponential backoff (``retry-backoff-ms`` base) and
    resend the frame, instead of failing fast. Once a request went out,
    failures keep failing fast — a timeout or a connection lost while
    awaiting the reply may mean the server already processed the
    request, and a resend could double-process it (the dropped
    connection still reconnects for the next frame)."""

    FACTORY_NAME = "tensor_query_client"

    PROPERTIES = {
        "dest-host": PropSpec("str", "127.0.0.1"),
        "dest-port": PropSpec("int", 0, desc="required"),
        "timeout": PropSpec("float", 10.0, desc="per-request (s)"),
        "connect-type": PropSpec("enum", "TCP", ("TCP", "MQTT", "HYBRID")),
        "topic": PropSpec("str", "nns-query"),
        "retry-max": PropSpec(
            "int", 0, desc="reconnect attempts on transport failure"
        ),
        "retry-backoff-ms": PropSpec(
            "float", 50.0, desc="reconnect backoff base (jittered, doubling)"
        ),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.host = str(self.get_property("dest-host", "127.0.0.1"))
        self.port = int(self.get_property("dest-port", 0))
        self.timeout = float(self.get_property("timeout", DEFAULT_TIMEOUT))
        self.connect_type = "TCP"
        self.topic = str(self.get_property("topic", "nns-query"))
        self.retry_max = max(0, int(self.get_property("retry-max", 0)))
        from nnstreamer_tpu.pipeline.faults import FaultPolicy

        self._retry_policy = FaultPolicy(
            on_error="retry",
            retry_max=self.retry_max,
            backoff_ms=float(self.get_property("retry-backoff-ms", 50.0)),
        )
        self._rng = random.Random(0xED6E)  # deterministic jitter stream
        self._transport = None
        # distributed correlation (docs/observability.md): every request
        # carries a frame_id that survives the hop via the wire meta
        # blob, so client and server traces merge into one timeline
        self._fid_seq = itertools.count()
        self._fid_prefix = f"{os.getpid():x}.{self.name}"
        # registry resolved ONCE at start() (the executor discipline):
        # obs_metrics.get() probes env+config on the None path, which
        # must stay off the per-frame edge hot path. Standalone callers
        # that skip start() simply record no metrics.
        self._obs_reg = None
        self._rtt_hist = None  # nns_edge_rtt_us histogram handle

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        self.connect_type = _check_connect_type(self)
        if self.port <= 0:
            raise NegotiationError(f"{self.name}: dest-port required")
        # the reply's spec is the remote pipeline's business — flexible
        # (caps compatibility is the user's responsibility, reference
        # tensor_query/README.md)
        return [TensorsSpec(format=TensorFormat.FLEXIBLE)]

    def _connect_once(self) -> None:
        # resolve (and validate) connect-type here, not only in start():
        # standalone callers may hit process() without start(), and the
        # property must be honored on that path too
        self.connect_type = _check_connect_type(self)
        self._transport = _make_client_transport(self.connect_type, self.topic)
        try:
            self._transport.connect(self.host, self.port)
        except (TransportError, OSError):
            self._drop_connection()
            raise

    def _drop_connection(self) -> None:
        if self._transport is not None:
            self._transport.close()
            self._transport = None

    def start(self) -> None:
        from nnstreamer_tpu.pipeline.faults import backoff_s

        self._obs_reg = obs_metrics.get()
        attempt = 0
        while True:
            try:
                self._connect_once()
                return
            except (TransportError, OSError) as exc:
                if attempt >= self.retry_max:
                    raise ElementError(
                        f"{self.name}: cannot reach query server "
                        f"{self.host}:{self.port}"
                        + (f" after {attempt + 1} attempts" if attempt else "")
                        + f": {exc}"
                    ) from exc
                time.sleep(backoff_s(attempt, self._retry_policy, self._rng))
                attempt += 1

    def stop(self) -> None:
        self._drop_connection()

    def process(self, frame: Frame) -> Optional[Frame]:
        from nnstreamer_tpu.pipeline.faults import backoff_s

        fid = frame.meta.get("frame_id")
        if fid is None:
            fid = f"{self._fid_prefix}.{next(self._fid_seq)}"
            frame = frame.with_meta(frame_id=fid)
        data = encode_message(frame)
        t_req = time.perf_counter()
        attempt = 0
        while True:
            sent = False
            try:
                if self._transport is None:
                    # reconnect after a timeout-dropped/failed connection
                    self._connect_once()
                self._transport.send(0, data)
                sent = True
                got = self._transport.recv(timeout=self.timeout)
                if got is None:
                    # In a pipeline this error poisons the stream, matching
                    # the reference's GST_FLOW_ERROR on query timeout. For
                    # standalone (direct process()) callers who catch and
                    # continue, drop the connection first so a reply
                    # arriving *after* the timeout can't be returned for
                    # the NEXT frame (off-by-one desync); the next call
                    # reconnects. Timeouts do NOT ride the reconnect-retry
                    # loop: the server may have received the request, and
                    # a resend could double-process it.
                    self._drop_connection()
                    raise ElementError(
                        f"{self.name}: query timeout after {self.timeout}s"
                    )
                _, payload = got
                if not payload:
                    raise TransportError("server closed the connection")
                break
            except (TransportError, OSError) as exc:
                self._drop_connection()
                # the retry loop covers CONNECT/SEND failures only: once
                # the request went out, a lost connection is the timeout
                # case in different clothes — the server may have
                # processed it, and a resend could double-process (the
                # reconnected transport still serves the NEXT frame)
                if sent or attempt >= self.retry_max:
                    raise ElementError(
                        f"{self.name}: query transport failed"
                        + (f" after {attempt + 1} attempts" if attempt else "")
                        + f": {exc}"
                    ) from exc
                time.sleep(backoff_s(attempt, self._retry_policy, self._rng))
                attempt += 1
        rtt_s = time.perf_counter() - t_req
        tracer = trace.get()
        if tracer is not None:
            # the client half of the cross-process pair: merge() lines
            # this span up with the server's frame_id-tagged spans
            tracer.complete(
                self.name, "edge", t_req, rtt_s, {"frame_id": fid}
            )
        reg = self._obs_reg
        if reg is not None:
            if self._rtt_hist is None:
                self._rtt_hist = reg.histogram(
                    "nns_edge_rtt_us", element=self.name
                )
            self._rtt_hist.observe(rtt_s * 1e6)
            reg.counter(
                "nns_edge_requests_total", element=self.name
            ).inc()
        reply = decode_message(payload)
        if isinstance(reply, EOS):
            return None
        if reply.meta.get("frame_id") is None:
            reply = reply.with_meta(frame_id=fid)
        return reply.with_pts(frame.pts, frame.duration)


@registry.element("tensor_query_serversrc")
class TensorQueryServerSrc(Source):
    """Emit incoming query requests, tagged with client_id meta.

    Props: host (default 127.0.0.1), port (0 = ephemeral; read back via
    ``bound_port``), id (pairing key, default "0"),
    connect-type=TCP|MQTT|HYBRID, topic (MQTT/HYBRID), data-host/
    data-port (HYBRID TCP data plane, default ephemeral loopback).
    """

    FACTORY_NAME = "tensor_query_serversrc"

    PROPERTIES = {
        "host": PropSpec("str", "127.0.0.1"),
        "port": PropSpec("int", 0, desc="0 = ephemeral"),
        "id": PropSpec("str", "0", desc="pairing key with serversink"),
        "connect-type": PropSpec("enum", "TCP", ("TCP", "MQTT", "HYBRID")),
        "topic": PropSpec("str", "nns-query"),
        "data-host": PropSpec("str", "127.0.0.1", desc="HYBRID data plane"),
        "data-port": PropSpec("int", 0, desc="HYBRID data plane"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.host = str(self.get_property("host", "127.0.0.1"))
        self.port = int(self.get_property("port", 0))
        self.srv_id = str(self.get_property("id", "0"))
        self.topic = str(self.get_property("topic", "nns-query"))
        # HYBRID: host/port address the broker; the TCP data plane binds
        # data-host:data-port (default ephemeral on loopback)
        self.data_host = str(self.get_property("data-host", "127.0.0.1"))
        self.data_port = int(self.get_property("data-port", 0))
        self.connect_type = "TCP"
        self.bound_port: Optional[int] = None
        self._transport = None

    def output_spec(self) -> Spec:
        self.connect_type = _check_connect_type(self)
        return TensorsSpec(format=TensorFormat.FLEXIBLE)

    def start(self) -> None:
        self.connect_type = _check_connect_type(self)
        self._transport = _make_server_transport(
            self.connect_type, self.topic, self.data_host, self.data_port
        )
        self.bound_port = self._transport.listen(self.host, self.port)
        _register_server(self.srv_id, self._transport)

    def stop(self) -> None:
        _unregister_server(self.srv_id, self._transport)
        if self._transport is not None:
            self._transport.close()
            self._transport = None

    def generate(self):
        got = self._transport.recv(timeout=0.1)
        if got is None:
            return None  # re-poll; executor loops until EOS/stop
        cid, payload = got
        if not payload:
            return None  # client disconnect event; keep serving others
        frame = decode_message(payload)
        if isinstance(frame, EOS):
            return None  # one client's EOS must not stop the server
        tracer = trace.get()
        if tracer is not None:
            tracer.instant(
                self.name, cat="edge",
                frame_id=frame.meta.get("frame_id"), client_id=cid,
            )
        return frame.with_meta(client_id=cid)


@registry.element("tensor_query_serversink")
class TensorQueryServerSink(Sink):
    """Send results back to the requesting client (by client_id meta).

    Props: id (pairing key matching the serversrc, default "0").
    """

    FACTORY_NAME = "tensor_query_serversink"

    PROPERTIES = {
        "id": PropSpec("str", "0", desc="pairing key with serversrc"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.srv_id = str(self.get_property("id", "0"))

    def render(self, frame: Frame) -> None:
        transport = _get_server(self.srv_id)
        if transport is None:
            raise ElementError(
                f"{self.name}: no tensor_query_serversrc with id={self.srv_id}"
            )
        cid = frame.meta.get("client_id")
        if cid is None:
            raise ElementError(
                f"{self.name}: frame lacks client_id meta (did it pass "
                "through tensor_query_serversrc?)"
            )
        tracer = trace.get()
        if tracer is not None:
            tracer.instant(
                self.name, cat="edge",
                frame_id=frame.meta.get("frame_id"), client_id=cid,
            )
        transport.send(cid, encode_message(frame))
