"""Query elements: request/response pipeline offload across hosts.

Reference: gst/nnstreamer/tensor_query/ —
- ``tensor_query_client`` (tensor_query_client.c:663-735): sink chain
  serializes the frame, sends to the server, blocks for the reply, pushes
  the reply downstream.
- ``tensor_query_serversrc`` (tensor_query_serversrc.c:299-427): push
  source emitting incoming requests tagged with their ``client_id`` meta
  (the GstMetaQuery analogue, tensor_meta.h:26-31).
- ``tensor_query_serversink`` (tensor_query_serversink.c:241-278): reads
  the ``client_id`` meta and sends the result back to that client.
- serversrc/sink pair through a global id table
  (tensor_query_server.c, hdr :25-73) — here :data:`_server_table`.

Transports (``connect-type``, reference tensor_query_common.c:35-42):
``TCP`` (in-tree native C++ edge library, python fallback), ``MQTT``
(request/reply topics over the broker), ``HYBRID`` (MQTT whois discovery
+ raw TCP bulk) — see query_transports.py. AITT stays vendor-gated like
its meson option.
"""

from __future__ import annotations

import itertools
import os
import random
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from nnstreamer_tpu import registry, trace
from nnstreamer_tpu.obs import metrics as obs_metrics
from nnstreamer_tpu.edge.admission import (
    REASON_DEADLINE,
    REASON_DRAINING,
    REASON_FAILED,
    REASON_MALFORMED,
    REASON_MAX_CLIENTS,
    AdmissionConfig,
    AdmissionController,
)
from nnstreamer_tpu.edge.fleet import (
    FleetEndpoints,
    HedgeTimer,
    PrefixRouter,
    ReplyDeduper,
    RttWindow,
    parse_hosts,
    prefix_route_keys,
)
from nnstreamer_tpu.edge.serialize import (
    ROUTE_META_KEY,
    Ctrl,
    Nack,
    decode_message,
    encode_ctrl,
    encode_message,
    encode_nack,
)
from nnstreamer_tpu.edge.transport import (
    ChaosCounter,
    ChaosTransport,
    TransportError,
    UnresolvableError,
    make_transport,
    resolve_target,
)
from nnstreamer_tpu.elements.base import (
    ElementError,
    HostElement,
    NegotiationError,
    PropSpec,
    Sink,
    Source,
    Spec,
)
from nnstreamer_tpu.tensors.frame import EOS, EOS_FRAME, Frame
from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec

# reference QUERY_DEFAULT_TIMEOUT_SEC (tensor_query_common.h:28) is 10 s
DEFAULT_TIMEOUT = 10.0

# serversrc readiness flags (docs/edge-serving.md "Running a fleet"):
# ready → serving; draining → graceful drain in progress (new submits
# NACK `draining`); dead → stopped/not started
SRV_READY = "ready"
SRV_DRAINING = "draining"
SRV_DEAD = "dead"

# serversrc/serversink pairing: id → shared server transport (+ the
# admission controller when one is configured, keyed separately so the
# transport-only consumers stay untouched; + the readiness flag so the
# fault-disposal paths can pick drain-aware NACK reasons)
_server_table: Dict[str, object] = {}
_controller_table: Dict[str, AdmissionController] = {}
_state_table: Dict[str, str] = {}
_server_lock = threading.Lock()


def _register_server(srv_id: str, transport, controller=None) -> None:
    with _server_lock:
        _server_table[srv_id] = transport
        _state_table[srv_id] = SRV_READY
        if controller is not None:
            _controller_table[srv_id] = controller
        else:
            _controller_table.pop(srv_id, None)


def _get_server(srv_id: str):
    with _server_lock:
        return _server_table.get(srv_id)


def _get_controller(srv_id: str) -> Optional[AdmissionController]:
    with _server_lock:
        return _controller_table.get(srv_id)


def server_state(srv_id: str) -> str:
    """The serversrc's readiness flag (ready / draining / dead)."""
    with _server_lock:
        return _state_table.get(srv_id, SRV_DEAD)


def _set_server_state(srv_id: str, state: str) -> None:
    with _server_lock:
        if srv_id in _state_table:
            _state_table[srv_id] = state


def _unregister_server(srv_id: str, transport=None) -> None:
    """Remove the pairing entry — but only if it still belongs to the
    caller (a restarted serversrc may have re-registered the id)."""
    with _server_lock:
        if transport is None or _server_table.get(srv_id) is transport:
            _server_table.pop(srv_id, None)
            _controller_table.pop(srv_id, None)
            _state_table.pop(srv_id, None)


def nack_for_shed(srv_id: str, cid, frame_id=None) -> None:
    """Deadline shed notification (pipeline/faults.py notify_shed): the
    executor dropped an admitted request before it consumed device time;
    tell the client so the request still has a terminal outcome, and
    return the admission budget. Best-effort — a vanished client must
    not poison the shedding node."""
    transport = _get_server(srv_id)
    if transport is not None and cid is not None:
        try:
            transport.send(
                cid, encode_nack(REASON_DEADLINE, 0.0, frame_id=frame_id)
            )
        except (TransportError, OSError):
            pass
    ctrl = _get_controller(srv_id)
    if ctrl is not None and cid is not None:
        ctrl.release(cid)


def discard_admitted(srv_id: str, cid, action: str, frame_id=None,
                     draining: bool = False) -> None:
    """A fault policy disposed of an admitted request (pipeline/faults.py
    notify_discard): return its admission budget — the in-flight slot
    must not stay pinned forever — and, unless the frame was delivered
    to a dead-letter consumer (``action == "route"``), NACK the client
    so the request does not end as a silent client-side timeout. The
    reason is ``failed`` (terminal) normally, but ``draining`` while
    the server is in a graceful drain — the disposal is then a
    restart artifact, not a verdict on the request, and a fleet client
    re-routes it to another endpoint instead of giving up.
    ``draining=True`` forces that reading when the DOWNSTREAM consumer
    is the one draining (an LLM serversink mid-drain behind a
    still-ready serversrc — docs/llm-serving.md)."""
    ctrl = _get_controller(srv_id)
    if ctrl is not None and cid is not None:
        ctrl.release(cid)
    if action == "route":
        return  # the dead-letter consumer owns the request's fate now
    transport = _get_server(srv_id)
    if transport is not None and cid is not None:
        if draining or server_state(srv_id) == SRV_DRAINING:
            reason, hint = REASON_DRAINING, (
                ctrl.cfg.retry_after_ms if ctrl is not None else 50.0
            )
        else:
            reason, hint = REASON_FAILED, 0.0
        try:
            transport.send(
                cid, encode_nack(reason, hint, frame_id=frame_id)
            )
        except (TransportError, OSError):
            pass


def drain_flushed(srv_id: str, cid, frame_id=None) -> None:
    """A draining server flushed a queued admitted request before it
    consumed device time (pipeline/faults.py notify_drain_flush): NACK
    the client ``draining`` — a fleet client re-routes the request to
    another endpoint, so a rolling restart loses nothing — and return
    the admission budget (the PR-6 release path)."""
    ctrl = _get_controller(srv_id)
    transport = _get_server(srv_id)
    if transport is not None and cid is not None:
        hint = ctrl.cfg.retry_after_ms if ctrl is not None else 50.0
        try:
            transport.send(
                cid, encode_nack(REASON_DRAINING, hint, frame_id=frame_id)
            )
        except (TransportError, OSError):
            pass
    if ctrl is not None and cid is not None:
        ctrl.release(cid)


def request_drain(host: str, port: int, connect_type: str = "TCP",
                  topic: str = "nns-query", attempts: int = 3) -> None:
    """Operator helper: ask the query server at ``host:port`` to drain
    gracefully (the ``drain`` control message — rolling restarts without
    dropping admitted work). Fire-and-forget once delivered: the server
    NACKs new submits ``draining`` from the moment the message lands.
    A couple of connect retries absorb transient accept races on a busy
    server; a server that stays unreachable raises."""
    last: Optional[Exception] = None
    for attempt in range(max(1, int(attempts))):
        if attempt:
            time.sleep(0.05 * attempt)
        t = _make_client_transport(str(connect_type).upper(), topic)
        try:
            t.connect(host, port)
            t.send(0, encode_ctrl("drain"))
            return
        except (TransportError, OSError) as exc:
            last = exc
        finally:
            t.close()
    raise TransportError(
        f"cannot deliver drain to {host}:{port}: {last}"
    )


# -- live KV-span migration handshake (docs/llm-serving.md) ----------------
# A draining LLM server re-hosts in-flight generations by asking a peer
# serversrc: ``migrate_probe`` (how many leading tokens does your prefix
# index cover? → strip those payloads) then ``migrate_span`` (the
# kv/migrate.py span bytes riding the CTRL payload). The serversrc
# routes both to the LLM server registered for the requested ``llm_id``
# — the pairing is process-local, like the serversrc/serversink tables.

_migration_table: Dict[int, object] = {}
_migration_lock = threading.Lock()


class MigrationRefused(RuntimeError):
    """The peer answered the migration handshake with ``migrate_nack``:
    the span was NOT adopted (no handler, draining, capacity, corrupt
    span...). The source keeps the request — fall back to local
    re-prefill resume. Capacity refusals carry the peer's
    ``retry_after_ms`` hint (the PR-18 PoolCapacityError taxonomy on
    the wire) so a disaggregated prefill server can back off instead
    of hammering a full decode pool."""

    def __init__(self, reason: str, retry_after_ms: float = 0.0) -> None:
        super().__init__(reason)
        self.reason = reason
        self.retry_after_ms = float(retry_after_ms)


def register_migration_handler(llm_id: int, handler) -> None:
    """Make an LLM server adoptable-at over this process's serversrcs.
    ``handler`` implements ``migration_probe(tokens) -> int`` and
    ``migration_adopt(span_bytes) -> new_rid`` (raising a
    ``kv.migrate.SpanError`` subclass to refuse)."""
    with _migration_lock:
        _migration_table[int(llm_id)] = handler


def unregister_migration_handler(llm_id: int, handler=None) -> None:
    with _migration_lock:
        if handler is None or _migration_table.get(int(llm_id)) is handler:
            _migration_table.pop(int(llm_id), None)


def _get_migration_handler(llm_id: int):
    with _migration_lock:
        h = _migration_table.get(int(llm_id))
        if h is None and len(_migration_table) == 1:
            # exactly one LLM server in this process — the common fleet
            # layout — so migrate-to=host:port works without the sender
            # guessing the peer's serversink id
            h = next(iter(_migration_table.values()))
        return h


def _ctrl_roundtrip(host: str, port: int, msg: bytes, connect_type: str,
                    topic: str, timeout: float):
    """Send one CTRL message and wait for the CTRL reply (the data
    protocol is fire-and-forget for CTRL; migration needs an answer)."""
    t = _make_client_transport(str(connect_type).upper(), topic)
    try:
        t.connect(host, port)
        t.send(0, msg)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            got = t.recv(timeout=0.1)
            if got is None:
                continue
            _cid, payload = got
            if not payload:
                raise TransportError(
                    "peer closed during migration handshake"
                )
            try:
                reply = decode_message(payload)
            except ValueError:
                continue  # garbage on the reply path: keep waiting
            if isinstance(reply, Ctrl):
                return reply
        raise TransportError(
            f"migration handshake with {host}:{port} timed out"
        )
    finally:
        t.close()


def probe_migration(host: str, port: int, tokens, llm_id: int = 0,
                    connect_type: str = "TCP", topic: str = "nns-query",
                    timeout: float = 5.0) -> int:
    """Ask the peer how many leading ``tokens`` its LLM server's prefix
    index already covers (full blocks only) — the warm-migration diet.
    Raises :class:`MigrationRefused` if the peer cannot host spans."""
    return probe_migration_full(
        host, port, tokens, llm_id=llm_id, connect_type=connect_type,
        topic=topic, timeout=timeout,
    )[0]


def probe_migration_full(host: str, port: int, tokens, llm_id: int = 0,
                         connect_type: str = "TCP",
                         topic: str = "nns-query",
                         timeout: float = 5.0):
    """:func:`probe_migration` plus the peer's full probe-ack meta as a
    dict — ``(shared_tokens, advert)``. Decode-role servers advertise
    ``role`` / ``free_slots`` / ``free_blocks`` there (pool headroom +
    prefix depth in one roundtrip), which the disaggregated prefill
    side uses to pick the handoff target."""
    reply = _ctrl_roundtrip(
        host, port,
        encode_ctrl("migrate_probe", llm_id=int(llm_id),
                    tokens=[int(x) for x in tokens]),
        connect_type, topic, timeout,
    )
    if reply.op != "migrate_probe_ack":
        raise MigrationRefused(
            str(reply.meta.get("reason", reply.op)),
            retry_after_ms=float(reply.meta.get("retry_after_ms", 0) or 0),
        )
    return int(reply.meta.get("shared_tokens", 0)), dict(reply.meta)


def send_migration(host: str, port: int, span_bytes: bytes,
                   llm_id: int = 0, connect_type: str = "TCP",
                   topic: str = "nns-query", timeout: float = 10.0) -> int:
    """Ship an encoded KV span to the peer; returns the rid the
    adopting server continues the generation under. Raises
    :class:`MigrationRefused` when the peer declines (the request is
    still whole on the caller's side — resume it locally)."""
    reply = _ctrl_roundtrip(
        host, port,
        encode_ctrl("migrate_span", payload=span_bytes,
                    llm_id=int(llm_id)),
        connect_type, topic, timeout,
    )
    if reply.op != "migrate_span_ack":
        raise MigrationRefused(
            str(reply.meta.get("reason", reply.op)),
            retry_after_ms=float(reply.meta.get("retry_after_ms", 0) or 0),
        )
    return int(reply.meta.get("rid", -1))


def fetch_handoff(host: str, port: int, rid: int, llm_id: int = 0,
                  connect_type: str = "TCP", topic: str = "nns-query",
                  timeout: float = 5.0):
    """Poll a decode peer for the outcome of a handed-off generation:
    ``None`` while rid is still decoding, else the full token list
    exactly once (the peer forgets the rid on fetch, so the prefill
    side — the only DELIVER path the client knows — cannot
    double-emit). Raises :class:`MigrationRefused` on a nack (rid
    unknown / peer draining): the caller's fallback ladder decides."""
    reply = _ctrl_roundtrip(
        host, port,
        encode_ctrl("disagg_fetch", llm_id=int(llm_id), rid=int(rid)),
        connect_type, topic, timeout,
    )
    if reply.op != "disagg_fetch_ack":
        raise MigrationRefused(
            str(reply.meta.get("reason", reply.op)),
            retry_after_ms=float(reply.meta.get("retry_after_ms", 0) or 0),
        )
    if not int(reply.meta.get("done", 0)):
        return None
    return [int(t) for t in reply.meta.get("tokens", [])]


CONNECT_TYPES = ("TCP", "MQTT", "HYBRID", "SHM")


def _check_connect_type(elem) -> str:
    """Validate and return connect-type (reference
    tensor_query_common.c:35-42; AITT is vendor-gated like its meson
    option)."""
    ct = str(elem.get_property("connect-type", "TCP")).upper()
    if ct not in CONNECT_TYPES:
        raise NegotiationError(
            f"{elem.name}: connect-type={ct} not built in "
            f"(have {'/'.join(CONNECT_TYPES)}; AITT is vendor-gated)"
        )
    return ct


def _make_client_transport(ct: str, topic: str):
    if ct == "MQTT":
        from nnstreamer_tpu.edge.query_transports import MqttQueryTransport

        return MqttQueryTransport(topic)
    if ct == "HYBRID":
        from nnstreamer_tpu.edge.query_transports import HybridClientTransport

        return HybridClientTransport(topic)
    if ct == "SHM":
        from nnstreamer_tpu.edge.query_transports import ShmClientTransport

        return ShmClientTransport()
    return make_transport()


def _make_server_transport(ct: str, topic: str, data_host: str,
                           data_port: int, max_conns: int = 0,
                           retry_after_ms: float = 50.0):
    if ct == "MQTT":
        from nnstreamer_tpu.edge.query_transports import MqttQueryTransport

        return MqttQueryTransport(topic)
    if ct == "HYBRID":
        from nnstreamer_tpu.edge.query_transports import HybridServerTransport

        t = HybridServerTransport(topic, data_host, data_port)
    elif ct == "SHM":
        from nnstreamer_tpu.edge.query_transports import ShmServerTransport

        return ShmServerTransport()
    else:
        # connection caps need the python acceptor's reject path; the
        # native transport still gets request-level admission NACKs
        t = make_transport(prefer_native=not max_conns)
    if max_conns and hasattr(t, "max_conns"):
        t.max_conns = max_conns
        t.reject_payload = encode_nack(REASON_MAX_CLIENTS, retry_after_ms)
    return t


@registry.element("tensor_query_client")
class TensorQueryClient(HostElement):
    """Offload frames to a remote pipeline and emit the replies.

    Props: dest-host (default 127.0.0.1), dest-port, timeout (seconds),
    connect-type=TCP|MQTT|HYBRID (MQTT: dest addresses the broker,
    payloads ride <topic>/req|rep topics; HYBRID: MQTT whois discovery +
    raw TCP bulk — reference tensor_query_common.c:35-42), topic
    (default nns-query). Requests are strictly synchronous request/reply
    per frame (the reference's max-request pipelining knob does not
    apply).

    Reconnect-with-backoff (docs/fault-tolerance.md): ``retry-max`` > 0
    makes CONNECT/SEND failures (unreachable server at start, a dead
    connection discovered while sending) reconnect with the fault
    layer's jittered exponential backoff (``retry-backoff-ms`` base) and
    resend the frame, instead of failing fast. Once a request went out,
    failures keep failing fast — a timeout or a connection lost while
    awaiting the reply may mean the server already processed the
    request, and a resend could double-process it (the dropped
    connection still reconnects for the next frame).

    Overload cooperation (docs/edge-serving.md): ``deadline-ms`` stamps
    a per-request SLO into the wire meta (the server sheds frames that
    can no longer meet it, and NACKs back); ``priority`` picks the
    admission class (lower = more urgent). Admission NACKs (max-clients
    / overload / client-backpressure / rate / malformed) mean the server
    did NOT process the request — the client honors the NACK's
    retry-after hint on its existing ``retry-max`` budget. The
    ``chaos-*`` properties inject deterministic network faults
    (docs/fault-tolerance.md) for testing those paths.

    Fleet mode (docs/edge-serving.md "Running a fleet"): ``hosts=
    h1:p1,h2:p2,...`` replaces the single ``dest-host``/``dest-port``
    binding with a health-scored endpoint fleet (edge/fleet.py):
    consecutive-failure ejection with jittered-backoff re-probes, a
    ``draining`` NACK benches an endpoint for exactly its retry-after
    hint (rolling restarts), and an in-flight request whose endpoint
    dies FAILS OVER to the next healthy endpoint — delivery stays
    at-most-once because replies are deduped by ``frame_id`` (a late
    duplicate from the first server is dropped, never pushed
    downstream). ``hedge-after-ms`` > 0 arms hedged requests: a
    straggling request is re-sent to a second endpoint after the delay,
    first reply wins, the loser's reply is deduped (< 0 adapts the
    threshold to the observed reply p99). Note the failover/hedge
    semantics differ from the single-endpoint path on purpose: a
    re-send may double-*process* on two servers, but never
    double-*delivers* — opt in only when requests are idempotent or the
    duplicate compute is acceptable."""

    FACTORY_NAME = "tensor_query_client"

    PROPERTIES = {
        "dest-host": PropSpec("str", "127.0.0.1"),
        "dest-port": PropSpec("int", 0, desc="required unless hosts= set"),
        "hosts": PropSpec(
            "str", None,
            desc="fleet endpoints h1:p1,h2:p2,... — overrides dest-host/"
            "dest-port and enables health-scored failover/hedging",
        ),
        "hedge-after-ms": PropSpec(
            "float", 0.0,
            desc="fleet hedging: re-send a straggling request to a "
            "second endpoint after this delay, first reply wins "
            "(0 = off, <0 = adaptive from the observed reply p99)",
        ),
        "prefix-route": PropSpec(
            "bool", False,
            desc="fleet mode: stamp rolling-CRC prompt-prefix keys into "
            "the request meta and prefer the endpoint that last served "
            "the longest matching prefix (cluster-wide KV prefix "
            "sharing; falls back to the least-loaded rotation)",
        ),
        "timeout": PropSpec("float", 10.0, desc="per-request (s)"),
        "connect-type": PropSpec("enum", "TCP", CONNECT_TYPES),
        "topic": PropSpec("str", "nns-query"),
        "retry-max": PropSpec(
            "int", 0, desc="reconnect attempts on transport failure"
        ),
        "retry-backoff-ms": PropSpec(
            "float", 50.0, desc="reconnect backoff base (jittered, doubling)"
        ),
        "deadline-ms": PropSpec(
            "float", 0.0,
            desc="per-request SLO stamped into the wire meta; the server "
            "sheds frames that can no longer meet it (0 = none)",
        ),
        "priority": PropSpec(
            "int", None,
            desc="admission priority class (lower = more urgent; "
            "default 1 server-side)",
        ),
        "chaos-drop-every-n": PropSpec(
            "int", 0,
            desc="chaos harness: sever the connection on every Nth send",
        ),
        "chaos-truncate-every-n": PropSpec(
            "int", 0,
            desc="chaos harness: send a truncated header every Nth send",
        ),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.host = str(self.get_property("dest-host", "127.0.0.1"))
        self.port = int(self.get_property("dest-port", 0))
        self.timeout = float(self.get_property("timeout", DEFAULT_TIMEOUT))
        self.connect_type = "TCP"
        self.topic = str(self.get_property("topic", "nns-query"))
        self.retry_max = max(0, int(self.get_property("retry-max", 0)))
        self.deadline_ms = float(self.get_property("deadline-ms", 0.0))
        raw_prio = self.get_property("priority")
        self.priority = None if raw_prio is None else int(raw_prio)
        self._chaos_drop_n = max(
            0, int(self.get_property("chaos-drop-every-n", 0))
        )
        self._chaos_trunc_n = max(
            0, int(self.get_property("chaos-truncate-every-n", 0))
        )
        self._chaos_counter = ChaosCounter()
        from nnstreamer_tpu.pipeline.faults import FaultPolicy

        self._retry_policy = FaultPolicy(
            on_error="retry",
            retry_max=self.retry_max,
            backoff_ms=float(self.get_property("retry-backoff-ms", 50.0)),
        )
        self._rng = random.Random(0xED6E)  # deterministic jitter stream
        self._transport = None
        # fleet mode (docs/edge-serving.md "Running a fleet"): hosts=
        # binds a health-scored endpoint selector instead of one socket
        self.hedge_after_ms = float(self.get_property("hedge-after-ms", 0.0))
        hosts_raw = self.get_property("hosts")
        self._fleet: Optional[FleetEndpoints] = None
        self._ep_transports: Dict[object, object] = {}
        self._dedup: Optional[ReplyDeduper] = None
        self._rtts: Optional[RttWindow] = None
        self.fleet_failovers = 0   # requests re-sent off a failed endpoint
        self.fleet_hedges = 0      # hedge sends fired
        self.stale_replies = 0     # late replies to already-terminal requests
        self._failover_ctr = None
        self._hedge_ctr = None
        self.prefix_route = bool(self.get_property("prefix-route", False))
        self._router: Optional[PrefixRouter] = None
        self._pfx_hit_ctr = None
        if hosts_raw:
            try:
                targets = parse_hosts(hosts_raw)
            except ValueError as exc:
                raise ElementError(f"{self.name}: {exc}") from exc
            self._fleet = FleetEndpoints(
                targets,
                probe_backoff_ms=max(
                    1.0, float(self.get_property("retry-backoff-ms", 50.0))
                ),
                rng=random.Random(0xF1EE7),
                name=self.name,
            )
            self._dedup = ReplyDeduper()
            self._rtts = RttWindow()
            if self.prefix_route:
                self._router = PrefixRouter()
        # distributed correlation (docs/observability.md): every request
        # carries a frame_id that survives the hop via the wire meta
        # blob, so client and server traces merge into one timeline
        self._fid_seq = itertools.count()
        self._fid_prefix = f"{os.getpid():x}.{self.name}"
        # registry resolved ONCE at start() (the executor discipline):
        # obs_metrics.get() probes env+config on the None path, which
        # must stay off the per-frame edge hot path. Standalone callers
        # that skip start() simply record no metrics.
        self._obs_reg = None
        self._rtt_hist = None  # nns_edge_rtt_us histogram handle
        self._nack_ctrs: Dict[str, object] = {}  # reason → counter

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        self.connect_type = _check_connect_type(self)
        if self.port <= 0 and self._fleet is None:
            raise NegotiationError(
                f"{self.name}: dest-port (or hosts=) required"
            )
        # the reply's spec is the remote pipeline's business — flexible
        # (caps compatibility is the user's responsibility, reference
        # tensor_query/README.md)
        return [TensorsSpec(format=TensorFormat.FLEXIBLE)]

    def _build_transport(self, connect_timeout: Optional[float] = None):
        t = _make_client_transport(self.connect_type, self.topic)
        if connect_timeout is not None:
            if not hasattr(t, "connect_timeout") \
                    and self.connect_type == "TCP":
                # the native transport has no bounded connect(): a
                # SYN-blackholed fleet endpoint would stall the request
                # for the OS default (~minutes) and block failover, so
                # fleet connections ride the python transport (same
                # framing, cross-checked in tests) where the clamp works
                t.close()
                t = make_transport(prefer_native=False)
            if hasattr(t, "connect_timeout"):
                t.connect_timeout = connect_timeout
        if self._chaos_drop_n or self._chaos_trunc_n:
            # the counter survives reconnects so the injection schedule
            # stays deterministic across the faults it causes (and, in
            # fleet mode, across endpoints)
            t = ChaosTransport(
                t, self._chaos_counter,
                drop_every_n=self._chaos_drop_n,
                truncate_every_n=self._chaos_trunc_n,
            )
        return t

    def _connect_once(self) -> None:
        # resolve (and validate) connect-type here, not only in start():
        # standalone callers may hit process() without start(), and the
        # property must be honored on that path too
        self.connect_type = _check_connect_type(self)
        if self.connect_type == "TCP":
            # re-resolve on EVERY reconnect attempt: a failed-over DNS
            # record points somewhere new, and an unresolvable name is a
            # DISTINCT terminal failure (UnresolvableError) instead of a
            # retry-max budget burned on a gone host
            resolve_target(self.host, self.port)
        self._transport = self._build_transport()
        try:
            self._transport.connect(self.host, self.port)
        except (TransportError, OSError):
            self._drop_connection()
            raise

    def _drop_connection(self) -> None:
        if self._transport is not None:
            self._transport.close()
            self._transport = None

    def start(self) -> None:
        from nnstreamer_tpu.pipeline.faults import backoff_s

        self._obs_reg = obs_metrics.get()
        if self._fleet is not None:
            self._start_fleet()
            return
        attempt = 0
        while True:
            try:
                self._connect_once()
                return
            except UnresolvableError as exc:
                # terminal, distinct: retrying a name that does not
                # resolve burns the whole budget for nothing
                raise ElementError(
                    f"{self.name}: query server host {self.host!r} is "
                    f"unresolvable: {exc}"
                ) from exc
            except (TransportError, OSError) as exc:
                if attempt >= self.retry_max:
                    raise ElementError(
                        f"{self.name}: cannot reach query server "
                        f"{self.host}:{self.port}"
                        + (f" after {attempt + 1} attempts" if attempt else "")
                        + f": {exc}"
                    ) from exc
                time.sleep(backoff_s(attempt, self._retry_policy, self._rng))
                attempt += 1

    def _start_fleet(self) -> None:
        """Fleet start: at least ONE endpoint must be reachable (the
        rest connect lazily on first dispatch/failover)."""
        from nnstreamer_tpu.pipeline.faults import backoff_s

        self.connect_type = _check_connect_type(self)
        attempt = 0
        while True:
            last_exc = None
            for ep in self._fleet.plan():
                try:
                    self._ep_transport(ep)
                    return
                except UnresolvableError as exc:
                    self._fleet.record_fail(ep, unresolvable=True)
                    last_exc = exc
                except (TransportError, OSError) as exc:
                    self._fleet.record_fail(ep)
                    last_exc = exc
            if attempt >= self.retry_max:
                addrs = ",".join(
                    e.addr for e in self._fleet.endpoints
                )
                raise ElementError(
                    f"{self.name}: no reachable endpoint in fleet "
                    f"[{addrs}]"
                    + (f": {last_exc}" if last_exc is not None else "")
                )
            time.sleep(max(
                backoff_s(attempt, self._retry_policy, self._rng),
                self._fleet.next_retry_in(),
            ))
            attempt += 1

    def _ep_transport(self, ep):
        """Get-or-connect the transport for one fleet endpoint. The
        connect timeout is clamped well under the request timeout so a
        blackholed endpoint cannot eat the whole deadline inside one
        connect; the hostname re-resolves on every (re)connect."""
        t = self._ep_transports.get(ep)
        if t is not None:
            return t
        if self.connect_type == "TCP":
            resolve_target(ep.host, ep.port)
        t = self._build_transport(
            connect_timeout=max(0.2, min(2.0, self.timeout / 2.0))
        )
        try:
            t.connect(ep.host, ep.port)
        except BaseException:
            t.close()
            raise
        self._ep_transports[ep] = t
        return t

    def _close_ep(self, ep) -> None:
        t = self._ep_transports.pop(ep, None)
        if t is not None:
            t.close()

    def stop(self) -> None:
        self._drop_connection()
        for ep in list(self._ep_transports):
            self._close_ep(ep)

    def _stamp_request(self, frame: Frame):
        """Correlation + SLO meta shared by the single-endpoint and
        fleet request paths."""
        fid = frame.meta.get("frame_id")
        if fid is None:
            fid = f"{self._fid_prefix}.{next(self._fid_seq)}"
            frame = frame.with_meta(frame_id=fid)
        if self.deadline_ms > 0 and "deadline_ms" not in frame.meta:
            frame = frame.with_meta(deadline_ms=self.deadline_ms)
        if self.priority is not None and "priority" not in frame.meta:
            frame = frame.with_meta(priority=self.priority)
        if self._router is not None and ROUTE_META_KEY not in frame.meta:
            keys = self._route_keys_of(frame)
            if keys:
                # _wire_meta keeps scalars only, so the key chain rides
                # flattened as one dot-joined hex string
                frame = frame.with_meta(**{ROUTE_META_KEY: ".".join(keys)})
        return frame, fid

    @staticmethod
    def _route_keys_of(frame: Frame) -> List[str]:
        """Rolling-CRC prefix keys of an LLM prompt frame — the first
        tensor when it is integer-typed (token ids); anything else
        (images, floats) routes by load alone."""
        if not frame.tensors:
            return []
        arr = np.asarray(frame.to_host().tensors[0])
        if not np.issubdtype(arr.dtype, np.integer):
            return []
        return prefix_route_keys(arr.ravel())

    def _finish_reply(self, msg, frame: Frame, fid, t_req: float):
        """Trace + metrics + reply normalization shared by both request
        paths."""
        rtt_s = time.perf_counter() - t_req
        if self._rtts is not None:
            self._rtts.record(rtt_s)  # feeds the adaptive hedge p99
        tracer = trace.get()
        if tracer is not None:
            # the client half of the cross-process pair: merge() lines
            # this span up with the server's frame_id-tagged spans
            tracer.complete(
                self.name, "edge", t_req, rtt_s, {"frame_id": fid}
            )
        reg = self._obs_reg
        if reg is not None:
            if self._rtt_hist is None:
                self._rtt_hist = reg.histogram(
                    "nns_edge_rtt_us", element=self.name
                )
            self._rtt_hist.observe(rtt_s * 1e6)
            reg.counter(
                "nns_edge_requests_total", element=self.name
            ).inc()
        reply = msg
        if isinstance(reply, EOS):
            return None
        if reply.meta.get("frame_id") is None:
            reply = reply.with_meta(frame_id=fid)
        return reply.with_pts(frame.pts, frame.duration)

    def process(self, frame: Frame) -> Optional[Frame]:
        from nnstreamer_tpu.pipeline.faults import backoff_s

        if self._fleet is not None:
            return self._process_fleet(frame)
        frame, fid = self._stamp_request(frame)
        data = encode_message(frame)
        t_req = time.perf_counter()
        attempt = 0
        while True:
            sent = False
            try:
                if self._transport is None:
                    # reconnect after a timeout-dropped/failed connection
                    self._connect_once()
                self._transport.send(0, data)
                sent = True
                got = self._transport.recv(timeout=self.timeout)
                if got is None:
                    # In a pipeline this error poisons the stream, matching
                    # the reference's GST_FLOW_ERROR on query timeout. For
                    # standalone (direct process()) callers who catch and
                    # continue, drop the connection first so a reply
                    # arriving *after* the timeout can't be returned for
                    # the NEXT frame (off-by-one desync); the next call
                    # reconnects. Timeouts do NOT ride the reconnect-retry
                    # loop: the server may have received the request, and
                    # a resend could double-process it.
                    self._drop_connection()
                    raise ElementError(
                        f"{self.name}: query timeout after {self.timeout}s"
                    )
                _, payload = got
                if not payload:
                    raise TransportError("server closed the connection")
                msg = decode_message(payload)
                if isinstance(msg, Nack):
                    # a NACK means the server did NOT process the request,
                    # so a resend cannot double-process: honor the
                    # retry-after hint on the existing retry budget.
                    # Reason "deadline" is terminal — the request WAS
                    # admitted and then shed; the budget it consumed is
                    # gone and the reply window with it.
                    self._count_nack(msg.reason)
                    if msg.reason == REASON_DEADLINE:
                        raise ElementError(
                            f"{self.name}: server shed the request "
                            f"(deadline {self.deadline_ms:.0f} ms missed)"
                        )
                    if msg.reason == REASON_FAILED:
                        # the server admitted AND processed the request,
                        # and its fault policy dropped it — a resend
                        # would re-run work that already failed
                        raise ElementError(
                            f"{self.name}: server failed the request "
                            "(dropped by its error policy)"
                        )
                    if attempt >= self.retry_max:
                        raise ElementError(
                            f"{self.name}: server rejected the request "
                            f"({msg.reason}) after {attempt + 1} attempt(s); "
                            f"retry-after hint {msg.retry_after_ms:.0f} ms"
                        )
                    delay = max(
                        msg.retry_after_ms / 1000.0,
                        backoff_s(attempt, self._retry_policy, self._rng),
                    )
                    attempt += 1
                    # reconnect for the retry: a conn-level reject (the
                    # max-clients accept path) NACKs then CLOSES, and a
                    # resend into that dead socket would buffer fine but
                    # fail at recv with sent=True — terminal, wasting
                    # the whole retry budget. The NACK guarantees the
                    # request was not processed, so reconnect+resend is
                    # always safe; the reconnect is wasted only on a
                    # still-healthy connection, and the retry-after
                    # sleep dwarfs the handshake.
                    self._drop_connection()
                    time.sleep(delay)
                    continue
                break
            except UnresolvableError as exc:
                # the satellite bugfix: a reconnect whose target no
                # longer RESOLVES is terminal with a distinct reason —
                # not retry-max spins against a gone name
                self._drop_connection()
                raise ElementError(
                    f"{self.name}: query server host {self.host!r} is "
                    f"unresolvable: {exc}"
                ) from exc
            except (TransportError, OSError) as exc:
                self._drop_connection()
                # the retry loop covers CONNECT/SEND failures only: once
                # the request went out, a lost connection is the timeout
                # case in different clothes — the server may have
                # processed it, and a resend could double-process (the
                # reconnected transport still serves the NEXT frame)
                if sent or attempt >= self.retry_max:
                    raise ElementError(
                        f"{self.name}: query transport failed"
                        + (f" after {attempt + 1} attempts" if attempt else "")
                        + f": {exc}"
                    ) from exc
                time.sleep(backoff_s(attempt, self._retry_policy, self._rng))
                attempt += 1
        return self._finish_reply(msg, frame, fid, t_req)

    # -- fleet request path (docs/edge-serving.md "Running a fleet") -------
    def _process_fleet(self, frame: Frame) -> Optional[Frame]:
        from nnstreamer_tpu.pipeline.faults import backoff_s

        frame, fid = self._stamp_request(frame)
        data = encode_message(frame)
        t_req = time.perf_counter()
        deadline = time.monotonic() + self.timeout
        hedger = HedgeTimer(self.hedge_after_ms, rtts=self._rtts)
        inflight: List = []   # [(endpoint, transport)] holding this request
        tried = set()         # endpoint idx already failed/NACKed this round
        sends = 0
        nack_attempt = 0      # retry budget for whole-fleet rejection rounds
        pending_hint_s = 0.0  # retry-after carried into the next round

        failed_eps = 0        # endpoints that failed/NACKed this request
        # prefix-aware routing: prefer the endpoint that last served the
        # longest recorded prefix of this prompt — advisory only, the
        # health/draining plan still decides who is sendable at all
        route_keys: List[str] = []
        pref_addr = None
        if self._router is not None:
            pfx = frame.meta.get(ROUTE_META_KEY)
            route_keys = str(pfx).split(".") if pfx else []
            best = self._router.best(route_keys) if route_keys else None
            if best is not None:
                pref_addr = best[0]

        def _send_next(is_hedge: bool = False):
            """Send this request to the next endpoint the plan allows;
            returns (sent, last_exc). Counts a failover whenever the
            request lands on an endpoint after another one failed it —
            whether the first failure happened at send time (dead
            socket, unresolvable) or after the request was in flight."""
            nonlocal sends, failed_eps
            last_exc = None
            plan = self._fleet.plan()
            if pref_addr is not None:
                # stable: non-preferred endpoints keep the plan's order
                plan.sort(key=lambda e: e.addr != pref_addr)
            for ep in plan:
                if ep.idx in tried or any(e is ep for e, _t in inflight):
                    continue
                try:
                    tr = self._ep_transport(ep)
                    tr.send(0, data)
                except UnresolvableError as exc:
                    self._fleet.record_fail(ep, unresolvable=True)
                    self._close_ep(ep)
                    tried.add(ep.idx)
                    ep.failovers += 1
                    failed_eps += 1
                    last_exc = exc
                    continue
                except (TransportError, OSError) as exc:
                    self._fleet.record_fail(ep)
                    self._close_ep(ep)
                    tried.add(ep.idx)
                    ep.failovers += 1
                    failed_eps += 1
                    last_exc = exc
                    continue
                ep.inflight += 1
                inflight.append((ep, tr))
                sends += 1
                if is_hedge:
                    self._count_hedge()
                elif failed_eps:
                    self._count_failover()
                if pref_addr is not None and ep.addr == pref_addr \
                        and not is_hedge:
                    self._count_prefix_hit()
                return True, None
            return False, last_exc

        def _drop_inflight(i: int, failed: bool) -> None:
            nonlocal failed_eps
            ep, _tr = inflight.pop(i)
            ep.inflight = max(0, ep.inflight - 1)
            if failed:
                ep.failovers += 1
                failed_eps += 1
                tried.add(ep.idx)

        while True:
            now = time.monotonic()
            if now >= deadline:
                # straggler timeout: every endpoint still holding the
                # request takes a health hit, but the connections stay —
                # the frame_id dedup drops their late replies, so the
                # NEXT request cannot be answered off-by-one
                for ep, _tr in inflight:
                    ep.inflight = max(0, ep.inflight - 1)
                    self._fleet.record_fail(ep)
                self._dedup.claim(fid)  # a late reply must never deliver
                raise ElementError(
                    f"{self.name}: query timeout after {self.timeout}s"
                )
            if not inflight:
                sent, last_exc = _send_next()
                if not sent:
                    if nack_attempt >= self.retry_max:
                        raise ElementError(
                            f"{self.name}: no fleet endpoint accepted the "
                            f"request after {nack_attempt + 1} round(s)"
                            + (f": {last_exc}" if last_exc else "")
                        )
                    delay = max(
                        pending_hint_s,
                        backoff_s(nack_attempt, self._retry_policy,
                                  self._rng),
                        self._fleet.next_retry_in(),
                    )
                    nack_attempt += 1
                    pending_hint_s = 0.0
                    time.sleep(min(delay, max(0.001, deadline - now)))
                    tried.clear()  # a fresh round may retry everyone —
                    failed_eps = 0  # and a same-endpoint resend after a
                    #                 whole-fleet-refused round is a
                    #                 RETRY, not a failover
                    continue
                if sends == 1:
                    hedger.arm()
            # wait for a reply on the in-flight transports; with a
            # hedge outstanding, round-robin short polls keep both live
            got = None
            src = 0
            if len(inflight) == 1:
                ep, tr = inflight[0]
                slice_s = min(0.02, max(0.001, deadline - now))
                try:
                    got = tr.recv(timeout=slice_s)
                except (TransportError, OSError):
                    got = (0, b"")
            else:
                for i, (ep, tr) in enumerate(inflight):
                    try:
                        got = tr.recv(timeout=0.005)
                    except (TransportError, OSError):
                        got = (0, b"")
                    if got is not None:
                        src = i
                        break
            if got is None:
                if hedger.due():
                    hedger.fire()  # one hedge per request, sent or not
                    _send_next(is_hedge=True)
                continue
            ep, tr = inflight[src]
            _cid, payload = got
            if not payload:
                # connection died under the request: fail over
                self._fleet.record_fail(ep)
                self._close_ep(ep)
                _drop_inflight(src, failed=True)
                continue
            try:
                msg = decode_message(payload)
            except ValueError:
                continue  # garbage on the reply path: ignore, keep waiting
            if isinstance(msg, Ctrl):
                continue  # control messages are client→server only
            if isinstance(msg, Nack):
                nfid = msg.frame_id
                if nfid is not None and nfid != fid:
                    self.stale_replies += 1
                    continue  # a NACK for an already-terminal request
                self._count_nack(msg.reason)
                if msg.reason == REASON_DRAINING:
                    # rolling restart: bench for exactly the hint and
                    # re-route — the request was NOT processed
                    self._fleet.mark_draining(ep, msg.retry_after_ms)
                    pending_hint_s = max(
                        pending_hint_s, msg.retry_after_ms / 1000.0
                    )
                    _drop_inflight(src, failed=True)
                    continue
                if msg.reason in (REASON_DEADLINE, REASON_FAILED):
                    # terminal verdicts — but a hedge may still win
                    _drop_inflight(src, failed=True)
                    if inflight:
                        continue
                    if msg.reason == REASON_DEADLINE:
                        raise ElementError(
                            f"{self.name}: server shed the request "
                            f"(deadline {self.deadline_ms:.0f} ms missed)"
                        )
                    raise ElementError(
                        f"{self.name}: server failed the request "
                        "(dropped by its error policy)"
                    )
                # retryable admission NACK (overload / rate / max-clients
                # / client-backpressure / malformed): the natural fleet
                # response is failover; the conn-level reject path also
                # CLOSES, so drop the transport before moving on
                pending_hint_s = max(
                    pending_hint_s, msg.retry_after_ms / 1000.0
                )
                self._close_ep(ep)
                _drop_inflight(src, failed=True)
                continue
            # DATA (or EOS) reply
            rfid = getattr(msg, "meta", {}).get(
                "frame_id"
            ) if not isinstance(msg, EOS) else None
            if rfid is not None and rfid != fid:
                # a late reply to an ALREADY-terminal request (timeout/
                # failover winner already delivered): at-most-once means
                # it is dropped here, never pushed downstream
                if self._dedup.seen(rfid):
                    self._dedup.claim(rfid)  # count the duplicate
                else:
                    self.stale_replies += 1
                continue
            if not self._dedup.claim(fid):
                continue  # hedge loser: the first reply already won
            for e, _t in inflight:
                e.inflight = max(0, e.inflight - 1)
            self._fleet.record_ok(ep)
            if self._router is not None and route_keys:
                # the answering endpoint now holds this prompt's KV
                # prefix — future repeat-prefix requests prefer it
                self._router.note(route_keys, ep.addr)
            return self._finish_reply(msg, frame, fid, t_req)

    def _count_failover(self) -> None:
        self.fleet_failovers += 1
        reg = self._obs_reg
        if reg is None:
            return
        if self._failover_ctr is None:
            self._failover_ctr = reg.counter(
                "nns_fleet_failovers_total", element=self.name
            )
        self._failover_ctr.inc()

    def _count_hedge(self) -> None:
        self.fleet_hedges += 1
        reg = self._obs_reg
        if reg is None:
            return
        if self._hedge_ctr is None:
            self._hedge_ctr = reg.counter(
                "nns_fleet_hedges_total", element=self.name
            )
        self._hedge_ctr.inc()

    def _count_prefix_hit(self) -> None:
        self._router.prefix_hits += 1
        reg = self._obs_reg
        if reg is None:
            return
        if self._pfx_hit_ctr is None:
            self._pfx_hit_ctr = reg.counter(
                "nns_route_prefix_hits_total", element=self.name
            )
        self._pfx_hit_ctr.inc()

    def fleet_stats(self) -> Dict[str, object]:
        """Executor.stats() hook (``fleet_*`` keys; nns-top --fleet)."""
        if self._fleet is None:
            return {}
        out = {
            "endpoints": self._fleet.snapshot(),
            "healthy": self._fleet.healthy_count(),
            "failovers": self.fleet_failovers,
            "hedges": self.fleet_hedges,
            "duplicate_replies": self._dedup.duplicates,
            "stale_replies": self.stale_replies,
        }
        if self._router is not None:
            out["prefix_hits"] = self._router.prefix_hits
            out["prefix_index"] = len(self._router)
        return out

    def _count_nack(self, reason: str) -> None:
        reg = self._obs_reg
        if reg is None:
            return
        ctr = self._nack_ctrs.get(reason)
        if ctr is None:
            ctr = self._nack_ctrs[reason] = reg.counter(
                "nns_edge_nacks_total", element=self.name, reason=reason
            )
        ctr.inc()


@registry.element("tensor_query_serversrc")
class TensorQueryServerSrc(Source):
    """Emit incoming query requests, tagged with client_id meta.

    Props: host (default 127.0.0.1), port (0 = ephemeral; read back via
    ``bound_port``), id (pairing key, default "0"),
    connect-type=TCP|MQTT|HYBRID|SHM, topic (MQTT/HYBRID), data-host/
    data-port (HYBRID TCP data plane, default ephemeral loopback).

    Admission control (docs/edge-serving.md): ``max-clients``,
    ``max-inflight``, ``per-client-inflight``, ``rate``/``rate-burst``
    bound what the server accepts — excess connections and requests get
    an explicit structured NACK (reason + ``retry-after-ms`` hint) on
    the wire instead of queueing forever. Admitted requests are served
    weighted-fair: strict priority classes (the client's ``priority``
    meta, lower = more urgent), round-robin across clients within a
    class, so one hot client cannot starve the rest. Every admitted
    frame is stamped with its admission time so the executor's
    deadline-aware shedder can drop SLO-missed frames before they
    consume device time. A serversrc with NO bound set keeps the legacy
    unbounded behavior (nns-lint NNS-W111 warns).
    """

    FACTORY_NAME = "tensor_query_serversrc"

    PROPERTIES = {
        "host": PropSpec("str", "127.0.0.1"),
        "port": PropSpec("int", 0, desc="0 = ephemeral"),
        "id": PropSpec("str", "0", desc="pairing key with serversink"),
        "connect-type": PropSpec("enum", "TCP", CONNECT_TYPES),
        "topic": PropSpec("str", "nns-query"),
        "data-host": PropSpec("str", "127.0.0.1", desc="HYBRID data plane"),
        "data-port": PropSpec("int", 0, desc="HYBRID data plane"),
        "max-clients": PropSpec(
            "int", 0, desc="admission: concurrent client cap (0 = none)"
        ),
        "max-inflight": PropSpec(
            "int", 0,
            desc="admission: global in-flight request cap (0 = none)",
        ),
        "per-client-inflight": PropSpec(
            "int", 0,
            desc="admission: per-client in-flight cap (0 = none)",
        ),
        "rate": PropSpec(
            "float", 0.0,
            desc="admission: global token-bucket rate, requests/s "
            "(0 = none)",
        ),
        "rate-burst": PropSpec(
            "int", 0, desc="token-bucket depth (0 = max(1, rate))"
        ),
        "retry-after-ms": PropSpec(
            "float", 50.0, desc="base retry-after hint carried by NACKs"
        ),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.host = str(self.get_property("host", "127.0.0.1"))
        self.port = int(self.get_property("port", 0))
        self.srv_id = str(self.get_property("id", "0"))
        self.topic = str(self.get_property("topic", "nns-query"))
        # HYBRID: host/port address the broker; the TCP data plane binds
        # data-host:data-port (default ephemeral on loopback)
        self.data_host = str(self.get_property("data-host", "127.0.0.1"))
        self.data_port = int(self.get_property("data-port", 0))
        self.connect_type = "TCP"
        self.bound_port: Optional[int] = None
        self._transport = None
        self._adm_cfg = AdmissionConfig.from_element(self)
        self._controller: Optional[AdmissionController] = None
        self.malformed_total = 0  # undecodable requests NACKed
        # readiness flag (docs/edge-serving.md "Running a fleet"):
        # ready / draining / dead — exposed via admission_stats() on the
        # obs endpoint; fleet clients learn "draining" from the NACKs
        self.state = SRV_DEAD
        self.drain_nacked = 0  # new submits NACKed while draining

    def output_spec(self) -> Spec:
        self.connect_type = _check_connect_type(self)
        return TensorsSpec(format=TensorFormat.FLEXIBLE)

    def start(self) -> None:
        self.connect_type = _check_connect_type(self)
        if self._adm_cfg.active:
            self._controller = AdmissionController(
                self._adm_cfg, name=self.name
            )
        self._transport = _make_server_transport(
            self.connect_type, self.topic, self.data_host, self.data_port,
            max_conns=self._adm_cfg.max_clients,
            retry_after_ms=self._adm_cfg.retry_after_ms,
        )
        self.bound_port = self._transport.listen(self.host, self.port)
        self.state = SRV_READY
        _register_server(self.srv_id, self._transport, self._controller)

    def stop(self) -> None:
        self.state = SRV_DEAD
        _unregister_server(self.srv_id, self._transport)
        if self._transport is not None:
            self._transport.close()
            self._transport = None

    # -- graceful drain (docs/edge-serving.md "Running a fleet") -----------
    def drain(self, flush_queued: bool = False) -> None:
        """Stop accepting new work: from now on new submits are NACKed
        with the terminal-after-retry reason ``draining`` (+ the
        ``retry-after-ms`` hint), while already-admitted requests keep
        flowing to their replies (or dead-letter) through the normal
        PR-6 budget-release path. ``flush_queued=True`` additionally
        NACKs the queued-but-unserved admitted backlog so those requests
        re-route NOW instead of waiting out this server. The rolling-
        restart recipe: ``drain()`` → wait for :meth:`drained` →
        ``Executor.drain()`` (quiesce the graph) → stop/restart — zero
        accepted requests lost. Also reachable over the wire via the
        ``drain`` control message (:func:`request_drain`)."""
        self.state = SRV_DRAINING
        _set_server_state(self.srv_id, SRV_DRAINING)
        if flush_queued and self._controller is not None:
            from nnstreamer_tpu.pipeline.faults import notify_drain_flush

            for frame in self._controller.flush_ready():
                notify_drain_flush(frame, self.name)

    def drained(self) -> bool:
        """True once drain() was called and no admitted request remains
        in flight (every accepted request reached its terminal
        outcome)."""
        if self.state != SRV_DRAINING:
            return False
        if self._controller is None:
            return True
        return self._controller.snapshot()["inflight"] == 0

    def _nack_draining(self, cid, frame_id=None) -> None:
        self.drain_nacked += 1
        if self._controller is not None:
            self._controller.count_reject(REASON_DRAINING)
        self._send_nack(
            cid, REASON_DRAINING, self._adm_cfg.retry_after_ms,
            frame_id=frame_id,
        )

    def _trace_in(self, frame, cid) -> None:
        tracer = trace.get()
        if tracer is not None:
            tracer.instant(
                self.name, cat="edge",
                frame_id=frame.meta.get("frame_id"), client_id=cid,
            )

    def _stamp(self, frame, cid):
        """Admission meta: client_id routes the reply, admit_t anchors
        the deadline shedder, _nns_srv lets the shedding node find this
        server to NACK — the latter two are local-only keys that never
        ride the wire (serialize._WIRE_META_SKIP)."""
        return frame.with_meta(
            client_id=cid, admit_t=time.monotonic(), _nns_srv=self.srv_id
        )

    def _send_nack(self, cid, reason: str, retry_after_ms: float,
                   frame_id=None) -> None:
        try:
            self._transport.send(
                cid, encode_nack(reason, retry_after_ms, frame_id=frame_id)
            )
        except (TransportError, OSError):
            pass  # the client vanished; nothing to tell

    def _handle_ctrl(self, cid, msg) -> None:
        """Operator/fleet control ops: ``drain``, and the migration
        handshake routed to the LLM server registered for the
        requested ``llm_id`` (docs/llm-serving.md). Every migrate op
        gets an explicit reply — the sender decides fallback on it."""
        if msg.op == "drain":
            self.drain()
            return
        if msg.op not in ("migrate_probe", "migrate_span", "disagg_fetch"):
            return  # unknown ctrl: ignore (both ends live in-tree)
        # spans must not LAND on a draining endpoint — but disagg_fetch
        # moves finished results OUT, and a draining decode server only
        # quiesces once its parked handoffs are collected
        if self.state == SRV_DRAINING and msg.op != "disagg_fetch":
            reply = encode_ctrl(
                "migrate_nack", reason="draining",
                retry_after_ms=float(self._adm_cfg.retry_after_ms),
            )
        else:
            handler = _get_migration_handler(
                int(msg.meta.get("llm_id", 0) or 0)
            )
            if handler is None:
                reply = encode_ctrl(
                    "migrate_nack", reason="no-migration-handler"
                )
            else:
                try:
                    if msg.op == "migrate_probe":
                        n = handler.migration_probe(
                            msg.meta.get("tokens", [])
                        )
                        # decode-role servers piggyback their pool
                        # headroom advert on the probe ack — one
                        # roundtrip answers "how warm AND how full"
                        advert = getattr(handler, "migration_advert",
                                         None)
                        extra = dict(advert()) if advert else {}
                        extra["shared_tokens"] = int(n)
                        reply = encode_ctrl("migrate_probe_ack", **extra)
                    elif msg.op == "disagg_fetch":
                        fetch = getattr(handler, "disagg_fetch", None)
                        if fetch is None:
                            reply = encode_ctrl(
                                "migrate_nack", reason="no-disagg-role"
                            )
                        else:
                            toks = fetch(int(msg.meta.get("rid", -1)))
                            if toks is None:
                                reply = encode_ctrl(
                                    "disagg_fetch_ack", done=0
                                )
                            else:
                                reply = encode_ctrl(
                                    "disagg_fetch_ack", done=1,
                                    tokens=[int(t) for t in toks],
                                )
                    else:
                        rid = handler.migration_adopt(msg.payload)
                        reply = encode_ctrl(
                            "migrate_span_ack", rid=int(rid)
                        )
                except Exception as exc:  # span taxonomy → wire reason
                    # capacity refusals are retryable, not fatal: NACK
                    # with the admission retry hint instead of letting
                    # the pool error crash the serversrc service loop
                    from nnstreamer_tpu.kv.blocks import PoolCapacityError
                    from nnstreamer_tpu.kv.migrate import SpanCapacityError
                    extra = {}
                    if isinstance(exc, (PoolCapacityError,
                                        SpanCapacityError)):
                        extra["retry_after_ms"] = float(
                            self._adm_cfg.retry_after_ms
                        )
                    reply = encode_ctrl(
                        "migrate_nack",
                        reason=f"{type(exc).__name__}: {exc}",
                        **extra,
                    )
        try:
            self._transport.send(cid, reply)
        except (TransportError, OSError):
            pass  # the migrating peer vanished; it will fall back

    def _handle_incoming(self, cid, payload) -> None:
        """Admission at arrival: decode, admit or NACK, queue."""
        ctrl = self._controller
        if not payload:
            ctrl.client_gone(cid)
            return
        try:
            msg = decode_message(payload)
        except ValueError:
            self.malformed_total += 1
            ctrl.count_reject(REASON_MALFORMED)
            self._send_nack(cid, REASON_MALFORMED, 0.0)
            return
        if isinstance(msg, Ctrl):
            self._handle_ctrl(cid, msg)
            return
        if isinstance(msg, (EOS, Nack)):
            return  # one client's EOS must not stop the server
        if self.state == SRV_DRAINING:
            # graceful drain: new work is refused with an explicit
            # reason + hint so fleet clients re-route immediately
            self._nack_draining(cid, frame_id=msg.meta.get("frame_id"))
            return
        frame = self._stamp(msg, cid)
        decision = ctrl.offer(cid, frame)
        if not decision.ok:
            self._send_nack(
                cid, decision.reason, decision.retry_after_ms,
                frame_id=frame.meta.get("frame_id"),
            )

    def generate(self):
        ctrl = self._controller
        if ctrl is None:
            # unbounded legacy path (nns-lint NNS-W111 warns): still
            # stamps admission meta so deadline shedding works
            got = self._transport.recv(timeout=0.1)
            if got is None:
                return None  # re-poll; executor loops until EOS/stop
            cid, payload = got
            if not payload:
                return None  # client disconnect; keep serving others
            try:
                frame = decode_message(payload)
            except ValueError:
                # one client's garbage must not crash the server for
                # everyone: same structured NACK as the admission path
                self.malformed_total += 1
                self._send_nack(cid, REASON_MALFORMED, 0.0)
                return None
            if isinstance(frame, Ctrl):
                self._handle_ctrl(cid, frame)
                return None
            if isinstance(frame, EOS):
                return None
            if isinstance(frame, Nack):
                return None  # NACKs are server→client only; ignore
            if self.state == SRV_DRAINING:
                self._nack_draining(
                    cid, frame_id=frame.meta.get("frame_id")
                )
                return None
            self._trace_in(frame, cid)
            return self._stamp(frame, cid)
        # drain everything that arrived (admitting or NACKing each),
        # then serve ONE request picked weighted-fair across clients
        got = self._transport.recv(
            timeout=0.0 if ctrl.has_ready() else 0.1
        )
        while got is not None:
            self._handle_incoming(*got)
            got = self._transport.recv(timeout=0.0)
        frame = ctrl.next_ready()
        if frame is None:
            return None
        self._trace_in(frame, frame.meta.get("client_id"))
        return frame

    def admission_stats(self) -> Dict[str, object]:
        """Executor.stats() hook (``adm_*`` keys; nns-top --clients).
        ``readiness`` is the drain/rolling-restart flag the obs endpoint
        exposes (ready / draining / dead)."""
        ctrl = self._controller
        out: Dict[str, object] = {"readiness": self.state}
        if ctrl is not None:
            out.update(ctrl.snapshot())
        if self.drain_nacked:
            out["drain_nacked"] = self.drain_nacked
        if self.malformed_total:
            out["malformed"] = self.malformed_total
        t = self._transport
        rejected_conns = getattr(t, "rejected_conns", 0) if t else 0
        if rejected_conns:
            out["rejected_conns"] = rejected_conns
        return out


@registry.element("tensor_query_serversink")
class TensorQueryServerSink(Sink):
    """Send results back to the requesting client (by client_id meta).

    Props: id (pairing key matching the serversrc, default "0").

    Overload resilience: a reply whose client vanished is counted
    (``reply_failed``) and skipped, never fatal — one dead client must
    not poison the serving pipeline for everyone else. Each rendered (or
    failed) reply releases the request's admission budget.
    """

    FACTORY_NAME = "tensor_query_serversink"

    PROPERTIES = {
        "id": PropSpec("str", "0", desc="pairing key with serversrc"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.srv_id = str(self.get_property("id", "0"))
        self.reply_failed = 0  # replies to vanished clients (skipped)

    def render(self, frame: Frame) -> None:
        transport = _get_server(self.srv_id)
        if transport is None:
            raise ElementError(
                f"{self.name}: no tensor_query_serversrc with id={self.srv_id}"
            )
        cid = frame.meta.get("client_id")
        if cid is None:
            raise ElementError(
                f"{self.name}: frame lacks client_id meta (did it pass "
                "through tensor_query_serversrc?)"
            )
        tracer = trace.get()
        if tracer is not None:
            tracer.instant(
                self.name, cat="edge",
                frame_id=frame.meta.get("frame_id"), client_id=cid,
            )
        try:
            transport.send(cid, encode_message(frame))
        except (TransportError, OSError):
            self.reply_failed += 1
        finally:
            # dead-lettered frames already released their budget at the
            # fault-gate disposal (faults.py route path) — releasing
            # again here would silently loosen the admission caps
            if not frame.meta.get("_nns_budget_released"):
                ctrl = _get_controller(self.srv_id)
                if ctrl is not None:
                    ctrl.release(cid)
