"""Admission control for the multi-tenant query serving plane.

ROADMAP item 5: one ``tensor_query_client`` reconnecting politely is not
a fleet. Before this module the server side accepted unbounded clients
and queued every request forever, so overload manifested as silent
latency collapse. The :class:`AdmissionController` makes the serving
plane say *no* early, fairly, and observably (docs/edge-serving.md):

- **bounded budgets** — a global in-flight cap (queued + executing
  requests), a per-client in-flight cap (per-client backpressure: one
  pipelining client cannot monopolize the server), and a client-count
  cap (``max-clients``).
- **token-bucket rate limiting** — a global requests/second bound with a
  configurable burst; rejects carry a ``retry-after`` hint computed from
  the bucket's actual refill deficit, so well-behaved clients back off
  by exactly as much as needed instead of guessing.
- **priority classes + weighted-fair dequeue** — each request carries an
  integer priority class (lower = more urgent, stamped by the client's
  ``priority`` property); the scheduler drains strictly by class and
  round-robins *clients* inside a class, so one hot client saturating
  its queue cannot starve the others (fair share at equal weights).
- **explicit structured NACKs** — every rejection is a typed wire
  message (edge/serialize.py ``KIND_NACK``) carrying the reason and the
  retry-after hint, never a hang.

Deadline shedding is the executor's half (pipeline/executor.py
``Node.shed_if_expired`` / pipeline/faults.py helpers): requests carry a
client SLO (``deadline_ms`` meta) and an admission timestamp
(``admit_t``, local-only), and nodes drop frames that can no longer meet
the SLO *before* they consume device time, NACKing the client so the
request still reaches a terminal outcome.

Single-writer-ish discipline: ``offer``/``next_ready`` run on the
serversrc's source thread, ``release`` on the serversink's sink thread —
the shared counters and queues are guarded by one short-hold lock (no
blocking calls under it, per the nns-san race rules).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, NamedTuple, Optional

from nnstreamer_tpu.obs import metrics as obs_metrics

#: NACK reasons the controller (and the serving layer) can emit; the
#: wire carries the string, clients and dashboards match on it.
REASON_MAX_CLIENTS = "max-clients"
REASON_OVERLOAD = "overload"            # global in-flight budget exhausted
REASON_CLIENT_BACKPRESSURE = "client-backpressure"  # per-client budget
REASON_RATE = "rate"                    # token bucket empty
REASON_MALFORMED = "malformed"          # undecodable request
REASON_DEADLINE = "deadline"            # shed: SLO already missed
REASON_FAILED = "failed"                # admitted, then dropped by a fault policy
REASON_DRAINING = "draining"            # graceful drain: retry ANOTHER endpoint


class Decision(NamedTuple):
    """Outcome of one admission check; ``retry_after_ms`` is the hint a
    NACK carries back to the client (0 = retry immediately/never)."""

    ok: bool
    reason: str = ""
    retry_after_ms: float = 0.0


ACCEPT = Decision(True)


@dataclass(frozen=True)
class AdmissionConfig:
    """Resolved admission knobs for one query server (0 = unbounded)."""

    max_clients: int = 0
    max_inflight: int = 0
    per_client_inflight: int = 0
    rate: float = 0.0          # requests/second, global token bucket
    burst: int = 0             # bucket depth (0 → max(1, ceil(rate)))
    retry_after_ms: float = 50.0  # base hint for budget NACKs
    # idle-slot reclamation for transports without disconnect events
    # (MQTT, SHM): a fully-idle client silent this long may be evicted
    # when the max-clients cap is hit
    idle_evict_s: float = 60.0

    @property
    def active(self) -> bool:
        return bool(
            self.max_clients or self.max_inflight
            or self.per_client_inflight or self.rate
        )

    @classmethod
    def from_element(cls, elem) -> "AdmissionConfig":
        def _num(key: str, cast, fallback):
            raw = elem.get_property(key)
            if raw is None:
                return fallback
            try:
                return cast(raw)
            except (TypeError, ValueError) as exc:
                raise ValueError(
                    f"{elem.name}: bad {key}={raw!r}: {exc}"
                ) from exc

        return cls(
            max_clients=max(0, _num("max-clients", int, 0)),
            max_inflight=max(0, _num("max-inflight", int, 0)),
            per_client_inflight=max(0, _num("per-client-inflight", int, 0)),
            rate=max(0.0, _num("rate", float, 0.0)),
            burst=max(0, _num("rate-burst", int, 0)),
            retry_after_ms=max(0.0, _num("retry-after-ms", float, 50.0)),
        )


class _Client:
    """Per-client admission state (guarded by the controller lock; the
    counter fields are read lock-free by snapshots — GIL-atomic)."""

    __slots__ = ("cid", "queues", "inflight", "admitted", "rejected",
                 "depth_gauge", "last_seen")

    def __init__(self, cid, now: float = 0.0) -> None:
        self.cid = cid
        # priority class -> FIFO of admitted-but-not-yet-served frames
        self.queues: Dict[int, deque] = {}
        self.inflight = 0    # admitted (queued + executing) until release
        self.admitted = 0
        self.rejected = 0
        self.depth_gauge = None  # nns_client_queue_depth handle (lazy)
        self.last_seen = now     # idle-eviction clock (offer/release)

    def queued(self) -> int:
        return sum(len(q) for q in self.queues.values())


class AdmissionController:
    """Server-side admission + weighted-fair request scheduling.

    ``offer(cid, frame)`` admits or rejects one decoded request at
    arrival; admitted frames are queued per (client, priority class).
    ``next_ready()`` is the weighted-fair dequeue the serversrc drives:
    strict priority across classes, round-robin across clients within a
    class. ``release(cid)`` returns one unit of in-flight budget (reply
    sent, or the frame was shed/dead-lettered)."""

    def __init__(self, cfg: AdmissionConfig, name: str = "admission") -> None:
        self.cfg = cfg
        self.name = name
        self._mu = threading.Lock()
        self._clients: Dict[Any, _Client] = {}
        self._inflight_total = 0
        self._ready = 0          # queued frames across all clients
        # round-robin cursor per priority class: the cid served last
        self._rr_last: Dict[int, Any] = {}
        # token bucket (rate > 0): starts full; the clock anchors on the
        # first offer's `now` so tests can inject a deterministic clock
        self._tokens = float(self.cfg.burst or max(1, int(cfg.rate) or 1))
        self._bucket_cap = self._tokens
        self._bucket_t: Optional[float] = None
        # totals (single-writer under _mu; GIL-atomic reads)
        self.admitted_total = 0
        self.rejected_total = 0
        self.released_total = 0
        self.rejected_by_reason: Dict[str, int] = {}
        # registry resolved ONCE at construction (the executor
        # discipline: obs_metrics.get() probes env+config on the None
        # path and must stay off the per-request path)
        self._reg = obs_metrics.get()
        self._reject_ctrs: Dict[str, Any] = {}

    # -- admission ---------------------------------------------------------
    def _refill(self, now: float) -> None:
        """Token-bucket refill (call with ``_mu`` held, rate > 0)."""
        if self._bucket_t is None:
            self._bucket_t = now
            return
        dt = now - self._bucket_t
        if dt > 0:
            self._tokens = min(
                self._bucket_cap, self._tokens + dt * self.cfg.rate
            )
            self._bucket_t = now

    def offer(self, cid, frame, now: Optional[float] = None) -> Decision:
        """Admit (and queue) or reject one request from client ``cid``."""
        cfg = self.cfg
        if now is None:
            now = time.monotonic()
        with self._mu:
            c = self._clients.get(cid)
            if c is None:
                if cfg.max_clients and len(self._clients) >= cfg.max_clients:
                    # transports without disconnect events (MQTT, SHM)
                    # never call client_gone: reclaim the stalest
                    # fully-idle slot before rejecting
                    self._evict_idle(now)
                if cfg.max_clients and len(self._clients) >= cfg.max_clients:
                    return self._reject(None, REASON_MAX_CLIENTS,
                                        cfg.retry_after_ms)
                c = self._clients[cid] = _Client(cid, now)
            c.last_seen = now
            if cfg.max_inflight and self._inflight_total >= cfg.max_inflight:
                return self._reject(c, REASON_OVERLOAD, cfg.retry_after_ms)
            if cfg.per_client_inflight \
                    and c.inflight >= cfg.per_client_inflight:
                return self._reject(c, REASON_CLIENT_BACKPRESSURE,
                                    cfg.retry_after_ms)
            if cfg.rate:
                self._refill(now)
                if self._tokens < 1.0:
                    hint = (1.0 - self._tokens) / cfg.rate * 1000.0
                    return self._reject(c, REASON_RATE,
                                        max(hint, cfg.retry_after_ms))
                self._tokens -= 1.0
            tier = self._tier(frame)
            c.queues.setdefault(tier, deque()).append(frame)
            c.inflight += 1
            c.admitted += 1
            self._inflight_total += 1
            self._ready += 1
            self.admitted_total += 1
            depth = c.queued()
        self._gauge_depth(c, depth)
        return ACCEPT

    @staticmethod
    def _tier(frame) -> int:
        meta = getattr(frame, "meta", None) or {}
        try:
            return int(meta.get("priority", 1))
        except (TypeError, ValueError):
            return 1

    def _reject(self, c: Optional[_Client], reason: str,
                retry_after_ms: float) -> Decision:
        """Record one rejection (call with ``_mu`` held)."""
        self.rejected_total += 1
        self.rejected_by_reason[reason] = (
            self.rejected_by_reason.get(reason, 0) + 1
        )
        if c is not None:
            c.rejected += 1
        ctr = self._reject_ctrs.get(reason)
        if ctr is None and self._reg is not None:
            ctr = self._reject_ctrs[reason] = self._reg.counter(
                "nns_admission_rejects_total",
                element=self.name, reason=reason,
            )
        if ctr is not None:
            ctr.inc()
        return Decision(False, reason, retry_after_ms)

    def count_reject(self, reason: str) -> None:
        """Record a rejection decided OUTSIDE the controller (transport
        connection caps, malformed payloads) so the per-reason totals
        and metrics stay one ledger."""
        with self._mu:
            self._reject(None, reason, 0.0)

    # -- scheduling --------------------------------------------------------
    def has_ready(self) -> bool:
        return self._ready > 0

    def next_ready(self):
        """Weighted-fair pick: strict priority across classes, round-
        robin across clients within a class. Returns a frame (still
        counted in-flight until ``release``) or None."""
        with self._mu:
            if not self._ready:
                return None
            tiers = sorted({
                t for c in self._clients.values()
                for t, q in c.queues.items() if q
            })
            for tier in tiers:
                cids = [
                    cid for cid, c in self._clients.items()
                    if c.queues.get(tier)
                ]
                if not cids:
                    continue
                last = self._rr_last.get(tier)
                if last in cids:
                    i = (cids.index(last) + 1) % len(cids)
                    cids = cids[i:] + cids[:i]
                cid = cids[0]
                c = self._clients[cid]
                frame = c.queues[tier].popleft()
                self._rr_last[tier] = cid
                self._ready -= 1
                depth = c.queued()
                break
            else:  # pragma: no cover - _ready tracked with the queues
                return None
        self._gauge_depth(c, depth)
        return frame

    def flush_ready(self):
        """Graceful-drain flush (docs/edge-serving.md "Running a
        fleet"): pop every queued-but-unserved admitted frame so the
        caller can NACK it ``draining`` — queued requests re-route to
        another endpoint instead of waiting out a dying server. The
        frames stay counted in-flight until the caller's
        ``release(cid)`` (the PR-6 budget-release path), so the
        accounting ledger never skips a state."""
        with self._mu:
            out = []
            for c in self._clients.values():
                for q in c.queues.values():
                    while q:
                        out.append(q.popleft())
            self._ready = 0
        for frame in out:
            cid = getattr(frame, "meta", {}).get("client_id")
            c = self._clients.get(cid)
            if c is not None:
                self._gauge_depth(c, 0)
        return out

    def _evict_idle(self, now: float) -> None:
        """Reclaim clients with nothing queued or in flight that have
        been silent for ``idle_evict_s`` (call with ``_mu`` held)."""
        horizon = now - self.cfg.idle_evict_s
        for cid in [
            cid for cid, c in self._clients.items()
            if not c.inflight and not c.queued() and c.last_seen <= horizon
        ]:
            del self._clients[cid]

    # -- completion --------------------------------------------------------
    def release(self, cid) -> None:
        """One admitted request reached a terminal outcome (reply sent,
        NACKed after shedding, or dead-lettered): return its budget."""
        with self._mu:
            c = self._clients.get(cid)
            if c is None or c.inflight <= 0:
                return  # duplicate release (shed + late reply): idempotent
            c.inflight -= 1
            self._inflight_total -= 1
            self.released_total += 1

    def client_gone(self, cid) -> None:
        """Connection closed: flush the client's queued requests (their
        replies have nowhere to go) and free its budget and slot."""
        with self._mu:
            c = self._clients.pop(cid, None)
            if c is None:
                return
            queued = c.queued()
            self._ready -= queued
            self._inflight_total -= c.inflight
        self._gauge_depth(c, 0)

    # -- observability -----------------------------------------------------
    def _gauge_depth(self, c: _Client, depth: int) -> None:
        reg = self._reg
        if reg is None:
            return
        if c.depth_gauge is None:
            c.depth_gauge = reg.gauge(
                "nns_client_queue_depth",
                element=self.name, client=str(c.cid),
            )
        c.depth_gauge.set(depth)

    def snapshot(self) -> Dict[str, Any]:
        """Stats for Executor.stats() / nns-top (--clients view)."""
        with self._mu:
            clients = {
                str(cid): {
                    "queued": c.queued(),
                    "inflight": c.inflight,
                    "admitted": c.admitted,
                    "rejected": c.rejected,
                }
                for cid, c in self._clients.items()
            }
            return {
                "admitted": self.admitted_total,
                "rejected": self.rejected_total,
                "released": self.released_total,
                "inflight": self._inflight_total,
                "queued": self._ready,
                "rejected_by_reason": dict(self.rejected_by_reason),
                "clients": clients,
            }
