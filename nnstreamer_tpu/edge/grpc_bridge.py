"""gRPC tensor bridge: tensor_src_grpc / tensor_sink_grpc elements.

Reference: ext/nnstreamer/extra/nnstreamer_grpc_*.cc (NNStreamerRPC class,
nnstreamer_grpc_common.h:32-83, protobuf AND flatbuf IDL variants in
nnstreamer_grpc_protobuf.cc / nnstreamer_grpc_flatbuf.cc) +
tensor_src_grpc.c / tensor_sink_grpc.c — each element runs as gRPC
*server or client* per property, streaming tensor messages. Both IDLs are
offered here too (``idl=protobuf`` default, ``idl=flatbuf``): protobuf
rides the wire-compatible schema in proto/nns_tensors.proto; flatbuf
reuses the converters/flatbuf.py codec (nnstreamer.fbs schema) with the
flatbuffer bytes streamed verbatim. The two IDLs register distinct
service names (as the reference does), so a mismatched pair fails loudly
instead of mis-parsing.

No generated stubs are needed: the service is registered with
``grpc.method_handlers_generic_handler`` using the IDL's serializers
(grpcio-tools is not in the image — same codegen-free approach as the
flatbuf codec).
"""

from __future__ import annotations

import queue as queue_mod
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

from nnstreamer_tpu import registry
from nnstreamer_tpu.converters.protobuf import frame_to_message, message_to_tensors
from nnstreamer_tpu.elements.base import (
    _parse_bool,
    ElementError,
    NegotiationError,
    PropSpec,
    Sink,
    Source,
    Spec,
)
from nnstreamer_tpu.tensors.frame import EOS_FRAME, Frame
from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec

def _require_grpc():
    try:
        import grpc  # noqa: F401

        return grpc
    except ImportError as exc:  # pragma: no cover - grpc is in the image
        raise ElementError(
            "grpc python package unavailable; tensor_*_grpc elements are "
            "gated (like the reference's grpc meson option)"
        ) from exc


class _ProtobufIdl:
    """Wire = proto/nns_tensors.proto messages (reference
    nnstreamer_grpc_protobuf.cc slot)."""

    name = "protobuf"
    service = "nnstreamer_tpu.proto.TensorService"

    def __init__(self):
        from nnstreamer_tpu.proto import nns_tensors_pb2 as pb

        self._pb = pb
        self.tensors_ser = pb.Tensors.SerializeToString
        self.tensors_des = pb.Tensors.FromString
        self.empty_ser = pb.Empty.SerializeToString
        self.empty_des = pb.Empty.FromString

    def empty(self):
        return self._pb.Empty()

    def frame_to_wire(self, frame: Frame):
        return frame_to_message(frame.to_host())

    def wire_to_frame(self, msg) -> Frame:
        return Frame(message_to_tensors(msg))


class _FlatbufIdl:
    """Wire = flatbuffer-serialized Tensors (converters/flatbuf.py codec,
    nnstreamer.fbs schema — reference nnstreamer_grpc_flatbuf.cc slot).
    The buffer bytes stream verbatim; Empty is the empty byte string."""

    name = "flatbuf"
    service = "nnstreamer_tpu.flatbuf.TensorService"

    def __init__(self):
        import flatbuffers  # noqa: F401 — gate like the reference meson option

        ident = lambda b: b  # noqa: E731
        self.tensors_ser = ident
        self.tensors_des = ident
        self.empty_ser = lambda _b: b""
        self.empty_des = lambda _b: b""

    def empty(self):
        return b""

    def frame_to_wire(self, frame: Frame):
        from nnstreamer_tpu.converters.flatbuf import encode_flatbuf

        import numpy as np

        return encode_flatbuf(
            [np.asarray(t) for t in frame.to_host().tensors]
        )

    def wire_to_frame(self, data) -> Frame:
        from nnstreamer_tpu.converters.flatbuf import decode_flatbuf

        tensors, _rate = decode_flatbuf(data)
        return Frame(tuple(tensors))


_IDLS = {"protobuf": _ProtobufIdl, "flatbuf": _FlatbufIdl}


def _make_idl(name: str):
    try:
        return _IDLS[name]()
    except KeyError:
        raise ElementError(
            f"unknown idl {name!r} (choose protobuf or flatbuf)"
        ) from None
    except ImportError as exc:
        raise ElementError(f"idl {name!r} unavailable: {exc}") from exc


def _service_handler(grpc, idl, send_handler=None, recv_handler=None):
    """Build the generic service handler with the IDL's serializers."""
    handlers = {}
    if send_handler is not None:  # client streams Tensors at us
        handlers["SendTensors"] = grpc.stream_unary_rpc_method_handler(
            send_handler,
            request_deserializer=idl.tensors_des,
            response_serializer=idl.empty_ser,
        )
    if recv_handler is not None:  # we stream Tensors to the client
        handlers["RecvTensors"] = grpc.unary_stream_rpc_method_handler(
            recv_handler,
            request_deserializer=idl.empty_des,
            response_serializer=idl.tensors_ser,
        )
    return grpc.method_handlers_generic_handler(idl.service, handlers)


def _bounded_put(q: "queue_mod.Queue", item, should_abort) -> bool:
    """Lossless bounded enqueue that can't wedge the producer thread: block
    with a short timeout and re-check the abort predicate, so gRPC flow
    control backpressures the sender while shutdown always unblocks.
    Returns False if aborted before the item landed."""
    while not should_abort():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue_mod.Full:
            continue
    return False


def _put_unless_stopped(q: "queue_mod.Queue", item, stopped: threading.Event) -> None:
    _bounded_put(q, item, stopped.is_set)


@registry.element("tensor_src_grpc")
class GrpcTensorSrc(Source):
    """Receive Tensors over gRPC and emit them as frames.

    Props: server (true = run a gRPC server accepting SendTensors streams,
    false = connect out and pull via RecvTensors), host, port (0 =
    ephemeral in server mode; read back via ``bound_port``).
    """

    FACTORY_NAME = "tensor_src_grpc"

    PROPERTIES = {
        "server": PropSpec("bool", True),
        "host": PropSpec("str", "127.0.0.1"),
        "port": PropSpec("int", 0, desc="0 = ephemeral in server mode"),
        "idl": PropSpec("enum", "protobuf", ("protobuf", "flatbuf")),
        "connection-timeout": PropSpec("float", 10.0),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.is_server = _parse_bool(self.get_property("server", True))
        self.host = str(self.get_property("host", "127.0.0.1"))
        self.port = int(self.get_property("port", 0))
        self.idl_name = str(self.get_property("idl", "protobuf"))
        self.bound_port: Optional[int] = None
        self._queue: "queue_mod.Queue" = queue_mod.Queue(maxsize=64)
        self._server = None
        self._channel = None
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()
        self._error: Optional[str] = None

    def output_spec(self) -> Spec:
        return TensorsSpec(format=TensorFormat.FLEXIBLE)

    # -- server mode: clients push streams at us ---------------------------
    def _start_server(self, grpc, idl) -> None:
        src = self

        def send_tensors(request_iterator, context):
            # a bare blocking put would wedge this grpc worker thread
            # forever once the consumer stops (the pool is non-daemon,
            # hanging interpreter exit)
            for msg in request_iterator:
                if src._stopped.is_set():
                    break
                _put_unless_stopped(
                    src._queue, idl.wire_to_frame(msg), src._stopped
                )
            return idl.empty()

        self._server = grpc.server(ThreadPoolExecutor(max_workers=4))
        self._server.add_generic_rpc_handlers(
            (_service_handler(grpc, idl, send_handler=send_tensors),)
        )
        self.bound_port = self._server.add_insecure_port(
            f"{self.host}:{self.port}"
        )
        if self.bound_port == 0:
            raise ElementError(f"{self.name}: cannot bind {self.host}:{self.port}")
        self._server.start()

    # -- client mode: we pull a stream from a remote sink ------------------
    def _start_client(self, grpc, idl) -> None:
        self._channel = grpc.insecure_channel(f"{self.host}:{self.port}")
        try:  # fail fast on unreachable server, like EdgeSrc.start
            grpc.channel_ready_future(self._channel).result(
                timeout=float(self.get_property("connection-timeout", 10.0))
            )
        except grpc.FutureTimeoutError as exc:
            self._channel.close()
            self._channel = None
            raise ElementError(
                f"{self.name}: cannot reach gRPC server "
                f"{self.host}:{self.port}"
            ) from exc
        call = self._channel.unary_stream(
            f"/{idl.service}/RecvTensors",
            request_serializer=idl.empty_ser,
            response_deserializer=idl.tensors_des,
        )

        def pull():
            try:
                for msg in call(idl.empty()):
                    if self._stopped.is_set():
                        break
                    _put_unless_stopped(
                        self._queue, idl.wire_to_frame(msg), self._stopped
                    )
            except grpc.RpcError as exc:
                if not self._stopped.is_set():
                    self._error = f"stream broke: {exc.code()}"
            _put_unless_stopped(self._queue, EOS_FRAME, self._stopped)

        self._thread = threading.Thread(target=pull, daemon=True)
        self._thread.start()

    def start(self) -> None:
        grpc = _require_grpc()
        idl = _make_idl(self.idl_name)
        self._stopped.clear()
        if self.is_server:
            self._start_server(grpc, idl)
        else:
            self._start_client(grpc, idl)

    def stop(self) -> None:
        self._stopped.set()
        if self._server is not None:
            self._server.stop(grace=0.5)
            self._server = None
        if self._channel is not None:
            self._channel.close()
            self._channel = None

    def generate(self):
        if self._error:
            raise ElementError(f"{self.name}: {self._error}")
        try:
            return self._queue.get(timeout=0.1)
        except queue_mod.Empty:
            return None


@registry.element("tensor_sink_grpc")
class GrpcTensorSink(Sink):
    """Send rendered frames over gRPC.

    Props: server (true = serve RecvTensors streams to subscribers,
    false = connect out and push via SendTensors), host, port.
    """

    FACTORY_NAME = "tensor_sink_grpc"

    PROPERTIES = {
        "server": PropSpec("bool", True),
        "host": PropSpec("str", "127.0.0.1"),
        "port": PropSpec("int", 0, desc="0 = ephemeral in server mode"),
        "idl": PropSpec("enum", "protobuf", ("protobuf", "flatbuf")),
        "connection-timeout": PropSpec("float", 10.0),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.is_server = _parse_bool(self.get_property("server", True))
        self.host = str(self.get_property("host", "127.0.0.1"))
        self.port = int(self.get_property("port", 0))
        self.idl_name = str(self.get_property("idl", "protobuf"))
        self.bound_port: Optional[int] = None
        self._idl = None
        self._server = None
        self._channel = None
        self._push_queue: "queue_mod.Queue" = queue_mod.Queue(maxsize=64)
        self._subscribers: List[queue_mod.Queue] = []
        self._sub_lock = threading.Lock()
        self._client_done = None
        self._stopping = threading.Event()
        self._error: Optional[str] = None

    def _push_abort(self):
        """Abort predicate for client-mode queue puts: a dead stream OR an
        element stop must unblock the producer — a stalled-but-alive stream
        (server stops reading, queue full) never sets _client_done, so
        stop() needs its own flag to avoid spinning forever."""
        done = self._client_done
        return self._stopping.is_set() or (done is not None and done.is_set())

    # -- server mode: subscribers pull a stream ----------------------------
    def _start_server(self, grpc, idl) -> None:
        sink = self

        def recv_tensors(request, context):
            q: "queue_mod.Queue" = queue_mod.Queue(maxsize=64)
            with sink._sub_lock:
                sink._subscribers.append(q)
            try:
                while True:
                    item = q.get()
                    if item is None:
                        break
                    yield item
            finally:
                with sink._sub_lock:
                    if q in sink._subscribers:
                        sink._subscribers.remove(q)

        self._server = grpc.server(ThreadPoolExecutor(max_workers=4))
        self._server.add_generic_rpc_handlers(
            (_service_handler(grpc, idl, recv_handler=recv_tensors),)
        )
        self.bound_port = self._server.add_insecure_port(
            f"{self.host}:{self.port}"
        )
        if self.bound_port == 0:
            raise ElementError(f"{self.name}: cannot bind {self.host}:{self.port}")
        self._server.start()

    # -- client mode: we push a stream to a remote src ---------------------
    def _start_client(self, grpc, idl) -> None:
        self._channel = grpc.insecure_channel(f"{self.host}:{self.port}")
        try:  # fail fast on unreachable server, like GrpcTensorSrc
            grpc.channel_ready_future(self._channel).result(
                timeout=float(self.get_property("connection-timeout", 10.0))
            )
        except grpc.FutureTimeoutError as exc:
            self._channel.close()
            self._channel = None
            raise ElementError(
                f"{self.name}: cannot reach gRPC server "
                f"{self.host}:{self.port}"
            ) from exc
        call = self._channel.stream_unary(
            f"/{idl.service}/SendTensors",
            request_serializer=idl.tensors_ser,
            response_deserializer=idl.empty_des,
        )

        def feed():
            while True:
                item = self._push_queue.get()
                if item is None:
                    return
                yield item

        self._client_done = threading.Event()

        def run():
            try:
                call(feed())
            except grpc.RpcError as exc:
                self._error = f"stream broke: {exc.code()}"
            self._client_done.set()

        threading.Thread(target=run, daemon=True).start()

    def start(self) -> None:
        grpc = _require_grpc()
        self._idl = _make_idl(self.idl_name)
        if self.is_server:
            self._start_server(grpc, self._idl)
        else:
            self._start_client(grpc, self._idl)

    def stop(self) -> None:
        self._stopping.set()
        self.on_eos()
        if self._server is not None:
            self._server.stop(grace=0.5)
            self._server = None
        if self._channel is not None:
            if self._client_done is not None:
                self._client_done.wait(timeout=5)
            self._channel.close()
            self._channel = None

    def render(self, frame: Frame) -> None:
        msg = self._idl.frame_to_wire(frame)
        if self.is_server:
            with self._sub_lock:
                subs = list(self._subscribers)
            for q in subs:
                try:
                    q.put_nowait(msg)
                except queue_mod.Full:
                    pass  # slow subscriber: drop (reference async mode)
        else:
            # bounded put that notices a dead stream or element stop: once
            # run() exits the feed() generator stops draining and a bare
            # put would block forever on the full queue
            if not _bounded_put(self._push_queue, msg, self._push_abort):
                raise ElementError(
                    f"{self.name}: {self._error or 'gRPC stream closed'}"
                )

    def on_eos(self) -> None:
        if self.is_server:
            with self._sub_lock:
                subs = list(self._subscribers)
            for q in subs:
                # a stalled subscriber's queue may be full — drain one slot
                # so the EOS sentinel lands instead of hanging shutdown
                while True:
                    try:
                        q.put_nowait(None)
                        break
                    except queue_mod.Full:
                        try:
                            q.get_nowait()
                        except queue_mod.Empty:
                            pass
        else:
            try:  # healthy stream: sentinel lands and feed() ends cleanly
                self._push_queue.put_nowait(None)
            except queue_mod.Full:
                _bounded_put(self._push_queue, None, self._push_abort)
