"""Fleet endpoint selection for the edge query layer (docs/edge-serving.md).

ROADMAP item 5's last gap: one ``tensor_query_client`` reconnecting
politely is not a fleet. PR 6 taught a *single* server to say no early
(admission NACKs, deadlines); this module teaches the *client* that
servers are interchangeable — ``tensor_query_client hosts=h1:p1,h2:p2``
binds a :class:`FleetEndpoints` selector instead of one socket:

- **health scoring** — per-endpoint consecutive-failure ejection with
  jittered, doubling backoff before a re-probe (the PR-7 ReplicaSet
  circuit/probe idiom, time-based because endpoint death is observed on
  the wall clock, not a dispatch counter). A ``draining`` NACK from a
  server doing a rolling restart benches the endpoint for exactly its
  ``retry-after`` hint.
- **failover plans** — :meth:`FleetEndpoints.plan` returns the ordered
  endpoints to try for ONE request: a due re-probe first (its request
  falls through to the healthy rotation if the probe fails), then the
  healthy round-robin.
- **reply dedup** — failover re-sends a request that may already be in
  flight on the first server, so delivery stays at-most-once only
  because every reply carries the PR-5 ``frame_id``:
  :class:`ReplyDeduper` remembers delivered ids and drops the late
  duplicate from the loser.
- **hedging** — :class:`HedgeTimer` decides when a straggling request
  earns a second send (``hedge-after-ms``; negative = adaptive, from
  :class:`RttWindow`'s observed p99). Deterministic under an injected
  clock so the tests pin the schedule exactly.
- **prefix-aware routing** — :class:`PrefixRouter` remembers which
  endpoint last served each rolling-CRC prompt-prefix key
  (:func:`prefix_route_keys`, the kv/blocks.py chain at routing
  granularity) so a repeat-prefix LLM request lands on the server whose
  pool already holds its longest cached prefix — cluster-wide prefix
  sharing, not just per-process (docs/llm-serving.md "Disaggregated
  serving").

Everything here is pure selection/accounting logic — no sockets — so the
tier-1 units run with fake clocks; the client element (edge/query.py)
owns the transports.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from nnstreamer_tpu.kv.blocks import roll_hash
from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.obs import metrics as obs_metrics

_log = get_logger("edge.fleet")

#: endpoint states surfaced by snapshots / nns-top --fleet
STATE_HEALTHY = "healthy"
STATE_EJECTED = "ejected"
STATE_DRAINING = "draining"


def parse_hosts(spec: str) -> List[Tuple[str, int]]:
    """``"h1:p1,h2:p2"`` → ``[(h1, p1), ...]`` (the client's ``hosts``
    property). Raises ValueError on malformed entries or duplicates so
    nns-lint (NNS-E005 via PropSpec coercion happens upstream; this is
    the semantic check) and the element constructor fail loudly."""
    out: List[Tuple[str, int]] = []
    seen = set()
    for raw in str(spec).split(","):
        raw = raw.strip()
        if not raw:
            continue
        host, _, port_s = raw.rpartition(":")
        if not host or not port_s.isdigit():
            raise ValueError(
                f"hosts entry {raw!r} is not host:port"
            )
        port = int(port_s)
        if port <= 0:
            raise ValueError(f"hosts entry {raw!r} has a bad port")
        key = (host, port)
        if key in seen:
            raise ValueError(f"hosts entry {raw!r} is listed twice")
        seen.add(key)
        out.append(key)
    if not out:
        raise ValueError(f"hosts={spec!r} names no endpoints")
    return out


class Endpoint:
    """One ``host:port`` dispatch target plus its health bookkeeping.
    All mutation happens through the owning :class:`FleetEndpoints`
    (single client thread by the element contract; snapshots read the
    GIL-atomic counters)."""

    __slots__ = (
        "idx", "host", "port", "healthy", "draining", "consec_fails",
        "fails", "served", "failovers", "inflight", "retry_at", "score",
        "unresolvable", "fail_streak",
    )

    def __init__(self, idx: int, host: str, port: int) -> None:
        self.idx = idx
        self.host = host
        self.port = port
        self.healthy = True
        self.draining = False
        self.consec_fails = 0   # toward ejection (eject_after)
        self.fail_streak = 0    # toward backoff doubling while benched
        self.fails = 0
        self.served = 0
        self.failovers = 0      # requests that failed over AWAY from here
        self.inflight = 0       # sends not yet replied/failed
        self.retry_at = 0.0     # benched until (monotonic); 0 = in rotation
        self.score = 1.0        # EWMA success rate (nns-top --fleet)
        self.unresolvable = False

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def state(self) -> str:
        if self.draining:
            return STATE_DRAINING
        return STATE_HEALTHY if self.healthy else STATE_EJECTED


class FleetEndpoints:
    """Health-scored endpoint selection for one fleet client.

    ``plan()`` yields the ordered endpoints to try for one request,
    ``record_ok`` / ``record_fail`` / ``mark_draining`` feed the scorer.
    ``clock`` and ``rng`` are injectable so the tier-1 units are
    deterministic (fake clock, seeded jitter)."""

    def __init__(
        self,
        targets: Sequence[Tuple[str, int]],
        eject_after: int = 3,
        probe_backoff_ms: float = 100.0,
        backoff_cap_ms: float = 3000.0,
        clock: Callable[[], float] = time.monotonic,
        rng: Optional[random.Random] = None,
        name: str = "fleet",
    ) -> None:
        if not targets:
            raise ValueError("FleetEndpoints needs at least one endpoint")
        self.endpoints = [
            Endpoint(i, h, p) for i, (h, p) in enumerate(targets)
        ]
        self.eject_after = max(1, int(eject_after))
        self.probe_backoff_ms = max(1.0, float(probe_backoff_ms))
        self.backoff_cap_ms = max(
            self.probe_backoff_ms, float(backoff_cap_ms)
        )
        self.clock = clock
        self.name = name
        self._rng = rng if rng is not None else random.Random(0xF1EE7)
        self._rr = 0
        # registry resolved ONCE at construction (the executor
        # discipline): obs_metrics.get() probes env+config on the None
        # path and must stay off the per-request path
        self._reg = obs_metrics.get()
        self._health_gauges: Dict[str, object] = {}

    # -- selection ---------------------------------------------------------
    def plan(self) -> List[Endpoint]:
        """Ordered dispatch plan for ONE request: a due benched endpoint
        is prepended as a re-probe (its request falls through to the
        healthy rotation when the probe fails — the ReplicaSet idiom),
        then the healthy round-robin, least-loaded first: the rotation
        is stably re-ordered by live ``inflight`` so an endpoint
        sitting on slow requests stops collecting new ones while its
        idle peers exist (ties keep the round-robin order, so an idle
        fleet still spreads). Draining endpoints rejoin only when their
        retry-after elapsed and nothing healthier exists."""
        now = self.clock()
        healthy = [
            e for e in self.endpoints if e.healthy and not e.draining
        ]
        benched = [
            e for e in self.endpoints if not (e.healthy and not e.draining)
        ]
        due = [e for e in benched if now >= e.retry_at]
        plan: List[Endpoint] = []
        if due and healthy:
            # probe the longest-benched due endpoint first; a recovered
            # server rejoins within one request of its backoff expiring
            plan.append(min(due, key=lambda e: e.retry_at))
        if healthy:
            start = self._rr % len(healthy)
            self._rr += 1
            rotation = healthy[start:] + healthy[:start]
            # stable: equal-inflight endpoints keep the rotation order
            plan.extend(sorted(rotation, key=lambda e: e.inflight))
        elif due:
            # nothing healthy: give every due endpoint a shot rather
            # than exhausting behind one dead probe target
            plan.extend(sorted(due, key=lambda e: e.retry_at))
        return plan

    def next_retry_in(self) -> float:
        """Seconds until the soonest benched endpoint is probe-eligible
        (0 when something is dispatchable right now) — the caller's
        sleep hint when a whole fleet is benched."""
        now = self.clock()
        if any(e.healthy and not e.draining for e in self.endpoints):
            return 0.0
        waits = [max(0.0, e.retry_at - now) for e in self.endpoints]
        return min(waits) if waits else 0.0

    # -- scoring -----------------------------------------------------------
    def record_ok(self, ep: Endpoint) -> None:
        was_ejected = not ep.healthy
        was_draining = ep.draining
        ep.served += 1
        ep.consec_fails = 0
        ep.fail_streak = 0
        ep.retry_at = 0.0
        ep.draining = False
        ep.unresolvable = False
        ep.score = min(1.0, 0.8 * ep.score + 0.2)
        ep.healthy = True
        if was_ejected:
            _log.warning("%s: endpoint %s recovered; back in rotation",
                         self.name, ep.addr)
        if was_ejected or was_draining:
            # a draining endpoint that recovered must flip the health
            # gauge back to 1 too, not only an ejected one
            self._gauge_health(ep)

    def record_fail(self, ep: Endpoint, unresolvable: bool = False) -> None:
        """One failed send/connect/reply on ``ep``: bench it after
        ``eject_after`` consecutive failures (immediately when the host
        no longer resolves — burning the retry budget on a gone name
        helps nobody) with jittered doubling backoff before a re-probe."""
        ep.fails += 1
        ep.consec_fails += 1
        ep.score = 0.8 * ep.score
        if unresolvable:
            ep.unresolvable = True
        was_healthy = ep.healthy
        if ep.consec_fails >= self.eject_after or unresolvable:
            ep.healthy = False
        if not ep.healthy:
            full_ms = min(
                self.probe_backoff_ms * (2.0 ** min(ep.fail_streak, 16)),
                self.backoff_cap_ms,
            )
            ep.fail_streak += 1
            jitter = 0.5 + 0.5 * self._rng.random()
            ep.retry_at = self.clock() + jitter * full_ms / 1000.0
            if was_healthy:
                _log.warning(
                    "%s: endpoint %s EJECTED after %d consecutive "
                    "failure(s)%s; re-probe in ~%.0f ms",
                    self.name, ep.addr, ep.consec_fails,
                    " (unresolvable)" if unresolvable else "", full_ms,
                )
                self._gauge_health(ep)

    def mark_draining(self, ep: Endpoint, retry_after_ms: float) -> None:
        """The endpoint NACKed ``draining`` (rolling restart): bench it
        for exactly the server's hint — it is not *failing*, it asked
        politely, so no consecutive-failure penalty accrues."""
        was = ep.draining
        ep.draining = True
        ep.retry_at = self.clock() + max(0.0, retry_after_ms) / 1000.0
        if not was:
            _log.info("%s: endpoint %s draining; retry in %.0f ms",
                      self.name, ep.addr, retry_after_ms)
            self._gauge_health(ep)

    # -- observability -----------------------------------------------------
    def _gauge_health(self, ep: Endpoint) -> None:
        reg = self._reg
        if reg is None:
            return
        g = self._health_gauges.get(ep.addr)
        if g is None:
            g = self._health_gauges[ep.addr] = reg.gauge(
                "nns_endpoint_healthy",
                element=self.name, endpoint=ep.addr,
            )
        g.set(1.0 if ep.healthy and not ep.draining else 0.0)

    def healthy_count(self) -> int:
        return sum(
            1 for e in self.endpoints if e.healthy and not e.draining
        )

    def snapshot(self) -> Dict[str, dict]:
        """Per-endpoint rows for ``fleet_stats()`` / nns-top --fleet."""
        return {
            e.addr: {
                "state": e.state(),
                "score": round(e.score, 3),
                "inflight": e.inflight,
                "served": e.served,
                "fails": e.fails,
                "failovers": e.failovers,
                "unresolvable": e.unresolvable,
            }
            for e in self.endpoints
        }


class ReplyDeduper:
    """frame_id-keyed at-most-once delivery across failover/hedging.

    A request re-sent to a second endpoint can be answered twice; only
    the FIRST reply for a frame_id is delivered (``claim`` returns True
    exactly once per id), and late duplicates — which may arrive many
    requests later on a connection the client kept open — are counted
    and dropped. Bounded FIFO memory so an unbounded stream of ids
    cannot grow the set forever."""

    __slots__ = ("capacity", "_seen", "_order", "duplicates")

    def __init__(self, capacity: int = 4096) -> None:
        self.capacity = max(16, int(capacity))
        self._seen: set = set()
        self._order: List[object] = []
        self.duplicates = 0

    def claim(self, frame_id) -> bool:
        """True when ``frame_id`` has not been delivered yet (caller
        delivers it); False for a duplicate (caller drops it)."""
        if frame_id in self._seen:
            self.duplicates += 1
            return False
        self._seen.add(frame_id)
        self._order.append(frame_id)
        if len(self._order) > self.capacity:
            evicted = self._order[: len(self._order) - self.capacity]
            del self._order[: len(self._order) - self.capacity]
            self._seen.difference_update(evicted)
        return True

    def seen(self, frame_id) -> bool:
        return frame_id in self._seen


#: routing granularity for prompt-prefix keys (tokens per key). Coarser
#: than many servers' kv block-size would miss shareable prefixes; finer
#: costs meta bytes for depth no pool can hold. 16 matches the default
#: ``block-size`` of tensor_llm_serversink.
ROUTE_BLOCK = 16


def prefix_route_keys(tokens, block: int = ROUTE_BLOCK,
                      max_blocks: int = 32) -> List[str]:
    """Rolling-CRC keys of a prompt's block-aligned prefixes — the
    kv/blocks.py :func:`~nnstreamer_tpu.kv.blocks.roll_hash` chain at
    routing granularity, one 8-hex-digit key per ``block`` tokens
    (``keys[i]`` covers ``tokens[:(i+1)*block]``). Capped at
    ``max_blocks`` keys: past 512 tokens the routing signal is already
    decisive and meta bytes stop paying for themselves."""
    toks = np.ascontiguousarray(
        list(tokens)[: int(block) * int(max_blocks)], np.int32
    )
    h = 0
    keys: List[str] = []
    for i in range(len(toks) // int(block)):
        h = roll_hash(h, toks[i * block:(i + 1) * block])
        keys.append(f"{h:08x}")
    return keys


class PrefixRouter:
    """Client-side cluster prefix index: which endpoint last served
    each prompt-prefix key.

    ``note(keys, addr)`` records a delivered reply's keys against the
    endpoint that answered; ``best(keys)`` returns the
    ``(addr, depth)`` of the longest recorded prefix of a new request
    (deepest key first), or ``None`` when no endpoint is known to hold
    any of it. The index is advisory — the caller still routes through
    health/draining state and falls back to the least-loaded rotation —
    so a stale entry costs one cold prefill, never correctness. Bounded
    FIFO like :class:`ReplyDeduper`: an unbounded stream of novel
    prompts cannot grow it forever."""

    __slots__ = ("capacity", "_owner", "_order", "prefix_hits")

    def __init__(self, capacity: int = 4096) -> None:
        self.capacity = max(16, int(capacity))
        self._owner: Dict[str, str] = {}
        self._order: List[str] = []
        self.prefix_hits = 0

    def note(self, keys: Sequence[str], addr: str) -> None:
        for k in keys:
            if k not in self._owner:
                self._order.append(k)
            self._owner[k] = addr  # latest server to hold it wins
        if len(self._order) > self.capacity:
            evicted = self._order[: len(self._order) - self.capacity]
            del self._order[: len(self._order) - self.capacity]
            for k in evicted:
                self._owner.pop(k, None)

    def best(self, keys: Sequence[str]) -> Optional[Tuple[str, int]]:
        for depth in range(len(keys), 0, -1):
            addr = self._owner.get(keys[depth - 1])
            if addr is not None:
                return addr, depth
        return None

    def __len__(self) -> int:
        return len(self._owner)


class RttWindow:
    """Rolling window of recent reply RTTs; feeds the adaptive hedge
    threshold (``hedge-after-ms`` < 0 = hedge past the observed p99)."""

    __slots__ = ("_vals", "capacity")

    def __init__(self, capacity: int = 256) -> None:
        self.capacity = max(8, int(capacity))
        self._vals: List[float] = []

    def record(self, rtt_s: float) -> None:
        self._vals.append(float(rtt_s))
        if len(self._vals) > self.capacity:
            del self._vals[: len(self._vals) - self.capacity]

    def __len__(self) -> int:
        return len(self._vals)

    def quantile(self, q: float) -> Optional[float]:
        if len(self._vals) < 8:
            return None  # too few samples to call anything a straggler
        xs = sorted(self._vals)
        i = min(len(xs) - 1, max(0, int(q * len(xs))))
        return xs[i]


class HedgeTimer:
    """When does ONE request earn its hedge? Fixed threshold
    (``after_ms`` > 0), adaptive (``after_ms`` < 0: the RttWindow's p99,
    floored at ``adaptive_floor_ms`` until enough samples exist), or
    never (0, the default). Deterministic under an injected clock —
    the tier-1 hedging test pins the schedule exactly."""

    __slots__ = ("after_ms", "clock", "rtts", "adaptive_floor_ms",
                 "t0", "fired")

    def __init__(
        self,
        after_ms: float,
        clock: Callable[[], float] = time.monotonic,
        rtts: Optional[RttWindow] = None,
        adaptive_floor_ms: float = 50.0,
    ) -> None:
        self.after_ms = float(after_ms)
        self.clock = clock
        self.rtts = rtts
        self.adaptive_floor_ms = float(adaptive_floor_ms)
        self.t0: Optional[float] = None
        self.fired = False

    def arm(self) -> None:
        self.t0 = self.clock()
        self.fired = False

    def threshold_s(self) -> Optional[float]:
        """Current hedge delay in seconds; None = hedging off."""
        if self.after_ms > 0:
            return self.after_ms / 1000.0
        if self.after_ms < 0:
            p99 = self.rtts.quantile(0.99) if self.rtts is not None else None
            if p99 is None:
                return self.adaptive_floor_ms / 1000.0
            return max(p99, self.adaptive_floor_ms / 1000.0)
        return None

    def due(self) -> bool:
        """True exactly while the hedge should fire (once: callers mark
        ``fire()`` after sending the hedge)."""
        if self.fired or self.t0 is None:
            return False
        thr = self.threshold_s()
        if thr is None:
            return False
        return self.clock() - self.t0 >= thr

    def fire(self) -> None:
        self.fired = True
