"""Shared-memory ring transport: the same-host zero-socket fast path.

Reference context: co-located pipeline shards in the reference still talk
through loopback TCP via nnstreamer-edge (gst/edge/edge_common.h default
port 3000). ``connect-type=SHM`` on edgesink/edgesrc replaces that hop
with the native SPSC ring in native/nns_shm.cpp (POSIX shm + process-
shared condvars): one memcpy in, one memcpy out, no syscall per frame on
the hot path.

Exposes the same transport surface as the TCP layer (listen/connect/
send/recv/peer_count/close) keyed by the element's ``port`` (segment name
``/nns-shm-<port>``). Single consumer by design — fan-out stays the TCP
transport's job.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

from nnstreamer_tpu.edge._build import build_native
from nnstreamer_tpu.edge.transport import TransportError

DEFAULT_CAPACITY = 32 * 1024 * 1024  # 32 MB ring
MIN_CAPACITY = 4096  # native layer clamps to this (nns_shm.cpp)
_MAX_MSG = 512 * 1024 * 1024


class MessageTooLarge(TransportError):
    """Permanent per-configuration failure: the message can NEVER fit the
    ring — callers should fail loudly, not retry/drop."""


def _load() -> ctypes.CDLL:
    path = build_native("nns_shm.cpp")
    if path is None:
        raise TransportError(
            "native shm transport unavailable (g++ build failed)"
        )
    try:
        lib = ctypes.CDLL(path)
    except OSError as exc:
        # e.g. a sanitizer build: libtsan needs LD_PRELOAD to dlopen into
        # an uninstrumented interpreter (static TLS)
        raise TransportError(
            f"native shm transport failed to load: {exc} "
            "(sanitizer builds need LD_PRELOAD of the sanitizer runtime)"
        )
    lib.nns_shm_create.restype = ctypes.c_void_p
    lib.nns_shm_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.nns_shm_open.restype = ctypes.c_void_p
    lib.nns_shm_open.argtypes = [ctypes.c_char_p]
    lib.nns_shm_write.restype = ctypes.c_int
    lib.nns_shm_write.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int,
    ]
    lib.nns_shm_read.restype = ctypes.c_int64
    lib.nns_shm_read.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int,
    ]
    lib.nns_shm_reader_count.restype = ctypes.c_uint32
    lib.nns_shm_reader_count.argtypes = [ctypes.c_void_p]
    lib.nns_shm_mark_closed.restype = None
    lib.nns_shm_mark_closed.argtypes = [ctypes.c_void_p]
    lib.nns_shm_close.restype = None
    lib.nns_shm_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
    return lib


_lib: Optional[ctypes.CDLL] = None


def _get_lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        _lib = _load()
    return _lib


def segment_name(port: int) -> str:
    return f"/nns-shm-{port}"


class ShmTransport:
    """Producer (listen) or consumer (connect) end of one shm ring."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self.capacity = max(int(capacity), MIN_CAPACITY)  # mirror native clamp
        self._h: Optional[int] = None
        self._producer = False
        self._buf = ctypes.create_string_buffer(4 * 1024 * 1024)

    # -- transport surface -------------------------------------------------
    def listen(self, host: str, port: int) -> int:
        lib = _get_lib()
        port = port or os.getpid() % 50000 + 10000
        h = lib.nns_shm_create(segment_name(port).encode(), self.capacity)
        if not h:
            raise TransportError(
                f"cannot create shm segment {segment_name(port)!r}: a live "
                "producer owns it (TCP-listen EADDRINUSE analogue), or shm "
                f"is unavailable; stale file: /dev/shm{segment_name(port)}"
            )
        self._h = h
        self._producer = True
        return port

    def connect(self, host: str, port: int) -> None:
        lib = _get_lib()
        h = lib.nns_shm_open(segment_name(port).encode())
        if not h:
            raise TransportError(
                f"no shm segment {segment_name(port)!r} (is the producer up?)"
            )
        self._h = h
        self._producer = False

    def send(self, cid, payload: bytes, timeout: float = 10.0) -> None:
        if self._h is None:
            raise TransportError("shm transport not started")
        if len(payload) + 8 > self.capacity // 2:
            # the ring guarantees progress only for messages ≤ capacity/2
            raise MessageTooLarge(
                f"shm message ({len(payload)} B) exceeds ring capacity/2 "
                f"({self.capacity // 2} B); raise the transport capacity "
                "(edgesink shm-capacity property)"
            )
        rc = _get_lib().nns_shm_write(
            self._h, payload, len(payload), int(timeout * 1000)
        )
        if rc == 0:
            raise TransportError("shm ring full (consumer stalled)")
        if rc < 0:
            raise TransportError("shm ring closed")

    def recv(self, timeout: Optional[float] = None) -> Optional[Tuple[int, bytes]]:
        if self._h is None:
            raise TransportError("shm transport not started")
        lib = _get_lib()
        ms = int((timeout if timeout is not None else 0.1) * 1000) or 1
        while True:
            n = lib.nns_shm_read(self._h, self._buf, len(self._buf), ms)
            if n == 0:
                return None  # timeout
            if n == -1:
                return (0, b"")  # closed + drained (EOS analogue)
            if n == -2:
                if len(self._buf) * 2 > _MAX_MSG:
                    raise TransportError("shm message exceeds max size")
                self._buf = ctypes.create_string_buffer(len(self._buf) * 2)
                continue
            # string_at copies exactly n bytes; .raw would materialize the
            # whole (possibly hundreds-of-MB) reader buffer per message
            return (0, ctypes.string_at(self._buf, n))

    def peer_count(self) -> int:
        if self._h is None:
            return 0
        return int(_get_lib().nns_shm_reader_count(self._h))

    def close(self) -> None:
        if self._h is None:
            return
        lib = _get_lib()
        if self._producer:
            lib.nns_shm_mark_closed(self._h)
        lib.nns_shm_close(self._h, 1 if self._producer else 0)
        self._h = None
