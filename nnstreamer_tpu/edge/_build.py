"""On-demand g++ build of the in-tree native components.

The reference links a prebuilt external nnstreamer-edge .so discovered via
pkg-config; here the native sources ship in-tree (native/*.cpp) and compile
once into cached .so files keyed on source mtime. A missing toolchain
degrades to the pure-python fallbacks (transport.py), the way the
reference's meson options degrade features — never a hard failure.
"""

from __future__ import annotations

import os
import subprocess
import threading
from typing import Optional

from nnstreamer_tpu.log import get_logger

_log = get_logger("edge.build")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
BUILD_DIR = os.path.join(_REPO_ROOT, "build")

# NNS_EDGE_SANITIZE=thread|address builds an instrumented variant (the
# race-detection story the reference lacks, SURVEY.md §5.2) — used by the
# concurrency stress test; separate .so name so normal runs stay fast.
SANITIZE = os.environ.get("NNS_EDGE_SANITIZE", "")
_suffix = f"_{SANITIZE}" if SANITIZE else ""

_lock = threading.Lock()
_cache: dict = {}  # source basename -> path | None (None = build failed)


def build_native(source_name: str, extra_flags=()) -> Optional[str]:
    """Compile native/<source_name> into build/lib<stem>.so (mtime-cached),
    honoring NNS_EDGE_SANITIZE. Returns None when the toolchain or source
    is unavailable (callers degrade gracefully); the failure is cached for
    the process lifetime."""
    src = os.path.join(_REPO_ROOT, "native", source_name)
    stem = os.path.splitext(source_name)[0]
    so = os.path.join(BUILD_DIR, f"lib{stem}{_suffix}.so")
    with _lock:
        if source_name in _cache:
            return _cache[source_name]
        result: Optional[str] = None
        if os.path.isfile(src):
            try:
                if not (
                    os.path.isfile(so)
                    and os.path.getmtime(so) >= os.path.getmtime(src)
                ):
                    os.makedirs(BUILD_DIR, exist_ok=True)
                    cmd = [
                        "g++", "-O2", "-std=c++17", "-fPIC", "-shared",
                        "-pthread", *extra_flags, src, "-o", so, "-lrt",
                    ]
                    if SANITIZE:
                        cmd[1:1] = [f"-fsanitize={SANITIZE}", "-g"]
                    subprocess.run(
                        cmd, check=True, capture_output=True, timeout=120
                    )
                    _log.info("built native lib: %s", so)
                result = so
            except (subprocess.SubprocessError, OSError) as exc:
                _log.warning("native build of %s failed: %s", source_name, exc)
        _cache[source_name] = result
        return result


def native_lib_path() -> Optional[str]:
    """The edge transport .so (compat wrapper over build_native)."""
    return build_native("nns_edge.cpp")
