"""On-demand g++ build of the native edge transport.

The reference links a prebuilt external nnstreamer-edge .so discovered via
pkg-config; here the native source ships in-tree (native/nns_edge.cpp) and
compiles once into a cached .so keyed on source mtime. A missing toolchain
degrades to the pure-python transport (transport.py), the way the
reference's meson options degrade features — never a hard failure.
"""

from __future__ import annotations

import os
import subprocess
import threading
from typing import Optional

from nnstreamer_tpu.log import get_logger

_log = get_logger("edge.build")
_lock = threading.Lock()
_cached: Optional[str] = None
_failed = False

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
SOURCE = os.path.join(_REPO_ROOT, "native", "nns_edge.cpp")
BUILD_DIR = os.path.join(_REPO_ROOT, "build")

# NNS_EDGE_SANITIZE=thread|address builds an instrumented variant (the
# race-detection story the reference lacks, SURVEY.md §5.2) — used by the
# concurrency stress test; separate .so name so normal runs stay fast.
SANITIZE = os.environ.get("NNS_EDGE_SANITIZE", "")
_suffix = f"_{SANITIZE}" if SANITIZE else ""
SO_PATH = os.path.join(BUILD_DIR, f"libnns_edge{_suffix}.so")


def native_lib_path() -> Optional[str]:
    """Compile (if stale) and return the .so path, or None if unavailable."""
    global _cached, _failed
    with _lock:
        if _cached:
            return _cached
        if _failed:
            return None
        if not os.path.isfile(SOURCE):
            _failed = True
            return None
        try:
            if not (
                os.path.isfile(SO_PATH)
                and os.path.getmtime(SO_PATH) >= os.path.getmtime(SOURCE)
            ):
                os.makedirs(BUILD_DIR, exist_ok=True)
                cmd = [
                    "g++", "-O2", "-std=c++17", "-fPIC", "-shared",
                    "-pthread", SOURCE, "-o", SO_PATH,
                ]
                if SANITIZE:
                    cmd[1:1] = [f"-fsanitize={SANITIZE}", "-g"]
                subprocess.run(
                    cmd, check=True, capture_output=True, timeout=120
                )
                _log.info("built native edge transport: %s", SO_PATH)
        except (subprocess.SubprocessError, OSError) as exc:
            _log.warning("native edge build failed (%s); using python transport", exc)
            _failed = True
            return None
        _cached = SO_PATH
        return _cached


# -- generic builder for other in-tree native components -------------------

_generic_lock = threading.Lock()
_generic_cache: dict = {}  # source basename -> path | None


def build_native(source_name: str, extra_flags=()) -> Optional[str]:
    """Compile native/<source_name> into build/lib<stem>.so (mtime-cached),
    honoring NNS_EDGE_SANITIZE like the edge transport. Returns None when
    the toolchain or source is unavailable (callers degrade gracefully)."""
    src = os.path.join(_REPO_ROOT, "native", source_name)
    stem = os.path.splitext(source_name)[0]
    so = os.path.join(BUILD_DIR, f"lib{stem}{_suffix}.so")
    with _generic_lock:
        if source_name in _generic_cache:
            return _generic_cache[source_name]
        result: Optional[str] = None
        if os.path.isfile(src):
            try:
                if not (
                    os.path.isfile(so)
                    and os.path.getmtime(so) >= os.path.getmtime(src)
                ):
                    os.makedirs(BUILD_DIR, exist_ok=True)
                    cmd = [
                        "g++", "-O2", "-std=c++17", "-fPIC", "-shared",
                        "-pthread", *extra_flags, src, "-o", so, "-lrt",
                    ]
                    if SANITIZE:
                        cmd[1:1] = [f"-fsanitize={SANITIZE}", "-g"]
                    subprocess.run(
                        cmd, check=True, capture_output=True, timeout=120
                    )
                    _log.info("built native lib: %s", so)
                result = so
            except (subprocess.SubprocessError, OSError) as exc:
                _log.warning("native build of %s failed: %s", source_name, exc)
        _generic_cache[source_name] = result
        return result
