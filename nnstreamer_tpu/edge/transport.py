"""Edge transport: length-prefixed blob streams over TCP.

API mirror of the external nnstreamer-edge library the reference's query/
edge elements use (nns_edge_create_handle/start/connect/send + event
callbacks, tensor_query_client.c:524-549,663-697). Two interchangeable
implementations behind one interface:

- :class:`NativeTransport` — ctypes binding to the in-tree C++ library
  (native/nns_edge.cpp, built on demand by _build.py). The product path.
- :class:`PyTransport` — pure-python sockets with identical framing, the
  fallback when no toolchain is available (and a cross-check in tests).

Framing on the wire: ``uint64_le length | payload``. A server tags each
message with the originating client id; ``send(0, ...)`` from a server
broadcasts (the pub/sub path of edgesink).
"""

from __future__ import annotations

import ctypes
import socket
import struct
import threading
import time
from collections import deque
from typing import Optional, Tuple

from nnstreamer_tpu.edge._build import native_lib_path

RecvResult = Optional[Tuple[int, bytes]]  # (client_id, payload); b"" = closed


class TransportError(RuntimeError):
    pass


class UnresolvableError(TransportError):
    """The target hostname no longer resolves (NXDOMAIN/EAI_*): a
    DISTINCT failure class — reconnect-with-backoff against a gone name
    burns the whole retry budget for nothing, so callers fail fast (or,
    in a fleet, eject the endpoint immediately) instead of retrying."""


def resolve_target(host: str, port: int) -> Tuple[str, int]:
    """Resolve ``host`` freshly (EVERY reconnect attempt must re-resolve
    — a failed-over DNS record points somewhere new, and the old A
    record may be the dead box). Returns the first (address, port);
    raises :class:`UnresolvableError` when the name does not resolve."""
    try:
        infos = socket.getaddrinfo(
            host, port, type=socket.SOCK_STREAM
        )
    except socket.gaierror as exc:
        raise UnresolvableError(
            f"cannot resolve {host!r}: {exc}"
        ) from exc
    if not infos:
        raise UnresolvableError(f"cannot resolve {host!r}: empty answer")
    addr = infos[0][4]
    return str(addr[0]), int(addr[1])


# --------------------------------------------------------------------- native
class _NativeLib:
    _instance = None
    _lock = threading.Lock()

    def __init__(self, path: str):
        lib = ctypes.CDLL(path)
        lib.nns_edge_create.restype = ctypes.c_void_p
        lib.nns_edge_listen.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
        lib.nns_edge_connect.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
        lib.nns_edge_get_port.argtypes = [ctypes.c_void_p]
        lib.nns_edge_peer_count.argtypes = [ctypes.c_void_p]
        lib.nns_edge_send.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
        ]
        lib.nns_edge_recv.restype = ctypes.c_int64
        lib.nns_edge_recv.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)), ctypes.c_int,
        ]
        lib.nns_edge_free_buf.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        lib.nns_edge_close.argtypes = [ctypes.c_void_p]
        self.lib = lib

    @classmethod
    def get(cls) -> Optional["_NativeLib"]:
        with cls._lock:
            if cls._instance is None:
                path = native_lib_path()
                if path is None:
                    return None
                cls._instance = cls(path)
            return cls._instance


class NativeTransport:
    """ctypes wrapper over the C++ handle (server or client role)."""

    def __init__(self) -> None:
        nl = _NativeLib.get()
        if nl is None:
            raise TransportError("native edge library unavailable")
        self._lib = nl.lib
        self._h = ctypes.c_void_p(self._lib.nns_edge_create())
        self._closed = False

    def listen(self, host: str = "127.0.0.1", port: int = 0) -> int:
        rc = self._lib.nns_edge_listen(self._h, host.encode(), port)
        if rc != 0:
            raise TransportError(f"listen({host}:{port}) failed rc={rc}")
        return self._lib.nns_edge_get_port(self._h)

    def connect(self, host: str, port: int) -> None:
        rc = self._lib.nns_edge_connect(self._h, host.encode(), port)
        if rc != 0:
            raise TransportError(f"connect({host}:{port}) failed rc={rc}")

    def send(self, client_id: int, data: bytes) -> None:
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        rc = self._lib.nns_edge_send(self._h, client_id, buf, len(data))
        if rc != 0:
            raise TransportError(f"send failed rc={rc}")

    def recv(self, timeout: Optional[float] = None) -> RecvResult:
        cid = ctypes.c_uint64()
        out = ctypes.POINTER(ctypes.c_uint8)()
        tmo = -1 if timeout is None else max(0, int(timeout * 1000))
        n = self._lib.nns_edge_recv(
            self._h, ctypes.byref(cid), ctypes.byref(out), tmo
        )
        if n < 0:
            return None
        if n == 0 and not out:
            return (cid.value, b"")  # connection-closed event
        data = ctypes.string_at(out, n)
        self._lib.nns_edge_free_buf(out)
        return (cid.value, data)

    def peer_count(self) -> int:
        return self._lib.nns_edge_peer_count(self._h)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._lib.nns_edge_close(self._h)


# --------------------------------------------------------------------- python
_LEN = struct.Struct("<Q")


class PyTransport:
    """Pure-python fallback; same wire framing and semantics.

    Connection admission (docs/edge-serving.md): a server with
    ``max_conns`` > 0 rejects accepts beyond the cap — the over-cap
    socket is sent ``reject_payload`` (one framed message, typically an
    admission NACK from edge/serialize.py) and closed, instead of
    silently holding a reader thread forever. ``rejected_conns`` counts
    them (acceptor-thread single-writer)."""

    max_conns = 0            # 0 = unbounded (instance attr overrides)
    reject_payload: Optional[bytes] = None
    connect_timeout = 10.0   # fleet clients shrink this: a blackholed
    #                          endpoint must not stall a whole request
    #                          deadline inside one connect()

    def __init__(self) -> None:
        self._is_server = False
        self._listen_sock: Optional[socket.socket] = None
        self._conns = {}
        self._next_id = 1
        self._conn_lock = threading.Lock()
        self._send_lock = threading.Lock()
        self._queue: deque = deque()
        self._q_cv = threading.Condition()
        self._threads = []
        self._running = False
        self.rejected_conns = 0

    # -- shared plumbing ---------------------------------------------------
    def _enqueue(self, cid: int, data: bytes) -> None:
        with self._q_cv:
            if len(self._queue) >= 4096:
                self._queue.popleft()
            self._queue.append((cid, data))
            self._q_cv.notify()

    def _reader(self, cid: int, sock: socket.socket) -> None:
        try:
            while True:
                hdr = self._read_exact(sock, _LEN.size)
                if hdr is None:
                    break
                (length,) = _LEN.unpack(hdr)
                payload = self._read_exact(sock, length) if length else b""
                if payload is None:
                    break
                self._enqueue(cid, payload)
        finally:
            with self._conn_lock:
                self._conns.pop(cid, None)
            try:
                sock.close()
            except OSError:
                pass
            if self._running:
                self._enqueue(cid, b"")  # closed event

    @staticmethod
    def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
        chunks = []
        while n > 0:
            try:
                c = sock.recv(n)
            except OSError:
                return None
            if not c:
                return None
            chunks.append(c)
            n -= len(c)
        return b"".join(chunks)

    def _acceptor(self) -> None:
        while self._running:
            try:
                sock, _ = self._listen_sock.accept()
            except OSError:
                break
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            over_cap = False
            with self._conn_lock:
                # reap finished readers so client churn can't grow the list
                self._threads = [t for t in self._threads if t.is_alive()]
                if self.max_conns and len(self._conns) >= self.max_conns:
                    over_cap = True
                else:
                    cid = self._next_id
                    self._next_id += 1
                    self._conns[cid] = sock
                    t = threading.Thread(
                        target=self._reader, args=(cid, sock), daemon=True
                    )
                    self._threads.append(t)
                    t.start()
            if over_cap:
                # reject on a short-lived thread: the NACK send can block
                # up to its 1 s timeout on a hostile/slow peer, and a
                # stream of over-cap connections must not serialize the
                # accept loop behind it (counter bumped HERE — acceptor
                # thread stays the single writer)
                self.rejected_conns += 1
                t = threading.Thread(
                    target=self._reject_conn, args=(sock,), daemon=True
                )
                with self._conn_lock:
                    self._threads.append(t)
                t.start()

    def _reject_conn(self, sock: socket.socket) -> None:
        try:
            payload = self.reject_payload
            if payload:
                sock.settimeout(1.0)
                sock.sendall(_LEN.pack(len(payload)) + payload)
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    # -- public API --------------------------------------------------------
    def listen(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._listen_sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen_sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen_sock.bind((host, port))
        self._listen_sock.listen(64)
        self._is_server = True
        self._running = True
        t = threading.Thread(target=self._acceptor, daemon=True)
        self._threads.append(t)
        t.start()
        return self._listen_sock.getsockname()[1]

    def connect(self, host: str, port: int) -> None:
        # create_connection re-resolves `host` on every call by design:
        # a reconnect after failover must chase the CURRENT record
        try:
            sock = socket.create_connection(
                (host, port), timeout=self.connect_timeout
            )
        except socket.gaierror as exc:
            raise UnresolvableError(
                f"cannot resolve {host!r}: {exc}"
            ) from exc
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(None)
        self._running = True
        with self._conn_lock:
            self._conns[0] = sock
            t = threading.Thread(target=self._reader, args=(0, sock), daemon=True)
            self._threads.append(t)
            t.start()

    def send(self, client_id: int, data: bytes) -> None:
        broadcast = self._is_server and client_id == 0
        with self._conn_lock:
            if broadcast:
                socks = list(self._conns.values())
            else:
                key = client_id if self._is_server else 0
                if key not in self._conns:
                    raise TransportError(f"no connection {key}")
                socks = [self._conns[key]]
        msg = _LEN.pack(len(data)) + data
        with self._send_lock:
            for s in socks:
                try:
                    s.sendall(msg)
                except OSError as exc:
                    # broadcast is best-effort: a dead subscriber is skipped
                    # (its reader thread prunes the connection); a directed
                    # send failure is the caller's error
                    if not broadcast:
                        raise TransportError(f"send failed: {exc}") from exc

    def recv(self, timeout: Optional[float] = None) -> RecvResult:
        with self._q_cv:
            if not self._q_cv.wait_for(
                lambda: self._queue or not self._running, timeout=timeout
            ):
                return None
            if not self._queue:
                return None
            return self._queue.popleft()

    def peer_count(self) -> int:
        with self._conn_lock:
            return len(self._conns)

    def close(self) -> None:
        self._running = False
        if self._listen_sock is not None:
            try:
                # shutdown BEFORE close: closing a listening socket does
                # not reliably wake a thread blocked in accept() (the fd
                # stays referenced); shutdown forces accept to return
                self._listen_sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listen_sock.close()
            except OSError:
                pass
        with self._conn_lock:
            for s in self._conns.values():
                try:
                    s.shutdown(socket.SHUT_RDWR)
                    s.close()
                except OSError:
                    pass
            self._conns.clear()
            threads = list(self._threads)
        with self._q_cv:
            self._q_cv.notify_all()
        # join the acceptor/readers under one bounded budget: their
        # sockets just closed, so they exit promptly — and a server
        # torn down by Executor.stop() must not read as a thread leak
        # merely because the sweep ran before the daemons noticed
        me = threading.current_thread()
        deadline = time.monotonic() + 2.0
        for t in threads:
            if t is me:
                continue
            t.join(timeout=max(0.05, deadline - time.monotonic()))


class ChaosCounter:
    """Mutable send counter shared across reconnects, so the injection
    schedule stays deterministic when the wrapped transport is rebuilt
    (the client reconnect path replaces its transport object)."""

    __slots__ = ("n",)

    def __init__(self) -> None:
        self.n = 0


class ChaosTransport:
    """Deterministic network-fault injector wrapping a query transport
    (docs/fault-tolerance.md, docs/edge-serving.md): the chaos harness's
    answer to "does the NACK/reconnect machinery actually work" without
    waiting for real packet loss.

    - ``drop_every_n``: every Nth send severs the connection mid-stream
      (the inner transport is closed, the send raises TransportError) —
      exercising the client's reconnect-with-backoff and the server's
      disconnect bookkeeping.
    - ``truncate_every_n``: every Nth send transmits a truncated edge
      header instead of the payload (framing intact, message garbage) —
      the server answers with a structured ``malformed`` NACK and the
      client retries.

    Counting is shared via :class:`ChaosCounter` so schedules survive
    the reconnects they themselves cause."""

    def __init__(self, inner, counter: Optional[ChaosCounter] = None,
                 drop_every_n: int = 0, truncate_every_n: int = 0) -> None:
        self.inner = inner
        self.counter = counter if counter is not None else ChaosCounter()
        self.drop_every_n = max(0, int(drop_every_n))
        self.truncate_every_n = max(0, int(truncate_every_n))

    # -- fault injection on the send path ----------------------------------
    def send(self, client_id: int, data: bytes) -> None:
        self.counter.n += 1
        n = self.counter.n
        if self.drop_every_n and n % self.drop_every_n == 0:
            self.inner.close()
            raise TransportError(
                f"chaos: connection dropped mid-stream (send {n})"
            )
        if self.truncate_every_n and n % self.truncate_every_n == 0:
            # a well-framed message whose edge header is cut short: the
            # peer's decode_message raises, never mis-parses
            self.inner.send(client_id, data[: min(len(data), 6)])
            return
        self.inner.send(client_id, data)

    # -- passthrough -------------------------------------------------------
    def listen(self, host: str = "127.0.0.1", port: int = 0) -> int:
        return self.inner.listen(host, port)

    def connect(self, host: str, port: int) -> None:
        self.inner.connect(host, port)

    def recv(self, timeout: Optional[float] = None) -> RecvResult:
        return self.inner.recv(timeout=timeout)

    def peer_count(self) -> int:
        return self.inner.peer_count()

    def close(self) -> None:
        self.inner.close()


def make_transport(prefer_native: bool = True):
    """Factory: native C++ transport when buildable, else python sockets."""
    if prefer_native:
        try:
            return NativeTransport()
        except TransportError:
            pass
    return PyTransport()
