"""SNTP client: cross-device clock alignment for MQTT pub/sub.

Reference: gst/mqtt/ntputil.c + Documentation/synchronization-in-mqtt-
elements.md — mqttsink stamps messages with an NTP-derived epoch so
mqttsrc on another device can rebase timestamps onto its own clock.
This is a dependency-free SNTPv4 (RFC 4330) unicast query: one 48-byte
UDP exchange → clock offset vs the server. ``walltime()`` returns local
epoch time corrected by the last measured offset; with no server
configured/reachable it falls back to the local clock (same degradation
the reference has when its NTP pool is unreachable).
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Optional, Sequence

NTP_PORT = 123
# seconds between the NTP epoch (1900) and the unix epoch (1970)
NTP_UNIX_DELTA = 2208988800

_lock = threading.Lock()
_offset: float = 0.0
_synced: bool = False


def query_offset(host: str, port: int = NTP_PORT, timeout: float = 2.0) -> float:
    """One SNTP exchange → (server_time - local_time) in seconds.
    Raises OSError on network failure."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.settimeout(timeout)
    try:
        # LI=0 VN=4 Mode=3 (client)
        pkt = bytearray(48)
        pkt[0] = (4 << 3) | 3
        t1 = time.time()
        origin = t1 + NTP_UNIX_DELTA
        struct.pack_into(">I", pkt, 40, int(origin))
        struct.pack_into(">I", pkt, 44, int((origin % 1) * (1 << 32)))
        sock.sendto(bytes(pkt), (host, port))
        data, _ = sock.recvfrom(48)
        t4 = time.time()
        if len(data) < 48:
            raise OSError(f"short NTP response ({len(data)} bytes)")

        def ts(offset: int) -> float:
            secs, frac = struct.unpack_from(">II", data, offset)
            return secs + frac / (1 << 32) - NTP_UNIX_DELTA

        t2 = ts(32)  # receive timestamp
        t3 = ts(40)  # transmit timestamp
        # RFC 4330 offset: ((t2 - t1) + (t3 - t4)) / 2
        return ((t2 - t1) + (t3 - t4)) / 2.0
    finally:
        sock.close()


def sync(
    servers: Sequence[str] = ("pool.ntp.org",),
    port: int = NTP_PORT,
    timeout: float = 2.0,
) -> bool:
    """Measure and install the global offset from the first reachable
    server. Returns True on success, False if none answered."""
    global _offset, _synced
    for host in servers:
        try:
            off = query_offset(host, port, timeout)
        except OSError:
            continue
        with _lock:
            _offset = off
            _synced = True
        return True
    return False


def set_offset(offset: float) -> None:
    """Install an externally-determined offset (tests; pre-synced hosts)."""
    global _offset, _synced
    with _lock:
        _offset = offset
        _synced = True


def reset() -> None:
    global _offset, _synced
    with _lock:
        _offset = 0.0
        _synced = False


def is_synced() -> bool:
    return _synced


def walltime() -> float:
    """Epoch seconds on the shared (NTP) timescale."""
    with _lock:
        return time.time() + _offset
