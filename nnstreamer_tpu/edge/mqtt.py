"""Minimal MQTT 3.1.1 client + in-process broker (QoS 0).

Reference: gst/mqtt/mqttsink.c / mqttsrc.c publish/subscribe GstBuffers via
paho-mqtt-c against an external broker (mqttsink.c:29). This framework
vendors the protocol subset those elements actually use — CONNECT/CONNACK,
PUBLISH (QoS 0), SUBSCRIBE/SUBACK with +/# topic filters, PING, DISCONNECT
— as a dependency-free stdlib-socket client, plus a tiny broker so
single-host tests and demos run self-contained (the reference's test suite
skips when no broker is installed, tests/check_broker.sh; ours never has
to). Point the client at any real MQTT 3.1.1 broker (mosquitto, EMQX) for
production fan-out.

Wire format notes (MQTT 3.1.1, OASIS spec): fixed header = packet type
nibble + flags nibble, then varint "remaining length"; strings are
big-endian u16-length-prefixed UTF-8.
"""

from __future__ import annotations

import queue as queue_mod
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

CONNECT, CONNACK, PUBLISH, SUBSCRIBE, SUBACK, UNSUBSCRIBE, UNSUBACK = (
    1, 2, 3, 8, 9, 10, 11,
)
PUBACK, PUBREC, PUBREL, PUBCOMP = 4, 5, 6, 7
PINGREQ, PINGRESP, DISCONNECT = 12, 13, 14

DEFAULT_PORT = 1883


class MqttError(RuntimeError):
    pass


# -- encoding helpers -------------------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n % 128
        n //= 128
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _string(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">H", len(b)) + b


def _packet(ptype: int, flags: int, payload: bytes) -> bytes:
    return bytes([(ptype << 4) | flags]) + _varint(len(payload)) + payload


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise MqttError("connection closed")
        buf += chunk
    return buf


def _read_packet(sock: socket.socket) -> Tuple[int, int, bytes]:
    head = _read_exact(sock, 1)[0]
    length, mult = 0, 1
    for _ in range(4):
        b = _read_exact(sock, 1)[0]
        length += (b & 0x7F) * mult
        if not (b & 0x80):
            break
        mult *= 128
    else:
        raise MqttError("malformed remaining-length")
    payload = _read_exact(sock, length) if length else b""
    return head >> 4, head & 0x0F, payload


def topic_matches(pattern: str, topic: str) -> bool:
    """MQTT filter match: '+' one level, '#' rest (must be last)."""
    pp = pattern.split("/")
    tp = topic.split("/")
    for i, p in enumerate(pp):
        if p == "#":
            return i == len(pp) - 1
        if i >= len(tp):
            return False
        if p != "+" and p != tp[i]:
            return False
    return len(pp) == len(tp)


# -- client -----------------------------------------------------------------

class MqttClient:
    """QoS-0 client. on_message(topic, payload) runs on the reader thread;
    alternatively recv() pulls from an internal queue."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = DEFAULT_PORT,
        client_id: str = "",
        keepalive: int = 60,
        on_message: Optional[Callable[[str, bytes], None]] = None,
    ) -> None:
        self.host, self.port = host, port
        self.client_id = client_id or f"nns-tpu-{id(self):x}"
        self.keepalive = keepalive
        self.on_message = on_message
        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._queue: "queue_mod.Queue" = queue_mod.Queue(maxsize=1024)
        self._reader: Optional[threading.Thread] = None
        self._pinger: Optional[threading.Thread] = None
        self._running = threading.Event()
        self._packet_id = 0

    # -- lifecycle
    def connect(self, timeout: float = 10.0) -> "MqttClient":
        sock = socket.create_connection((self.host, self.port), timeout=timeout)
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        var = (
            _string("MQTT") + bytes([4])  # protocol level 3.1.1
            + bytes([0x02])  # clean session
            + struct.pack(">H", self.keepalive)
        )
        sock.sendall(_packet(CONNECT, 0, var + _string(self.client_id)))
        ptype, _, payload = _read_packet(sock)
        if ptype != CONNACK or len(payload) < 2 or payload[1] != 0:
            sock.close()
            raise MqttError(f"CONNACK refused: {payload!r}")
        self._sock = sock
        self._running.set()
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()
        if self.keepalive:
            self._pinger = threading.Thread(target=self._ping_loop, daemon=True)
            self._pinger.start()
        return self

    def close(self) -> None:
        self._running.clear()
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                with self._send_lock:
                    sock.sendall(_packet(DISCONNECT, 0, b""))
            except OSError:
                pass
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            sock.close()
        if self._reader is not None:
            self._reader.join(timeout=2)

    # -- ops
    def publish(self, topic: str, payload: bytes) -> None:
        sock = self._sock
        if sock is None:
            raise MqttError("not connected")
        pkt = _packet(PUBLISH, 0, _string(topic) + payload)
        with self._send_lock:
            sock.sendall(pkt)

    def subscribe(self, topic_filter: str) -> None:
        sock = self._sock
        if sock is None:
            raise MqttError("not connected")
        self._packet_id = (self._packet_id % 0xFFFF) + 1
        payload = struct.pack(">H", self._packet_id) + _string(topic_filter) + bytes([0])
        with self._send_lock:
            sock.sendall(_packet(SUBSCRIBE, 0x02, payload))

    def recv(self, timeout: Optional[float] = None) -> Optional[Tuple[str, bytes]]:
        """Next (topic, payload), or None on timeout."""
        try:
            return self._queue.get(timeout=timeout)
        except queue_mod.Empty:
            return None

    # -- loops
    def _read_loop(self) -> None:
        try:
            while self._running.is_set():
                sock = self._sock
                if sock is None:
                    return
                ptype, _flags, payload = _read_packet(sock)
                if ptype == PUBLISH:
                    tlen = struct.unpack(">H", payload[:2])[0]
                    topic = payload[2 : 2 + tlen].decode()
                    body = payload[2 + tlen :]
                    if self.on_message is not None:
                        self.on_message(topic, body)
                    else:
                        if self._queue.full():  # drop-oldest backpressure
                            try:
                                self._queue.get_nowait()
                            except queue_mod.Empty:
                                pass
                        self._queue.put((topic, body))
                # SUBACK/PINGRESP need no action at QoS 0
        except (MqttError, OSError):
            pass

    def _ping_loop(self) -> None:
        interval = max(self.keepalive / 2.0, 1.0)
        while self._running.is_set():
            time.sleep(interval)
            sock = self._sock
            if sock is None:
                return
            try:
                with self._send_lock:
                    sock.sendall(_packet(PINGREQ, 0, b""))
            except OSError:
                return


# -- broker -----------------------------------------------------------------

class MqttBroker:
    """In-process QoS-0 broker: CONNECT handshake, SUBSCRIBE bookkeeping,
    PUBLISH fan-out with wildcard matching. Port 0 = ephemeral."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.host = host
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind((host, port))
        self._listen.listen(32)
        self.port = self._listen.getsockname()[1]
        self._lock = threading.Lock()
        # sock -> (send_lock, [topic filters])
        self._clients: Dict[socket.socket, Tuple[threading.Lock, List[str]]] = {}
        self._running = threading.Event()
        self._running.set()
        self._acceptor = threading.Thread(target=self._accept_loop, daemon=True)
        self._acceptor.start()

    def close(self) -> None:
        self._running.clear()
        try:
            self._listen.close()
        except OSError:
            pass
        with self._lock:
            socks = list(self._clients)
            self._clients.clear()
        for s in socks:
            try:
                s.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while self._running.is_set():
            try:
                sock, _ = self._listen.accept()
            except OSError:
                return
            threading.Thread(
                target=self._client_loop, args=(sock,), daemon=True
            ).start()

    def _client_loop(self, sock: socket.socket) -> None:
        # All writes to this socket go through send_lock: _fanout delivers
        # PUBLISHes from publisher threads concurrently with the acks sent
        # here, and interleaved sendall calls would corrupt MQTT framing.
        send_lock = threading.Lock()

        def _send(pkt: bytes) -> None:
            with send_lock:
                sock.sendall(pkt)

        try:
            ptype, _, _payload = _read_packet(sock)
            if ptype != CONNECT:
                sock.close()
                return
            with self._lock:
                self._clients[sock] = (send_lock, [])
            _send(_packet(CONNACK, 0, bytes([0, 0])))
            while self._running.is_set():
                ptype, flags, payload = _read_packet(sock)
                if ptype == PUBLISH:
                    qos = (flags >> 1) & 0x3
                    tlen = struct.unpack(">H", payload[:2])[0]
                    topic = payload[2 : 2 + tlen].decode()
                    if qos:
                        # QoS 1/2 publishes carry a packet id after the
                        # topic; strip it before fan-out and acknowledge
                        # (delivery to subscribers stays at-most-once).
                        pid = payload[2 + tlen : 4 + tlen]
                        payload = payload[: 2 + tlen] + payload[4 + tlen :]
                        _send(_packet(PUBACK if qos == 1 else PUBREC, 0, pid))
                    self._fanout(topic, payload, exclude=None)
                elif ptype == PUBREL:
                    _send(_packet(PUBCOMP, 0, payload[:2]))
                elif ptype == SUBSCRIBE:
                    pid = payload[:2]
                    pos, filters = 2, []
                    while pos < len(payload):
                        flen = struct.unpack(">H", payload[pos : pos + 2])[0]
                        filters.append(payload[pos + 2 : pos + 2 + flen].decode())
                        pos += 2 + flen + 1  # + requested QoS byte
                    with self._lock:
                        if sock in self._clients:
                            self._clients[sock][1].extend(filters)
                    _send(_packet(SUBACK, 0, pid + bytes([0] * len(filters))))
                elif ptype == PINGREQ:
                    _send(_packet(PINGRESP, 0, b""))
                elif ptype == DISCONNECT:
                    break
        except (MqttError, OSError):
            pass
        finally:
            with self._lock:
                self._clients.pop(sock, None)
            try:
                sock.close()
            except OSError:
                pass

    def _fanout(self, topic: str, publish_payload: bytes, exclude) -> None:
        pkt = _packet(PUBLISH, 0, publish_payload)
        with self._lock:
            targets = [
                (s, lk)
                for s, (lk, filters) in self._clients.items()
                if s is not exclude and any(topic_matches(f, topic) for f in filters)
            ]
        for s, lk in targets:
            try:
                with lk:
                    s.sendall(pkt)
            except OSError:
                pass  # dead subscriber: its loop cleans up
