"""mqttsink / mqttsrc: pub/sub tensor streams through an MQTT broker.

Reference: gst/mqtt/mqttsink.c (1406 LoC) / mqttsrc.c (1423) — publish
arbitrary buffers to ``pub-topic``, subscribe on ``sub-topic``, with the
message header carrying the sender's NTP-aligned send time so receivers on
other devices can rebase timestamps (Documentation/synchronization-in-
mqtt-elements.md, gst/mqtt/ntputil.c → edge/ntp.py here).

Message layout: ``<B d q`` (version, sent-walltime epoch-s on the NTP
timescale, reserved) + the edge frame codec (edge/serialize.py — the caps
equivalent travels in the flexible-tensor headers, like the reference
smuggles caps in its message header). Broker: any MQTT 3.1.1 QoS-0 broker;
the in-repo ``edge.mqtt.MqttBroker`` makes tests/demos self-contained.

Received frames carry meta: ``mqtt_sent_time`` (sender walltime) and
``mqtt_transit_s`` (receiver walltime − send time; ≈ network+broker
latency when both ends are NTP-synced).
"""

from __future__ import annotations

import struct
from typing import Optional

from nnstreamer_tpu import registry
from nnstreamer_tpu.edge import ntp
from nnstreamer_tpu.edge.mqtt import DEFAULT_PORT, MqttClient, MqttError
from nnstreamer_tpu.edge.serialize import decode_message, encode_message
from nnstreamer_tpu.elements.base import (
    _parse_bool,
    ElementError,
    PropSpec,
    Sink,
    Source,
    Spec,
)
from nnstreamer_tpu.tensors.frame import EOS, EOS_FRAME, Frame
from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec

_MSG_HDR = struct.Struct("<Bdq")
_MSG_VERSION = 1


def _wrap(payload: bytes) -> bytes:
    return _MSG_HDR.pack(_MSG_VERSION, ntp.walltime(), 0) + payload


def _unwrap(data: bytes):
    if len(data) < _MSG_HDR.size:
        raise ValueError(f"mqtt message too short: {len(data)}")
    version, sent, _ = _MSG_HDR.unpack_from(data)
    if version != _MSG_VERSION:
        raise ValueError(f"unsupported mqtt message version {version}")
    return sent, data[_MSG_HDR.size :]


def _maybe_ntp_sync(element, enabled: bool) -> None:
    """Best-effort one-shot SNTP sync (reference resyncs periodically; the
    offset is process-global so one sync serves all elements)."""
    if not enabled or ntp.is_synced():
        return
    servers = str(element.get_property("ntp-servers", "pool.ntp.org"))
    ntp.sync([s for s in servers.split(",") if s], timeout=2.0)


@registry.element("mqttsink")
class MqttSink(Sink):
    """Props: host, port (broker), pub-topic (required), ntp-sync (bool),
    ntp-servers (comma list), client-id."""

    FACTORY_NAME = "mqttsink"

    PROPERTIES = {
        "host": PropSpec("str", "127.0.0.1", desc="broker host"),
        "port": PropSpec("int", 1883, desc="broker port"),
        "pub-topic": PropSpec("str", "", desc="required"),
        "ntp-sync": PropSpec("bool", False),
        "ntp-servers": PropSpec("str", "pool.ntp.org", desc="comma list"),
        "client-id": PropSpec("str", ""),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.host = str(self.get_property("host", "127.0.0.1"))
        self.port = int(self.get_property("port", DEFAULT_PORT))
        self.topic = str(self.get_property("pub-topic", ""))
        if not self.topic:
            raise ValueError(f"{self.name}: mqttsink needs pub-topic=")
        self.ntp_sync = _parse_bool(self.get_property("ntp-sync", False))
        self._client: Optional[MqttClient] = None

    def start(self) -> None:
        _maybe_ntp_sync(self, self.ntp_sync)
        try:
            self._client = MqttClient(
                self.host, self.port,
                client_id=str(self.get_property("client-id", "")),
            ).connect()
        except (MqttError, OSError) as exc:
            raise ElementError(
                f"{self.name}: cannot reach MQTT broker "
                f"{self.host}:{self.port}: {exc}"
            ) from exc

    def stop(self) -> None:
        client, self._client = self._client, None
        if client is not None:
            try:
                client.publish(self.topic, _wrap(encode_message(EOS_FRAME)))
            except (MqttError, OSError):
                pass
            client.close()

    def render(self, frame: Frame) -> None:
        if self._client is None:
            raise ElementError(f"{self.name}: not started")
        try:
            self._client.publish(self.topic, _wrap(encode_message(frame)))
        except (MqttError, OSError) as exc:
            raise ElementError(f"{self.name}: publish failed: {exc}") from exc

    def on_eos(self) -> None:
        if self._client is not None:
            try:
                self._client.publish(self.topic, _wrap(encode_message(EOS_FRAME)))
            except (MqttError, OSError):
                pass


@registry.element("mqttsrc")
class MqttSrc(Source):
    """Props: host, port (broker), sub-topic (required, wildcards ok),
    ntp-sync, ntp-servers, client-id."""

    FACTORY_NAME = "mqttsrc"

    PROPERTIES = {
        "host": PropSpec("str", "127.0.0.1", desc="broker host"),
        "port": PropSpec("int", 1883, desc="broker port"),
        "sub-topic": PropSpec("str", "", desc="required; wildcards ok"),
        "ntp-sync": PropSpec("bool", False),
        "ntp-servers": PropSpec("str", "pool.ntp.org", desc="comma list"),
        "client-id": PropSpec("str", ""),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.host = str(self.get_property("host", "127.0.0.1"))
        self.port = int(self.get_property("port", DEFAULT_PORT))
        self.topic = str(self.get_property("sub-topic", ""))
        if not self.topic:
            raise ValueError(f"{self.name}: mqttsrc needs sub-topic=")
        self.ntp_sync = _parse_bool(self.get_property("ntp-sync", False))
        self._client: Optional[MqttClient] = None

    def output_spec(self) -> Spec:
        return TensorsSpec(format=TensorFormat.FLEXIBLE)

    def start(self) -> None:
        _maybe_ntp_sync(self, self.ntp_sync)
        try:
            self._client = MqttClient(
                self.host, self.port,
                client_id=str(self.get_property("client-id", "")),
            ).connect()
            self._client.subscribe(self.topic)
        except (MqttError, OSError) as exc:
            raise ElementError(
                f"{self.name}: cannot reach MQTT broker "
                f"{self.host}:{self.port}: {exc}"
            ) from exc

    def stop(self) -> None:
        client, self._client = self._client, None
        if client is not None:
            client.close()

    def generate(self):
        got = self._client.recv(timeout=0.1)
        if got is None:
            return None
        _topic, data = got
        try:
            sent, payload = _unwrap(data)
            msg = decode_message(payload)
        except ValueError as exc:
            raise ElementError(f"{self.name}: bad message: {exc}") from exc
        if isinstance(msg, EOS):
            return EOS_FRAME
        now = ntp.walltime()
        return msg.with_meta(mqtt_sent_time=sent, mqtt_transit_s=now - sent)
