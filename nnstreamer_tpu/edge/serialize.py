"""Frame ↔ wire-message codec for the edge layer.

The payload the transports carry: a small frame header followed by the
flexible-tensor encoding (tensors/meta.py — the same self-describing header
the reference uses for format=flexible streams and its edge serialization,
SURVEY.md §5.8).

Layout (little-endian):

    uint8  version (2; v1 — no meta blob, flags always 0 — still decodes)
    uint8  kind    (0 = DATA, 1 = EOS, 2 = NACK, 3 = CTRL)
    int64  pts     (ns; -1 = unknown)
    int64  duration(ns; -1 = unknown)
    uint32 flags   (bit 0: a meta blob follows the header)
    [uint32 meta_len + UTF-8 JSON meta blob, when flags bit 0]
    [flex tensors...]

The meta blob is the distributed-correlation channel (docs/
observability.md): JSON-scalar frame meta — notably the ``frame_id``
tensor_query_client stamps — crosses tensor_query/edgesrc hops, so the
client's trace span and the server-side spans for the same frame share
an identity and ``trace.merge()`` can line them up on one timeline.
Per-hop-local keys (``client_id``, the transport pairing tag;
``wall_t0``, a perf_counter reading meaningless in another process;
``admit_t``, the server's local admission stamp; ``_nns_srv``, the
serversrc pairing key) never ride the wire.

``KIND_NACK`` (docs/edge-serving.md) is the admission layer's explicit
rejection: no tensors, just a meta blob carrying ``nack_reason``
(max-clients / overload / client-backpressure / rate / malformed /
deadline / failed), a ``retry_after_ms`` hint, and — when known — the
``frame_id`` of the rejected request. ``decode_message`` returns it as
a :class:`Nack` so clients can back off instead of timing out.
"""

from __future__ import annotations

import json
import struct

from nnstreamer_tpu.tensors.frame import EOS, EOS_FRAME, Frame
from nnstreamer_tpu.tensors.meta import decode_frame_tensors, encode_frame_tensors

_HDR = struct.Struct("<BBqqI")
_META_LEN = struct.Struct("<I")
# v2 added the flagged meta blob; v1 messages (reserved field always 0)
# decode through the same path, and a v1 peer receiving v2 fails with a
# clean unsupported-version error instead of mis-parsing the blob as
# tensor data
VERSION = 2
_DECODABLE_VERSIONS = (1, 2)
KIND_DATA = 0
KIND_EOS = 1
KIND_NACK = 2
# control channel (docs/edge-serving.md "Running a fleet"): an operator
# message to the serving plane rather than a request — ``drain``
# (graceful drain for rolling restarts) and the ``migrate_*`` live-
# migration handshake. Same framing as a NACK: the meta blob
# (``ctrl_op``) instead of tensors, plus optional opaque payload bytes
# after it (the KV span). Both ends of this protocol live in-tree, so
# no version bump is needed.
KIND_CTRL = 3
FLAG_META = 1

# meta keys that must NOT cross a hop: local to the process that set them
_WIRE_META_SKIP = frozenset({
    "client_id", "wall_t0", "admit_t", "_nns_srv", "_nns_budget_released",
})

#: request-meta key carrying the prompt's rolling-CRC prefix keys as one
#: dot-joined hex string (``_wire_meta`` keeps scalars only, so the list
#: rides flattened). Stamped by the fleet client, echoed back in the
#: reply meta like any propagatable key — the PrefixRouter learns which
#: endpoint answered which prefix from the echo (docs/edge-serving.md
#: "Prefix-aware routing").
ROUTE_META_KEY = "_nns_pfx"


class Nack:
    """A structured rejection from the serving plane (docs/
    edge-serving.md): the request was NOT processed; ``retry_after_ms``
    hints when a retry might be admitted (reason ``deadline`` is the one
    terminal NACK — the request was admitted but shed)."""

    __slots__ = ("reason", "retry_after_ms", "frame_id")

    def __init__(self, reason: str, retry_after_ms: float = 0.0,
                 frame_id=None) -> None:
        self.reason = reason
        self.retry_after_ms = float(retry_after_ms)
        self.frame_id = frame_id

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return (
            f"Nack(reason={self.reason!r}, "
            f"retry_after_ms={self.retry_after_ms})"
        )


class Ctrl:
    """A control message to the serving plane (``KIND_CTRL``):
    ``op == "drain"`` (stop accepting new work, NACK new submits
    ``draining``, finish the admitted in-flight, then quiesce) and the
    live-migration handshake (docs/llm-serving.md "Migration &
    recovery"): ``migrate_probe`` / ``migrate_probe_ack`` (prefix
    coverage query before shipping), ``migrate_span`` /
    ``migrate_span_ack`` (the KV span itself riding ``payload``), and
    the disaggregated-serving poll (docs/llm-serving.md "Disaggregated
    serving"): ``disagg_fetch`` / ``disagg_fetch_ack`` — the prefill
    server collecting a handed-off generation's finished tokens from
    its decode peer. ``payload`` is opaque trailing bytes after the
    meta blob — v1/v2 decoders ignored trailing CTRL bytes, so no
    version bump."""

    __slots__ = ("op", "meta", "payload")

    def __init__(self, op: str, meta=None, payload: bytes = b"") -> None:
        self.op = op
        self.meta = meta or {}
        self.payload = payload

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"Ctrl(op={self.op!r})"


def encode_ctrl(op: str, payload: bytes = b"", **extra) -> bytes:
    meta = {"ctrl_op": str(op)}
    meta.update(extra)
    enc = json.dumps(meta, separators=(",", ":")).encode()
    return (
        _HDR.pack(VERSION, KIND_CTRL, -1, -1, FLAG_META)
        + _META_LEN.pack(len(enc)) + enc + payload
    )


def encode_nack(reason: str, retry_after_ms: float = 0.0,
                frame_id=None) -> bytes:
    meta = {"nack_reason": reason, "retry_after_ms": float(retry_after_ms)}
    if frame_id is not None:
        meta["frame_id"] = frame_id
    enc = json.dumps(meta, separators=(",", ":")).encode()
    return (
        _HDR.pack(VERSION, KIND_NACK, -1, -1, FLAG_META)
        + _META_LEN.pack(len(enc)) + enc
    )


def _wire_meta(frame) -> dict:
    """The JSON-safe, propagatable subset of a frame's meta."""
    out = {}
    for k, v in frame.meta.items():
        if k in _WIRE_META_SKIP:
            continue
        if v is None or isinstance(v, (str, int, float, bool)):
            out[k] = v
    return out


def encode_message(frame) -> bytes:
    if isinstance(frame, EOS):
        return _HDR.pack(VERSION, KIND_EOS, -1, -1, 0)
    pts = -1 if frame.pts is None else frame.pts
    dur = -1 if frame.duration is None else frame.duration
    host = frame.to_host()
    meta = _wire_meta(frame)
    flags = FLAG_META if meta else 0
    blob = b""
    if meta:
        enc = json.dumps(meta, separators=(",", ":")).encode()
        blob = _META_LEN.pack(len(enc)) + enc
    return (
        _HDR.pack(VERSION, KIND_DATA, pts, dur, flags)
        + blob
        + encode_frame_tensors(host.tensors)
    )


def decode_message(data: bytes):
    """→ Frame, EOS_FRAME, :class:`Nack`, or :class:`Ctrl`. Raises
    ValueError on malformed input."""
    if len(data) < _HDR.size:
        raise ValueError(f"edge message too short: {len(data)}")
    version, kind, pts, dur, flags = _HDR.unpack_from(data)
    if version not in _DECODABLE_VERSIONS:
        raise ValueError(f"unsupported edge message version {version}")
    if kind == KIND_EOS:
        return EOS_FRAME
    off = _HDR.size
    meta = {}
    if flags & FLAG_META:
        if len(data) < off + _META_LEN.size:
            raise ValueError("edge message meta length truncated")
        (meta_len,) = _META_LEN.unpack_from(data, off)
        off += _META_LEN.size
        if len(data) < off + meta_len:
            raise ValueError("edge message meta blob truncated")
        try:
            meta = json.loads(data[off:off + meta_len])
        except (UnicodeDecodeError, json.JSONDecodeError) as exc:
            raise ValueError(
                f"edge message meta not valid JSON: {exc}"
            ) from exc
        if not isinstance(meta, dict):
            raise ValueError("edge message meta is not an object")
        off += meta_len
    if kind == KIND_NACK:
        return Nack(
            str(meta.get("nack_reason", "unspecified")),
            float(meta.get("retry_after_ms", 0.0) or 0.0),
            meta.get("frame_id"),
        )
    if kind == KIND_CTRL:
        return Ctrl(str(meta.get("ctrl_op", "")), meta, data[off:])
    tensors = decode_frame_tensors(data[off:])
    return Frame(
        tensors,
        pts=None if pts < 0 else pts,
        duration=None if dur < 0 else dur,
        meta=meta,
    )
