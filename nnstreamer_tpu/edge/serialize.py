"""Frame ↔ wire-message codec for the edge layer.

The payload the transports carry: a small frame header followed by the
flexible-tensor encoding (tensors/meta.py — the same self-describing header
the reference uses for format=flexible streams and its edge serialization,
SURVEY.md §5.8).

Layout (little-endian):

    uint8  version (1)
    uint8  kind    (0 = DATA, 1 = EOS)
    int64  pts     (ns; -1 = unknown)
    int64  duration(ns; -1 = unknown)
    uint32 reserved
    [flex tensors...]
"""

from __future__ import annotations

import struct
from typing import Optional

from nnstreamer_tpu.tensors.frame import EOS, EOS_FRAME, Frame
from nnstreamer_tpu.tensors.meta import decode_frame_tensors, encode_frame_tensors

_HDR = struct.Struct("<BBqqI")
VERSION = 1
KIND_DATA = 0
KIND_EOS = 1


def encode_message(frame) -> bytes:
    if isinstance(frame, EOS):
        return _HDR.pack(VERSION, KIND_EOS, -1, -1, 0)
    pts = -1 if frame.pts is None else frame.pts
    dur = -1 if frame.duration is None else frame.duration
    host = frame.to_host()
    return _HDR.pack(VERSION, KIND_DATA, pts, dur, 0) + encode_frame_tensors(
        host.tensors
    )


def decode_message(data: bytes):
    """→ Frame, or EOS_FRAME. Raises ValueError on malformed input."""
    if len(data) < _HDR.size:
        raise ValueError(f"edge message too short: {len(data)}")
    version, kind, pts, dur, _ = _HDR.unpack_from(data)
    if version != VERSION:
        raise ValueError(f"unsupported edge message version {version}")
    if kind == KIND_EOS:
        return EOS_FRAME
    tensors = decode_frame_tensors(data[_HDR.size :])
    return Frame(
        tensors,
        pts=None if pts < 0 else pts,
        duration=None if dur < 0 else dur,
    )
