"""Query connect-type transports beyond raw TCP: MQTT and HYBRID.

Reference: tensor_query_common.c:35-42 — the query elements accept
connect-type TCP / MQTT / HYBRID (/ AITT, vendor-gated). Semantics:

- ``MQTT``: request/reply payloads ride the broker. Client publishes to
  ``<topic>/req/<client_id>`` and subscribes ``<topic>/rep/<client_id>``;
  the server subscribes ``<topic>/req/+`` and replies on the rep topic of
  the requesting client. dest-host/dest-port address the *broker*.
- ``HYBRID``: MQTT for discovery/control only, raw TCP for bulk tensors
  (the reference's broker-assisted mode). The server listens on an
  ephemeral TCP port and answers ``<topic>/whois`` discovery requests with
  ``host:port``; clients then speak plain TCP.

Both adapters expose the same surface as the native TCP transport
(connect/listen/send/recv/close/peer_count) so the query elements stay
transport-agnostic, like the reference elements over nns_edge handles.
"""

from __future__ import annotations

import itertools
import os
import queue as queue_mod
import threading
import time
from typing import Optional, Tuple

from nnstreamer_tpu.edge.mqtt import MqttClient, MqttError
from nnstreamer_tpu.edge.transport import TransportError, make_transport

_client_seq = itertools.count(1)


class MqttQueryTransport:
    """Request/reply over an MQTT broker, one topic pair per client."""

    def __init__(self, topic: str = "nns-query") -> None:
        self.topic = topic.rstrip("/")
        self._mqtt: Optional[MqttClient] = None
        self._queue: "queue_mod.Queue" = queue_mod.Queue(maxsize=1024)
        self._server = False
        self._cid = f"c{os.getpid()}-{next(_client_seq)}"

    # -- server side -------------------------------------------------------
    def listen(self, host: str, port: int) -> int:
        port = port or 1883
        try:
            self._mqtt = MqttClient(
                host, port, on_message=self._on_message
            ).connect()
        except (MqttError, OSError) as exc:
            raise TransportError(f"cannot reach MQTT broker {host}:{port}: {exc}")
        self._server = True
        self._mqtt.subscribe(f"{self.topic}/req/+")
        return port

    # -- client side -------------------------------------------------------
    def connect(self, host: str, port: int) -> None:
        port = port or 1883
        try:
            self._mqtt = MqttClient(
                host, port, on_message=self._on_message
            ).connect()
        except (MqttError, OSError) as exc:
            raise TransportError(f"cannot reach MQTT broker {host}:{port}: {exc}")
        self._mqtt.subscribe(f"{self.topic}/rep/{self._cid}")

    # -- shared ------------------------------------------------------------
    def _on_message(self, topic: str, payload: bytes) -> None:
        cid = topic.rsplit("/", 1)[-1]
        if self._queue.full():  # drop-oldest backpressure, like the client
            try:
                self._queue.get_nowait()
            except queue_mod.Empty:
                pass
        self._queue.put((cid, payload))

    def send(self, cid, payload: bytes) -> None:
        if self._mqtt is None:
            raise TransportError("mqtt transport not connected")
        if self._server:
            dest = f"{self.topic}/rep/{cid}"
        else:
            dest = f"{self.topic}/req/{self._cid}"
        try:
            self._mqtt.publish(dest, payload)
        except (MqttError, OSError) as exc:
            raise TransportError(f"mqtt publish failed: {exc}")

    def recv(self, timeout: Optional[float] = None) -> Optional[Tuple[str, bytes]]:
        try:
            return self._queue.get(timeout=timeout)
        except queue_mod.Empty:
            return None

    def peer_count(self) -> int:
        return 1 if self._mqtt is not None else 0

    def close(self) -> None:
        if self._mqtt is not None:
            self._mqtt.close()
            self._mqtt = None


class HybridServerTransport:
    """TCP data plane + MQTT discovery: answers whois with host:port."""

    def __init__(self, topic: str = "nns-query", data_host: str = "127.0.0.1",
                 data_port: int = 0) -> None:
        self.topic = topic.rstrip("/")
        self.data_host = data_host
        self.data_port = data_port
        self._tcp = None
        self._disc: Optional[MqttClient] = None
        self._addr = ""

    def listen(self, host: str, port: int) -> int:
        self._tcp = make_transport()
        tcp_port = self._tcp.listen(self.data_host, self.data_port)
        self._addr = f"{self.data_host}:{tcp_port}"
        try:
            self._disc = MqttClient(
                host, port or 1883, on_message=self._on_whois
            ).connect()
        except (MqttError, OSError) as exc:
            self._tcp.close()
            self._tcp = None
            raise TransportError(
                f"cannot reach MQTT broker {host}:{port or 1883}: {exc}"
            )
        self._disc.subscribe(f"{self.topic}/whois")
        # announce once proactively for clients that subscribed early
        self._announce()
        return tcp_port

    def _announce(self) -> None:
        try:
            self._disc.publish(f"{self.topic}/host", self._addr.encode())
        except (MqttError, OSError):
            pass  # discovery is best-effort; TCP plane keeps serving

    def _on_whois(self, topic: str, payload: bytes) -> None:
        self._announce()

    def send(self, cid, payload: bytes) -> None:
        self._tcp.send(cid, payload)

    def recv(self, timeout: Optional[float] = None):
        return self._tcp.recv(timeout=timeout)

    def peer_count(self) -> int:
        return self._tcp.peer_count() if self._tcp is not None else 0

    def close(self) -> None:
        if self._disc is not None:
            self._disc.close()
            self._disc = None
        if self._tcp is not None:
            self._tcp.close()
            self._tcp = None


class HybridClientTransport:
    """Discover the server's TCP address over MQTT, then speak TCP."""

    DISCOVERY_TIMEOUT = 5.0

    def __init__(self, topic: str = "nns-query") -> None:
        self.topic = topic.rstrip("/")
        self._tcp = None

    def connect(self, host: str, port: int) -> None:
        try:
            disc = MqttClient(host, port or 1883).connect()
        except (MqttError, OSError) as exc:
            raise TransportError(
                f"cannot reach MQTT broker {host}:{port or 1883}: {exc}"
            )
        try:
            disc.subscribe(f"{self.topic}/host")
            deadline = time.monotonic() + self.DISCOVERY_TIMEOUT
            addr = None
            while time.monotonic() < deadline:
                try:
                    disc.publish(f"{self.topic}/whois", b"?")
                except (MqttError, OSError) as exc:
                    raise TransportError(f"discovery publish failed: {exc}")
                got = disc.recv(timeout=0.5)
                if got is not None:
                    addr = got[1].decode()
                    break
            if addr is None:
                raise TransportError(
                    f"no query server answered whois on {self.topic!r} "
                    f"within {self.DISCOVERY_TIMEOUT}s"
                )
        finally:
            disc.close()
        h, _, p = addr.rpartition(":")
        if not h or not p.isdigit():
            raise TransportError(
                f"malformed discovery announcement {addr!r} on "
                f"{self.topic}/host (expected host:port)"
            )
        self._tcp = make_transport()
        self._tcp.connect(h, int(p))

    def send(self, cid, payload: bytes) -> None:
        self._tcp.send(cid, payload)

    def recv(self, timeout: Optional[float] = None):
        return self._tcp.recv(timeout=timeout)

    def peer_count(self) -> int:
        return self._tcp.peer_count() if self._tcp is not None else 0

    def close(self) -> None:
        if self._tcp is not None:
            self._tcp.close()
            self._tcp = None
