"""Query connect-type transports beyond raw TCP: MQTT and HYBRID.

Reference: tensor_query_common.c:35-42 — the query elements accept
connect-type TCP / MQTT / HYBRID (/ AITT, vendor-gated). Semantics:

- ``MQTT``: request/reply payloads ride the broker. Client publishes to
  ``<topic>/req/<client_id>`` and subscribes ``<topic>/rep/<client_id>``;
  the server subscribes ``<topic>/req/+`` and replies on the rep topic of
  the requesting client. dest-host/dest-port address the *broker*.
- ``HYBRID``: MQTT for discovery/control only, raw TCP for bulk tensors
  (the reference's broker-assisted mode). The server listens on an
  ephemeral TCP port and answers ``<topic>/whois`` discovery requests with
  ``host:port``; clients then speak plain TCP.
- ``SHM``: co-located processes skip sockets entirely — request and
  reply each ride one SPSC shared-memory ring (edge/shm.py over
  native/nns_shm.cpp), one memcpy in, one out, no syscall per frame on
  the hot path. Single client by design (the rings are SPSC); the
  ``port`` property keys the segment names.

All adapters expose the same surface as the native TCP transport
(connect/listen/send/recv/close/peer_count) so the query elements stay
transport-agnostic, like the reference elements over nns_edge handles.
Fleet mode (``tensor_query_client hosts=...``, docs/edge-serving.md
"Running a fleet") builds one adapter per endpoint through the same
factory: for MQTT each ``host:port`` names a broker, for SHM the port
keys the ring pair (host ignored) — so failover/hedging compose with
every connect-type, though the health scorer's re-resolve fast-path
(``UnresolvableError``) only applies to the TCP family.
"""

from __future__ import annotations

import itertools
import os
import queue as queue_mod
import threading
import time
from typing import Optional, Tuple

from nnstreamer_tpu.edge.mqtt import MqttClient, MqttError
from nnstreamer_tpu.edge.transport import TransportError, make_transport

_client_seq = itertools.count(1)


class MqttQueryTransport:
    """Request/reply over an MQTT broker, one topic pair per client."""

    def __init__(self, topic: str = "nns-query") -> None:
        self.topic = topic.rstrip("/")
        self._mqtt: Optional[MqttClient] = None
        self._queue: "queue_mod.Queue" = queue_mod.Queue(maxsize=1024)
        self._server = False
        self._cid = f"c{os.getpid()}-{next(_client_seq)}"

    # -- server side -------------------------------------------------------
    def listen(self, host: str, port: int) -> int:
        port = port or 1883
        try:
            self._mqtt = MqttClient(
                host, port, on_message=self._on_message
            ).connect()
        except (MqttError, OSError) as exc:
            raise TransportError(f"cannot reach MQTT broker {host}:{port}: {exc}")
        self._server = True
        self._mqtt.subscribe(f"{self.topic}/req/+")
        return port

    # -- client side -------------------------------------------------------
    def connect(self, host: str, port: int) -> None:
        port = port or 1883
        try:
            self._mqtt = MqttClient(
                host, port, on_message=self._on_message
            ).connect()
        except (MqttError, OSError) as exc:
            raise TransportError(f"cannot reach MQTT broker {host}:{port}: {exc}")
        self._mqtt.subscribe(f"{self.topic}/rep/{self._cid}")

    # -- shared ------------------------------------------------------------
    def _on_message(self, topic: str, payload: bytes) -> None:
        cid = topic.rsplit("/", 1)[-1]
        if self._queue.full():  # drop-oldest backpressure, like the client
            try:
                self._queue.get_nowait()
            except queue_mod.Empty:
                pass
        self._queue.put((cid, payload))

    def send(self, cid, payload: bytes) -> None:
        if self._mqtt is None:
            raise TransportError("mqtt transport not connected")
        if self._server:
            dest = f"{self.topic}/rep/{cid}"
        else:
            dest = f"{self.topic}/req/{self._cid}"
        try:
            self._mqtt.publish(dest, payload)
        except (MqttError, OSError) as exc:
            raise TransportError(f"mqtt publish failed: {exc}")

    def recv(self, timeout: Optional[float] = None) -> Optional[Tuple[str, bytes]]:
        try:
            return self._queue.get(timeout=timeout)
        except queue_mod.Empty:
            return None

    def peer_count(self) -> int:
        return 1 if self._mqtt is not None else 0

    def close(self) -> None:
        if self._mqtt is not None:
            self._mqtt.close()
            self._mqtt = None


class HybridServerTransport:
    """TCP data plane + MQTT discovery: answers whois with host:port.

    ``max_conns``/``reject_payload`` (set by the serversrc's admission
    layer before listen) pass through to the TCP data plane; the python
    transport enforces them, the native one admits at request level
    only."""

    max_conns = 0
    reject_payload = None

    def __init__(self, topic: str = "nns-query", data_host: str = "127.0.0.1",
                 data_port: int = 0) -> None:
        self.topic = topic.rstrip("/")
        self.data_host = data_host
        self.data_port = data_port
        self._tcp = None
        self._disc: Optional[MqttClient] = None
        self._addr = ""

    def listen(self, host: str, port: int) -> int:
        # conn caps need the python transport's acceptor-side rejection
        self._tcp = make_transport(prefer_native=not self.max_conns)
        if self.max_conns:
            self._tcp.max_conns = self.max_conns
            self._tcp.reject_payload = self.reject_payload
        tcp_port = self._tcp.listen(self.data_host, self.data_port)
        self._addr = f"{self.data_host}:{tcp_port}"
        try:
            self._disc = MqttClient(
                host, port or 1883, on_message=self._on_whois
            ).connect()
        except (MqttError, OSError) as exc:
            self._tcp.close()
            self._tcp = None
            raise TransportError(
                f"cannot reach MQTT broker {host}:{port or 1883}: {exc}"
            )
        self._disc.subscribe(f"{self.topic}/whois")
        # announce once proactively for clients that subscribed early
        self._announce()
        return tcp_port

    def _announce(self) -> None:
        try:
            self._disc.publish(f"{self.topic}/host", self._addr.encode())
        except (MqttError, OSError):
            pass  # discovery is best-effort; TCP plane keeps serving

    def _on_whois(self, topic: str, payload: bytes) -> None:
        self._announce()

    @property
    def rejected_conns(self) -> int:
        return getattr(self._tcp, "rejected_conns", 0) if self._tcp else 0

    def send(self, cid, payload: bytes) -> None:
        self._tcp.send(cid, payload)

    def recv(self, timeout: Optional[float] = None):
        return self._tcp.recv(timeout=timeout)

    def peer_count(self) -> int:
        return self._tcp.peer_count() if self._tcp is not None else 0

    def close(self) -> None:
        if self._disc is not None:
            self._disc.close()
            self._disc = None
        if self._tcp is not None:
            self._tcp.close()
            self._tcp = None


class ShmServerTransport:
    """connect-type=SHM server side: two SPSC rings for ONE co-located
    client — ``/nns-shm-<port>`` carries requests (client writes, server
    reads), ``/nns-shm-<port+1>`` carries replies. The server creates
    both segments (it starts first and owns their lifetime: closing
    marks them closed so the client drains then sees EOS)."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        from nnstreamer_tpu.edge.shm import DEFAULT_CAPACITY, ShmTransport

        cap = capacity or DEFAULT_CAPACITY
        self._req = ShmTransport(cap)
        self._rep = ShmTransport(cap)
        self._port = 0

    def listen(self, host: str, port: int) -> int:
        port = port or (os.getpid() % 50000 + 10000)
        self._req.listen(host, port)
        try:
            self._rep.listen(host, port + 1)
        except TransportError:
            self._req.close()
            raise
        self._port = port
        return port

    def send(self, cid, payload: bytes) -> None:
        self._rep.send(0, payload)

    def recv(self, timeout: Optional[float] = None):
        got = self._req.recv(timeout=timeout)
        if got is None:
            return None
        # one fixed client id: the rings are SPSC, so "which client" is
        # structural — 1 keeps the serversink's client_id path uniform
        return (1, got[1])

    def peer_count(self) -> int:
        return self._rep.peer_count()

    def close(self) -> None:
        self._req.close()
        self._rep.close()


class ShmClientTransport:
    """connect-type=SHM client side: opens the server's ring pair
    (requests written to ``<port>``, replies read from ``<port+1>``)."""

    def __init__(self) -> None:
        self._req = None
        self._rep = None

    def connect(self, host: str, port: int) -> None:
        from nnstreamer_tpu.edge.shm import ShmTransport

        req = ShmTransport()
        rep = ShmTransport()
        req.connect(host, port)
        try:
            rep.connect(host, port + 1)
        except TransportError:
            req.close()
            raise
        self._req, self._rep = req, rep

    def send(self, cid, payload: bytes) -> None:
        if self._req is None:
            raise TransportError("shm query transport not connected")
        self._req.send(0, payload)

    def recv(self, timeout: Optional[float] = None):
        if self._rep is None:
            raise TransportError("shm query transport not connected")
        return self._rep.recv(timeout=timeout)

    def peer_count(self) -> int:
        return 1 if self._req is not None else 0

    def close(self) -> None:
        if self._req is not None:
            self._req.close()
            self._req = None
        if self._rep is not None:
            self._rep.close()
            self._rep = None


class HybridClientTransport:
    """Discover the server's TCP address over MQTT, then speak TCP."""

    DISCOVERY_TIMEOUT = 5.0

    def __init__(self, topic: str = "nns-query") -> None:
        self.topic = topic.rstrip("/")
        self._tcp = None

    def connect(self, host: str, port: int) -> None:
        try:
            disc = MqttClient(host, port or 1883).connect()
        except (MqttError, OSError) as exc:
            raise TransportError(
                f"cannot reach MQTT broker {host}:{port or 1883}: {exc}"
            )
        try:
            disc.subscribe(f"{self.topic}/host")
            deadline = time.monotonic() + self.DISCOVERY_TIMEOUT
            addr = None
            while time.monotonic() < deadline:
                try:
                    disc.publish(f"{self.topic}/whois", b"?")
                except (MqttError, OSError) as exc:
                    raise TransportError(f"discovery publish failed: {exc}")
                got = disc.recv(timeout=0.5)
                if got is not None:
                    addr = got[1].decode()
                    break
            if addr is None:
                raise TransportError(
                    f"no query server answered whois on {self.topic!r} "
                    f"within {self.DISCOVERY_TIMEOUT}s"
                )
        finally:
            disc.close()
        h, _, p = addr.rpartition(":")
        if not h or not p.isdigit():
            raise TransportError(
                f"malformed discovery announcement {addr!r} on "
                f"{self.topic}/host (expected host:port)"
            )
        self._tcp = make_transport()
        self._tcp.connect(h, int(p))

    def send(self, cid, payload: bytes) -> None:
        self._tcp.send(cid, payload)

    def recv(self, timeout: Optional[float] = None):
        return self._tcp.recv(timeout=timeout)

    def peer_count(self) -> int:
        return self._tcp.peer_count() if self._tcp is not None else 0

    def close(self) -> None:
        if self._tcp is not None:
            self._tcp.close()
            self._tcp = None
