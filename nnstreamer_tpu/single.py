"""Single-shot invoke API: open a model, invoke it, no pipeline.

Reference: gst/nnstreamer/tensor_filter/tensor_filter_single.c — the
GStreamer-free GObject underlying the ML C-API's ml_single_invoke
(SURVEY.md §3.5). Lifecycle parity:

    g_object_new + set_property   → SingleShot(framework=, model=, ...)
    klass->start (open_fw)        → SingleShot.open() / context-manager enter
    klass->invoke (:321)          → SingleShot.invoke(...)
    set-input-info                → SingleShot.set_input_info(...)
    klass->stop                   → SingleShot.close()
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.backends.base import Backend, BackendError, FilterProps
from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec

_log = get_logger("single")


class SingleShot:
    """Open → invoke → close, with framework auto-detection.

    >>> with SingleShot(framework="scaler", custom="factor:3",
    ...                 input_spec=TensorsSpec.from_strings("4", "float32")) as s:
    ...     (out,) = s.invoke(np.ones(4, np.float32))
    """

    def __init__(
        self,
        framework: str = "auto",
        model: Union[str, Sequence[str]] = (),
        input_spec: Optional[TensorsSpec] = None,
        output_spec: Optional[TensorsSpec] = None,
        custom: str = "",
        accelerator: str = "",
        **options: str,
    ) -> None:
        models = (model,) if isinstance(model, str) else tuple(model)
        models = tuple(m for m in models if m)
        if framework == "auto":
            # extension-based detection (tensor_filter_common.c:1155-1218)
            detected = registry.detect_filter_framework(models[0]) if models else None
            if detected is None:
                raise BackendError(
                    f"cannot auto-detect framework for model {models[:1]}"
                )
            framework = detected
        self.props = FilterProps(
            framework=framework,
            model=models,
            input_spec=input_spec,
            output_spec=output_spec,
            custom=custom,
            accelerator=accelerator,
            options=dict(options),
        )
        self._backend: Optional[Backend] = None

    # -- lifecycle ---------------------------------------------------------
    @property
    def backend(self) -> Backend:
        if self._backend is None:
            raise BackendError("SingleShot not opened")
        return self._backend

    @property
    def is_open(self) -> bool:
        return self._backend is not None

    def open(self) -> "SingleShot":
        if self._backend is not None:
            return self
        cls = registry.get(registry.KIND_FILTER, self.props.framework)
        backend: Backend = cls()
        backend.open(self.props)
        if self.props.input_spec is not None:
            try:
                cur_in, _ = backend.get_model_info()
                need_set = not cur_in.is_compatible(self.props.input_spec)
            except BackendError:
                need_set = True
            if need_set:
                backend.set_input_info(self.props.input_spec)
        self._backend = backend
        return self

    def close(self) -> None:
        if self._backend is not None:
            self._backend.close()
            self._backend = None

    def __enter__(self) -> "SingleShot":
        return self.open()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- negotiation -------------------------------------------------------
    @property
    def input_spec(self) -> TensorsSpec:
        return self.backend.get_model_info()[0]

    @property
    def output_spec(self) -> TensorsSpec:
        return self.backend.get_model_info()[1]

    def set_input_info(self, spec: TensorsSpec) -> TensorsSpec:
        return self.backend.set_input_info(spec)

    # -- execution ---------------------------------------------------------
    def invoke(self, *tensors: Any) -> Tuple[Any, ...]:
        """Invoke on raw arrays (device or host); returns tuple of outputs.
        A single Frame argument is unwrapped and rewrapped."""
        if len(tensors) == 1 and isinstance(tensors[0], Frame):
            frame = tensors[0]
            out = self.backend.invoke_timed(frame.tensors)
            return frame.with_tensors(out)
        return tuple(self.backend.invoke_timed(tuple(tensors)))

    def reload_model(self, model: Union[str, Sequence[str]]) -> None:
        """Hot model swap (reference is-updatable / RELOAD_MODEL)."""
        models = (model,) if isinstance(model, str) else tuple(model)
        self.backend.reload(models)

    # -- stats (reference latency/throughput read-only props) -------------
    @property
    def latency_us(self) -> float:
        return self.backend.stats.latency_us

    @property
    def throughput_fps(self) -> float:
        return self.backend.stats.throughput_fps
