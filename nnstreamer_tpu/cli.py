"""nns-launch: run pipeline descriptions from the command line.

The reference's CLI is GStreamer's gst-launch-1.0 / gst-inspect-1.0
(SURVEY.md §1 L6). Usage:

    python -m nnstreamer_tpu.cli "videotestsrc num-frames=10 ! \\
        tensor_converter ! tensor_transform mode=typecast option=float32 ! \\
        tensor_sink name=out"

    python -m nnstreamer_tpu.cli --inspect                # list elements
    python -m nnstreamer_tpu.cli --inspect tensor_filter  # element detail
    python -m nnstreamer_tpu.cli --dot "..." > graph.dot  # graph dump
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from nnstreamer_tpu.platform_pin import honor_jax_platforms_env

honor_jax_platforms_env()


def _inspect(name: str | None) -> int:
    from nnstreamer_tpu import registry

    if not name:
        print("Available elements:")
        for n in registry.available(registry.KIND_ELEMENT):
            cls = registry.get(registry.KIND_ELEMENT, n)
            doc = (cls.__doc__ or "").strip().splitlines()
            print(f"  {n:24s} {doc[0] if doc else ''}")
        for kind, label in (
            (registry.KIND_FILTER, "filter backends"),
            (registry.KIND_DECODER, "decoder subplugins"),
            (registry.KIND_CONVERTER, "converter subplugins"),
        ):
            names = registry.available(kind)
            if names:
                print(f"\nAvailable {label}: {', '.join(names)}")
        return 0
    cls = registry.get(registry.KIND_ELEMENT, name)
    print(f"Element: {name}\n")
    print(cls.__doc__ or "(no documentation)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="nns-launch", description=__doc__)
    ap.add_argument("description", nargs="?", help="pipeline description")
    ap.add_argument("--inspect", nargs="?", const="", default=None, metavar="ELEMENT")
    ap.add_argument("--dot", action="store_true", help="print graphviz, don't run")
    ap.add_argument(
        "--check", action="store_true",
        help="statically lint the pipeline without starting it; "
        "exit 0 clean / 1 warnings / 2 errors (see docs/linting.md)",
    )
    ap.add_argument("--timeout", type=float, default=None, help="run timeout (s)")
    ap.add_argument(
        "--stats", action="store_true",
        help="print per-node stats JSON (enables nns-obs metrics, so the "
        "rows carry latency_p50/p95/p99_ms and queue-wait percentiles)",
    )
    ap.add_argument(
        "--metrics", metavar="FILE", default=None,
        help="write a one-shot nns-obs JSON snapshot at EOS "
        "(docs/observability.md; nns-top renders it)",
    )
    ap.add_argument(
        "--trace", metavar="FILE", default=None,
        help="write chrome://tracing JSON of per-element frame spans",
    )
    ap.add_argument(
        "--profile", metavar="DIR", default=None,
        help="capture an on-device (XLA/TPU) profile into a TensorBoard logdir",
    )
    ap.add_argument("--quiet", "-q", action="store_true")
    from nnstreamer_tpu import __version__

    ap.add_argument(
        "--version", action="version", version=f"nns-launch {__version__}"
    )
    args = ap.parse_args(argv)

    if args.inspect is not None:
        return _inspect(args.inspect or None)
    if not args.description:
        ap.error("pipeline description required")

    if args.check:
        from nnstreamer_tpu.analysis import annotated_dot, lint

        result = lint(args.description)
        if args.dot:
            print(annotated_dot(result))
        elif not args.quiet or result.diagnostics:
            print(result.render())
        return result.exit_code

    from nnstreamer_tpu.elements.base import ElementError, NegotiationError
    from nnstreamer_tpu.pipeline.parse import ParseError, parse_pipeline

    # gst-launch-style diagnostics: construction/negotiation failures are
    # user errors — one clean line and rc 1, never a traceback dump
    try:
        pipeline = parse_pipeline(args.description)
        pipeline.negotiate()
    except (ParseError, NegotiationError, ElementError, KeyError, ValueError) as exc:
        print(f"nns-launch: {exc}", file=sys.stderr)
        return 1
    if args.dot:
        print(pipeline.dump_dot())
        return 0
    if not args.quiet:
        print(f"Setting pipeline PLAYING ({len(pipeline.elements)} elements)", file=sys.stderr)
    import contextlib

    from nnstreamer_tpu import trace as trace_mod
    from nnstreamer_tpu.obs import metrics as obs_metrics

    if args.stats or args.metrics:
        # percentile columns need the histograms recording; executors
        # resolve the registry at construction, which happens in run()
        obs_metrics.enable()
    tracer = trace_mod.enable() if args.trace else None
    profile_cm = (
        trace_mod.device_profile(args.profile) if args.profile
        else contextlib.nullcontext()
    )
    t0 = time.perf_counter()
    timed_out = False
    with profile_cm:
        try:
            ex = pipeline.run(timeout=args.timeout)
        except TimeoutError:
            # operator-requested bound on an endless pipeline: a stop, not a bug
            ex = pipeline._executor
            timed_out = True
        except (ElementError, NegotiationError, RuntimeError) as exc:
            print(f"nns-launch: pipeline error: {exc}", file=sys.stderr)
            return 1
    dt = time.perf_counter() - t0
    if tracer is not None:
        tracer.save(args.trace)
        if not args.quiet:
            print(f"Trace written to {args.trace}", file=sys.stderr)
    if not args.quiet:
        msg = "Timeout reached" if timed_out else "EOS"
        print(f"{msg} after {dt:.3f}s", file=sys.stderr)
        for e in pipeline.elements:
            if hasattr(e, "rendered"):
                print(f"  {e.name}: rendered {e.rendered} frames", file=sys.stderr)
    if args.metrics:
        from nnstreamer_tpu.obs import expo

        expo.dump_json(
            args.metrics,
            expo.snapshot(obs_metrics.get(), ex.stats(), ex.totals()),
        )
        if not args.quiet:
            print(f"Metrics snapshot written to {args.metrics}", file=sys.stderr)
    if args.stats:
        stats = ex.stats()
        # pipeline-wide frame accounting rides alongside the per-node
        # rows (produced / rendered / dropped-by-reason / balance);
        # element names are user-chosen, so never clobber a node row
        totals_key = "__pipeline__"
        while totals_key in stats:
            totals_key = "_" + totals_key
        stats[totals_key] = ex.totals()
        print(json.dumps(stats, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
