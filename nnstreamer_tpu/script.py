"""Loader for user python-script subplugins.

Reference: the embedded-CPython subplugins (tensor_filter_python3.cc,
tensor_converter_python3, tensordec-python3 +
extra/nnstreamer_python3_helper.cc). Here scripts are plain python modules
loaded by path; the class name looked up per kind keeps one file usable as
several subplugin kinds at once.
"""

from __future__ import annotations

import importlib.util
import os
from typing import Any, Sequence


def load_script_object(path: str, class_names: Sequence[str]) -> Any:
    """Load ``path`` and instantiate the first matching class attribute."""
    if not os.path.isfile(path):
        raise FileNotFoundError(f"script not found: {path}")
    spec = importlib.util.spec_from_file_location(
        f"nns_tpu_script_{abs(hash(path))}", path
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    for name in class_names:
        obj = getattr(module, name, None)
        if obj is not None:
            return obj() if isinstance(obj, type) else obj
    raise AttributeError(
        f"{path} defines none of {list(class_names)}"
    )
