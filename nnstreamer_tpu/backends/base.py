"""Backend interface: the pluggable inference engine contract.

TPU-native redesign of the reference's subplugin ABI
(gst/nnstreamer/include/nnstreamer_plugin_api_filter.h —
GstTensorFilterFramework v0/v1, and the C++ class variant
nnstreamer_cppplugin_api_filter.hh:67-187). The lifecycle maps 1:1:

    fw->open / close            → Backend.open / close
    getModelInfo(GET_IN_OUT)    → Backend.get_model_info
    getModelInfo(SET_INPUT)     → Backend.set_input_info
    fw->invoke                  → Backend.invoke
    RELOAD_MODEL event          → Backend.reload  (is-updatable hot swap)

The TPU-first addition is :meth:`Backend.traceable_fn`: a backend that can
express its computation as a pure jax function returns it so the pipeline
compiler can fuse it with adjacent transform/decoder stages into ONE XLA
program — the whole point of keeping tensors device-resident (SURVEY.md §7
"hard parts"). Backends that wrap host libraries (tflite, torch) return
None and act as fusion barriers with explicit host transfer.
"""

from __future__ import annotations

import dataclasses
import time
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from nnstreamer_tpu.tensors.spec import TensorsSpec


@dataclass
class FilterProps:
    """Filter properties shared by the element and single-shot API
    (reference property engine: tensor_filter_common.c:103-128)."""

    framework: str = "auto"
    model: Tuple[str, ...] = ()  # 1..N model files (caffe2-style pairs allowed)
    input_spec: Optional[TensorsSpec] = None  # user override (input/inputtype props)
    output_spec: Optional[TensorsSpec] = None
    custom: str = ""  # backend-specific option string (custom= prop)
    accelerator: str = ""  # e.g. "true:tpu", parsed leniently
    invoke_dynamic: bool = False  # output shape may vary per frame
    options: Dict[str, str] = field(default_factory=dict)

    @property
    def model_path(self) -> str:
        return self.model[0] if self.model else ""

    def custom_dict(self) -> Dict[str, str]:
        """Parse ``key:value,key2:value2`` custom strings (the convention of
        reference subplugins, e.g. edgetpu's device_type:dummy)."""
        out: Dict[str, str] = {}
        for part in self.custom.split(","):
            part = part.strip()
            if not part:
                continue
            if ":" in part:
                k, v = part.split(":", 1)
                out[k.strip()] = v.strip()
            else:
                out[part] = "true"
        return out


class BackendError(RuntimeError):
    pass


class Backend(ABC):
    """One loaded model instance inside a filter stage."""

    #: subplugin name (set by the registry decorator)
    name: str = "base"
    #: whether outputs may change shape per-invoke (flexible output)
    invoke_dynamic: bool = False
    #: whether invoke_batched() may be fed a micro-batch of frames in one
    #: call (pipeline/batching.py). Host-library backends whose invoke is
    #: strictly per-frame (tflite set_tensor/invoke/get_tensor) leave this
    #: False and keep per-frame invokes; backends that can amortize a
    #: window (stacking, engine-side batching) opt in.
    batchable: bool = False

    #: whether invoke() is the identity over its tensors (the
    #: passthrough test backend). A fused segment made only of identity
    #: ops short-circuits the device entirely — no jitted program, no
    #: per-frame XLA dispatch — so a passthrough filter measures the
    #: EXECUTOR's overhead, not jax's (bench executor ceilings,
    #: docs/streaming.md).
    IS_IDENTITY: bool = False

    #: whether invoke() accepts device-resident input arrays (the
    #: backend stages/reshards them itself — jax device_put). The
    #: executor's link negotiation (Node._out_wants_host) keeps the
    #: device-resident handoff alive into such a backend's host node
    #: (a device-pinned placement stage) instead of forcing a coalesced
    #: D2H; host-library backends read tensor bytes on host and leave
    #: this False (docs/streaming.md, docs/serving-plane.md).
    DEVICE_INPUT_OK: bool = False

    def __init__(self) -> None:
        self.props: Optional[FilterProps] = None
        self.stats = InvokeStats()

    # -- lifecycle ---------------------------------------------------------
    @abstractmethod
    def open(self, props: FilterProps) -> None:
        """Load the model / init the device. Reference fw->open."""

    def close(self) -> None:
        """Release resources. Reference fw->close."""

    def reload(self, model_paths: Sequence[str]) -> None:
        """Zero-downtime model swap (reference RELOAD_MODEL,
        nnstreamer_plugin_api_filter.h:204,377-383). Default: close+open with
        new paths; backends may double-buffer instead."""
        assert self.props is not None, "reload before open"
        self.close()
        self.open(dataclasses.replace(self.props, model=tuple(model_paths)))

    # -- negotiation -------------------------------------------------------
    @abstractmethod
    def get_model_info(self) -> Tuple[TensorsSpec, TensorsSpec]:
        """(input_spec, output_spec) after open. Reference
        getModelInfo(GET_IN_OUT_INFO)."""

    def set_input_info(self, in_spec: TensorsSpec) -> TensorsSpec:
        """Renegotiate for a different input shape; returns the new output
        spec. Reference getModelInfo(SET_INPUT_INFO) trial negotiation
        (nnstreamer_plugin_api_filter.h:351-368). Default: reject unless the
        input already matches."""
        cur_in, cur_out = self.get_model_info()
        if cur_in.is_compatible(in_spec):
            return cur_out
        raise BackendError(
            f"{self.name}: cannot renegotiate input {cur_in} -> {in_spec}"
        )

    # -- execution ---------------------------------------------------------
    @abstractmethod
    def invoke(self, tensors: Tuple[Any, ...]) -> Tuple[Any, ...]:
        """Run inference on one frame's tensors. Reference fw->invoke
        (the hot call, tensor_filter.c:721)."""

    def traceable_fn(self) -> Optional[Callable[[Tuple[Any, ...]], Tuple[Any, ...]]]:
        """Pure jax function equivalent to invoke(), or None if this backend
        is host-bound (fusion barrier)."""
        return None

    def invoke_batched(
        self, batch: Sequence[Tuple[Any, ...]]
    ) -> List[Tuple[Any, ...]]:
        """Run inference on a micro-batch of frames' tensors in ONE call
        (only used when ``batchable``). The default chains invoke() —
        still worthwhile (one lock acquisition / one timed section per
        window); genuinely batchable engines override with a stacked
        implementation."""
        return [tuple(self.invoke(ts)) for ts in batch]

    # -- instrumented invoke (reference latency/throughput props,
    #    tensor_filter.c:334-433) ----------------------------------------
    def invoke_timed(self, tensors: Tuple[Any, ...]) -> Tuple[Any, ...]:
        t0 = time.perf_counter_ns()
        out = self.invoke(tensors)
        self.stats.record(time.perf_counter_ns() - t0)
        return out


class InvokeStats:
    """Sliding-window latency/throughput, mirroring the reference's
    10-invoke window (GST_TF_STAT_MAX_RECENT, tensor_filter_common.h:57) and
    cumulative per-framework stats (nnstreamer_plugin_api_filter.h:169-174)."""

    WINDOW = 10

    def __init__(self) -> None:
        self.total_invoke_num = 0
        self.total_invoke_latency_ns = 0
        self._recent: List[Tuple[int, int]] = []  # (wall_ns_when, latency_ns)

    def record(self, latency_ns: int) -> None:
        self.total_invoke_num += 1
        self.total_invoke_latency_ns += latency_ns
        self._recent.append((time.monotonic_ns(), latency_ns))
        if len(self._recent) > self.WINDOW:
            self._recent.pop(0)

    @property
    def latency_us(self) -> float:
        """Average latency over the recent window, µs (reference 'latency'
        read-only property)."""
        if not self._recent:
            return 0.0
        return sum(l for _, l in self._recent) / len(self._recent) / 1000.0

    @property
    def throughput_fps(self) -> float:
        """Recent throughput, frames/sec (reference 'throughput' property,
        reported ×1000 there; plain fps here)."""
        if len(self._recent) < 2:
            return 0.0
        span = self._recent[-1][0] - self._recent[0][0]
        if span <= 0:
            return 0.0
        return (len(self._recent) - 1) * 1e9 / span
