"""Custom filter backends: in-process callables and user python scripts.

Reference parity:
- ``custom-easy`` — register a function + specs in-process, no file
  (include/tensor_filter_custom_easy.h; here :func:`register_custom_easy`).
- ``custom`` — load a user script file implementing a filter class
  (the reference's ``custom`` .so vtable, include/tensor_filter_custom.h:
  46-111, merged with the python3 subplugin protocol
  ext/nnstreamer/tensor_filter/tensor_filter_python3.cc:286-291: the class
  must define ``invoke`` and either ``setInputDim`` or
  ``getInputDim``+``getOutputDim``).

A custom callable may be jax-traceable; pass ``traceable=True`` at
registration (or define ``TRACEABLE = True`` on the script class) to let the
pipeline compiler fuse it.
"""

from __future__ import annotations

import importlib.util
import os
import threading
from typing import Any, Callable, Dict, Optional, Tuple

from nnstreamer_tpu import registry
from nnstreamer_tpu.backends.base import Backend, BackendError, FilterProps
from nnstreamer_tpu.tensors.spec import TensorsSpec

_custom_easy_lock = threading.Lock()
_custom_easy_table: Dict[
    str, Tuple[Callable, Optional[TensorsSpec], Optional[TensorsSpec], bool]
] = {}


def register_custom_easy(
    name: str,
    fn: Callable[[Tuple[Any, ...]], Tuple[Any, ...]],
    in_spec: Optional[TensorsSpec] = None,
    out_spec: Optional[TensorsSpec] = None,
    *,
    traceable: bool = False,
) -> None:
    """NNS_custom_easy_register analogue: model name → in-process function."""
    with _custom_easy_lock:
        _custom_easy_table[name] = (fn, in_spec, out_spec, traceable)


def unregister_custom_easy(name: str) -> bool:
    """NNS_custom_easy_unregister analogue."""
    with _custom_easy_lock:
        return _custom_easy_table.pop(name, None) is not None


@registry.filter_backend("custom-easy")
class CustomEasyBackend(Backend):
    """framework=custom-easy model=<registered-name>."""

    name = "custom-easy"

    def open(self, props: FilterProps) -> None:
        self.props = props
        key = props.model_path
        with _custom_easy_lock:
            if key not in _custom_easy_table:
                raise BackendError(f"custom-easy model {key!r} not registered")
            self._fn, self._in, self._out, self._traceable = _custom_easy_table[key]
        if self._in is None:
            self._in = props.input_spec
        if self._out is None:
            self._out = props.output_spec or self._in

    def get_model_info(self):
        if self._in is None or self._out is None:
            raise BackendError("custom-easy: specs unknown; register with specs "
                               "or set input/output on the filter")
        return self._in, self._out

    def set_input_info(self, in_spec: TensorsSpec) -> TensorsSpec:
        if self._in is None or self._in.is_compatible(in_spec):
            self._in = in_spec
            if self._out is None:
                self._out = in_spec
            return self._out
        raise BackendError(f"custom-easy: fixed input {self._in} != {in_spec}")

    def invoke(self, tensors):
        return tuple(self._fn(tensors))

    def traceable_fn(self):
        return self._fn if self._traceable else None


class CustomScriptProtocolError(BackendError):
    pass


@registry.filter_backend("custom")
class CustomScriptBackend(Backend):
    """framework=custom model=/path/to/script.py

    The script defines ``CustomFilter`` (or a module-level ``filter_class``)
    with the python3-subplugin protocol:

        class CustomFilter:
            def getInputDim(self) -> TensorsSpec: ...   # or setInputDim
            def getOutputDim(self) -> TensorsSpec: ...
            def setInputDim(self, in_spec) -> TensorsSpec: ...  # returns out
            def invoke(self, tensors) -> tuple: ...
            TRACEABLE = False  # optional

    Matching reference behavior: shape-fixed filters implement the two
    getters; shape-polymorphic ones implement setInputDim
    (tensor_filter_python3.cc:286-291,402-583).
    """

    name = "custom"

    def open(self, props: FilterProps) -> None:
        self.props = props
        path = props.model_path
        if not os.path.isfile(path):
            raise BackendError(f"custom: script not found: {path}")
        spec = importlib.util.spec_from_file_location(
            f"nns_tpu_custom_{abs(hash(path))}", path
        )
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        cls = getattr(module, "CustomFilter", None) or getattr(
            module, "filter_class", None
        )
        if cls is None:
            raise CustomScriptProtocolError(
                f"custom: {path} defines no CustomFilter class"
            )
        self._obj = cls() if isinstance(cls, type) else cls
        if not hasattr(self._obj, "invoke"):
            raise CustomScriptProtocolError(f"custom: {path} has no invoke()")
        has_set = hasattr(self._obj, "setInputDim")
        has_get = hasattr(self._obj, "getInputDim") and hasattr(
            self._obj, "getOutputDim"
        )
        if not (has_set or has_get):
            raise CustomScriptProtocolError(
                f"custom: {path} must define setInputDim or "
                "getInputDim+getOutputDim"
            )
        self._in: Optional[TensorsSpec] = None
        self._out: Optional[TensorsSpec] = None
        if has_get:
            self._in = self._obj.getInputDim()
            self._out = self._obj.getOutputDim()
        elif props.input_spec is not None:
            self._in = props.input_spec
            self._out = self._obj.setInputDim(props.input_spec)

    def get_model_info(self):
        if self._in is None or self._out is None:
            raise BackendError("custom: input spec not negotiated yet")
        return self._in, self._out

    def set_input_info(self, in_spec: TensorsSpec) -> TensorsSpec:
        if hasattr(self._obj, "setInputDim"):
            self._in = in_spec
            self._out = self._obj.setInputDim(in_spec)
            return self._out
        return super().set_input_info(in_spec)

    def invoke(self, tensors):
        return tuple(self._obj.invoke(tensors))

    def traceable_fn(self):
        if getattr(self._obj, "TRACEABLE", False):
            return lambda tensors: tuple(self._obj.invoke(tensors))
        return None
