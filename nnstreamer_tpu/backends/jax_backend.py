"""The primary TPU backend: models as pure jax functions compiled by XLA.

This is the analogue slot of the reference's tensorflow-lite subplugin (its
default CPU engine, ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_
lite.cc) — but TPU-first: a model is a pure function + params pytree, jitted
once at open (the reference's fw->open = "model load, device init",
SURVEY.md §3.1), with shapes fixed by negotiation so XLA compiles exactly
one executable. The un-jitted function is exposed for fusion with adjacent
transform/decoder stages.

Model sources (by ``model=`` value):

- ``zoo:<name>`` — built-in model zoo (nnstreamer_tpu/models/zoo.py), e.g.
  ``zoo:mobilenet_v2``. Options via custom string
  (``custom="num_classes:1001,width:1.0"``).
- ``<path>.py`` — user script defining
  ``get_model(options: dict) -> (fn, input_spec | None)`` where ``fn`` is a
  pure traceable callable ``(*tensors) -> tensor | tuple``.
- ``<path>.jaxexport`` / ``<path>.stablehlo`` — a serialized
  ``jax.export.Exported`` artifact (StableHLO); the TPU equivalent of
  loading a .tflite flatbuffer.
"""

from __future__ import annotations

import importlib.util
import os
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.backends.base import Backend, BackendError, FilterProps
from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.tensors.spec import DType, TensorSpec, TensorsSpec

_log = get_logger("backends.jax")

_cache_initialized = False


def _init_persistent_cache() -> None:
    """``NNS_TPU_COMPILE_CACHE_DIR`` (or ``[jax] persistent_cache``)
    enables XLA's on-disk compilation cache — the checkpoint/resume
    analogue for an inference framework (SURVEY.md §5.4:
    compiled-executable persistence), cutting model-open time on every
    process restart. The warm-restart path (Executor.drain/snapshot/
    resume, docs/resilience.md) leans on it: a restarted pipeline
    replays its programs from disk and reaches steady-state fps in
    seconds instead of a cold recompile.

    Corruption tolerant by construction: cache errors are forced
    non-fatal (``jax_raise_persistent_cache_errors=False``), so a
    truncated/garbage entry logs and recompiles — a stale cache can
    slow a restart down, never crash it."""
    global _cache_initialized
    if _cache_initialized:
        return
    _cache_initialized = True
    from nnstreamer_tpu.config import conf

    cache_dir = (
        os.environ.get("NNS_TPU_COMPILE_CACHE_DIR")
        or conf().get("jax", "persistent_cache")
    )
    if not cache_dir:
        return
    cache_dir = os.path.expanduser(cache_dir)
    try:
        # CPU AOT cache entries embed the COMPILING host's feature set
        # yet reload on any host (cpu_aot_loader then warns about
        # mismatched machine features and may SIGILL mid-inference) —
        # key the directory by a host fingerprint so a cache baked on
        # one machine is never replayed on a different one. TPU entries
        # key on the device kind already and stay SHARED (a fleet
        # cache over NFS must not recompile per host CPU stepping), so
        # the fingerprint applies only when the backend compiling into
        # this cache is the CPU.
        if jax.default_backend() == "cpu":
            import hashlib
            import platform as _platform

            fp = _platform.machine()
            try:
                with open("/proc/cpuinfo") as f:
                    flags = next(
                        (ln for ln in f if ln.startswith("flags")), ""
                    )
                if flags:
                    fp += (
                        "-" + hashlib.sha1(flags.encode()).hexdigest()[:12]
                    )
            except OSError:
                pass
            cache_dir = os.path.join(cache_dir, fp)
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        # a bad cache entry (truncated write, version skew, bit rot) must
        # log + recompile, never kill the pipeline
        jax.config.update("jax_raise_persistent_cache_errors", False)
        _log.info("persistent compilation cache at %s", cache_dir)
    except Exception as exc:  # cache is an optimization, never fatal
        _log.warning("persistent cache setup failed: %s", exc)


def _spec_from_avals(avals) -> TensorsSpec:
    return TensorsSpec(
        tuple(
            TensorSpec(tuple(int(d) for d in a.shape), DType.from_any(a.dtype))
            for a in avals
        )
    )


def _as_tuple(x) -> Tuple[Any, ...]:
    if isinstance(x, (tuple, list)):
        return tuple(x)
    return (x,)


@registry.filter_backend("jax")
class JaxBackend(Backend):
    """framework=jax: jitted pure-function inference on the default device
    (TPU when present), optionally sharded over a mesh (see parallel/)."""

    name = "jax"
    DEVICE_INPUT_OK = True  # invoke() device_puts/reshards its inputs

    def __init__(self) -> None:
        super().__init__()
        self._fn: Optional[Callable] = None
        self._jitted: Optional[Callable] = None
        self._in_spec: Optional[TensorsSpec] = None
        self._out_spec: Optional[TensorsSpec] = None
        self._device = None
        self._shardings = None  # (in_shardings, out_shardings) when sharded
        self._mesh_spec: Optional[str] = None  # e.g. "dp2tp4" (mesh: option)
        self._mesh = None
        self._apply: Optional[Callable] = None  # params-explicit fn
        self._params = None
        self._param_shardings = None
        self._placed_params = None
        self._params_explicit = False

    # -- lifecycle ---------------------------------------------------------
    def open(self, props: FilterProps) -> None:
        _init_persistent_cache()
        self.props = props
        path = props.model_path
        options = props.custom_dict()
        # per-stage device placement (SURVEY.md §7 build order 5): a
        # pipeline shards across chips by pinning each filter to a device;
        # inter-stage hops are device_put transfers riding ICI, replacing
        # the reference's host TCP between pipeline segments
        if "device" in options:
            devs = jax.devices()
            idx = int(options["device"])
            if not (0 <= idx < len(devs)):
                raise BackendError(
                    f"jax: device:{idx} out of range (have {len(devs)})"
                )
            self._device = devs[idx]
        # mesh-sharded filter (the TP/DP inference story): custom
        # "mesh:dp2tp4" pjits this filter over a named device mesh —
        # replaces the reference's accelerator-string device selection
        # (tensor_filter_common.c:451-) with XLA GSPMD partitioning
        mesh_spec = options.get("mesh") or self._parse_accel_mesh(
            props.accelerator
        )
        if mesh_spec:
            if self._device is not None:
                raise BackendError("jax: device: and mesh: are exclusive")
            self._mesh_spec = mesh_spec
        if path.startswith("zoo:"):
            self._open_zoo(path[len("zoo:"):], options)
        elif path.endswith(".py"):
            self._open_script(path, options)
        elif path.endswith((".jaxexport", ".stablehlo", ".hlo")):
            self._open_exported(path)
        elif path.endswith(".tflite"):
            self._open_tflite(path)
        else:
            raise BackendError(f"jax: unsupported model source {path!r}")
        if self._in_spec is None:
            self._in_spec = props.input_spec
        if self._in_spec is not None and self._in_spec.is_static:
            self._compile()

    def _open_zoo(self, name: str, options) -> None:
        from nnstreamer_tpu.models import zoo

        opts = {k: v for k, v in options.items() if k not in ("device", "mesh")}
        m = zoo.get(name, **opts)
        self._fn = m.fn
        self._in_spec = m.input_spec
        self._apply = m.apply
        self._params = m.params

    def _open_script(self, path: str, options) -> None:
        if not os.path.isfile(path):
            raise BackendError(f"jax: model script not found: {path}")
        spec = importlib.util.spec_from_file_location(
            f"nns_tpu_jaxmodel_{abs(hash(path))}", path
        )
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        if not hasattr(module, "get_model"):
            raise BackendError(f"jax: {path} defines no get_model(options)")
        fn, in_spec = module.get_model(options)
        self._fn = fn
        self._in_spec = in_spec

    def _open_tflite(self, path: str) -> None:
        """framework=jax model=<f>.tflite: decode the flatbuffer
        (tools/tflite_parse) and trace the whole graph as ONE jnp
        program (tools/tflite_exec) — the reference's canonical .tflite
        fixtures run TPU-native through XLA with no interpreter in the
        invoke loop (vs tensor_filter_tensorflow_lite.cc's per-op CPU
        dispatch). Quantized graphs run fake-quant float (exact weight
        dequant + per-tensor activation grids)."""
        if not os.path.isfile(path):
            raise BackendError(f"jax: tflite model not found: {path}")
        from nnstreamer_tpu.tools.tflite_exec import TFLiteProgram

        try:
            prog = TFLiteProgram(path)
            # trace NOW: tracing is lazy, so an unsupported op would
            # otherwise escape later (at _compile/invoke) as a raw
            # NotImplementedError instead of the backend error contract
            jax.eval_shape(prog.trace, *(
                jax.ShapeDtypeStruct(s, d)
                for s, d in zip(prog.input_shapes, prog.input_dtypes)
            ))
        except NotImplementedError as exc:
            raise BackendError(f"jax: cannot compile {path}: {exc}") from exc
        self._fn = lambda *ts: tuple(prog.trace(*ts))
        self._in_spec = TensorsSpec(tuple(
            TensorSpec(tuple(int(d) for d in s), DType.from_any(dt))
            for s, dt in zip(prog.input_shapes, prog.input_dtypes)
        ))

    def _open_exported(self, path: str) -> None:
        with open(path, "rb") as f:
            blob = f.read()
        exported = jax.export.deserialize(bytearray(blob))
        self._fn = lambda *tensors: exported.call(*tensors)
        self._in_spec = _spec_from_avals(exported.in_avals)

    # -- compile -----------------------------------------------------------
    @staticmethod
    def _parse_accel_mesh(accelerator: str) -> Optional[str]:
        """``accelerator=true:tpu:mesh=dp2tp4`` → ``dp2tp4`` (the reference's
        accelerator-string grammar, extended with a mesh clause)."""
        for part in (accelerator or "").split(":"):
            part = part.strip()
            if part.startswith("mesh="):
                return part[len("mesh="):]
        return None

    def _build_mesh_shardings(self) -> None:
        """Turn the mesh spec + negotiated input spec into jit shardings.

        Any GSPMD sharding annotation compiles to a *correct* program (XLA
        inserts the collectives); the choices here are the perf defaults:
        batch dim over ``dp``, last weight dim over ``tp`` (column-parallel
        matmuls/convs), everything else replicated.
        """
        import math
        import re

        from jax.sharding import NamedSharding, PartitionSpec as P

        from nnstreamer_tpu.parallel.mesh import make_mesh

        pairs = re.findall(r"([a-z]+)(\d+)", self._mesh_spec)
        if not pairs or "".join(f"{a}{s}" for a, s in pairs) != self._mesh_spec:
            raise BackendError(
                f"jax: bad mesh spec {self._mesh_spec!r} (want e.g. dp2tp4)"
            )
        axes = tuple(a for a, _ in pairs)
        sizes = tuple(int(s) for _, s in pairs)
        n = math.prod(sizes)
        if n > len(jax.devices()):
            raise BackendError(
                f"jax: mesh {self._mesh_spec} needs {n} devices, "
                f"have {len(jax.devices())}"
            )
        mesh = make_mesh(n, axes=axes, shape=sizes)
        ax = dict(zip(axes, sizes))
        dp, tp = ax.get("dp", 1), ax.get("tp", 1)
        rep = NamedSharding(mesh, P())
        in_sh = []
        for t in self._in_spec:
            if dp > 1 and len(t.shape) >= 1 and t.shape[0] % dp == 0:
                in_sh.append(
                    NamedSharding(mesh, P("dp", *([None] * (len(t.shape) - 1))))
                )
            else:
                in_sh.append(rep)
        param_sh = None
        if self._apply is not None and self._params is not None:
            def rule(leaf):
                shp = tuple(getattr(leaf, "shape", ()))
                if tp > 1 and len(shp) >= 2 and shp[-1] % tp == 0 and shp[-1] >= tp:
                    return NamedSharding(
                        mesh, P(*([None] * (len(shp) - 1)), "tp")
                    )
                return rep

            param_sh = jax.tree_util.tree_map(rule, self._params)
        elif tp > 1:
            _log.warning(
                "jax: mesh %s has tp>1 but model exposes no params-explicit "
                "apply; falling back to input sharding only", self._mesh_spec,
            )
        self._mesh = mesh
        self._shardings = (tuple(in_sh), None)
        self._param_shardings = param_sh

    def _compile(self) -> None:
        assert self._fn is not None and self._in_spec is not None
        fn = self._fn
        wrapped = lambda *tensors: _as_tuple(fn(*tensors))  # noqa: E731
        if self._mesh_spec:
            self._build_mesh_shardings()
        dummies = [
            jax.ShapeDtypeStruct(t.shape, t.dtype.np_dtype) for t in self._in_spec
        ]
        sharded = (
            self._shardings is not None
            and self._param_shardings is not None
        )
        if sharded or (
            self._apply is not None
            and self._params is not None
            and self._shardings is None
        ):
            # params-explicit invoke (docs/streaming.md): weights are
            # device_put ONCE here — sharded across the mesh, or pinned
            # to the single target device — and passed as explicit jit
            # arguments, so every compiled entry (per shape, per batch
            # bucket) shares the same resident copy instead of
            # re-embedding the params as per-program constants:
            # steady-state invokes touch no host weight memory at all
            apply = self._apply
            wrapped_p = lambda p, *xs: _as_tuple(apply(p, *xs))  # noqa: E731
            jit_kwargs = {}
            placement = None
            if sharded:
                placement = self._param_shardings
                jit_kwargs = dict(
                    in_shardings=(self._param_shardings, *self._shardings[0])
                )
                if self._shardings[1] is not None:
                    jit_kwargs["out_shardings"] = self._shardings[1]
            elif self._device is not None:
                placement = self._device
                jit_kwargs = dict(
                    out_shardings=jax.sharding.SingleDeviceSharding(
                        self._device
                    )
                )
            self._jitted = jax.jit(wrapped_p, **jit_kwargs)
            self._placed_params = jax.device_put(self._params, placement)
            self._params_explicit = True
            outs = jax.eval_shape(wrapped_p, self._params, *dummies)
        else:
            jit_kwargs = {}
            if self._shardings is not None:
                jit_kwargs = dict(in_shardings=self._shardings[0])
                if self._shardings[1] is not None:
                    jit_kwargs["out_shardings"] = self._shardings[1]
            elif self._device is not None:
                single = jax.sharding.SingleDeviceSharding(self._device)
                jit_kwargs = dict(out_shardings=single)
            self._jitted = jax.jit(wrapped, **jit_kwargs)
            self._params_explicit = False
            # shape inference without running (reference getModelInfo): one
            # abstract evaluation of the jitted function
            outs = jax.eval_shape(wrapped, *dummies)
        self._out_spec = _spec_from_avals(_as_tuple(outs))

    def plane_fn(self):
        """``(fn, device)`` for the serving plane (serving_plane/
        sharding.py). Unlike :meth:`traceable_fn` — which refuses when a
        device pin makes FUSION illegal — the plane builds its own
        program and honors the pin itself, so ``plane= device=N``
        batches on chip N instead of silently degrading to a per-frame
        host loop. Mesh-sharded state still returns (None, None): the
        plane's own ``plane-mode=shard`` is the sharded path."""
        fn = self._fn
        if fn is None or self._mesh_spec or self._shardings is not None:
            return None, None
        return (lambda tensors: _as_tuple(fn(*tensors))), self._device

    def pin_device(self, idx: int) -> None:
        """Post-open per-stage placement — the Hermes planner's entry
        (serving_plane/placement.py): pin this stage to device ``idx``
        and recompile so weights land there once. Inter-stage hops then
        ride async device_put (ICI on real chips). The ``device:``
        custom option builds the same state at open; this hook exists
        because the planner runs after backends opened (it reuses them
        for the memory estimate)."""
        devs = jax.devices()
        if not (0 <= idx < len(devs)):
            raise BackendError(
                f"jax: device {idx} out of range (have {len(devs)})"
            )
        if self._mesh_spec or self._shardings is not None:
            raise BackendError("jax: device pin and mesh are exclusive")
        self._device = devs[idx]
        if self._in_spec is not None and self._in_spec.is_static \
                and self._jitted is not None:
            self._compile()

    def set_shardings(
        self, in_shardings, out_shardings=None, param_shardings=None
    ) -> None:
        """Install jit shardings programmatically (the parallel layer's
        entry; the ``mesh:`` custom option builds the same state from a
        spec string)."""
        self._shardings = (tuple(in_shardings), out_shardings)
        self._param_shardings = param_shardings
        self._mesh_spec = None  # explicit shardings override the spec string
        if self._in_spec is not None and self._in_spec.is_static:
            self._compile()

    # -- negotiation -------------------------------------------------------
    def get_model_info(self) -> Tuple[TensorsSpec, TensorsSpec]:
        if self._in_spec is None:
            raise BackendError("jax: input spec unknown (shape-polymorphic "
                               "model needs set_input_info)")
        if self._out_spec is None:
            if not self._in_spec.is_static:
                raise BackendError(f"jax: input spec not static: {self._in_spec}")
            self._compile()
        return self._in_spec, self._out_spec

    def set_input_info(self, in_spec: TensorsSpec) -> TensorsSpec:
        if not in_spec.is_static:
            raise BackendError(f"jax: spec must be static, got {in_spec}")
        self._in_spec = in_spec
        self._compile()
        return self._out_spec

    # -- execution ---------------------------------------------------------
    def invoke(self, tensors: Tuple[Any, ...]) -> Tuple[Any, ...]:
        if self._jitted is None:
            self.get_model_info()
        # validate against the negotiated spec (reference tensor_filter.c:592)
        # — a silent mismatch would retrace/recompile per frame.
        if len(tensors) != self._in_spec.num_tensors:
            raise BackendError(
                f"jax: expected {self._in_spec.num_tensors} tensors, got {len(tensors)}"
            )
        if not (self.props is not None and self.props.invoke_dynamic):
            for t, s in zip(tensors, self._in_spec):
                if tuple(t.shape) != s.shape:
                    raise BackendError(
                        f"jax: input shape {tuple(t.shape)} != negotiated {s.shape}"
                    )
        # invoke-dynamic: per-frame shapes may drift (e.g. tensor_crop
        # output feeding a size-agnostic model); jax.jit retraces per new
        # shape and caches each executable
        if self._device is not None:
            # cross-stage hop: async device→device transfer (ICI on TPU)
            tensors = tuple(jax.device_put(t, self._device) for t in tensors)
        elif self._shardings is not None:
            # reshard inputs arriving from any placement (committed
            # single-device arrays from an upstream stage included) onto
            # this filter's mesh; device_put is async and rides ICI
            tensors = tuple(
                jax.device_put(t, s)
                for t, s in zip(tensors, self._shardings[0])
            )
        if self._params_explicit:
            return self._jitted(self._placed_params, *tensors)
        return self._jitted(*tensors)

    def traceable_fn(self):
        fn = self._fn
        if fn is None:
            return None
        if self._device is not None or self._shardings is not None or self._mesh_spec:
            # a device-pinned or mesh-sharded stage is a fusion barrier:
            # fusing it into a neighbor's XLA program would silently drop
            # the placement/partitioning
            return None
        return lambda tensors: _as_tuple(fn(*tensors))

    def warmup(self) -> None:
        """Compile + run once on zeros (first compile is slow on TPU; do it
        before streaming starts, like the reference loads the model at
        PAUSED, not on the first frame)."""
        in_spec, _ = self.get_model_info()
        zeros = tuple(jnp.zeros(t.shape, t.dtype.np_dtype) for t in in_spec)
        out = self.invoke(zeros)
        jax.block_until_ready(out)
