"""The primary TPU backend: models as pure jax functions compiled by XLA.

This is the analogue slot of the reference's tensorflow-lite subplugin (its
default CPU engine, ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_
lite.cc) — but TPU-first: a model is a pure function + params pytree, jitted
once at open (the reference's fw->open = "model load, device init",
SURVEY.md §3.1), with shapes fixed by negotiation so XLA compiles exactly
one executable. The un-jitted function is exposed for fusion with adjacent
transform/decoder stages.

Model sources (by ``model=`` value):

- ``zoo:<name>`` — built-in model zoo (nnstreamer_tpu/models/zoo.py), e.g.
  ``zoo:mobilenet_v2``. Options via custom string
  (``custom="num_classes:1001,width:1.0"``).
- ``<path>.py`` — user script defining
  ``get_model(options: dict) -> (fn, input_spec | None)`` where ``fn`` is a
  pure traceable callable ``(*tensors) -> tensor | tuple``.
- ``<path>.jaxexport`` / ``<path>.stablehlo`` — a serialized
  ``jax.export.Exported`` artifact (StableHLO); the TPU equivalent of
  loading a .tflite flatbuffer.
"""

from __future__ import annotations

import importlib.util
import os
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.backends.base import Backend, BackendError, FilterProps
from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.tensors.spec import DType, TensorSpec, TensorsSpec

_log = get_logger("backends.jax")

_cache_initialized = False


def _init_persistent_cache() -> None:
    """[jax] persistent_cache = DIR enables XLA's on-disk compilation cache
    — the checkpoint/resume analogue for an inference framework (SURVEY.md
    §5.4: compiled-executable persistence), cutting model-open time on
    every process restart."""
    global _cache_initialized
    if _cache_initialized:
        return
    _cache_initialized = True
    from nnstreamer_tpu.config import conf

    cache_dir = conf().get("jax", "persistent_cache")
    if not cache_dir:
        return
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        _log.info("persistent compilation cache at %s", cache_dir)
    except Exception as exc:  # cache is an optimization, never fatal
        _log.warning("persistent cache setup failed: %s", exc)


def _spec_from_avals(avals) -> TensorsSpec:
    return TensorsSpec(
        tuple(
            TensorSpec(tuple(int(d) for d in a.shape), DType.from_any(a.dtype))
            for a in avals
        )
    )


def _as_tuple(x) -> Tuple[Any, ...]:
    if isinstance(x, (tuple, list)):
        return tuple(x)
    return (x,)


@registry.filter_backend("jax")
class JaxBackend(Backend):
    """framework=jax: jitted pure-function inference on the default device
    (TPU when present), optionally sharded over a mesh (see parallel/)."""

    name = "jax"

    def __init__(self) -> None:
        super().__init__()
        self._fn: Optional[Callable] = None
        self._jitted: Optional[Callable] = None
        self._in_spec: Optional[TensorsSpec] = None
        self._out_spec: Optional[TensorsSpec] = None
        self._device = None
        self._shardings = None  # (in_shardings, out_shardings) when sharded

    # -- lifecycle ---------------------------------------------------------
    def open(self, props: FilterProps) -> None:
        _init_persistent_cache()
        self.props = props
        path = props.model_path
        options = props.custom_dict()
        # per-stage device placement (SURVEY.md §7 build order 5): a
        # pipeline shards across chips by pinning each filter to a device;
        # inter-stage hops are device_put transfers riding ICI, replacing
        # the reference's host TCP between pipeline segments
        if "device" in options:
            devs = jax.devices()
            idx = int(options["device"])
            if not (0 <= idx < len(devs)):
                raise BackendError(
                    f"jax: device:{idx} out of range (have {len(devs)})"
                )
            self._device = devs[idx]
        if path.startswith("zoo:"):
            self._open_zoo(path[len("zoo:"):], options)
        elif path.endswith(".py"):
            self._open_script(path, options)
        elif path.endswith((".jaxexport", ".stablehlo", ".hlo")):
            self._open_exported(path)
        else:
            raise BackendError(f"jax: unsupported model source {path!r}")
        if self._in_spec is None:
            self._in_spec = props.input_spec
        if self._in_spec is not None and self._in_spec.is_static:
            self._compile()

    def _open_zoo(self, name: str, options) -> None:
        from nnstreamer_tpu.models import zoo

        m = zoo.get(name, **options)
        self._fn = m.fn
        self._in_spec = m.input_spec

    def _open_script(self, path: str, options) -> None:
        if not os.path.isfile(path):
            raise BackendError(f"jax: model script not found: {path}")
        spec = importlib.util.spec_from_file_location(
            f"nns_tpu_jaxmodel_{abs(hash(path))}", path
        )
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        if not hasattr(module, "get_model"):
            raise BackendError(f"jax: {path} defines no get_model(options)")
        fn, in_spec = module.get_model(options)
        self._fn = fn
        self._in_spec = in_spec

    def _open_exported(self, path: str) -> None:
        with open(path, "rb") as f:
            blob = f.read()
        exported = jax.export.deserialize(bytearray(blob))
        self._fn = lambda *tensors: exported.call(*tensors)
        self._in_spec = _spec_from_avals(exported.in_avals)

    # -- compile -----------------------------------------------------------
    def _compile(self) -> None:
        assert self._fn is not None and self._in_spec is not None
        fn = self._fn
        wrapped = lambda *tensors: _as_tuple(fn(*tensors))  # noqa: E731
        jit_kwargs = {}
        if self._shardings is not None:
            jit_kwargs = dict(
                in_shardings=self._shardings[0], out_shardings=self._shardings[1]
            )
        elif self._device is not None:
            single = jax.sharding.SingleDeviceSharding(self._device)
            jit_kwargs = dict(out_shardings=single)
        self._jitted = jax.jit(wrapped, **jit_kwargs)
        # shape inference without running (reference getModelInfo): one
        # abstract evaluation of the jitted function
        dummies = [
            jax.ShapeDtypeStruct(t.shape, t.dtype.np_dtype) for t in self._in_spec
        ]
        outs = jax.eval_shape(wrapped, *dummies)
        self._out_spec = _spec_from_avals(_as_tuple(outs))

    def set_shardings(self, in_shardings, out_shardings) -> None:
        """Install jit shardings (used by the parallel layer before open
        completes or on renegotiation)."""
        self._shardings = (in_shardings, out_shardings)
        if self._in_spec is not None and self._in_spec.is_static:
            self._compile()

    # -- negotiation -------------------------------------------------------
    def get_model_info(self) -> Tuple[TensorsSpec, TensorsSpec]:
        if self._in_spec is None:
            raise BackendError("jax: input spec unknown (shape-polymorphic "
                               "model needs set_input_info)")
        if self._out_spec is None:
            if not self._in_spec.is_static:
                raise BackendError(f"jax: input spec not static: {self._in_spec}")
            self._compile()
        return self._in_spec, self._out_spec

    def set_input_info(self, in_spec: TensorsSpec) -> TensorsSpec:
        if not in_spec.is_static:
            raise BackendError(f"jax: spec must be static, got {in_spec}")
        self._in_spec = in_spec
        self._compile()
        return self._out_spec

    # -- execution ---------------------------------------------------------
    def invoke(self, tensors: Tuple[Any, ...]) -> Tuple[Any, ...]:
        if self._jitted is None:
            self.get_model_info()
        # validate against the negotiated spec (reference tensor_filter.c:592)
        # — a silent mismatch would retrace/recompile per frame.
        if len(tensors) != self._in_spec.num_tensors:
            raise BackendError(
                f"jax: expected {self._in_spec.num_tensors} tensors, got {len(tensors)}"
            )
        for t, s in zip(tensors, self._in_spec):
            if tuple(t.shape) != s.shape:
                raise BackendError(
                    f"jax: input shape {tuple(t.shape)} != negotiated {s.shape}"
                )
        if self._device is not None:
            # cross-stage hop: async device→device transfer (ICI on TPU)
            tensors = tuple(jax.device_put(t, self._device) for t in tensors)
        return self._jitted(*tensors)

    def traceable_fn(self):
        fn = self._fn
        if fn is None:
            return None
        if self._device is not None:
            # a device-pinned stage is a fusion barrier: fusing it into a
            # neighbor's XLA program would silently drop the placement
            return None
        return lambda tensors: _as_tuple(fn(*tensors))

    def warmup(self) -> None:
        """Compile + run once on zeros (first compile is slow on TPU; do it
        before streaming starts, like the reference loads the model at
        PAUSED, not on the first frame)."""
        in_spec, _ = self.get_model_info()
        zeros = [jnp.zeros(t.shape, t.dtype.np_dtype) for t in in_spec]
        out = self._jitted(*zeros)
        jax.block_until_ready(out)
