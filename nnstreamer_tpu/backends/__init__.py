"""Filter backends ("subplugins" in reference terms).

Importing this package registers the built-in backends, the analogue of the
reference's per-backend .so constructors calling nnstreamer_filter_probe
(nnstreamer_plugin_api_filter.h:505). Optional heavy backends (tflite) are
gated on their imports.
"""

from nnstreamer_tpu.backends.base import (  # noqa: F401
    Backend,
    BackendError,
    FilterProps,
    InvokeStats,
)
from nnstreamer_tpu.backends import fakes  # noqa: F401  (registers)
from nnstreamer_tpu.backends import custom  # noqa: F401  (registers)
from nnstreamer_tpu.backends.custom import (  # noqa: F401
    register_custom_easy,
    unregister_custom_easy,
)
from nnstreamer_tpu.backends import jax_backend  # noqa: F401  (registers)

try:  # torch is optional (cpu parity backend)
    from nnstreamer_tpu.backends import torch_backend  # noqa: F401
except Exception:  # pragma: no cover
    pass

try:  # tflite is optional; absent in the base image
    from nnstreamer_tpu.backends import tflite_backend  # noqa: F401
except Exception:  # pragma: no cover
    pass
