"""Deterministic fake backends for tests and examples.

Reference: tests/nnstreamer_example/ builds custom_example_{passthrough,
scaler,average,framecounter,...} .so stand-ins used wherever a real model is
not the point (SURVEY.md §4). These are the same stand-ins, as jax-traceable
backends so they also exercise the fusion path.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.backends.base import Backend, BackendError, FilterProps
from nnstreamer_tpu.tensors.spec import DType, TensorSpec, TensorsSpec


@registry.filter_backend("passthrough")
class PassthroughBackend(Backend):
    """Identity filter (custom_example_passthrough). Accepts any static
    input spec; output spec == input spec."""

    name = "passthrough"
    IS_IDENTITY = True

    def open(self, props: FilterProps) -> None:
        self.props = props
        self._spec = props.input_spec

    def get_model_info(self) -> Tuple[TensorsSpec, TensorsSpec]:
        if self._spec is None:
            raise BackendError("passthrough: input spec unknown until set")
        return self._spec, self._spec

    def set_input_info(self, in_spec: TensorsSpec) -> TensorsSpec:
        self._spec = in_spec
        return in_spec

    def invoke(self, tensors: Tuple[Any, ...]) -> Tuple[Any, ...]:
        return tensors

    def traceable_fn(self) -> Callable:
        return lambda tensors: tensors


@registry.filter_backend("scaler")
class ScalerBackend(Backend):
    """Multiply-by-constant (custom_example_scaler). custom="factor:2.0"."""

    name = "scaler"

    def open(self, props: FilterProps) -> None:
        self.props = props
        self._factor = float(props.custom_dict().get("factor", "2.0"))
        self._spec = props.input_spec

    def get_model_info(self) -> Tuple[TensorsSpec, TensorsSpec]:
        if self._spec is None:
            raise BackendError("scaler: input spec unknown until set")
        return self._spec, self._spec

    def set_input_info(self, in_spec: TensorsSpec) -> TensorsSpec:
        self._spec = in_spec
        return in_spec

    def invoke(self, tensors):
        return self.traceable_fn()(tensors)

    def traceable_fn(self) -> Callable:
        f = self._factor
        return lambda tensors: tuple(
            (jnp.asarray(t) * jnp.asarray(f, dtype=jnp.asarray(t).dtype)) for t in tensors
        )


@registry.filter_backend("average")
class AverageBackend(Backend):
    """Spatial average per tensor (custom_example_average): NHWC → N11C."""

    name = "average"

    def open(self, props: FilterProps) -> None:
        self.props = props
        self._in_spec = props.input_spec
        self._out_spec = self._derive_out(self._in_spec) if self._in_spec else None

    @staticmethod
    def _derive_out(in_spec: TensorsSpec) -> TensorsSpec:
        outs = []
        for t in in_spec:
            if t.rank < 3:
                raise BackendError(f"average: rank>=3 required, got {t}")
            shape = list(t.shape)
            shape[-3] = 1
            shape[-2] = 1
            outs.append(TensorSpec(tuple(shape), t.dtype))
        return TensorsSpec(tuple(outs), in_spec.format, in_spec.rate)

    def get_model_info(self) -> Tuple[TensorsSpec, TensorsSpec]:
        if self._in_spec is None:
            raise BackendError("average: input spec unknown until set")
        return self._in_spec, self._out_spec

    def set_input_info(self, in_spec: TensorsSpec) -> TensorsSpec:
        self._in_spec = in_spec
        self._out_spec = self._derive_out(in_spec)
        return self._out_spec

    def invoke(self, tensors):
        return self.traceable_fn()(tensors)

    def traceable_fn(self) -> Callable:
        def fn(tensors):
            out = []
            for t in tensors:
                a = jnp.asarray(t)
                m = jnp.mean(
                    a.astype(jnp.float32), axis=(-3, -2), keepdims=True
                )
                out.append(m.astype(a.dtype))
            return tuple(out)

        return fn


@registry.filter_backend("hostscaler")
class HostScalerBackend(Backend):
    """Host-bound scaler (numpy, no traceable fn — a fusion barrier) that
    declares the ``batchable`` capability: invoke_batched stacks the
    window and multiplies once. The test stand-in for an engine with a
    real batched entry point (vs tflite's strictly per-frame invoke)."""

    name = "hostscaler"
    batchable = True

    def open(self, props: FilterProps) -> None:
        self.props = props
        self._factor = float(props.custom_dict().get("factor", "2.0"))
        self._spec = props.input_spec
        self.batched_calls = 0  # tests assert the batched entry was used

    def get_model_info(self) -> Tuple[TensorsSpec, TensorsSpec]:
        if self._spec is None:
            raise BackendError("hostscaler: input spec unknown until set")
        return self._spec, self._spec

    def set_input_info(self, in_spec: TensorsSpec) -> TensorsSpec:
        self._spec = in_spec
        return in_spec

    def invoke(self, tensors):
        return tuple(
            (np.asarray(t) * self._factor).astype(np.asarray(t).dtype)
            for t in tensors
        )

    def invoke_batched(self, batch):
        self.batched_calls += 1
        n_t = len(batch[0])
        cols = []
        for i in range(n_t):
            stacked = np.stack([np.asarray(ts[i]) for ts in batch])
            cols.append((stacked * self._factor).astype(stacked.dtype))
        return [tuple(col[j] for col in cols) for j in range(len(batch))]


@registry.filter_backend("faulty")
class FaultyBackend(Backend):
    """Chaos-injection passthrough (docs/fault-tolerance.md): a
    deterministic stand-in for a flaky inference engine, used to drive
    the executor's error policies end-to-end. Host-bound (no traceable
    fn) so failures raise per frame. Options via ``custom=``:

    - ``fail_rate:0.2`` — probability an invoke raises (seeded RNG).
    - ``fail_every_n:5`` — every Nth invoke raises (deterministic; a
      retried frame re-rolls on the next invoke count).
    - ``fail_first_n:3`` — the first N invokes raise, then healthy
      (circuit-breaker recovery scenarios).
    - ``latency_spike_ms:50`` + ``spike_every_n:10`` — periodic stalls.
    - ``raise_type:backend|value|runtime`` — exception class raised.
    - ``strict_shapes:true`` — invokes validate tensors against the
      opened spec, so tensor_chaos-corrupted frames raise here.
    - ``batchable:true`` — declare the micro-batch capability; the
      default invoke_batched chains invoke(), so one poisoned frame
      fails the whole window (the batch-split path under test).
    - ``seed:7`` — RNG seed (default 0).

    Device-plane modes (pipeline/device_faults.py, docs/resilience.md):

    - ``oom_every_n:5`` — every Nth invoke raises DeviceOOMError (host
      path; drives the circuit after repeated hits).
    - ``oom_above_rows:2`` — any dispatch wider than N rows raises
      DeviceOOMError: in ``invoke_batched`` by window length, and via
      the ``device_probe(rows)`` hook FusedSegment.process_batch calls
      with the padded bucket before dispatching — a deterministic
      "this device fits bucket N" boundary that exercises the fused
      OOM-degrade ladder.
    - ``compile_fail:true`` (with ``traceable:true``) — the traceable fn
      raises DeviceCompileError whenever it is being TRACED (jit/vmap
      compile of the fused program fails) while the eager path — the
      same fn on concrete arrays — still works: the compile-fallback
      breaker's scenario. ``compile_fail_first_n:K`` bounds the outage
      to the first K traces so recovery probes can observe a comeback.
    - ``device_lost_at:7`` — invoke N and every later one raise
      DeviceLostError (a lost device stays lost; replica-failover
      food). ``device_lost_for:M`` bounds the outage to M invokes so
      circuit-recovery probes can observe a comeback. With
      ``only_replica:<i>`` the loss applies only to the replica whose
      opened ``_replica:<i>`` index matches (parallel/replicas.py
      stamps it), so a 2-replica failover run kills exactly one.
    - ``traceable:true`` — expose a traceable fn (so the backend can
      fuse); trace-time injections above apply there.
    """

    name = "faulty"

    _RAISES = {
        "backend": BackendError,
        "value": ValueError,
        "runtime": RuntimeError,
    }

    def open(self, props: FilterProps) -> None:
        import random

        # runtime import: backends load before the elements package
        from nnstreamer_tpu.elements.base import _parse_bool

        self.props = props
        opts = props.custom_dict()
        self._spec = props.input_spec
        self._fail_rate = float(opts.get("fail_rate", "0"))
        self._fail_every_n = int(opts.get("fail_every_n", "0"))
        self._fail_first_n = int(opts.get("fail_first_n", "0"))
        self._spike_ms = float(opts.get("latency_spike_ms", "0"))
        self._spike_every_n = int(opts.get("spike_every_n", "0"))
        self._strict = _parse_bool(opts.get("strict_shapes", "false"))
        self.batchable = _parse_bool(opts.get("batchable", "false"))
        self._exc = self._RAISES.get(
            opts.get("raise_type", "backend").lower(), BackendError
        )
        self._rng = random.Random(int(opts.get("seed", "0")))
        # device-plane chaos (pipeline/device_faults.py)
        self._oom_every_n = int(opts.get("oom_every_n", "0"))
        self._oom_above_rows = int(opts.get("oom_above_rows", "0"))
        self._compile_fail = _parse_bool(opts.get("compile_fail", "false"))
        self._compile_fail_first_n = int(opts.get("compile_fail_first_n", "0"))
        self._device_lost_at = int(opts.get("device_lost_at", "0"))
        self._device_lost_for = int(opts.get("device_lost_for", "0"))
        self._traceable = _parse_bool(opts.get("traceable", "false"))
        # replica scoping: parallel/replicas.py opens each replica with
        # `_replica:<i>` appended to custom; only_replica:<i> restricts
        # the device-plane injections to that one instance so failover
        # runs kill exactly the replica they mean to
        self._replica_idx = opts.get("_replica")
        only = opts.get("only_replica")
        self._inject = (
            only is None
            or (self._replica_idx is not None
                and int(only) == int(self._replica_idx))
        )
        self.invokes = 0
        self.failures = 0
        self.batched_calls = 0
        self.device_faults = 0
        self.traces = 0  # traceable-fn trace-time entries observed

    def get_model_info(self) -> Tuple[TensorsSpec, TensorsSpec]:
        if self._spec is None:
            raise BackendError("faulty: input spec unknown until set")
        return self._spec, self._spec

    def set_input_info(self, in_spec: TensorsSpec) -> TensorsSpec:
        self._spec = in_spec
        return in_spec

    def _maybe_fail(self) -> None:
        n = self.invokes
        fail = (
            (self._fail_first_n and n <= self._fail_first_n)
            or (self._fail_every_n and n % self._fail_every_n == 0)
            or (self._fail_rate and self._rng.random() < self._fail_rate)
        )
        if fail:
            self.failures += 1
            raise self._exc(f"faulty: injected failure on invoke {n}")

    def _device_fault(self, exc_cls, msg: str):
        from nnstreamer_tpu.pipeline.device_faults import DeviceFaultError

        assert issubclass(exc_cls, DeviceFaultError)
        self.failures += 1
        self.device_faults += 1
        raise exc_cls(msg)

    def _maybe_device_fail(self) -> None:
        if not self._inject:
            return
        from nnstreamer_tpu.pipeline.device_faults import (
            DeviceLostError,
            DeviceOOMError,
        )

        n = self.invokes
        if self._device_lost_at and n >= self._device_lost_at and (
            not self._device_lost_for
            or n < self._device_lost_at + self._device_lost_for
        ):
            self._device_fault(
                DeviceLostError, f"faulty: device lost at invoke {n}"
            )
        if self._oom_every_n and n % self._oom_every_n == 0:
            self._device_fault(
                DeviceOOMError, f"faulty: RESOURCE_EXHAUSTED on invoke {n}"
            )

    def device_probe(self, rows: int) -> None:
        """Deterministic device-capacity boundary for the fused batched
        path: FusedSegment.process_batch probes every member backend
        with the padded bucket before dispatching, so a bucket wider
        than ``oom_above_rows`` OOMs exactly like a real
        RESOURCE_EXHAUSTED from the stacked program would."""
        from nnstreamer_tpu.pipeline.device_faults import DeviceOOMError

        if self._inject and self._oom_above_rows and rows > self._oom_above_rows:
            self._device_fault(
                DeviceOOMError,
                f"faulty: RESOURCE_EXHAUSTED allocating {rows} rows "
                f"(fits {self._oom_above_rows})",
            )

    def invoke(self, tensors: Tuple[Any, ...]) -> Tuple[Any, ...]:
        import time as _t

        self.invokes += 1
        if self._spike_every_n and self.invokes % self._spike_every_n == 0:
            _t.sleep(self._spike_ms / 1000.0)
        if self._strict and self._spec is not None:
            for t, ts in zip(tensors, self._spec):
                if tuple(np.asarray(t).shape) != tuple(ts.shape):
                    self.failures += 1
                    raise self._exc(
                        f"faulty: corrupted frame — tensor shape "
                        f"{np.asarray(t).shape} != spec {ts.shape}"
                    )
        self._maybe_device_fail()
        self._maybe_fail()
        return tensors

    def invoke_batched(self, batch):
        self.batched_calls += 1
        self.device_probe(len(batch))
        return super().invoke_batched(batch)

    def traceable_fn(self) -> Optional[Callable]:
        """Identity fn when ``traceable:true`` (the backend then fuses
        like a jax model); with ``compile_fail`` the fn raises
        DeviceCompileError when it sees TRACERS (a jit/vmap compile of
        the fused program) but passes concrete arrays through — the
        compile-breaker's exact scenario: the jitted path is broken,
        the eager path still serves."""
        if not self._traceable:
            return None

        def fn(tensors):
            import jax

            tracing = any(
                isinstance(t, jax.core.Tracer) for t in tensors
            )
            if tracing:
                self.traces += 1
                if self._inject and self._compile_fail and (
                    not self._compile_fail_first_n
                    or self.traces <= self._compile_fail_first_n
                ):
                    from nnstreamer_tpu.pipeline.device_faults import (
                        DeviceCompileError,
                    )

                    self.device_faults += 1
                    raise DeviceCompileError(
                        f"faulty: injected compilation failure "
                        f"(trace {self.traces})"
                    )
            return tensors

        return fn


@registry.filter_backend("framecounter")
class FrameCounterBackend(Backend):
    """Emits a running uint32 frame count (custom_example_framecounter) —
    stateful, so host-bound (no traceable fn)."""

    name = "framecounter"

    def open(self, props: FilterProps) -> None:
        self.props = props
        self._count = 0
        self._in_spec = props.input_spec

    def get_model_info(self) -> Tuple[TensorsSpec, TensorsSpec]:
        out = TensorsSpec.of(TensorSpec((1,), DType.UINT32))
        return (self._in_spec or TensorsSpec()), out

    def set_input_info(self, in_spec: TensorsSpec) -> TensorsSpec:
        self._in_spec = in_spec
        return self.get_model_info()[1]

    def invoke(self, tensors):
        out = np.array([self._count], dtype=np.uint32)
        self._count += 1
        return (out,)

    # warm restart (docs/resilience.md): the running count is exactly
    # the kind of per-element state Executor.snapshot()/restore() exists
    # to carry across a drain/resume round-trip
    def state_snapshot(self) -> dict:
        return {"count": self._count}

    def state_restore(self, snap: dict) -> None:
        self._count = int(snap.get("count", 0))
