"""TFLite backend (optional): parity path for .tflite models.

Reference: ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc —
the reference's default engine. Gated on a TFLite interpreter being
importable (tflite_runtime, ai_edge_litert, or tensorflow); absent in the
base image, in which case this module's import fails and the backend simply
isn't registered (same as a missing .so in the reference).
"""

from __future__ import annotations

import os
from typing import Any, Tuple

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.backends.base import Backend, BackendError, FilterProps
from nnstreamer_tpu.tensors.spec import DType, TensorSpec, TensorsSpec

def _load_interpreter():
    """Resolve a TFLite Interpreter class lazily — importing tensorflow
    costs seconds, so it must not happen at registry-load time, only when a
    tflite model is actually opened (the reference dlopens the subplugin .so
    lazily for the same reason, nnstreamer_subplugin.c:157-166)."""
    try:  # pragma: no cover - depends on image contents
        from tflite_runtime.interpreter import Interpreter  # type: ignore

        return Interpreter
    except ImportError:
        pass
    try:
        from ai_edge_litert.interpreter import Interpreter  # type: ignore

        return Interpreter
    except ImportError:
        pass
    import tensorflow as tf  # type: ignore

    return tf.lite.Interpreter  # lazy-loader attr; not a real submodule


def _spec_from_details(details) -> TensorsSpec:
    return TensorsSpec(
        tuple(
            TensorSpec(
                tuple(int(x) for x in d["shape"]),
                DType.from_any(np.dtype(d["dtype"]).name),
                d.get("name"),
            )
            for d in details
        )
    )


@registry.filter_backend("tflite")
class TFLiteBackend(Backend):
    """framework=tflite model=m.tflite — host CPU interpreter."""

    name = "tflite"

    def open(self, props: FilterProps) -> None:
        self.props = props
        path = props.model_path
        if not os.path.isfile(path):
            raise BackendError(f"tflite: model not found: {path}")
        threads = int(props.custom_dict().get("num_threads", "0")) or None
        Interpreter = _load_interpreter()
        self._interp = Interpreter(model_path=path, num_threads=threads)
        self._interp.allocate_tensors()

    def get_model_info(self) -> Tuple[TensorsSpec, TensorsSpec]:
        return (
            _spec_from_details(self._interp.get_input_details()),
            _spec_from_details(self._interp.get_output_details()),
        )

    def set_input_info(self, in_spec: TensorsSpec) -> TensorsSpec:
        details = self._interp.get_input_details()
        if len(details) != in_spec.num_tensors:
            raise BackendError("tflite: tensor count mismatch")
        for d, t in zip(details, in_spec):
            self._interp.resize_tensor_input(d["index"], list(t.shape))
        self._interp.allocate_tensors()
        return self.get_model_info()[1]

    def invoke(self, tensors: Tuple[Any, ...]) -> Tuple[Any, ...]:
        details = self._interp.get_input_details()
        if len(tensors) != len(details):
            raise BackendError(
                f"tflite: expected {len(details)} input tensors, got {len(tensors)}"
            )
        for d, t in zip(details, tensors):
            self._interp.set_tensor(d["index"], np.asarray(t, dtype=d["dtype"]))
        self._interp.invoke()
        return tuple(
            self._interp.get_tensor(d["index"])
            for d in self._interp.get_output_details()
        )
