"""CPU torch backend: the output-parity reference path.

Reference: ext/nnstreamer/tensor_filter/tensor_filter_pytorch.cc (TorchScript
via libtorch). Here: torch.jit.load on CPU. This backend exists for parity
testing (BASELINE.md: "output parity vs CPU path") and as an example of a
host-bound backend that acts as a fusion barrier (traceable_fn → None).
"""

from __future__ import annotations

import os
from typing import Any, Tuple

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.backends.base import Backend, BackendError, FilterProps
from nnstreamer_tpu.tensors.spec import DType, TensorSpec, TensorsSpec


@registry.filter_backend("torch")
class TorchBackend(Backend):
    """framework=torch model=script.pt — TorchScript on CPU."""

    name = "torch"

    def open(self, props: FilterProps) -> None:
        try:
            import torch
        except ImportError as exc:  # pragma: no cover
            raise BackendError("torch not available") from exc
        self.props = props
        path = props.model_path
        if not os.path.isfile(path):
            raise BackendError(f"torch: model not found: {path}")
        self._torch = torch
        self._module = torch.jit.load(path, map_location="cpu")
        self._module.eval()
        self._in_spec = props.input_spec
        self._out_spec = props.output_spec

    def get_model_info(self) -> Tuple[TensorsSpec, TensorsSpec]:
        if self._in_spec is None:
            raise BackendError("torch: set input spec (TorchScript carries no "
                               "static shapes)")
        if self._out_spec is None:
            self._out_spec = self._probe_output(self._in_spec)
        return self._in_spec, self._out_spec

    def _probe_output(self, in_spec: TensorsSpec) -> TensorsSpec:
        """Shape inference by a zero-input trial run (the reference's
        trial-negotiation fallback, nnstreamer_plugin_api_filter.h:351-368)."""
        zeros = [
            self._torch.zeros(tuple(t.shape), dtype=self._torch_dtype(t.dtype))
            for t in in_spec
        ]
        with self._torch.no_grad():
            out = self._module(*zeros)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        return TensorsSpec(
            tuple(
                TensorSpec(tuple(int(d) for d in o.shape), DType.from_any(str(o.numpy().dtype)))
                for o in outs
            )
        )

    def _torch_dtype(self, dt: DType):
        return getattr(self._torch, dt.value if dt is not DType.BFLOAT16 else "bfloat16")

    def set_input_info(self, in_spec: TensorsSpec) -> TensorsSpec:
        self._in_spec = in_spec
        self._out_spec = self._probe_output(in_spec)
        return self._out_spec

    def invoke(self, tensors: Tuple[Any, ...]) -> Tuple[Any, ...]:
        torch = self._torch
        ins = [torch.from_numpy(np.ascontiguousarray(np.asarray(t))) for t in tensors]
        with torch.no_grad():
            out = self._module(*ins)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        return tuple(o.numpy() for o in outs)
