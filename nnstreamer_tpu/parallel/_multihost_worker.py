"""Multi-host dryrun worker: one PROCESS of a simulated pod slice.

Run as::

    python -m nnstreamer_tpu.parallel._multihost_worker \
        <phase> <pid> <nprocs> <coordinator> <workdir> [devices_per_proc]

Each process pins a virtual CPU platform with ``devices_per_proc``
devices, joins the jax.distributed runtime at ``coordinator``, and builds
ONE GLOBAL dp×tp mesh spanning every process — the single-machine
stand-in for a TPU pod (SURVEY.md §5.8: hosts rendezvous, jax.devices()
goes global, collectives ride DCN between processes).

Phases (the checkpoint/restart drill, §5.4 applied across hosts):

- ``fresh``:  run one sharded training step, checkpoint the state from
  ALL processes (orbax multihost save), record the post-step eval loss.
- ``resume``: a brand-new process set (the simulated host restart)
  restores the checkpoint directly onto the mesh shardings, verifies the
  eval loss matches the recorded one bit-for-bit, then trains one more
  step — proving the pod resumes where it left off.
"""

from __future__ import annotations

import os
import sys


def run_phase(
    phase: str,
    pid: int,
    nprocs: int,
    coordinator: str,
    workdir: str,
    devices_per_proc: int = 4,
) -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={devices_per_proc}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp  # noqa: F401
    import numpy as np

    from nnstreamer_tpu.models import mobilenet_v2
    from nnstreamer_tpu.parallel import checkpoint as ckpt
    from nnstreamer_tpu.parallel import multihost
    from nnstreamer_tpu.parallel.mesh import make_mesh
    from nnstreamer_tpu.parallel.train import (
        loss_fn,
        make_train_step,
        param_shardings,
    )

    multihost.initialize(
        coordinator_address=coordinator, num_processes=nprocs, process_id=pid
    )
    assert jax.process_count() == nprocs, jax.process_count()
    n_global = len(jax.devices())
    assert n_global == nprocs * devices_per_proc, n_global

    mesh = make_mesh(n_global, axes=("dp", "tp"))
    dp = mesh.shape["dp"]
    batch = max(2 * dp, dp)
    params0 = mobilenet_v2.init_params(jax.random.PRNGKey(0), num_classes=16)
    p_shard = param_shardings(mesh, params0)

    from jax.sharding import NamedSharding, PartitionSpec as P

    batch_shard = NamedSharding(mesh, P("dp"))

    def global_batch(seed, shape, hi, dtype):
        # identical host data on every process; each contributes the
        # shards it addresses
        full = np.random.default_rng(seed).integers(0, hi, shape).astype(dtype)
        return jax.make_array_from_callback(
            shape, batch_shard, lambda idx: full[idx]
        )

    images = global_batch(0, (batch, 32, 32, 3), 255, np.uint8)
    labels = global_batch(1, (batch,), 16, np.int32)
    images2 = global_batch(2, (batch, 32, 32, 3), 255, np.uint8)
    labels2 = global_batch(3, (batch,), 16, np.int32)

    eval_loss = jax.jit(loss_fn)
    ckpt_path = os.path.join(workdir, "pod_ckpt")
    loss_file = os.path.join(workdir, "eval_loss.txt")

    if phase == "fresh":
        step, params, opt_state = make_train_step(mesh, params0)
        params, opt_state, loss = step(params, opt_state, images, labels)
        jax.block_until_ready(loss)
        assert np.isfinite(float(loss)), f"non-finite loss {loss}"
        ckpt.save(ckpt_path, {"params": params})
        l2 = float(eval_loss(params, images2, labels2))
        if multihost.is_primary():
            with open(loss_file, "w") as f:
                f.write(repr(l2))
        multihost.barrier("fresh-saved")
        print(f"proc{pid} fresh ok loss={float(loss):.6f} eval={l2:.6f}",
              flush=True)
    elif phase == "resume":
        # simulated host restart: nothing survives but the checkpoint —
        # restore it straight onto this (new) process set's mesh shardings
        restored = ckpt.restore(
            ckpt_path, like={"params": params0}, shardings={"params": p_shard}
        )["params"]
        l2 = float(eval_loss(restored, images2, labels2))
        with open(loss_file) as f:
            want = float(f.read())
        assert abs(l2 - want) < 1e-6, (l2, want)
        # training continues from the restored state
        step, params, opt_state = make_train_step(mesh, restored)
        params, opt_state, loss = step(params, opt_state, images2, labels2)
        jax.block_until_ready(loss)
        assert np.isfinite(float(loss)), f"non-finite resumed loss {loss}"
        print(f"proc{pid} resume ok eval={l2:.6f} next={float(loss):.6f}",
              flush=True)
    else:
        raise SystemExit(f"unknown phase {phase!r}")
    multihost.shutdown()


if __name__ == "__main__":
    run_phase(
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
        sys.argv[4],
        sys.argv[5],
        int(sys.argv[6]) if len(sys.argv) > 6 else 4,
    )
