"""Pipeline parallelism: GPipe-style microbatch rotation over a mesh axis.

The reference's only pipeline parallelism is streaming elements on threads
(SURVEY.md §2.6 item 1); on TPU the analogue for *model* pipelining is
stage-sharded layers with activations hopping stage→stage over ICI. Layers
live in a stacked pytree (leaves [L, ...], models/transformer.py layout);
sharding the leading dim over the ``pp`` axis gives every device a
contiguous stage slice. The schedule is the classic (M + S − 1)-tick loop:
each tick every stage runs one microbatch and ``ppermute`` hands its output
to the next stage — a bubble of (S−1)/(M+S−1), amortized by more
microbatches. The tick loop is a ``lax.scan``, so the same code path
differentiates for training.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from nnstreamer_tpu.parallel.mesh import shard_map as _shard_map


def pipeline_forward_local(
    stage_params,
    x,
    axis_name: str,
    stage_fn: Callable,
    n_microbatches: int,
):
    """Per-shard schedule (call inside shard_map).

    stage_params: this stage's layer slice (leaves [L/S, ...]).
    x: full input [N, ...] (replicated; stage 0 feeds it in), N = M * mb.
    stage_fn(x_mb, stage_params) → y_mb, same shape (homogeneous stages).
    Returns the full output [N, ...] (replicated via final psum).
    """
    s = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    m = n_microbatches
    n = x.shape[0]
    if n % m:
        raise ValueError(f"pipeline: batch {n} not divisible by {m} microbatches")
    x_mbs = x.reshape((m, n // m) + x.shape[1:])
    ticks = m + s - 1
    perm = [(i, i + 1) for i in range(s - 1)]

    def tick(recv, t):
        feed = x_mbs[jnp.clip(t, 0, m - 1)]
        inp = jnp.where(idx == 0, feed, recv)
        out = stage_fn(inp, stage_params)
        return jax.lax.ppermute(out, axis_name, perm), out

    init = jnp.zeros_like(x_mbs[0])
    _, outs = jax.lax.scan(tick, init, jnp.arange(ticks))
    # outs [ticks, mb, ...]; the last stage's microbatch j completes at
    # tick j + s - 1 → its valid stream is outs[s-1:]
    y = outs[s - 1 :]
    y = jnp.where(idx == s - 1, y, 0)
    y = jax.lax.psum(y, axis_name)  # only the last stage contributes
    return y.reshape((n,) + y.shape[2:])


def make_pipeline_forward(
    mesh: Mesh,
    stage_fn: Callable,
    n_microbatches: int,
    axis: str = "pp",
):
    """Jitted full-array entry: (stacked_params, x) → y.

    stacked_params leaves are [L, ...], sharded over ``axis`` on the
    leading dim; L must divide by the axis size. x and y are replicated.
    """
    fn = _shard_map(
        functools.partial(
            pipeline_forward_local,
            axis_name=axis,
            stage_fn=stage_fn,
            n_microbatches=n_microbatches,
        ),
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(fn)
