"""Replica failover for the parallel serving plane (docs/resilience.md).

The ROADMAP's scale-out phase multiplexes client streams over model
REPLICAS (mesh-sharded or device-pinned copies of one model). A replica
is exactly the unit that dies in production — a preempted chip, a wedged
runtime — and Hermes-style multi-chip placement (PAPERS.md) only works
if the dispatcher survives that. :class:`ReplicaSet` is the health/
failover core, deliberately generic over "a callable that invokes one
replica" so it serves both the tensor_filter ``replicas=N`` wiring
(elements/filter.py) and programmatic dispatchers:

- **dispatch** round-robins frames over healthy replicas;
- **failover**: a device-classified fault (pipeline/device_faults.py)
  re-dispatches the in-flight frame onto another replica — the frame is
  never lost to a dying replica — and after ``unhealthy_after``
  CONSECUTIVE device faults the replica is marked unhealthy and leaves
  the rotation;
- **recovery**: every ``probe_every`` dispatches, one frame probes an
  unhealthy replica; success re-admits it;
- **exhaustion**: when no replica is healthy (and the probe budget this
  dispatch is spent), :class:`ReplicaExhaustedError` raises with the
  last underlying fault chained — the caller's error policy
  (pipeline/faults.py drop/retry/route) then disposes of the frame,
  which for admitted edge requests NACKs the client and releases its
  admission budget exactly once (the PR-6 accounting).

Non-device exceptions (bad input, user code) propagate unchanged: they
say nothing about replica health, and retrying them elsewhere would
just fail N times.

Thread safety: health state mutates under a lock; the invokes themselves
run outside it so replicas serve concurrently from many executor
threads.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.pipeline.device_faults import (
    ReplicaExhaustedError,
    classify_device_fault,
)

_log = get_logger("parallel.replicas")


class Replica:
    """One dispatch target: an invoke callable plus health bookkeeping."""

    __slots__ = ("idx", "invoke", "healthy", "consec_faults", "faults",
                 "served", "fault_kinds")

    def __init__(self, idx: int, invoke: Callable[..., Any]) -> None:
        self.idx = idx
        self.invoke = invoke
        self.healthy = True
        self.consec_faults = 0
        self.faults = 0
        self.served = 0
        self.fault_kinds: Dict[str, int] = {}


class ReplicaSet:
    """Load-balanced dispatch over N replicas with device-fault-driven
    failover (module docstring has the contract)."""

    def __init__(
        self,
        invokes: Sequence[Callable[..., Any]],
        unhealthy_after: int = 3,
        probe_every: int = 64,
        classify: Callable[[BaseException], Optional[str]] =
        classify_device_fault,
    ) -> None:
        if not invokes:
            raise ValueError("ReplicaSet needs at least one replica")
        self.replicas: List[Replica] = [
            Replica(i, fn) for i, fn in enumerate(invokes)
        ]
        self.unhealthy_after = max(1, int(unhealthy_after))
        self.probe_every = max(1, int(probe_every))
        self.classify = classify
        self._lock = threading.Lock()
        self._rr = 0            # round-robin cursor over healthy replicas
        self._probe_rr = 0      # rotation cursor over unhealthy replicas
        self._since_probe = 0   # dispatches since the last recovery probe
        self.failovers = 0      # frames re-dispatched off a faulting replica
        self.exhaustions = 0    # dispatches whose whole plan faulted

    # -- selection ---------------------------------------------------------
    def _next_targets(self) -> List[Replica]:
        """Ordered dispatch plan for ONE frame: healthy replicas from the
        round-robin cursor; every probe_every dispatches an unhealthy
        replica is prepended as a recovery probe (its frame falls
        through to the healthy rotation if the probe fails)."""
        with self._lock:
            healthy = [r for r in self.replicas if r.healthy]
            sick = [r for r in self.replicas if not r.healthy]
            plan: List[Replica] = []
            if not sick:
                # cadence counts dispatches WHILE benched — an idle-high
                # counter would probe a just-benched (still dead) replica
                # on the very next frame instead of probe_every later
                self._since_probe = 0
            else:
                self._since_probe += 1
            if sick and (
                not healthy or self._since_probe >= self.probe_every
            ):
                self._since_probe = 0
                # rotate the probe across sick replicas: always probing
                # the lowest index starves the rest of recovery when it
                # is permanently dead
                start = self._probe_rr % len(sick)
                self._probe_rr += 1
                if healthy:
                    plan.append(sick[start])
                else:
                    # nothing healthy left: give EVERY benched replica a
                    # shot this frame rather than exhausting behind one
                    # dead probe target
                    plan.extend(sick[start:] + sick[:start])
            if healthy:
                start = self._rr % len(healthy)
                self._rr += 1
                plan.extend(healthy[start:] + healthy[:start])
            return plan

    # -- health bookkeeping ------------------------------------------------
    def _record_fault(self, rep: Replica, kind: str) -> None:
        with self._lock:
            rep.faults += 1
            rep.fault_kinds[kind] = rep.fault_kinds.get(kind, 0) + 1
            rep.consec_faults += 1
            if rep.healthy and rep.consec_faults >= self.unhealthy_after:
                rep.healthy = False
                _log.warning(
                    "replica %d UNHEALTHY after %d consecutive device "
                    "fault(s) (last: %s); redistributing its load",
                    rep.idx, rep.consec_faults, kind,
                )

    def _record_ok(self, rep: Replica) -> None:
        with self._lock:
            rep.consec_faults = 0
            rep.served += 1
            if not rep.healthy:
                rep.healthy = True
                _log.warning("replica %d recovered; back in rotation",
                             rep.idx)

    # -- dispatch ----------------------------------------------------------
    def dispatch(self, *args, **kwargs):
        """Invoke one frame on the replica set. Device faults fail over
        to the next target in this frame's plan; raises
        ReplicaExhaustedError (last fault chained) when the plan runs
        dry with nothing healthy left."""
        last: Optional[BaseException] = None
        plan = self._next_targets()
        for n, rep in enumerate(plan):
            try:
                out = rep.invoke(*args, **kwargs)
            except Exception as exc:  # noqa: BLE001 — classified below
                kind = self.classify(exc)
                if kind is None:
                    # not a replica-health signal: the caller's problem
                    raise
                self._record_fault(rep, kind)
                if n + 1 < len(plan):
                    with self._lock:
                        self.failovers += 1
                last = exc
                continue
            self._record_ok(rep)
            return out
        with self._lock:
            self.exhaustions += 1
        raise ReplicaExhaustedError(
            f"all {len(self.replicas)} replicas unhealthy"
            + (f" (last fault: {last})" if last is not None else "")
        ) from last

    # -- observability / warm restart --------------------------------------
    @property
    def healthy_count(self) -> int:
        return sum(1 for r in self.replicas if r.healthy)

    def stats(self) -> Dict[str, Any]:
        return {
            "replicas": len(self.replicas),
            "healthy": self.healthy_count,
            "failovers": self.failovers,
            "exhaustions": self.exhaustions,
            "served": [r.served for r in self.replicas],
            "faults": [r.faults for r in self.replicas],
        }

    def snapshot(self) -> dict:
        return {
            "healthy": [r.healthy for r in self.replicas],
            "failovers": self.failovers,
            "exhaustions": self.exhaustions,
        }

    def restore(self, snap: dict) -> None:
        flags = snap.get("healthy") or []
        for rep, ok in zip(self.replicas, flags):
            rep.healthy = bool(ok)
            rep.consec_faults = 0
        self.failovers = int(snap.get("failovers", 0))
        self.exhaustions = int(snap.get("exhaustions", 0))
