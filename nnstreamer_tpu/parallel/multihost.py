"""Multi-host (DCN) runtime: the jax.distributed bring-up for pod slices.

The reference scales across machines with nnstreamer-edge over TCP/MQTT
(SURVEY.md §5.8); the TPU-native equivalent inside a pod is the JAX
distributed runtime — every host runs the same program, a coordinator
rendezvous wires the hosts, `jax.devices()` becomes the GLOBAL device list,
and the same Mesh/sharding code from this package spans hosts: XLA routes
collectives over ICI within a slice and DCN across slices. Host-external
clients still enter through the edge layer (tensor_query / gRPC / MQTT).

Typical pod bring-up (same command on every host):

    from nnstreamer_tpu.parallel import multihost, mesh
    multihost.initialize()           # TPU pods: env auto-detection
    m = mesh.make_mesh(axes=("dp", "tp"))   # spans ALL hosts' chips

For CPU/GPU clusters or manual rendezvous, pass coordinator_address,
num_processes and process_id explicitly (the torchrun-style contract).
"""

from __future__ import annotations

from typing import Optional

import jax

from nnstreamer_tpu.log import get_logger

_log = get_logger("parallel.multihost")
_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[list] = None,
) -> None:
    """Join the multi-host runtime. On TPU pods all arguments auto-detect
    from the TPU environment; elsewhere pass them explicitly. Idempotent."""
    global _initialized
    if _initialized:
        return
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    jax.distributed.initialize(**kwargs)
    _initialized = True
    _log.info(
        "multihost up: process %d/%d, %d global / %d local devices",
        jax.process_index(), jax.process_count(),
        len(jax.devices()), len(jax.local_devices()),
    )


def shutdown() -> None:
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def barrier(tag: str = "barrier") -> None:
    """Block until EVERY process reaches this point (a psum over the
    global device set — rides DCN between hosts). The pod-level fence for
    ordering singleton work: e.g. every process must finish its
    checkpoint shards before the primary records the step as durable, and
    a restarted pod must not read a checkpoint mid-write. A missing host
    surfaces as this call timing out at the collective layer — the
    failure-detection primitive of the multi-host runtime."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(tag)


def is_primary() -> bool:
    """True on the process that should do singleton work (logging, golden
    dumps, checkpoint writes)."""
    return jax.process_index() == 0


def process_info() -> dict:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
    }
