"""Ring attention: sequence-parallel exact attention over a mesh axis.

A genuinely new capability vs the reference (SURVEY.md §5.7: its "sequence"
axis is time; it has no attention). Long-context streams need attention over
sequences larger than one chip's HBM, so sequence parallelism is first-class
here: Q/K/V are sharded along the sequence dim over a mesh axis, K/V blocks
rotate around the ring via ``jax.lax.ppermute`` (ICI neighbor exchange —
the collective rides the torus links), and each device accumulates its
queries' attention with the flash-attention online-softmax recurrence, so
the full [T, T] score matrix never materializes (Liu et al. 2023,
arXiv:2310.01889 pattern; implementation is original).

The ring loop is a ``lax.scan`` (reverse-differentiable: ppermute has a
transpose rule, so the same code path trains). Causal masking uses global
block offsets from ``axis_index``; fully-masked blocks contribute zeros
(compute is not skipped — at ring scale the skip is a constant factor the
overlap hides).

Layouts: q, k, v are [batch, seq_local, heads, head_dim].
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nnstreamer_tpu.parallel.mesh import shard_map as _shard_map

NEG_INF = -1e30


def _online_block(q, k, v, mask, m_prev, l_prev, o_prev, scale):
    """One flash-attention accumulation step over a K/V block.

    q [B,Tq,H,D], k/v [B,Tk,H,D], mask [Tq,Tk] True=attend.
    Running stats: m (max) [B,H,Tq], l (denominator) [B,H,Tq],
    o (unnormalized out) [B,Tq,H,D].
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    s = jnp.where(mask[None, None, :, :], s, NEG_INF)
    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m_prev, m_blk)
    # guard exp(-inf - -inf): a still-empty row keeps alpha = 0
    alpha = jnp.where(m_prev <= NEG_INF, 0.0, jnp.exp(m_prev - m_new))
    p = jnp.where(
        (m_new <= NEG_INF)[..., None], 0.0, jnp.exp(s - m_new[..., None])
    )
    l_new = l_prev * alpha + jnp.sum(p, axis=-1)
    o_new = o_prev * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v
    )
    return m_new, l_new, o_new


def _online_block_chunked(q, k, v, mask, m_prev, l_prev, o_prev, scale,
                          chunk: int):
    """Same recurrence, but scanning K/V in ``chunk``-sized pieces so the
    live score tensor is [B,H,Tq,chunk] instead of [B,H,Tq,Tk] — the
    HBM-bounding path for long local sequences (the in-shard analogue of
    the ring's cross-shard blocking)."""
    tk = k.shape[1]
    if chunk <= 0 or tk % chunk:
        raise ValueError(
            f"ring attention: kv_chunk must be a positive divisor of the "
            f"local sequence ({tk}), got {chunk}"
        )
    nc = tk // chunk
    b, _, h, d = k.shape
    kc = k.reshape(b, nc, chunk, h, d).transpose(1, 0, 2, 3, 4)
    vc = v.reshape(b, nc, chunk, h, d).transpose(1, 0, 2, 3, 4)
    maskc = mask.reshape(mask.shape[0], nc, chunk).transpose(1, 0, 2)

    def step(carry, xs):
        m, l, o = carry
        kb, vb, mb = xs
        m, l, o = _online_block(q, kb, vb, mb, m, l, o, scale)
        return (m, l, o), None

    (m, l, o), _ = jax.lax.scan(
        step, (m_prev, l_prev, o_prev), (kc, vc, maskc)
    )
    return m, l, o


def ring_attention_local(
    q, k, v, axis_name: str, causal: bool = True,
    scale: Optional[float] = None, kv_chunk: Optional[int] = None,
):
    """The per-shard computation (call inside shard_map / shard-mapped jit).

    Sequence is sharded contiguously over ``axis_name``: shard i holds
    global positions [i*Tl, (i+1)*Tl). Returns the local output block
    [B, Tl, H, D] in float32. ``kv_chunk`` bounds the live score tensor to
    [B,H,Tl,kv_chunk] (long-context HBM control); None = whole block.
    """
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, tl, h, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    qf = q.astype(jnp.float32)

    q_pos = my * tl + jnp.arange(tl)  # global positions of local queries

    m0 = jnp.full((b, h, tl), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, tl), jnp.float32)
    o0 = jnp.zeros((b, tl, h, d), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, i):
        kb, vb, m, l, o = carry
        # after i rotations we hold the block originally on shard (my - i)
        src = (my - i) % n
        k_pos = src * tl + jnp.arange(tl)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = jnp.ones((tl, tl), bool)
        kf, vf = kb.astype(jnp.float32), vb.astype(jnp.float32)
        if kv_chunk is not None and kv_chunk < tl:
            m, l, o = _online_block_chunked(
                qf, kf, vf, mask, m, l, o, scale, kv_chunk
            )
        else:
            m, l, o = _online_block(qf, kf, vf, mask, m, l, o, scale)
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return (kb, vb, m, l, o), None

    (_, _, m, l, o), _ = jax.lax.scan(
        step, (k, v, m0, l0, o0), jnp.arange(n)
    )
    denom = l.transpose(0, 2, 1)[..., None]  # [B,Tq,H,1]
    return jnp.where(denom > 0, o / jnp.maximum(denom, 1e-30), 0.0)


def make_ring_attention(
    mesh: Mesh, axis: str = "sp", causal: bool = True,
    kv_chunk: Optional[int] = None,
):
    """Jitted full-array entry: (q, k, v) [B, T, H, D] sequence-sharded over
    ``axis`` → attention output with the same sharding."""
    spec = P(None, axis, None, None)

    fn = _shard_map(
        functools.partial(
            ring_attention_local, axis_name=axis, causal=causal,
            kv_chunk=kv_chunk,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return jax.jit(fn)


def dense_attention(q, k, v, causal: bool = True, scale: Optional[float] = None):
    """Single-device reference (and the small-sequence fast path)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        mask = jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
