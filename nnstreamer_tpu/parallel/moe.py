"""Mixture-of-experts FFN with expert parallelism over a mesh axis.

Expert weights shard over the ``ep`` axis (each device owns
n_experts/axis_size experts); tokens stay sequence/batch-sharded. Each
device computes its local experts' contribution for its tokens weighted by
the (replicated) router's top-k gate probabilities, and one ``psum``
combines across the axis — expert parallelism in its exact dense
formulation: every expert sees every token, with below-top-k gates zeroed.
That trades FLOPs for zero routing state: no capacity factor, no token
dropping, no all_to_all dispatch — exact, differentiable, and XLA shards it
cleanly. A capacity-based all_to_all dispatch path is the planned perf
upgrade for large expert counts (same API).

Plugs into the transformer as ``ffn_fn`` (models/transformer.py
block_apply), replacing the dense SwiGLU MLP.
"""

from __future__ import annotations

import math
from typing import Dict

import jax
import jax.numpy as jnp


def init_moe_params(
    key, d_model: int, d_ff: int, n_experts: int, n_layers: int = 1
) -> Dict:
    """Stacked per-layer MoE params: leaves [L, E, ...] so a transformer
    block stack can scan over L while ep shards E."""
    ks = jax.random.split(key, 4)
    std_in = math.sqrt(1.0 / d_model)
    std_out = math.sqrt(1.0 / d_ff)
    shape = (n_layers, n_experts)
    return {
        "gate": jax.random.normal(ks[0], (n_layers, d_model, n_experts), jnp.float32)
        * std_in,
        "w_in": jax.random.normal(ks[1], shape + (d_model, d_ff), jnp.float32) * std_in,
        "w_out": jax.random.normal(ks[2], shape + (d_ff, d_model), jnp.float32)
        * std_out,
    }


def gate_probs(x, gate_w, top_k: int):
    """Router: [B,T,D] → [B,T,E] probabilities, zero outside the top-k,
    renormalized over the kept experts (standard top-k softmax gating)."""
    logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    e = logits.shape[-1]
    k = min(top_k, e)
    top_vals, _ = jax.lax.top_k(logits, k)
    thresh = top_vals[..., -1:]
    masked = jnp.where(logits >= thresh, logits, -jnp.inf)
    return jax.nn.softmax(masked, axis=-1)


def moe_ffn_local(
    x, p: Dict, axis_name: str, top_k: int = 2
):
    """Per-shard MoE FFN (call inside shard_map). x [B,T,D] token-sharded
    (or replicated) over other axes; p holds THIS shard's expert slice
    (w_in [E_local, D, F], w_out [E_local, F, D]) and the full router
    ``gate`` [D, E_total]. Returns the combined [B,T,D] float32."""
    n = jax.lax.psum(1.0, axis_name)  # axis size (float to keep psum cheap)
    idx = jax.lax.axis_index(axis_name)
    e_local = p["w_in"].shape[0]
    probs = gate_probs(x, p["gate"], top_k)  # [B,T,E_total]
    start = (idx * e_local).astype(jnp.int32)
    local_probs = jax.lax.dynamic_slice_in_dim(
        probs, start, e_local, axis=-1
    )  # [B,T,E_local]
    xf = x.astype(jnp.float32)
    hidden = jax.nn.silu(jnp.einsum("btd,edf->btef", xf, p["w_in"].astype(jnp.float32)))
    expert_out = jnp.einsum("btef,efd->bted", hidden, p["w_out"].astype(jnp.float32))
    local = jnp.einsum("bted,bte->btd", expert_out, local_probs)
    return jax.lax.psum(local, axis_name)


def moe_ffn_dense(x, p: Dict, top_k: int = 2):
    """Single-device reference: identical math, no sharding. p leaves carry
    the full expert dim."""
    probs = gate_probs(x, p["gate"], top_k)
    xf = x.astype(jnp.float32)
    hidden = jax.nn.silu(jnp.einsum("btd,edf->btef", xf, p["w_in"].astype(jnp.float32)))
    expert_out = jnp.einsum("btef,efd->bted", hidden, p["w_out"].astype(jnp.float32))
    return jnp.einsum("bted,bte->btd", expert_out, probs)
