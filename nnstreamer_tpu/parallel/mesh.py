"""Device mesh helpers: the substrate of the multi-chip layer.

The reference scales across devices by sharding *pipelines* over host
networking (tensor_query/edge, SURVEY.md §2.6). TPU-native scaling instead
starts from a jax.sharding.Mesh over ICI: single filters shard via jit
shardings (TP/DP), pipeline stages place on device subsets, and collectives
ride ICI instead of TCP. These helpers build meshes that work identically on
real chips and on the virtual CPU mesh used in tests.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_map(f, **kw):
    """Version-portable ``shard_map``: newer jax exports it top-level
    (``jax.shard_map``, replication check spelled ``check_vma``), older
    releases keep it under ``jax.experimental.shard_map`` with the check
    named ``check_rep`` — the MULTICHIP dryrun must launch on both (the
    bench machine and the CI image disagree)."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:  # pragma: no cover - depends on the installed jax
        from jax.experimental.shard_map import shard_map as sm

        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
    return sm(f, **kw)


def make_mesh(
    n_devices: Optional[int] = None,
    axes: Sequence[str] = ("dp", "tp"),
    shape: Optional[Sequence[int]] = None,
    devices=None,
) -> Mesh:
    """Build a Mesh over the first n devices.

    Default factoring puts as much as possible on the *last* axis (model/tp —
    contiguous devices share fastest ICI links) and the remainder on the
    first (data). shape=None with axes=("dp","tp") on 8 devices → (2, 4).
    """
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(f"need {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    n = len(devs)
    if shape is None:
        if len(axes) == 1:
            shape = (n,)
        elif len(axes) == 2:
            # largest power-of-two split favoring the last axis
            tp = 1
            while tp * 2 <= n and (n % (tp * 2)) == 0 and tp * 2 <= 4:
                tp *= 2
            shape = (n // tp, tp)
        else:
            shape = (n,) + (1,) * (len(axes) - 1)
    if math.prod(shape) != n:
        raise ValueError(f"mesh shape {shape} != {n} devices")
    arr = np.array(devs).reshape(shape)
    return Mesh(arr, tuple(axes))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Shard the leading (batch) dim over the data axis."""
    return NamedSharding(mesh, P(axis))


def channel_sharding(mesh: Mesh, ndim: int, axis: str = "tp") -> NamedSharding:
    """Shard the trailing (channel/feature) dim — NHWC/HWIO tensors."""
    return NamedSharding(mesh, P(*([None] * (ndim - 1) + [axis])))
