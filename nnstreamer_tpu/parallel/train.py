"""Sharded training step for the flagship model (dp × tp over a Mesh).

The reference is inference-only (SURVEY.md §5.3-5.4: no training story); the
TPU build adds a genuinely new capability: the flagship classifier trains
data-parallel × tensor-parallel over a device mesh via jit shardings — XLA
inserts the psum/all-gather collectives over ICI (scaling-book recipe: pick
a mesh, annotate shardings, let XLA do the rest).

Sharding layout for MobileNet-v2:
- batch: P('dp') on the leading dim (pure DP).
- params: channel-sharded P(..., 'tp') on the big trailing-channel tensors
  (head conv HWIO on O, classifier W on its input row dim to match the
  sharded 1280-feature activations); everything else replicated. XLA's SPMD
  propagation shards the intermediate activations to match.
- optimizer state inherits the param shardings (optax states mirror the
  param pytree).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nnstreamer_tpu.models import mobilenet_v2


def param_shardings(mesh: Mesh, params) -> Any:
    """NamedSharding pytree for MobileNet-v2 params: TP on the classifier
    and head channels, replicated elsewhere."""
    repl = NamedSharding(mesh, P())

    def assign(path, leaf):
        keys = [getattr(p, "key", getattr(p, "idx", None)) for p in path]
        if "classifier" in keys:
            if keys[-1] == "w":  # (1280, classes): shard the feature rows
                return NamedSharding(mesh, P("tp", None))
            return repl  # bias: small, replicated
        if "head" in keys:
            if keys[-1] == "w":  # HWIO: shard output channels
                return NamedSharding(mesh, P(None, None, None, "tp"))
            return NamedSharding(mesh, P("tp"))  # bn vectors over 1280
        return repl

    return jax.tree_util.tree_map_with_path(assign, params)


def loss_fn(params, images, labels, compute_dtype=jnp.float32):
    logits = mobilenet_v2.apply(
        params, images, train=True, compute_dtype=compute_dtype
    )
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    return jnp.mean(loss)


def make_train_step(
    mesh: Mesh,
    params,
    learning_rate: float = 0.05,
    compute_dtype=jnp.float32,
) -> Tuple[Any, Any, Any]:
    """Returns (jitted_step, sharded_params, sharded_opt_state).

    jitted_step(params, opt_state, images, labels) -> (params, opt_state,
    loss); images sharded P('dp'), loss replicated.
    """
    tx = optax.sgd(learning_rate, momentum=0.9)
    p_shard = param_shardings(mesh, params)
    params = jax.device_put(params, p_shard)
    opt_state = jax.jit(
        tx.init, out_shardings=_opt_shardings(tx, params, p_shard)
    )(params)
    batch_shard = NamedSharding(mesh, P("dp"))
    repl = NamedSharding(mesh, P())

    @functools.partial(
        jax.jit,
        in_shardings=(p_shard, _opt_shardings(tx, params, p_shard), batch_shard, batch_shard),
        out_shardings=(p_shard, _opt_shardings(tx, params, p_shard), repl),
        donate_argnums=(0, 1),
    )
    def step(params, opt_state, images, labels):
        loss, grads = jax.value_and_grad(loss_fn)(
            params, images, labels, compute_dtype
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step, params, opt_state


def _opt_shardings(tx, params, p_shard):
    """Optimizer-state shardings: mirror the param pytree inside each
    optax state leaf (momentum buffers shard like their params)."""
    state_shape = jax.eval_shape(tx.init, params)

    # optax.sgd+momentum: state is (TraceState(trace=params-like), EmptyState)
    import optax as _o

    def map_state(s):
        if isinstance(s, _o.TraceState):
            return _o.TraceState(trace=p_shard)
        return s

    return jax.tree_util.tree_map(
        map_state, state_shape, is_leaf=lambda x: isinstance(x, _o.TraceState)
    )
