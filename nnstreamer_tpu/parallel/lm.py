"""Sharded long-context LM training: dp × sp × ep over one mesh.

The composition layer: transformer LM (models/transformer.py) trained with
- **dp**: batch sharded over the data axis,
- **sp**: sequence sharded over the sequence axis; attention runs as an
  *inner shard_map* (ring_attention or ulysses) while everything else stays
  in the outer jit — XLA propagates shardings and inserts the grad
  collectives itself (the scaling-book recipe: annotate, don't hand-write
  collectives),
- **ep** (optional): MoE expert dim sharded via sharding constraints on the
  expert weights; the expert-combine einsum partitions over ``ep`` and XLA
  emits the psum.

This is the "full training step" the driver's dryrun compiles over a
virtual mesh; on hardware the same code lays dp/sp/ep onto ICI.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nnstreamer_tpu.parallel.mesh import shard_map as _shard_map

from nnstreamer_tpu.models import transformer as tfm
from nnstreamer_tpu.parallel import moe as moe_mod
from nnstreamer_tpu.parallel.ring_attention import ring_attention_local
from nnstreamer_tpu.parallel.ulysses import ulysses_attention_local


def init_lm_params(
    key,
    vocab: int = 1024,
    d_model: int = 256,
    n_heads: int = 8,
    n_layers: int = 4,
    d_ff: Optional[int] = None,
    n_experts: int = 0,
    moe_d_ff: Optional[int] = None,
) -> Dict:
    """Transformer params; with n_experts > 0 the MoE leaves are merged
    into the stacked block pytree (moe_gate [L,D,E], moe_w_in [L,E,D,F],
    moe_w_out [L,E,F,D]) so one lax.scan drives both."""
    k1, k2 = jax.random.split(key)
    params = tfm.init_params(k1, vocab, d_model, n_heads, n_layers, d_ff)
    if n_experts > 0:
        mo = moe_mod.init_moe_params(
            k2, d_model, moe_d_ff or (d_ff or 4 * d_model) // 2, n_experts, n_layers
        )
        blocks = params["blocks"]
        # the dense MLP is replaced; drop its weights from the pytree
        for name in ("w_gate", "w_up", "w_down"):
            del blocks[name]
        blocks["moe_gate"] = mo["gate"]
        blocks["moe_w_in"] = mo["w_in"]
        blocks["moe_w_out"] = mo["w_out"]
    return params


def _make_attn_fn(mesh: Mesh, kind: str, dp_axis: str, sp_axis: str,
                  kv_chunk=None):
    local = {
        "ring": ring_attention_local,
        "ulysses": ulysses_attention_local,
    }[kind]
    if kv_chunk is not None and kind != "ring":
        raise ValueError(
            f"kv_chunk applies to attn='ring' only (got attn={kind!r}); "
            "ulysses gathers full sequences per head and has no chunked path"
        )
    extra = {"kv_chunk": kv_chunk} if kind == "ring" else {}
    spec = P(dp_axis, sp_axis, None, None)

    def attn(q, k, v, causal=True):
        return _shard_map(
            functools.partial(local, axis_name=sp_axis, causal=causal, **extra),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )(q, k, v)

    return attn


def _make_moe_ffn(mesh: Mesh, ep_axis: Optional[str], top_k: int):
    ep = P(ep_axis) if ep_axis else P()

    def ffn(y, blk):
        p = {
            "gate": blk["moe_gate"],
            "w_in": jax.lax.with_sharding_constraint(
                blk["moe_w_in"], NamedSharding(mesh, ep)
            ),
            "w_out": jax.lax.with_sharding_constraint(
                blk["moe_w_out"], NamedSharding(mesh, ep)
            ),
        }
        return moe_mod.moe_ffn_dense(y, p, top_k=top_k)

    return ffn


def loss_fn(params, tokens, n_heads, attn_fn=None, ffn_fn=None, compute_dtype=jnp.float32):
    """Next-token cross-entropy over tokens [B, T+1] (inputs = [:, :-1])."""
    logits = tfm.apply(
        params, tokens[:, :-1], n_heads, attn_fn, ffn_fn, compute_dtype
    )
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(logits, tokens[:, 1:])
    )


def param_shardings(mesh: Mesh, params, ep_axis: Optional[str]) -> Dict:
    """Replicated everywhere except MoE expert weights (leading-L stacked,
    expert dim sharded over ep)."""
    repl = NamedSharding(mesh, P())

    def assign(path, leaf):
        keys = [getattr(p, "key", None) for p in path]
        if ep_axis and keys and str(keys[-1]).startswith("moe_w"):
            return NamedSharding(mesh, P(None, ep_axis))
        return repl

    return jax.tree_util.tree_map_with_path(assign, params)


def make_lm_train_step(
    mesh: Mesh,
    params: Dict,
    n_heads: int,
    attn: str = "ring",
    dp_axis: str = "dp",
    sp_axis: str = "sp",
    ep_axis: Optional[str] = None,
    top_k: int = 2,
    learning_rate: float = 0.1,
    compute_dtype=jnp.float32,
    kv_chunk=None,
) -> Tuple:
    """Returns (jitted_step, sharded_params). step(params, tokens) →
    (params, loss); tokens [B, T+1] sharded (dp, sp). ``kv_chunk`` bounds
    the in-shard attention score tensor for long contexts (ring only)."""
    attn_fn = _make_attn_fn(mesh, attn, dp_axis, sp_axis, kv_chunk=kv_chunk)
    is_moe = "moe_gate" in params["blocks"]
    ffn_fn = _make_moe_ffn(mesh, ep_axis, top_k) if is_moe else None
    p_shard = param_shardings(mesh, params, ep_axis)
    params = jax.device_put(params, p_shard)
    # tokens shard on batch only: [B, T+1] has a ragged +1 on the sequence
    # dim, so sequence sharding starts at the attention boundary (the inner
    # shard_map's in_specs make XLA reshard q/k/v to (dp, sp) there and
    # propagate outward)
    tok_shard = NamedSharding(mesh, P(dp_axis))
    repl = NamedSharding(mesh, P())

    @functools.partial(
        jax.jit,
        in_shardings=(p_shard, tok_shard),
        out_shardings=(p_shard, repl),
        donate_argnums=(0,),
    )
    def step(params, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(
            params, tokens, n_heads, attn_fn, ffn_fn, compute_dtype
        )
        params = jax.tree_util.tree_map(
            lambda p, g: p - learning_rate * g.astype(p.dtype), params, grads
        )
        return params, loss

    return step, params
