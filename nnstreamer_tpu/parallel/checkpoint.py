"""Checkpoint / resume for training state (orbax-backed).

The reference is a stateless inference framework — its closest analogues
are model hot-reload and tensor_repo recurrent state (SURVEY.md §5.4).
Since this framework adds training (parallel/train.py, parallel/lm.py), it
also adds the matching persistence: save/restore of arbitrary pytrees
(params, optimizer state, step counters) that is **sharding-aware** — on
restore each leaf materializes directly with the sharding you pass, so a
dp×tp×sp×ep run resumes onto the same (or a re-factored) mesh without a
host-memory detour through one process.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax

from nnstreamer_tpu.log import get_logger

_log = get_logger("parallel.checkpoint")


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save(path: str, state: Any, force: bool = True) -> None:
    """Write a pytree checkpoint (atomic rename on completion)."""
    path = os.path.abspath(path)
    _checkpointer().save(path, state, force=force)
    _log.info("checkpoint saved: %s", path)


def restore(path: str, like: Optional[Any] = None, shardings: Optional[Any] = None):
    """Read a checkpoint.

    like: a pytree of arrays or ShapeDtypeStructs giving the expected
    structure/dtypes. shardings: matching pytree of NamedShardings — leaves
    restore directly onto devices with that placement (multi-host safe).
    With neither, restores as host numpy arrays.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    if like is None:
        return _checkpointer().restore(path)
    if shardings is not None:
        targets = jax.tree_util.tree_map(
            lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
            like,
            shardings,
        )
    else:
        targets = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), like
        )
    return _checkpointer().restore(
        path, restore_args=ocp.checkpoint_utils.construct_restore_args(targets)
    )


def latest_step(root: str) -> Optional[int]:
    """Scan a directory of step-named checkpoints (root/step_N) → max N."""
    if not os.path.isdir(root):
        return None
    steps = []
    for entry in os.listdir(root):
        if entry.startswith("step_"):
            try:
                steps.append(int(entry[len("step_"):]))
            except ValueError:
                continue
    return max(steps) if steps else None


def step_path(root: str, step: int) -> str:
    return os.path.join(root, f"step_{step}")
