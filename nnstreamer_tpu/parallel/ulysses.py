"""Ulysses sequence parallelism: all-to-all head↔sequence re-sharding.

The second long-context strategy (DeepSpeed-Ulysses pattern, arXiv:2309.14509
— implementation original): instead of rotating K/V around a ring, one
``all_to_all`` re-shards [B, T/n, H, D] → [B, T, H/n, D], every device runs
*dense* attention over the full sequence for its heads, and a second
all_to_all restores sequence sharding. Two collectives total (vs n-1 ring
hops) at the cost of holding full-sequence K/V per head group — the right
trade when heads ≥ mesh axis and the sequence fits HBM; ring_attention is
the choice when it doesn't. Both share the same [B, T, H, D] layout, so the
transformer picks per config.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from nnstreamer_tpu.parallel.mesh import shard_map as _shard_map

from nnstreamer_tpu.parallel.ring_attention import dense_attention


def ulysses_attention_local(
    q, k, v, axis_name: str, causal: bool = True,
    attn_fn: Optional[Callable] = None,
):
    """Per-shard computation: q/k/v [B, T_local, H, D] sequence-sharded →
    output with the same sharding. Requires H % axis_size == 0."""
    attn = attn_fn or dense_attention
    n = jax.lax.psum(1, axis_name)
    h = q.shape[2]
    if h % n:
        raise ValueError(f"ulysses: heads {h} not divisible by axis size {n}")

    def seq_to_head(x):  # [B, T/n, H, D] → [B, T, H/n, D]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def head_to_seq(x):  # [B, T, H/n, D] → [B, T/n, H, D]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    o = attn(seq_to_head(q), seq_to_head(k), seq_to_head(v), causal=causal)
    return head_to_seq(o.astype(q.dtype)).astype(jnp.float32)


def make_ulysses_attention(mesh: Mesh, axis: str = "sp", causal: bool = True):
    """Jitted full-array entry matching make_ring_attention's signature."""
    spec = P(None, axis, None, None)
    fn = _shard_map(
        functools.partial(ulysses_attention_local, axis_name=axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return jax.jit(fn)
