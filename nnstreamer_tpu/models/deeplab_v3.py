"""DeepLab-v3 (MobileNet-v2 backbone) — the segmentation benchmark model.

The reference's segmentation fixture is deeplabv3_257_mv_gpu.tflite
(tests/nnstreamer_decoder_image_segment/, decoder mode
``tflite-deeplab``, tensordec-imagesegment.c:107-119): 257x257 input,
[257,257,21] per-class score map output. Same topology from scratch in jnp:
MobileNet-v2 backbone at output-stride 16 (last downsample made atrous,
rate-2 depthwise convs — conv2d dilation), reduced mobile ASPP (1x1 branch +
image-level pooling), 21-class 1x1 classifier, bilinear upsample back to
input resolution — all one XLA program, resize included (the reference does
the argmax on CPU per pixel; our image_segment decoder jits it).

fn: uint8 NHWC [N,257,257,3] → seg scores [N,257,257,21] float32.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from nnstreamer_tpu.models import mobilenet_v2, nn

NUM_CLASSES = 21
INPUT_SIZE = 257
_ASPP_CH = 256


def init_params(key, num_classes: int = NUM_CLASSES) -> Dict:
    keys = iter(jax.random.split(key, 8))
    p: Dict = {"backbone": mobilenet_v2.init_params(next(keys))}
    p["aspp_conv"] = {"w": nn.init_conv(next(keys), 1, 1, 320, _ASPP_CH),
                      "bn": nn.init_bn(_ASPP_CH)}
    p["aspp_pool"] = {"w": nn.init_conv(next(keys), 1, 1, 320, _ASPP_CH),
                      "bn": nn.init_bn(_ASPP_CH)}
    p["project"] = {"w": nn.init_conv(next(keys), 1, 1, 2 * _ASPP_CH, _ASPP_CH),
                    "bn": nn.init_bn(_ASPP_CH)}
    p["classifier"] = {
        "w": nn.init_conv(next(keys), 1, 1, _ASPP_CH, num_classes),
        "b": jnp.zeros((num_classes,), jnp.float32),
    }
    return p


def _backbone_os16(bb: Dict, x, train: bool):
    """MobileNet-v2 blocks with the stride-2 of the 160-channel group (block
    13) removed and subsequent depthwise convs dilated — classic
    output-stride-16 atrous surgery; returns the 320-channel map (no head)."""
    y = nn.relu6(
        nn.batch_norm(nn.conv2d(x, bb["stem"]["w"], stride=2), bb["stem"]["bn"], train)
    )
    strides = mobilenet_v2._block_strides()
    for i, (blk, stride) in enumerate(zip(bb["blocks"], strides)):
        eff_stride, dilation = stride, 1
        if i >= 13:  # the stride-2 160 group and beyond run atrous
            eff_stride, dilation = 1, 2
        y = _block_atrous(y, blk, eff_stride, dilation, train)
    return y


def _block_atrous(x, blk: Dict, stride: int, dilation: int, train: bool):
    y = x
    if "expand" in blk:
        y = nn.relu6(nn.batch_norm(nn.conv2d(y, blk["expand"]["w"]), blk["expand"]["bn"], train))
    groups = y.shape[-1]
    y = nn.relu6(
        nn.batch_norm(
            nn.conv2d(y, blk["dw"]["w"], stride=stride, groups=groups, dilation=dilation),
            blk["dw"]["bn"],
            train,
        )
    )
    y = nn.batch_norm(nn.conv2d(y, blk["project"]["w"]), blk["project"]["bn"], train)
    if stride == 1 and y.shape[-1] == x.shape[-1]:
        y = y + x
    return y


def apply(params: Dict, x, train: bool = False, compute_dtype=jnp.float32):
    n = x.shape[0]
    size = x.shape[1]
    if x.dtype == jnp.uint8:
        x = mobilenet_v2.normalize_uint8(x, compute_dtype)
    else:
        x = x.astype(compute_dtype)
    if compute_dtype != jnp.float32:
        params = nn.cast_params(params, compute_dtype)
    feat = _backbone_os16(params["backbone"], x, train)  # [N, s/16, s/16, 320]
    a = nn.relu6(nn.batch_norm(
        nn.conv2d(feat, params["aspp_conv"]["w"]), params["aspp_conv"]["bn"], train
    ))
    pooled = jnp.mean(feat, axis=(1, 2), keepdims=True)
    pooled = nn.relu6(
        nn.batch_norm(nn.conv2d(pooled, params["aspp_pool"]["w"]), params["aspp_pool"]["bn"], train)
    )
    pooled = jnp.broadcast_to(pooled, a.shape)
    y = jnp.concatenate([a, pooled], axis=-1)
    y = nn.relu6(nn.batch_norm(
        nn.conv2d(y, params["project"]["w"]), params["project"]["bn"], train
    ))
    logits = nn.conv2d(y, params["classifier"]["w"]) + params["classifier"]["b"]
    logits = jax.image.resize(
        logits.astype(jnp.float32), (n, size, size, logits.shape[-1]), "bilinear"
    )
    return logits
