"""Minimal functional NN primitives shared by the model zoo.

Pure-jnp layers with explicit params pytrees — no flax dependency in the
product path, so models are plain (fn, params) pairs the jax backend can jit
and the pipeline compiler can fuse. NHWC layout throughout (TPU-native conv
layout; channels-last keeps the lane dimension = channels for the MXU).
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def relu6(x):
    return jnp.minimum(jnp.maximum(x, 0.0), 6.0)


def conv2d(x, w, stride: int = 1, groups: int = 1, padding="SAME", dilation: int = 1):
    """NHWC conv; w is HWIO (I = in_channels // groups). ``dilation`` is the
    atrous rate (DeepLab output-stride control)."""
    return jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=(stride, stride),
        padding=padding,
        rhs_dilation=(dilation, dilation),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups,
    )


def batch_norm(x, p: Dict, train: bool = False, eps: float = 1e-3):
    """Functional batchnorm. Inference uses stored moments; train mode uses
    batch moments (sufficient for the dryrun/training-step path; moment EMA
    updates are the optimizer loop's concern)."""
    if train:
        axes = tuple(range(x.ndim - 1))
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
    else:
        mean, var = p["mean"], p["var"]
    inv = jax.lax.rsqrt(var + eps) * p["scale"]
    return x * inv + (p["bias"] - mean * inv)


def dense(x, p: Dict):
    return x @ p["w"] + p["b"]


def sep_conv(x, p: Dict, stride: int = 1, train: bool = False, dilation: int = 1):
    """Depthwise 3x3 + pointwise 1x1, BN+ReLU6 after each (MobileNet-v1
    block; also SSDLite head building block)."""
    c = x.shape[-1]
    y = relu6(
        batch_norm(
            conv2d(x, p["dw"]["w"], stride=stride, groups=c, dilation=dilation),
            p["dw"]["bn"],
            train,
        )
    )
    return relu6(batch_norm(conv2d(y, p["pw"]["w"]), p["pw"]["bn"], train))


def init_sep_conv(key, cin: int, cout: int) -> Dict:
    k1, k2 = jax.random.split(key)
    return {
        "dw": {"w": init_conv(k1, 3, 3, cin, cin, groups=cin), "bn": init_bn(cin)},
        "pw": {"w": init_conv(k2, 1, 1, cin, cout), "bn": init_bn(cout)},
    }


# -- initializers ---------------------------------------------------------

def _fan_in_out(shape: Sequence[int]) -> Tuple[int, int]:
    if len(shape) == 2:
        return shape[0], shape[1]
    rf = math.prod(shape[:-2])
    return shape[-2] * rf, shape[-1] * rf


def init_conv(key, h, w, cin, cout, groups: int = 1):
    shape = (h, w, cin // groups, cout)
    fan_in, _ = _fan_in_out(shape)
    std = math.sqrt(2.0 / max(fan_in, 1))
    return jax.random.normal(key, shape, jnp.float32) * std


def init_bn(c: int) -> Dict:
    return {
        "scale": jnp.ones((c,), jnp.float32),
        "bias": jnp.zeros((c,), jnp.float32),
        "mean": jnp.zeros((c,), jnp.float32),
        "var": jnp.ones((c,), jnp.float32),
    }


def init_dense(key, cin: int, cout: int) -> Dict:
    std = math.sqrt(1.0 / max(cin, 1))
    return {
        "w": jax.random.normal(key, (cin, cout), jnp.float32) * std,
        "b": jnp.zeros((cout,), jnp.float32),
    }


def cast_params(params, dtype):
    """Cast float leaves of a params pytree (bfloat16 serving)."""
    def _cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(_cast, params)


def param_count(params) -> int:
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))
