"""Built-in model zoo: named (fn, params, spec) bundles for framework=jax.

The analogue of the reference's tests/test_models/models/ fixture set
(add.tflite, mobilenet_v2_..., deeplabv3_...), but as constructively seeded
jax models: ``model=zoo:<name>`` always works offline with deterministic
params (seed via custom option ``seed:N``). Weight files can be layered in
via ``params:<path.npz>``.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu.tensors.spec import DType, TensorSpec, TensorsSpec


@dataclass
class ZooModel:
    name: str
    fn: Callable  # (*tensors) -> tensor | tuple, pure & traceable
    input_spec: Optional[TensorsSpec]
    params: Optional[Dict] = None
    # params-explicit form ``apply(params, *tensors)``: required for mesh-
    # sharded filters (custom="mesh:dp2tp4") — closed-over params would be
    # baked into the jaxpr as replicated constants, defeating TP sharding
    apply: Optional[Callable] = None


_FACTORIES: Dict[str, Callable[..., ZooModel]] = {}


def model_factory(name: str):
    def deco(fn):
        _FACTORIES[name] = fn
        return fn

    return deco


def get(name: str, **options: str) -> ZooModel:
    if name not in _FACTORIES:
        raise KeyError(f"unknown zoo model {name!r}; known: {sorted(_FACTORIES)}")
    return _FACTORIES[name](**options)


def available():
    return sorted(_FACTORIES)


def _load_params_overlay(params, options):
    path = options.get("params")
    if not path:
        return params
    blob = np.load(path, allow_pickle=True)
    flat = {k: jnp.asarray(v) for k, v in blob.items()}
    leaves, treedef = jax.tree_util.tree_flatten(params)
    new_leaves = [flat[f"p{i}"] if f"p{i}" in flat else l for i, l in enumerate(leaves)]
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


@model_factory("add")
def _add(**options) -> ZooModel:
    """y = x + const (the reference's add.tflite test model)."""
    const = float(options.get("const", 2.0))
    dims = options.get("dims", "1")
    spec = TensorsSpec.of(TensorSpec.from_dim_string(dims, "float32"))

    def fn(x):
        return x + jnp.asarray(const, x.dtype)

    return ZooModel("add", fn, spec)


@model_factory("mobilenet_v2")
def _mobilenet_v2(**options) -> ZooModel:
    from nnstreamer_tpu.models import mobilenet_v2

    seed = int(options.get("seed", 0))
    num_classes = int(options.get("num_classes", 1001))
    width = float(options.get("width", 1.0))
    batch = int(options.get("batch", 1))
    size = int(options.get("size", 224))
    compute_dtype = _compute_dtype(options)
    params = mobilenet_v2.init_params(
        jax.random.PRNGKey(seed), num_classes=num_classes, width=width
    )
    params = _load_params_overlay(params, options)

    if options.get("quantize") == "int8w":
        # weight-only int8 with the fused on-device dequant epilogue
        # (models/quantize.py apply_int8w): int8 weights + per-channel
        # scales device-resident, dequantized at the matmul operand
        # inside the segment; no calibration pass, no per-activation
        # quant math — the winning int8 configuration
        # (docs/on-device-ops.md)
        from nnstreamer_tpu.models import quantize as qz

        qparams = qz.quantize_mobilenet_weights(qz.fold_mobilenet(params))

        def qw_apply(p, image):
            return qz.apply_int8w(p, image, compute_dtype=compute_dtype)

        def qw_fn(image):
            return qw_apply(qparams, image)

        spec = _image_spec(batch, size, options.get("input_dtype", "uint8"))
        return ZooModel("mobilenet_v2", qw_fn, spec, qparams, qw_apply)

    if options.get("quantize") == "int8":
        # the reference's *_quant.tflite slot, redesigned for the MXU's
        # s8×s8→s32 path (models/quantize.py): fold BN, calibrate
        # activation scales on seeded sample batches, serve int8
        from nnstreamer_tpu.models import quantize as qz

        folded = qz.fold_mobilenet(params)
        rng = np.random.default_rng(seed)
        calib = [
            jnp.asarray(rng.integers(0, 256, (batch, size, size, 3), np.uint8))
            for _ in range(int(options.get("calib_batches", 2)))
        ]
        qparams = qz.quantize_mobilenet(
            folded, qz.calibrate_mobilenet(folded, calib)
        )
        def q_apply(p, image):
            return qz.apply_int8(p, image, compute_dtype=compute_dtype)

        def q_fn(image):
            return q_apply(qparams, image)

        spec = _image_spec(batch, size, options.get("input_dtype", "uint8"))
        return ZooModel("mobilenet_v2", q_fn, spec, qparams, q_apply)

    def apply_fn(p, image):
        return mobilenet_v2.apply(p, image, compute_dtype=compute_dtype)

    def fn(image):
        return apply_fn(params, image)

    spec = _image_spec(batch, size, options.get("input_dtype", "uint8"))
    return ZooModel("mobilenet_v2", fn, spec, params, apply_fn)


def _image_spec(batch: int, size: int, in_dtype: str) -> TensorsSpec:
    return TensorsSpec.of(
        TensorSpec((batch, size, size, 3), DType.from_any(in_dtype), name="image")
    )


def _compute_dtype(options) -> "jnp.dtype":
    compute = options.get("compute_dtype", "float32")
    return jnp.bfloat16 if compute == "bfloat16" else jnp.dtype(compute)


@model_factory("ssd_mobilenet_v2")
def _ssd_mobilenet_v2(**options) -> ZooModel:
    """Raw 2-tensor SSD (locations + class logits) for decoder
    mode=mobilenet-ssd; the analogue of ssd_mobilenet_v2_coco.tflite."""
    from nnstreamer_tpu.models import ssd_mobilenet

    seed = int(options.get("seed", 0))
    batch = int(options.get("batch", 1))
    num_classes = int(options.get("num_classes", ssd_mobilenet.NUM_CLASSES))
    dtype = _compute_dtype(options)
    params = _load_params_overlay(
        ssd_mobilenet.init_params(jax.random.PRNGKey(seed), num_classes), options
    )

    def apply_fn(p, image):
        return ssd_mobilenet.apply(
            p, image, compute_dtype=dtype, num_classes=num_classes
        )

    def fn(image):
        return apply_fn(params, image)

    spec = _image_spec(batch, 300, options.get("input_dtype", "uint8"))
    return ZooModel("ssd_mobilenet_v2", fn, spec, params, apply_fn)


@model_factory("ssd_mobilenet_v2_pp")
def _ssd_mobilenet_v2_pp(**options) -> ZooModel:
    """SSD + on-device NMS → the TFLite detection-postprocess 4-tensor
    layout (decoder mode=mobilenet-ssd-postprocess). Batch-1."""
    from nnstreamer_tpu.models import ssd_mobilenet

    seed = int(options.get("seed", 0))
    max_out = int(options.get("max_out", 10))
    threshold = float(options.get("threshold", 0.001))
    dtype = _compute_dtype(options)
    params = _load_params_overlay(
        ssd_mobilenet.init_params(jax.random.PRNGKey(seed)), options
    )
    priors = jnp.asarray(ssd_mobilenet.generate_anchors())

    def apply_fn(p, image):
        return ssd_mobilenet.apply_postprocessed(
            p, image, priors, max_out=max_out, threshold=threshold,
            compute_dtype=dtype,
        )

    def fn(image):
        return apply_fn(params, image)

    spec = _image_spec(1, 300, options.get("input_dtype", "uint8"))
    return ZooModel("ssd_mobilenet_v2_pp", fn, spec, params, apply_fn)


@model_factory("yolov5")
def _yolov5(**options) -> ZooModel:
    """YOLOv5-style detector (models/yolo.py): [B,S,S,3] → decoded
    [B, rows, 5+C] predictions for decoder mode=yolov5 — the native
    model behind the reference's yolov5 decoder fixtures
    (tensordec-boundingbox.c yolov5 mode; yolov5s tflite fixtures).
    Options: size (default 320), num_classes (80), width (32), batch,
    seed, compute_dtype."""
    from nnstreamer_tpu.models import yolo

    seed = int(options.get("seed", 0))
    batch = int(options.get("batch", 1))
    size = int(options.get("size", 320))
    num_classes = int(options.get("num_classes", 80))
    width = int(options.get("width", 32))
    dtype = _compute_dtype(options)
    if size % 32:
        raise ValueError(f"yolov5 size must be a multiple of 32, got {size}")
    params = _load_params_overlay(
        yolo.init_params(
            jax.random.PRNGKey(seed), num_classes=num_classes, width=width
        ),
        options,
    )

    def apply_fn(p, image):
        return yolo.apply(
            p, image, num_classes=num_classes, compute_dtype=dtype
        )

    def fn(image):
        return apply_fn(params, image)

    spec = _image_spec(batch, size, options.get("input_dtype", "uint8"))
    return ZooModel("yolov5", fn, spec, params, apply_fn)


@model_factory("kws")
def _kws(**options) -> ZooModel:
    """Keyword-spotting raw-waveform classifier (models/audio.py, an
    M5-style conv net) — the zoo's audio model family, exercising the
    converter's audio path (gsttensor_converter.c media dispatch) with
    real inference. Input [samples, channels] S16LE (the converter's
    audio tensor) or batched [B, samples, C]. Options: samples (1024),
    channels (1), num_classes (12), width (32), batch, seed,
    compute_dtype."""
    from nnstreamer_tpu.models import audio

    seed = int(options.get("seed", 0))
    batch = int(options.get("batch", 1))
    samples = int(options.get("samples", 1024))
    channels = int(options.get("channels", 1))
    num_classes = int(options.get("num_classes", 12))
    width = int(options.get("width", 32))
    dtype = _compute_dtype(options)
    params = _load_params_overlay(
        audio.init_params(
            jax.random.PRNGKey(seed), num_classes=num_classes, width=width
        ),
        options,
    )

    def apply_fn(p, pcm):
        return audio.apply(p, pcm, compute_dtype=dtype)

    def fn(pcm):
        return apply_fn(params, pcm)

    shape = (
        (samples, channels) if batch == 1
        else (batch, samples, channels)
    )
    spec = TensorsSpec.of(TensorSpec(shape, DType.INT16, name="pcm"))
    return ZooModel("kws", fn, spec, params, apply_fn)


@model_factory("posenet")
def _posenet(**options) -> ZooModel:
    """PoseNet MobileNet-v1 257x257 multi-output (heatmap/offsets/
    displacements) — decoder mode=pose-estimation."""
    from nnstreamer_tpu.models import posenet

    seed = int(options.get("seed", 0))
    batch = int(options.get("batch", 1))
    dtype = _compute_dtype(options)
    params = _load_params_overlay(posenet.init_params(jax.random.PRNGKey(seed)), options)

    def apply_fn(p, image):
        return posenet.apply(p, image, compute_dtype=dtype)

    def fn(image):
        return apply_fn(params, image)

    spec = _image_spec(batch, posenet.INPUT_SIZE, options.get("input_dtype", "uint8"))
    return ZooModel("posenet", fn, spec, params, apply_fn)


@model_factory("deeplab_v3")
def _deeplab_v3(**options) -> ZooModel:
    """DeepLab-v3 MobileNet-v2 257x257x21 — decoder mode=image-segment
    (tflite-deeplab)."""
    from nnstreamer_tpu.models import deeplab_v3

    seed = int(options.get("seed", 0))
    batch = int(options.get("batch", 1))
    dtype = _compute_dtype(options)
    params = _load_params_overlay(
        deeplab_v3.init_params(jax.random.PRNGKey(seed)), options
    )

    def apply_fn(p, image):
        return deeplab_v3.apply(p, image, compute_dtype=dtype)

    def fn(image):
        return apply_fn(params, image)

    spec = _image_spec(batch, deeplab_v3.INPUT_SIZE, options.get("input_dtype", "uint8"))
    return ZooModel("deeplab_v3", fn, spec, params, apply_fn)


@model_factory("face_detect")
def _face_detect(**options) -> ZooModel:
    """Face detector. Default output: [max_faces,7] OV detection rows
    (decoder mode=ov-face-detection). ``output=regions`` emits int32
    [max_faces,4] pixel (x,y,w,h) for tensor_crop, scaled to
    ``frame_size=W:H`` (defaults to the model input size).
    ``output=regions+image`` emits (image, regions) so a downstream
    crop-resize transform fuses the whole cascade on device
    (docs/on-device-ops.md)."""
    from nnstreamer_tpu.models import face_pipeline as fp

    seed = int(options.get("seed", 0))
    max_faces = int(options.get("max_faces", fp.MAX_FACES))
    dtype = _compute_dtype(options)
    out_mode = options.get("output", "ov")
    threshold = float(options.get("threshold", 0.5))
    frame_size = options.get("frame_size", f"{fp.DETECT_SIZE}:{fp.DETECT_SIZE}")
    fw, fh = (int(v) for v in frame_size.split(":"))
    params = _load_params_overlay(
        fp.init_detect_params(jax.random.PRNGKey(seed)), options
    )

    def apply_fn(p, image):
        if out_mode in ("regions+image", "regions_image"):
            return fp.apply_detect_regions_with_image(
                p, image, fw, fh, max_faces=max_faces,
                threshold=threshold, compute_dtype=dtype,
            )
        det = fp.apply_detect(p, image, max_faces=max_faces, compute_dtype=dtype)
        if out_mode == "regions":
            return fp.detections_to_regions(det, fw, fh, threshold)
        return det

    def fn(image):
        return apply_fn(params, image)

    spec = _image_spec(1, fp.DETECT_SIZE, options.get("input_dtype", "uint8"))
    return ZooModel("face_detect", fn, spec, params, apply_fn)


@model_factory("face_composite")
def _face_composite(**options) -> ZooModel:
    """Fused detect→crop+resize→landmark cascade as ONE XLA program
    (fp.apply_composite): fixed shapes, all max_faces crops batched on
    the MXU, zero host hops — the TPU-first form of the element-level
    tensor_crop composite. fn: uint8 [1,S,S,3] → (landmarks [max,136],
    detections [max,7])."""
    from nnstreamer_tpu.models import face_pipeline as fp

    seed = int(options.get("seed", 0))
    max_faces = int(options.get("max_faces", fp.MAX_FACES))
    threshold = float(options.get("threshold", 0.5))
    size = int(options.get("size", fp.DETECT_SIZE))
    dtype = _compute_dtype(options)
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    params = {
        "detect": _load_params_overlay(fp.init_detect_params(k1), options),
        "landmark": fp.init_landmark_params(k2),
    }

    def apply_fn(p, image):
        return fp.apply_composite(
            p["detect"], p["landmark"], image,
            max_faces=max_faces, threshold=threshold, compute_dtype=dtype,
        )

    def fn(image):
        return apply_fn(params, image)

    spec = _image_spec(1, size, options.get("input_dtype", "uint8"))
    return ZooModel("face_composite", fn, spec, params, apply_fn)


@model_factory("transformer_lm")
def _transformer_lm(**options) -> ZooModel:
    """Decoder-only transformer LM (models/transformer.py) — the
    long-context flagship. fn: int32 tokens [B,T] → logits [B,T,V]."""
    from nnstreamer_tpu.models import transformer as tfm

    seed = int(options.get("seed", 0))
    vocab = int(options.get("vocab", 1024))
    d_model = int(options.get("d_model", 256))
    n_heads = int(options.get("n_heads", 8))
    n_layers = int(options.get("n_layers", 4))
    batch = int(options.get("batch", 1))
    seqlen = int(options.get("seqlen", 128))
    dtype = _compute_dtype(options)
    n_kv_heads = int(options.get("n_kv_heads", n_heads))
    params = _load_params_overlay(
        tfm.init_params(
            jax.random.PRNGKey(seed), vocab, d_model, n_heads, n_layers,
            n_kv_heads=n_kv_heads,
        ),
        options,
    )
    if options.get("quantize") == "int8w":
        # weight-only int8 (models/quantize.py): decode reads every
        # weight once per token, so fewer bytes/weight → more tok/s
        from nnstreamer_tpu.models import quantize as qz

        params = qz.quantize_lm_weights(params)
    attn_kind = options.get("attn", "dense")
    if attn_kind == "flash":
        from nnstreamer_tpu.ops.pallas.flash_attention import make_flash_attention

        attn_fn = make_flash_attention()
    elif attn_kind == "dense":
        attn_fn = None
    else:
        raise KeyError(f"transformer_lm: unknown attn {attn_kind!r}")

    gen_tokens = int(options.get("generate", 0))
    if gen_tokens > 0:
        # serving mode: prompt frames in, generated token frames out — the
        # whole KV-cache loop (models/decode.py) is one jitted program, so
        # a tensor_filter stage becomes an LLM generation server.
        # decode strategies: greedy/sampled (default), beam search, or
        # draft-free n-gram speculation
        from nnstreamer_tpu.models import decode as dec

        strategy = options.get("decode", "greedy")
        temperature = float(options.get("temperature", 0.0))
        gen_seed = int(options.get("gen_seed", 0))
        if strategy == "beam":
            beam_width = int(options.get("beam_width", 4))

            def fn(tokens):
                toks, _ = dec.beam_search(
                    params, tokens, n_heads, gen_tokens,
                    beam_width=beam_width, compute_dtype=dtype,
                )
                return toks
        elif strategy == "ngram":
            # the WHOLE speculative generation is one compiled program
            # (device while_loop: on-device n-gram mining + chunk
            # verify; speculative.ngram_generate_scanned) — the
            # host-looped ngram_speculative_generate pays a round trip
            # per round, the per-token poison the serving pumps remove
            from nnstreamer_tpu.models.speculative import (
                ngram_generate_scanned,
            )

            spec_k = int(options.get("spec_k", 4))
            spec_g = int(options.get("spec_ngram", 2))

            def fn(tokens):
                toks, _ = ngram_generate_scanned(
                    params, tokens, n_heads, gen_tokens, k=spec_k,
                    g=spec_g, compute_dtype=dtype,
                )
                return toks
        elif strategy == "greedy":
            def fn(tokens):
                return dec.generate(
                    params, tokens, n_heads, gen_tokens,
                    temperature=temperature,
                    rng=jax.random.PRNGKey(gen_seed),
                    compute_dtype=dtype,
                )
        else:
            raise KeyError(
                f"transformer_lm: unknown decode strategy {strategy!r} "
                "(greedy|beam|ngram)"
            )
        apply_fn = None
    else:
        def apply_fn(p, tokens):
            return tfm.apply(
                p, tokens, n_heads, attn_fn=attn_fn, compute_dtype=dtype
            )

        def fn(tokens):
            return apply_fn(params, tokens)

    spec = TensorsSpec.of(
        TensorSpec((batch, seqlen), DType.from_any("int32"), name="tokens")
    )
    return ZooModel("transformer_lm", fn, spec, params, apply_fn)


@model_factory("vit")
def _vit(**options) -> ZooModel:
    """Vision Transformer classifier (models/vit.py): patch-embed +
    non-causal encoder stack, image-labeling compatible logits."""
    from nnstreamer_tpu.models import vit

    seed = int(options.get("seed", 0))
    num_classes = int(options.get("num_classes", 1001))
    d_model = int(options.get("d_model", 384))
    n_heads = int(options.get("n_heads", 6))
    n_layers = int(options.get("n_layers", 12))
    patch = int(options.get("patch", vit.PATCH))
    batch = int(options.get("batch", 1))
    size = int(options.get("size", vit.INPUT_SIZE))
    if size % patch:
        raise ValueError(f"vit: size {size} not divisible by patch {patch}")
    dtype = _compute_dtype(options)
    params = _load_params_overlay(
        vit.init_params(
            jax.random.PRNGKey(seed), num_classes, d_model, n_heads,
            n_layers, patch, size,
        ),
        options,
    )

    def apply_fn(p, image):
        return vit.apply(p, image, n_heads, compute_dtype=dtype)

    def fn(image):
        return apply_fn(params, image)

    spec = _image_spec(batch, size, options.get("input_dtype", "uint8"))
    return ZooModel("vit", fn, spec, params, apply_fn)


@model_factory("face_landmark")
def _face_landmark(**options) -> ZooModel:
    """68-point landmark net on face crops (global-pooled trunk, so any
    crop size ≥16 works; spec advertises the canonical 112)."""
    from nnstreamer_tpu.models import face_pipeline as fp

    seed = int(options.get("seed", 0))
    batch = int(options.get("batch", 1))
    size = int(options.get("size", fp.LANDMARK_SIZE))
    dtype = _compute_dtype(options)
    params = _load_params_overlay(
        fp.init_landmark_params(jax.random.PRNGKey(seed)), options
    )

    def apply_fn(p, image):
        return fp.apply_landmark(p, image, compute_dtype=dtype)

    def fn(image):
        return apply_fn(params, image)

    spec = _image_spec(batch, size, options.get("input_dtype", "uint8"))
    return ZooModel("face_landmark", fn, spec, params, apply_fn)
