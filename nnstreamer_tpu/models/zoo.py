"""Built-in model zoo: named (fn, params, spec) bundles for framework=jax.

The analogue of the reference's tests/test_models/models/ fixture set
(add.tflite, mobilenet_v2_..., deeplabv3_...), but as constructively seeded
jax models: ``model=zoo:<name>`` always works offline with deterministic
params (seed via custom option ``seed:N``). Weight files can be layered in
via ``params:<path.npz>``.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu.tensors.spec import DType, TensorSpec, TensorsSpec


@dataclass
class ZooModel:
    name: str
    fn: Callable  # (*tensors) -> tensor | tuple, pure & traceable
    input_spec: Optional[TensorsSpec]
    params: Optional[Dict] = None


_FACTORIES: Dict[str, Callable[..., ZooModel]] = {}


def model_factory(name: str):
    def deco(fn):
        _FACTORIES[name] = fn
        return fn

    return deco


def get(name: str, **options: str) -> ZooModel:
    if name not in _FACTORIES:
        raise KeyError(f"unknown zoo model {name!r}; known: {sorted(_FACTORIES)}")
    return _FACTORIES[name](**options)


def available():
    return sorted(_FACTORIES)


def _load_params_overlay(params, options):
    path = options.get("params")
    if not path:
        return params
    blob = np.load(path, allow_pickle=True)
    flat = {k: jnp.asarray(v) for k, v in blob.items()}
    leaves, treedef = jax.tree_util.tree_flatten(params)
    new_leaves = [flat[f"p{i}"] if f"p{i}" in flat else l for i, l in enumerate(leaves)]
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


@model_factory("add")
def _add(**options) -> ZooModel:
    """y = x + const (the reference's add.tflite test model)."""
    const = float(options.get("const", 2.0))
    dims = options.get("dims", "1")
    spec = TensorsSpec.of(TensorSpec.from_dim_string(dims, "float32"))

    def fn(x):
        return x + jnp.asarray(const, x.dtype)

    return ZooModel("add", fn, spec)


@model_factory("mobilenet_v2")
def _mobilenet_v2(**options) -> ZooModel:
    from nnstreamer_tpu.models import mobilenet_v2

    seed = int(options.get("seed", 0))
    num_classes = int(options.get("num_classes", 1001))
    width = float(options.get("width", 1.0))
    batch = int(options.get("batch", 1))
    size = int(options.get("size", 224))
    compute = options.get("compute_dtype", "float32")
    in_dtype = options.get("input_dtype", "uint8")
    params = mobilenet_v2.init_params(
        jax.random.PRNGKey(seed), num_classes=num_classes, width=width
    )
    params = _load_params_overlay(params, options)
    compute_dtype = jnp.dtype(compute) if compute != "bfloat16" else jnp.bfloat16

    def fn(image):
        return mobilenet_v2.apply(params, image, compute_dtype=compute_dtype)

    spec = TensorsSpec.of(
        TensorSpec((batch, size, size, 3), DType.from_any(in_dtype), name="image")
    )
    return ZooModel("mobilenet_v2", fn, spec, params)
