"""Face detection + landmark models — the composite-pipeline benchmark pair.

BASELINE.md's composite config is face→crop→landmark across chips: a face
detector whose boxes drive ``tensor_crop``, with a landmark net on each crop
— the reference builds the same cascades from its decoder modes
(``ov-face-detection``, tensordec-boundingbox.c:121-127) plus tensor_crop
(gsttensor_crop.c). Two zoo models:

- ``face_detect``: uint8 [N,128,128,3] → either OV-style detection rows
  [max_faces, 7] (image_id, label, conf, x1, y1, x2, y2 — normalized; feeds
  the bounding-box decoder's ov-face-detection mode) or, with
  ``output=regions``, pixel [max_faces, 4] (x, y, w, h) int32 regions that
  feed tensor_crop directly. Anchor-free 8x8-grid head; box decode + top-k
  run on device (fixed shapes, one XLA program).
- ``face_landmark``: uint8 [N,112,112,3] crop → [N, 136] normalized (x,y)
  pairs for 68 landmarks.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from nnstreamer_tpu.models import mobilenet_v2, nn

MAX_FACES = 16
DETECT_SIZE = 128
LANDMARK_SIZE = 112
NUM_LANDMARKS = 68

# detector trunk: (out_channels, stride) sep-conv plan, 128 → 8 grid
_DET_BLOCKS = ((32, 2), (64, 1), (64, 2), (128, 1), (128, 2), (128, 1))
_GRID = 8

# landmark trunk: 112 → 7
_LMK_BLOCKS = ((32, 2), (64, 2), (128, 2), (128, 1))


def init_detect_params(key) -> Dict:
    keys = iter(jax.random.split(key, 16))
    p: Dict = {"stem": {"w": nn.init_conv(next(keys), 3, 3, 3, 16), "bn": nn.init_bn(16)}}
    cin = 16
    blocks = []
    for cout, _ in _DET_BLOCKS:
        blocks.append(nn.init_sep_conv(next(keys), cin, cout))
        cin = cout
    p["blocks"] = blocks
    # per-cell head: (objectness, dy, dx, dh, dw)
    p["head"] = {
        "w": nn.init_conv(next(keys), 3, 3, cin, 5),
        "b": jnp.zeros((5,), jnp.float32),
    }
    return p


def apply_detect(params: Dict, x, max_faces: int = MAX_FACES, compute_dtype=jnp.float32):
    """→ [max_faces, 7] OV detection rows (batch-1 semantics like the
    reference's OV face models)."""
    if x.dtype == jnp.uint8:
        x = mobilenet_v2.normalize_uint8(x, compute_dtype)
    else:
        x = x.astype(compute_dtype)
    if compute_dtype != jnp.float32:
        params = nn.cast_params(params, compute_dtype)
    y = nn.relu6(
        nn.batch_norm(nn.conv2d(x, params["stem"]["w"], stride=2), params["stem"]["bn"], False)
    )
    for blk, (_, stride) in zip(params["blocks"], _DET_BLOCKS):
        y = nn.sep_conv(y, blk, stride=stride)
    head = (nn.conv2d(y, params["head"]["w"]) + params["head"]["b"]).astype(jnp.float32)
    g = head.shape[1]
    head = head.reshape(-1, g * g, 5)[0]  # batch-1
    conf = jax.nn.sigmoid(head[:, 0])
    # cell-anchored decode: center = cell center + tanh offset, size = sigmoid
    rows = (jnp.arange(g * g) // g).astype(jnp.float32)
    cols = (jnp.arange(g * g) % g).astype(jnp.float32)
    cy = (rows + 0.5) / g + jnp.tanh(head[:, 1]) / g
    cx = (cols + 0.5) / g + jnp.tanh(head[:, 2]) / g
    bh = jax.nn.sigmoid(head[:, 3])
    bw = jax.nn.sigmoid(head[:, 4])
    x1 = jnp.clip(cx - bw / 2, 0.0, 1.0)
    y1 = jnp.clip(cy - bh / 2, 0.0, 1.0)
    x2 = jnp.clip(cx + bw / 2, 0.0, 1.0)
    y2 = jnp.clip(cy + bh / 2, 0.0, 1.0)
    top_conf, top_idx = jax.lax.top_k(conf, max_faces)
    det = jnp.stack(
        [
            jnp.zeros((max_faces,), jnp.float32),  # image_id
            jnp.ones((max_faces,), jnp.float32),  # label (face)
            top_conf,
            x1[top_idx],
            y1[top_idx],
            x2[top_idx],
            y2[top_idx],
        ],
        axis=-1,
    )
    return det


def detections_to_regions(det, frame_w: int, frame_h: int, threshold: float = 0.5):
    """[max,7] OV rows → [max,4] int32 pixel (x, y, w, h) for tensor_crop;
    below-threshold rows become zero-size regions (crop skips them)."""
    keep = det[:, 2] >= threshold
    x = det[:, 3] * frame_w
    y = det[:, 4] * frame_h
    w = (det[:, 5] - det[:, 3]) * frame_w
    h = (det[:, 6] - det[:, 4]) * frame_h
    out = jnp.stack([x, y, w, h], axis=-1)
    return jnp.where(keep[:, None], out, 0.0).astype(jnp.int32)


def apply_detect_regions_with_image(
    det_params: Dict,
    image,
    frame_w: int,
    frame_h: int,
    max_faces: int = MAX_FACES,
    threshold: float = 0.5,
    compute_dtype=jnp.float32,
):
    """Detector head + image passthrough: (image, regions [max,4] int32).

    The 2-tensor output that lets the element cascade fuse end to end
    (docs/on-device-ops.md): a downstream ``tensor_transform
    mode=crop-resize`` consumes (image, regions) as ONE traceable op, so
    detect→crop→landmark runs as adjacent device segments with the PR-8
    resident handoff — no tee, no tensor_crop Routing node, no host hop.
    The image rides through untouched (same array, no copy on device)."""
    det = apply_detect(det_params, image, max_faces, compute_dtype)
    return image, detections_to_regions(det, frame_w, frame_h, threshold)


def apply_composite(
    det_params: Dict,
    lmk_params: Dict,
    image,
    max_faces: int = MAX_FACES,
    threshold: float = 0.5,
    compute_dtype=jnp.float32,
):
    """The whole detect→crop→landmark cascade as ONE XLA program.

    The element-level composite (tensor_crop + second filter) is faithful
    to the reference's cascade shape but pays a host hop per frame: crop
    output sizes are data-dependent, so the regions must materialize on
    host (gsttensor_crop.c emits variable-size flexible buffers). Here the
    crop is ops/image.crop_and_resize to the canonical LANDMARK_SIZE —
    fixed shapes end to end, the landmark net runs all max_faces crops as
    one batch on the MXU, and nothing leaves HBM.

    uint8 [1, H, W, 3] → (landmarks [max_faces, 136], det [max_faces, 7]).
    Below-threshold rows keep top-k order; mask with ``det[:, 2]``.
    """
    from nnstreamer_tpu.ops.image import crop_and_resize

    det = apply_detect(det_params, image, max_faces, compute_dtype)
    h, w = image.shape[1], image.shape[2]
    scale = jnp.asarray([w, h, w, h], jnp.float32)
    boxes = det[:, 3:7] * scale  # normalized x1,y1,x2,y2 → pixels
    img = image[0]
    if img.dtype == jnp.uint8:
        img = mobilenet_v2.normalize_uint8(img, compute_dtype)
    else:
        img = img.astype(compute_dtype)
    crops = crop_and_resize(img, boxes, LANDMARK_SIZE, LANDMARK_SIZE)
    lmk = apply_landmark(lmk_params, crops, compute_dtype)
    keep = det[:, 2] >= threshold
    return jnp.where(keep[:, None], lmk, 0.0), det


def init_landmark_params(key, num_landmarks: int = NUM_LANDMARKS) -> Dict:
    keys = iter(jax.random.split(key, 12))
    p: Dict = {"stem": {"w": nn.init_conv(next(keys), 3, 3, 3, 16), "bn": nn.init_bn(16)}}
    cin = 16
    blocks = []
    for cout, _ in _LMK_BLOCKS:
        blocks.append(nn.init_sep_conv(next(keys), cin, cout))
        cin = cout
    p["blocks"] = blocks
    p["fc"] = nn.init_dense(next(keys), cin, 2 * num_landmarks)
    return p


def apply_landmark(params: Dict, x, compute_dtype=jnp.float32):
    """uint8 NHWC crop (any HxW ≥ 16) → [N, 2*num_landmarks] in [0,1]."""
    if x.dtype == jnp.uint8:
        x = mobilenet_v2.normalize_uint8(x, compute_dtype)
    else:
        x = x.astype(compute_dtype)
    if compute_dtype != jnp.float32:
        params = nn.cast_params(params, compute_dtype)
    y = nn.relu6(
        nn.batch_norm(nn.conv2d(x, params["stem"]["w"], stride=2), params["stem"]["bn"], False)
    )
    for blk, (_, stride) in zip(params["blocks"], _LMK_BLOCKS):
        y = nn.sep_conv(y, blk, stride=stride)
    y = jnp.mean(y, axis=(1, 2))  # global pool makes the net crop-size agnostic
    return jax.nn.sigmoid(nn.dense(y, params["fc"])).astype(jnp.float32)
