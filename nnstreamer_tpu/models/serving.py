"""Continuous-batching LLM serving: slot-based KV-cache decode.

models/decode.py serves one request at a time; real serving multiplexes
many streams of different lengths onto one chip. The TPU-shaped answer is
slot-based continuous batching: a fixed [n_slots] batch of KV-cache slots,
one batched decode program stepping ALL active slots per token, and
requests joining/leaving between steps — shapes never change, so XLA
compiles a fixed handful of programs for the server's lifetime.

This is the genuinely-new analogue of the reference's one-server-many-
clients query path (tensor_query_serversrc client_id demultiplexing,
gst/nnstreamer/tensor_query/tensor_query_serversrc.c:379-427): there the
multiplexed unit is a frame, here it is a decode step.

Correctness invariant (tested): a request served in a busy batch yields
byte-identical greedy tokens to models/decode.generate() run alone —
per-slot positions, per-slot masks, and inactive-slot write gating make
slots fully isolated.

Design notes:
- per-slot RoPE positions (`pos` [B]) — rope() here takes per-batch
  positions, unlike the shared-position prefill path;
- cache writes go through a batched dynamic_update_slice (vmap over the
  slot axis) and are gated by `active`, so idle slots never mutate;
- prompts are right-padded to a fixed prompt bucket; causal masking makes
  the pad positions unreachable (they are never attended and the cache
  beyond the true length is rewritten before the mask can include it);
- ``cache_dtype="int8"`` stores the KV cache quantized (per-token-per-
  head scales, quantize_kv) — 4× less HBM than f32, i.e. 4× the live
  context per chip, dequantized on the attention read (blockwise in VMEM
  when the Pallas kernel runs, so HBM traffic stays at the int8 bytes);
- sampling (temperature / top-k / top-p) runs INSIDE the step program
  with per-slot parameters and per-slot fold_in(seed, position) keys —
  one int32 per slot crosses to host per step, never [B, V] logits;
- admission decouples from decode: submit() prefills outside the state
  lock and queues a pending insert that the next step() applies, so the
  compiled step runs with no lock held and admission never serializes
  behind an in-flight device step.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu.models import decode as dec
from nnstreamer_tpu.models import transformer as tfm
from nnstreamer_tpu.models.speculative import ngram_lookup


def quantize_kv(t):
    """[..., H, Dh] float → (int8 same shape, f32 scale [..., H]).
    Per-token-per-head symmetric scales keep the error tight without
    storing more than 1/Dh extra floats — the cache shrinks 4× vs f32
    (2× vs bf16), which is more live slots or longer contexts per chip."""
    m = jnp.maximum(jnp.max(jnp.abs(t.astype(jnp.float32)), axis=-1), 1e-8)
    scale = m / 127.0
    q = jnp.clip(jnp.round(t.astype(jnp.float32) / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_kv(q, scale):
    return q.astype(jnp.float32) * scale[..., None]


def batched_decode_step(
    params: Dict,
    tok,
    pos,
    active,
    cache: Tuple[jax.Array, jax.Array],
    n_heads: int,
    compute_dtype=jnp.float32,
    attn_fn=None,
    windowed: bool = False,
):
    """One decode step for a whole slot batch.

    tok [B] int32, pos [B] int32 (per-slot fill level), active [B] bool →
    (logits [B, V] f32, cache', pos'). Inactive slots: cache and pos are
    unchanged and their logits are garbage (callers must gate on
    ``active``). ``attn_fn(q, ck, cv, pos) -> [B,1,H,Dh]`` overrides the
    inline masked attention (the Pallas single-pass kernel,
    ops/pallas/decode_attention.py); with an int8 cache the attn_fn
    receives the quantized entries ``(ck8, kscale)`` / ``(cv8, vscale)``
    directly — the kernel dequantizes blockwise in VMEM, which is the
    whole point of quantizing (HBM traffic stays at int8 bytes).

    ``cache`` is either ``(ck, cv)`` (float) or
    ``((ck8, kscale), (cv8, vscale))`` (int8, see quantize_kv).

    ``windowed=True`` treats the cache's length dim as a RING over the
    last max_len tokens (sliding-window attention): writes land at
    ``pos % max_len``, and that is the ONLY change — the ≤pos liveness
    mask saturates to all-live once pos ≥ max_len, which is exactly the
    ring's semantics (every entry then holds one of the last max_len
    tokens). K rows are stored already RoPE-rotated at their absolute
    position, so the softmax needs only the *set* of the last-W keys,
    never their ring order; ``pos`` keeps counting absolute tokens,
    which keeps RoPE exact for as long as f32 can hold the position
    (~16.7M tokens — rope() computes angles in float32).
    The same saturation argument makes windowed compose with attn_fn
    (the Pallas kernel's ``cols ≤ pos`` mask degenerates identically)."""
    quantized = isinstance(cache[0], tuple)
    max_len = (cache[0][0] if quantized else cache[0]).shape[2]
    b = tok.shape[0]
    x = tfm.embed_lookup(params["embed"], tok, compute_dtype)[:, None, :]
    gate = active[:, None, None, None]
    wpos = pos % max_len if windowed else pos

    def write(c, new):
        """c [B,max_len,H,Dh] ← new [B,1,H,Dh] at per-slot pos, if active."""
        written = jax.vmap(
            lambda cb, nb, p: jax.lax.dynamic_update_slice(cb, nb, (p, 0, 0))
        )(c, new.astype(c.dtype), wpos)
        return jnp.where(gate, written, c)

    def write_scale(sc, new):
        """sc [B,max_len,H] ← new [B,1,H] at per-slot pos, if active."""
        written = jax.vmap(
            lambda sb, nb, p: jax.lax.dynamic_update_slice(sb, nb, (p, 0))
        )(sc, new, wpos)
        return jnp.where(gate[..., 0], written, sc)

    def body(carry, layer):
        x = carry
        if quantized:
            blk, ck8, ksc, cv8, vsc = layer
        else:
            blk, ck, cv = layer
        bsz, _, d = x.shape
        # per-slot positions: block_qkv → rope() take [B,T] (here T=1);
        # k/v come back with KV ≤ H heads (GQA) matching the cache
        q, k, v = tfm.block_qkv(x, blk, n_heads, pos[:, None])
        if quantized:
            k8, ks = quantize_kv(k)
            v8, vs = quantize_kv(v)
            ck8 = write(ck8, k8)
            ksc = write_scale(ksc, ks)
            cv8 = write(cv8, v8)
            vsc = write_scale(vsc, vs)
            out_layer = (ck8, ksc, cv8, vsc)
            if attn_fn is None:
                ck = dequantize_kv(ck8, ksc)
                cv = dequantize_kv(cv8, vsc)
        else:
            ck = write(ck, k)
            cv = write(cv, v)
            out_layer = (ck, cv)
        if attn_fn is not None:
            if quantized:
                o = attn_fn(q, (ck8, ksc), (cv8, vsc), pos)
            else:
                o = attn_fn(q, ck, cv, pos)  # [B,1,H,Dh] f32
        else:
            # liveness mask [B, max_len]: the ≤pos prefix — which
            # saturates to all-live past a ring wrap (windowed), exactly
            # the last-W-tokens semantics
            mask = jnp.arange(max_len)[None, :] <= pos[:, None]
            o = tfm.cache_attention(q, ck, cv, mask[:, None, :])
        o = o.astype(x.dtype).reshape(bsz, 1, -1)
        x = x + o @ tfm.wt(blk["wo"], x.dtype)
        x = tfm.block_ffn(x, blk)
        return x, out_layer

    if quantized:
        (ck8, ksc), (cv8, vsc) = cache
        xs = (params["blocks"], ck8, ksc, cv8, vsc)
    else:
        xs = (params["blocks"],) + tuple(cache)
    x, out_layers = jax.lax.scan(body, x, xs)
    if quantized:
        ck8, ksc, cv8, vsc = out_layers
        cache_out = ((ck8, ksc), (cv8, vsc))
    else:
        cache_out = out_layers
    x = tfm.rmsnorm(x, params["ln_f"])
    logits = (x @ tfm.wt(params["head"], x.dtype)).astype(jnp.float32)[:, 0]
    return logits, cache_out, pos + active.astype(jnp.int32)


def batched_verify_step(
    params: Dict,
    toks,
    pos,
    active,
    cache: Tuple[jax.Array, jax.Array],
    n_heads: int,
    compute_dtype=jnp.float32,
):
    """Score per-slot k-token candidate chunks in ONE forward — the
    continuous-batching speculation verify (models/speculative.py's
    _verify generalized to per-slot positions, the same way
    batched_decode_step generalizes decode_step).

    toks [B, k] int32 (row 0 = the slot's pending token, rows 1..k-1 =
    proposals), pos [B] (per-slot fill), active [B] →
    (logits [B, k, V] f32, cache'). Chunk K/V land at per-slot positions
    pos..pos+k-1, gated on ``active``; the caller advances each slot's
    pos by its accepted count — rejected positions are overwritten
    before any mask can reach them (verify_chunk's invariant, held
    per slot). Caller must guarantee pos + k ≤ max_len for every active
    slot (dynamic_update_slice would clamp and corrupt otherwise)."""
    quantized = isinstance(cache[0], tuple)
    max_len = (cache[0][0] if quantized else cache[0]).shape[2]
    b, k = toks.shape
    x = tfm.embed_lookup(params["embed"], toks, compute_dtype)  # [B,k,D]
    positions = pos[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :]
    gate = active[:, None, None, None]

    def write_chunk(c, new):
        """c [B,max_len,H,Dh] ← new [B,k,H,Dh] at per-slot pos."""
        written = jax.vmap(
            lambda cb, nb, p: jax.lax.dynamic_update_slice(cb, nb, (p, 0, 0))
        )(c, new.astype(c.dtype), pos)
        return jnp.where(gate, written, c)

    def write_scale_chunk(sc, new):
        written = jax.vmap(
            lambda sb, nb, p: jax.lax.dynamic_update_slice(sb, nb, (p, 0))
        )(sc, new, pos)
        return jnp.where(gate[..., 0], written, sc)

    # per-slot causal mask over the cache: query i attends ≤ pos_b + i
    mask = (
        jnp.arange(max_len)[None, None, :] <= positions[:, :, None]
    )  # [B, k, max_len]

    def body(carry, layer):
        x = carry
        if quantized:
            blk, ck8, ksc, cv8, vsc = layer
        else:
            blk, ck, cv = layer
        bsz = x.shape[0]
        q, kk, v = tfm.block_qkv(x, blk, n_heads, positions)
        if quantized:
            k8, ks = quantize_kv(kk)
            v8, vs = quantize_kv(v)
            ck8 = write_chunk(ck8, k8)
            ksc = write_scale_chunk(ksc, ks)
            cv8 = write_chunk(cv8, v8)
            vsc = write_scale_chunk(vsc, vs)
            ck = dequantize_kv(ck8, ksc)
            cv = dequantize_kv(cv8, vsc)
            out_layer = (ck8, ksc, cv8, vsc)
        else:
            ck = write_chunk(ck, kk)
            cv = write_chunk(cv, v)
            out_layer = (ck, cv)
        o = tfm.cache_attention(q, ck, cv, mask)
        o = o.astype(x.dtype).reshape(bsz, k, -1)
        x = x + o @ tfm.wt(blk["wo"], x.dtype)
        x = tfm.block_ffn(x, blk)
        return x, out_layer

    if quantized:
        (ck8, ksc), (cv8, vsc) = cache
        xs = (params["blocks"], ck8, ksc, cv8, vsc)
    else:
        xs = (params["blocks"],) + tuple(cache)
    x, out_layers = jax.lax.scan(body, x, xs)
    if quantized:
        ck8, ksc, cv8, vsc = out_layers
        cache_out = ((ck8, ksc), (cv8, vsc))
    else:
        cache_out = out_layers
    x = tfm.rmsnorm(x, params["ln_f"])
    logits = (x @ tfm.wt(params["head"], x.dtype)).astype(jnp.float32)
    return logits, cache_out


def sample_tokens(logits, temp, top_k, top_p, keys):
    """Per-slot token selection INSIDE the step program.

    logits [B, V] f32; temp [B] f32 (≤ 0 → greedy); top_k [B] int32
    (0 → disabled); top_p [B] f32 (1.0 → disabled; the nucleus keeps the
    smallest most-probable set with mass ≥ top_p, boundary token
    included); keys [B, 2] uint32 per-slot PRNG keys → tok [B] int32.
    Everything is branch-free so one compiled program serves any mix of
    greedy and sampling slots — and only [B] token ids ever cross to the
    host, never the [B, V] logits (at a 32k–128k vocab that transfer is
    megabytes per step)."""
    v = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temp, 1e-6)[:, None]
    # top-k: threshold at the k-th largest value per row where enabled
    desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(
        desc, jnp.clip(top_k - 1, 0, v - 1)[:, None], axis=-1
    )
    scaled = jnp.where((top_k > 0)[:, None] & (scaled < kth), -jnp.inf, scaled)
    # top-p over the (possibly top-k-truncated) distribution
    probs = jax.nn.softmax(scaled, axis=-1)
    sp = jnp.sort(probs, axis=-1)[:, ::-1]
    csum = jnp.cumsum(sp, axis=-1)
    n_keep = jnp.sum(csum < top_p[:, None], axis=-1) + 1
    cutoff = jnp.take_along_axis(
        sp, jnp.clip(n_keep - 1, 0, v - 1)[:, None], axis=-1
    )
    scaled = jnp.where(
        (top_p < 1.0)[:, None] & (probs < cutoff), -jnp.inf, scaled
    )
    sampled = jax.vmap(jax.random.categorical)(keys, scaled).astype(jnp.int32)
    return jnp.where(temp > 0, sampled, greedy)


def insert_slot(cache, ks, vs, slot):
    """Write one prefilled request's K/V [L,1,P,H,Dh] into cache slot
    ``slot`` (quantizing when the cache is int8). Stale positions beyond
    P from a previous occupant are harmless: the decode mask only ever
    covers positions the new occupant has itself written (each step
    writes position ``pos`` before the mask grows to include it)."""

    def put(c, new):
        # [L, B, max_len, H, Dh]; write [L, 1, P, H, Dh] at (0, slot, 0)
        return jax.lax.dynamic_update_slice(
            c, new.astype(c.dtype), (0, slot, 0, 0, 0)
        )

    def put_scale(sc, new):
        # [L, B, max_len, H] ← [L, 1, P, H]
        return jax.lax.dynamic_update_slice(sc, new, (0, slot, 0, 0))

    if isinstance(cache[0], tuple):
        (ck8, ksc), (cv8, vsc) = cache
        k8, kscale = quantize_kv(ks)
        v8, vscale = quantize_kv(vs)
        return (
            (put(ck8, k8), put_scale(ksc, kscale)),
            (put(cv8, v8), put_scale(vsc, vscale)),
        )
    cache_k, cache_v = cache
    return put(cache_k, ks), put(cache_v, vs)


@dataclass
class _Request:
    rid: int
    budget: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    stop_token: Optional[int] = None
    key: Optional[np.ndarray] = None  # base PRNG key [2] uint32
    prompt: Optional[np.ndarray] = None  # spec_step's proposal context
    tokens: List[int] = field(default_factory=list)
    done: bool = False

    def finished(self) -> bool:
        """Budget exhausted, or the stop token was emitted (which stays
        in the output, like an EOS id in any serving API)."""
        if len(self.tokens) >= self.budget:
            return True
        return bool(self.tokens) and self.tokens[-1] == self.stop_token


@dataclass
class _PendingInsert:
    """A prefilled request waiting for the next step() to splice its K/V
    into the batch cache (submit never touches device state directly, so
    the compiled step runs lock-free)."""

    slot: int
    ks: jax.Array
    vs: jax.Array
    first_tok: int
    fill: int  # cache fill level (= absolute position count)
    req: _Request


class ContinuousBatcher:
    """Continuous-batching server over a fixed slot batch (greedy by
    default; per-request temperature/top-k/top-p sampling via submit()).

    submit() may be called at any time (thread-safe); step() advances every
    active slot by one token. Finished requests free their slot for the
    next submit — the batch never drains to admit new work.
    """

    def __init__(
        self,
        params: Dict,
        n_heads: int,
        n_slots: int = 4,
        max_len: int = 256,
        prompt_len: int = 64,
        compute_dtype=jnp.float32,
        attn_impl: str = "xla",
        keep_results: int = 1024,
        cache_dtype: str = "auto",
        mesh=None,
        slots_axis: str = "dp",
        windowed: bool = False,
    ):
        """``windowed=True`` makes max_len a sliding attention window
        over a ring-buffer cache: generations AND prompts of any length
        run in the fixed [max_len] cache, each token attending the
        previous max_len (Mistral-style sliding-window attention — the
        time-axis sibling of tensor_aggregator's bounded windows).

        The full feature matrix composes: attn_impl="pallas" works with
        cache_dtype="int8" (the kernel takes the scale operands and
        dequantizes in VMEM), with mesh= (the step program is wrapped in
        shard_map over the slot axis, so each device runs the kernel on
        its local slots), and with windowed=True."""
        if prompt_len > max_len:
            raise ValueError("prompt_len must be ≤ max_len")
        if cache_dtype not in ("auto", "int8"):
            raise ValueError(f"unknown cache_dtype {cache_dtype!r}")
        quantized_cache = cache_dtype == "int8"
        if attn_impl == "pallas":
            from nnstreamer_tpu.ops.pallas.decode_attention import (
                make_decode_attention,
            )

            attn_fn = make_decode_attention()
        elif attn_impl == "xla":
            attn_fn = None
        else:
            raise ValueError(f"unknown attn_impl {attn_impl!r}")
        self.params = params
        self.n_heads = n_heads
        self.n_slots = n_slots
        self.max_len = max_len
        self.windowed = windowed
        self._attn_impl = attn_impl
        self.prompt_len = prompt_len
        self.compute_dtype = compute_dtype
        self._lock = threading.Lock()       # host/device state
        self._step_lock = threading.Lock()  # serializes device steps
        self._next_rid = 0
        self._slots: List[Optional[_Request]] = [None] * n_slots
        self._pending: List[_PendingInsert] = []
        # finished requests await pickup here; bounded FIFO so a caller
        # that never collects cannot grow the host heap without limit
        self._done_pool: "OrderedDict[int, _Request]" = OrderedDict()
        self._keep_results = keep_results

        L, d = params["blocks"]["ln1"].shape
        hd = d // n_heads
        kv = tfm.n_kv_heads_of(params["blocks"]["wqkv"], d, n_heads)
        shape = (L, n_slots, max_len, kv, hd)
        if quantized_cache:
            sshape = shape[:-1]
            self._cache = (
                (jnp.zeros(shape, jnp.int8), jnp.ones(sshape, jnp.float32)),
                (jnp.zeros(shape, jnp.int8), jnp.ones(sshape, jnp.float32)),
            )
        else:
            self._cache = (
                jnp.zeros(shape, compute_dtype),
                jnp.zeros(shape, compute_dtype),
            )
        self._tok = jnp.zeros((n_slots,), jnp.int32)
        self._pos = jnp.zeros((n_slots,), jnp.int32)
        self._active = np.zeros((n_slots,), bool)
        # per-slot sampling state lives ON DEVICE so the step program
        # samples in place (host sees one token id per slot per step)
        self._temp = jnp.zeros((n_slots,), jnp.float32)
        self._topk = jnp.zeros((n_slots,), jnp.int32)
        self._topp = jnp.ones((n_slots,), jnp.float32)
        self._keys = jnp.zeros((n_slots, 2), jnp.uint32)

        if mesh is not None:
            # shard the slot axis over the mesh: the batched step runs
            # SPMD with each device decoding its share of the slots (the
            # data-parallel serving layout; params stay replicated, so
            # the only cross-device traffic is the host-driven admit)
            from jax.sharding import NamedSharding, PartitionSpec as P

            from nnstreamer_tpu.parallel.mesh import batch_sharding

            n_mesh = mesh.shape[slots_axis]
            if n_slots % n_mesh:
                raise ValueError(
                    f"n_slots={n_slots} must divide over mesh axis "
                    f"{slots_axis!r} (size {n_mesh})"
                )
            cache_sh = NamedSharding(mesh, P(None, slots_axis))
            vec_sh = batch_sharding(mesh, slots_axis)
            self._vec_sh = vec_sh
            self._cache = jax.tree_util.tree_map(
                lambda c: jax.device_put(c, cache_sh), self._cache
            )
            self._tok = jax.device_put(self._tok, vec_sh)
            self._pos = jax.device_put(self._pos, vec_sh)
            self._temp = jax.device_put(self._temp, vec_sh)
            self._topk = jax.device_put(self._topk, vec_sh)
            self._topp = jax.device_put(self._topp, vec_sh)
            self._keys = jax.device_put(self._keys, vec_sh)
        else:
            self._vec_sh = None

        self._prefill = jax.jit(
            lambda toks: dec.prefill(
                params, toks, n_heads, prompt_len,
                compute_dtype=compute_dtype,
            )
        )
        # chunked-prefill programs (prompts longer than the bucket): a
        # staging cache padded to a bucket multiple — plus one spare
        # bucket so chunk starts NOT aligned to the bucket (the prefix-
        # caching path) still fit their full-width writes
        self._stage_len = (-(-max_len // prompt_len) + 1) * prompt_len
        self._stage_shape = (L, 1, self._stage_len, kv, hd)
        self._prefill_chunk = jax.jit(
            lambda toks, cpos, cache: dec.verify_chunk(
                params, toks, cpos, cache, n_heads,
                compute_dtype=compute_dtype,
            )
        )
        self._advance_chunk = jax.jit(
            lambda toks, cpos, cache: dec.verify_chunk(
                params, toks, cpos, cache, n_heads,
                compute_dtype=compute_dtype, return_logits=False,
            )[1]
        )
        # windowed (ring) chunked-prefill programs: exact sliding-window
        # prefill for prompts of ANY length in the fixed W ring
        self._ring_shape = (L, 1, max_len, kv, hd)
        self._wchunk = jax.jit(
            lambda toks, cpos, n, cache: dec.windowed_chunk(
                params, toks, cpos, n, cache, n_heads,
                compute_dtype=compute_dtype,
            )[:2]
        )
        self._wadvance = jax.jit(
            lambda toks, cpos, n, cache: dec.windowed_chunk(
                params, toks, cpos, n, cache, n_heads,
                compute_dtype=compute_dtype, return_logits=False,
            )[1]
        )

        def step_impl(sampling):
            def impl(tok, pos, active, cache, temp, topk, topp, keys):
                logits, cache, pos2 = batched_decode_step(
                    params, tok, pos, active, cache, n_heads,
                    compute_dtype, attn_fn=attn_fn, windowed=windowed,
                )
                if sampling:
                    # per-slot key = fold_in(base, fill level): token
                    # streams are deterministic per (seed, position),
                    # independent of batch composition
                    sub = jax.vmap(jax.random.fold_in)(keys, pos2)
                    new = sample_tokens(logits, temp, topk, topp, sub)
                else:
                    new = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return jnp.where(active, new, tok), cache, pos2

            return impl

        if mesh is not None and attn_impl == "pallas":
            # GSPMD cannot partition the kernel's custom call over the
            # slot-sharded cache — but the step is slot-parallel by
            # construction, so shard_map IS the partition: each device
            # runs the whole step (kernel included) on its local slots
            from jax.sharding import PartitionSpec as P

            ax = slots_axis
            vec, cac = P(ax), P(None, ax)
            specs = dict(
                in_specs=(vec, vec, vec, cac, vec, vec, vec, vec),
                out_specs=(vec, cac, vec),
                check_vma=False,
            )
            self._step_greedy = jax.jit(
                jax.shard_map(step_impl(False), mesh=mesh, **specs)
            )
            self._step_sampling = jax.jit(
                jax.shard_map(step_impl(True), mesh=mesh, **specs)
            )
        else:
            self._step_greedy = jax.jit(step_impl(False))
            self._step_sampling = jax.jit(step_impl(True))
        # first-token pick: same device sampler over the prefill logits
        self._sample1 = jax.jit(
            lambda logits, temp, topk, topp, key: sample_tokens(
                logits[None, :], temp, topk, topp, key[None]
            )[0]
        )
        self._insert = jax.jit(insert_slot)
        # speculative verify: per-slot k-chunk scoring (spec_step); jit
        # caches one program per distinct chunk width
        self._verify = jax.jit(
            lambda toks, pos_, active, cache: batched_verify_step(
                params, toks, pos_, active, cache, n_heads, compute_dtype
            )
        )
        self._load_prefix = jax.jit(
            lambda stage, ks, vs: (
                jax.lax.dynamic_update_slice(stage[0], ks, (0, 0, 0, 0, 0)),
                jax.lax.dynamic_update_slice(stage[1], vs, (0, 0, 0, 0, 0)),
            )
        )
        # registered shared prefixes:
        # id → ((ck, cv) trimmed to plen, plen, prefix tokens)
        self._prefixes: Dict[
            int, Tuple[Tuple[jax.Array, jax.Array], int, np.ndarray]
        ] = {}
        self._next_prefix = 0
        self._n_steps = 0
        self._n_tokens = 0
        self._n_spec_rounds = 0
        self._n_spec_accepted = 0
        self._step_time_s = 0.0

    def _empty_stage(self):
        return (
            jnp.zeros(self._stage_shape, self.compute_dtype),
            jnp.zeros(self._stage_shape, self.compute_dtype),
        )

    def _stage_chunks(self, tokens, base: int, stage, want_logits: bool):
        """Advance a staging cache with ``tokens`` written at absolute
        positions base..base+t-1, one prompt_len bucket per verify_chunk
        call. Every copy of the chunked-prefill invariant (full-width pad
        writes overwritten before masked; bucket-stride chunk starts;
        verify_chunk's absolute pos) lives HERE. Returns (final chunk's
        logits or None, advanced stage)."""
        P = self.prompt_len
        t = tokens.shape[0]
        cpos = 0
        logits = None
        while cpos < t:
            n = min(P, t - cpos)
            chunk = np.zeros((1, P), np.int32)
            chunk[0, :n] = tokens[cpos : cpos + n]
            args = (
                jnp.asarray(chunk), jnp.asarray(base + cpos, jnp.int32),
                stage,
            )
            if want_logits and cpos + n >= t:
                logits, stage, _ = self._prefill_chunk(*args)
            else:
                # non-final buckets only advance the cache (no
                # vocab-head projection)
                stage = self._advance_chunk(*args)
            cpos += n
        return logits, stage

    def _stage_ring(self, tokens):
        """Windowed chunked prefill: advance a fresh W-ring with the
        whole prompt, one bucket per windowed_chunk call (exact sliding-
        window attention — decode.windowed_chunk). Returns (final
        chunk's logits, ring (ks, vs), last-row index)."""
        # submit() enforces max_len % P == 0 before any prompt longer
        # than one bucket reaches here (bucket-sized prompts never chunk,
        # so unaligned windowed configs stay valid for them)
        P = self.prompt_len
        ring = (
            jnp.zeros(self._ring_shape, self.compute_dtype),
            jnp.zeros(self._ring_shape, self.compute_dtype),
        )
        t = tokens.shape[0]
        cpos = 0
        logits = None
        while cpos < t:
            n = min(P, t - cpos)
            chunk = np.zeros((1, P), np.int32)
            chunk[0, :n] = tokens[cpos : cpos + n]
            args = (
                jnp.asarray(chunk), jnp.asarray(cpos, jnp.int32),
                jnp.asarray(n, jnp.int32), ring,
            )
            if cpos + n >= t:
                logits, ring = self._wchunk(*args)
            else:
                ring = self._wadvance(*args)
            cpos += n
        return logits, ring, (t - 1) % P  # last real row of the final chunk

    def register_prefix(self, tokens) -> int:
        """Prefill a shared prompt prefix (e.g. a system prompt) ONCE and
        return its id; submit(prefix=id) starts from its K/V instead of
        re-prefilling it per request — the admission cost of the shared
        part is paid one time. Stored trimmed to the prefix length;
        release with unregister_prefix when no longer needed."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        plen = tokens.shape[0]
        if self.windowed:
            # a prefix's ring placement depends on what follows it (its
            # absolute positions shift per request), so the cached K/V
            # cannot be spliced into a ring — fundamental, not a TODO
            raise ValueError("prefix caching needs an unwindowed cache")
        if not (0 < plen < self.max_len):
            raise ValueError(
                f"prefix length {plen} not in (0, max_len={self.max_len})"
            )
        _, stage = self._stage_chunks(tokens, 0, self._empty_stage(), False)
        trimmed = (stage[0][:, :, :plen], stage[1][:, :, :plen])
        with self._lock:
            pid = self._next_prefix
            self._next_prefix += 1
            # tokens ride along so spec_step's prompt-lookup context
            # covers the shared prefix too (proposal quality, not
            # correctness — n-gram matches often live in a system prompt)
            self._prefixes[pid] = (trimmed, plen, tokens)
        return pid

    def unregister_prefix(self, pid: int) -> bool:
        """Release a registered prefix's device memory (in-flight
        requests are unaffected — their slot cache holds a copy)."""
        with self._lock:
            return self._prefixes.pop(pid, None) is not None

    # -- client API --------------------------------------------------------
    def submit(
        self,
        prompt: np.ndarray,
        max_new_tokens: int,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        seed: Optional[int] = None,
        stop_token: Optional[int] = None,
        prefix: Optional[int] = None,
    ) -> Optional[int]:
        """Claim a free slot for ``prompt`` [T]; returns a request id, or
        None when the batch is full (caller queues/retries — the
        admission queue is the caller's policy, not the batcher's).
        Prompts longer than the prompt_len bucket prefill in bucket-sized
        chunks (decode.verify_chunk; decode.windowed_chunk on a ring when
        windowed), so T is bounded by the cache — or by nothing at all
        when windowed (the ring retains the last max_len tokens, exactly
        sliding-window semantics).

        Sampling is per-request: temperature ≤ 0 is greedy; otherwise
        softmax sampling, optionally top-k truncated and/or top-p
        (nucleus) filtered (0 < top_p < 1; the boundary token is kept),
        with a deterministic per-request stream: every token is keyed by
        fold_in(PRNGKey(seed), fill-level), so the stream depends only on
        (seed, position) — never on batch composition."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        t = prompt.shape[0]
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be ≥ 1, got {max_new_tokens}")
        if t == 0:
            raise ValueError("empty prompt")
        plen = 0
        pfx = None
        pfx_tokens = None
        if prefix is not None:
            with self._lock:
                if prefix not in self._prefixes:
                    raise ValueError(f"unknown prefix id {prefix}")
                pfx, plen, pfx_tokens = self._prefixes[prefix]
        if self.windowed and t > self.prompt_len and self.max_len % self.prompt_len:
            # checked before any slot is claimed: ring chunked prefill
            # needs bucket-aligned chunks (a mid-chunk ring wrap would
            # corrupt live entries). Bucket-sized prompts never chunk, so
            # unaligned windowed configs stay valid for them.
            raise ValueError(
                f"windowed long prompts need max_len({self.max_len}) to "
                f"be a multiple of prompt_len({self.prompt_len}) so "
                "prefill chunks never wrap the ring mid-chunk"
            )
        if not self.windowed and plen + t > self.max_len:
            raise ValueError(
                f"prefix({plen}) + prompt({t}) > max_len {self.max_len}"
            )
        if not self.windowed and plen + t + max_new_tokens > self.max_len:
            raise ValueError(
                f"{plen}+{t}+{max_new_tokens} tokens would overflow "
                f"max_len={self.max_len} (windowed=True lifts this: the "
                "cache becomes a sliding ring)"
            )
        with self._lock:
            # claim only — the slot is owned (so no other submit takes it)
            # but inactive, so concurrent step() calls skip it while the
            # prefill below runs outside the lock
            try:
                slot = next(
                    i for i, r in enumerate(self._slots) if r is None
                )
            except StopIteration:
                return None
            rid = self._next_rid
            self._next_rid += 1
            req = _Request(
                rid, max_new_tokens, temperature=temperature, top_k=top_k,
                top_p=top_p, stop_token=stop_token,
                key=np.asarray(
                    jax.random.PRNGKey(rid if seed is None else seed)
                ),
                # spec_step's proposal context — the prefix's tokens are
                # part of the stream the n-gram lookup should mine
                prompt=(
                    prompt if pfx_tokens is None
                    else np.concatenate([pfx_tokens, prompt])
                ),
            )
            self._slots[slot] = req

        try:
            P = self.prompt_len
            if pfx is None and t <= P:
                # single-program fast path for bucket-sized prompts
                padded = np.zeros((1, P), np.int32)
                padded[0, :t] = prompt
                logits, (ks, vs), _ = self._prefill(jnp.asarray(padded))
                logits_row = logits[0, t - 1]
            elif self.windowed:
                # ring chunked prefill: exact sliding-window attention
                # for prompts of any length (the ring keeps the last W)
                logits, (ks, vs), last = self._stage_ring(prompt)
                logits_row = logits[0, last]
            else:
                # chunked prefill (_stage_chunks): the staging cache
                # starts empty or preloaded with the registered prefix
                if pfx is None:
                    stage = self._empty_stage()
                else:
                    stage = self._load_prefix(self._empty_stage(), *pfx)
                logits, stage = self._stage_chunks(prompt, plen, stage, True)
                last = (t - 1) % P  # true last token's index in the chunk
                logits_row = logits[0, last]
                ks = stage[0][:, :, : self.max_len]
                vs = stage[1][:, :, : self.max_len]
            fill = plen + t
            first = int(
                self._sample1(
                    logits_row,
                    jnp.asarray([temperature], jnp.float32),
                    jnp.asarray([top_k], jnp.int32),
                    jnp.asarray([top_p], jnp.float32),
                    jax.random.fold_in(jnp.asarray(req.key), fill),
                )
            )
        except Exception:
            # release the claimed slot or n_slots failed prefills would
            # brick the server with every slot claimed-but-never-active
            with self._lock:
                self._slots[slot] = None
            raise

        with self._lock:
            req.tokens.append(first)
            if req.finished():
                self._finish(slot)
            else:
                self._pending.append(
                    _PendingInsert(slot, ks, vs, first, fill, req)
                )
        return rid

    def _apply_pending_locked(self) -> None:
        """Splice queued admissions into the device state (_lock held)."""
        for p in self._pending:
            if self._slots[p.slot] is not p.req:
                continue  # request vanished (defensive; cannot happen)
            self._cache = self._insert(self._cache, p.ks, p.vs, p.slot)
            self._tok = self._pin(self._tok.at[p.slot].set(p.first_tok))
            self._pos = self._pin(self._pos.at[p.slot].set(p.fill))
            self._temp = self._pin(
                self._temp.at[p.slot].set(p.req.temperature)
            )
            self._topk = self._pin(self._topk.at[p.slot].set(p.req.top_k))
            self._topp = self._pin(self._topp.at[p.slot].set(p.req.top_p))
            self._keys = self._pin(
                self._keys.at[p.slot].set(jnp.asarray(p.req.key))
            )
            self._active[p.slot] = True
        self._pending.clear()

    def step(self) -> Dict[int, int]:
        """Advance every active slot one token; returns {rid: token}.

        The compiled step runs OUTSIDE the state lock (admission only
        needs the lock for its bookkeeping, so submit() never waits on an
        in-flight device step); _step_lock serializes concurrent
        steppers. Slots admitted while a step is in flight join at the
        next step."""
        import time as _time

        t0 = _time.perf_counter()
        with self._step_lock:
            return self._plain_step_locked(t0)

    def _plain_step_locked(self, t0) -> Dict[int, int]:
        """step() body; caller holds _step_lock."""
        import time as _time

        with self._lock:
            self._apply_pending_locked()
            if not self._active.any():
                return {}
            active_np = self._active.copy()
            sampling = any(
                req is not None and active_np[s] and req.temperature > 0
                for s, req in enumerate(self._slots)
            )
            args = (
                self._tok, self._pos, jnp.asarray(active_np),
                self._cache, self._temp, self._topk, self._topp,
                self._keys,
            )
        step_fn = self._step_sampling if sampling else self._step_greedy
        new_tok, cache, pos = step_fn(*args)
        toks = np.asarray(new_tok)  # [B] ids — the only host transfer
        with self._lock:
            self._cache = cache
            self._pos = pos
            self._tok = new_tok
            emitted: Dict[int, int] = {}
            for slot, req in enumerate(self._slots):
                if req is None or not active_np[slot]:
                    continue
                tok = int(toks[slot])
                req.tokens.append(tok)
                emitted[req.rid] = tok
                if req.finished():
                    self._finish(slot)
            self._n_steps += 1
            self._n_tokens += len(emitted)
            self._step_time_s += _time.perf_counter() - t0
            return emitted

    def spec_step(self, k: int = 4, ngram: int = 2) -> Dict[int, int]:
        """One SPECULATIVE round: every active slot verifies k-1 guessed
        continuation tokens in one batched forward and commits its
        accepted prefix plus one bonus token — several tokens per program
        launch when the guesses land. Proposals are prompt-lookup
        (n-gram) from each slot's own context (vLLM-style self-drafting:
        no draft model; models/speculative.py's scheme batched over
        slots). Exact greedy equivalence with step() by construction —
        verification IS the greedy model, wrong guesses only waste their
        verify columns. Falls back to a plain step when speculation
        can't apply (a sampling slot, a windowed ring cache, a Pallas
        batcher — its kernel's accumulation order differs from the
        verify forward's — or no room for a chunk). Returns {rid: last
        emitted token}; use partials() for the full per-round stream."""
        import time as _time

        t0 = _time.perf_counter()
        with self._step_lock:
            with self._lock:
                self._apply_pending_locked()
                if not self._active.any():
                    return {}
                active_np = self._active.copy()
                sampling = any(
                    req is not None and active_np[s] and req.temperature > 0
                    for s, req in enumerate(self._slots)
                )
                k_round = 1
                # pallas batchers fall back too: the verify forward uses
                # inline XLA attention, whose accumulation order differs
                # from the Pallas decode kernel's — mixing them inside
                # one generation would break the exact-equivalence
                # promise on near-tied logits
                if (
                    not self.windowed and not sampling
                    and self._attn_impl != "pallas"
                ):
                    pos_np = np.asarray(self._pos)
                    room = min(
                        int(self.max_len - pos_np[s])
                        for s in range(self.n_slots) if active_np[s]
                    )
                    k_round = max(1, min(k, room))
                if k_round >= 2:
                    toks_host = np.zeros((self.n_slots, k_round), np.int32)
                    tok_np = np.asarray(self._tok)
                    any_found = False
                    for s, req in enumerate(self._slots):
                        if req is None or not active_np[s]:
                            continue
                        toks_host[s, 0] = tok_np[s]
                        ctx = np.concatenate(
                            [req.prompt, np.asarray(req.tokens, np.int32)]
                        )
                        cand = ngram_lookup(ctx, k_round - 1, ngram)
                        # -1 sentinel for found-nothing columns: a real
                        # greedy token (≥ 0) can never match it, so the
                        # acceptance scan stops at the pending token
                        # instead of crediting accidental token-0 hits
                        # (zero-fill is indistinguishable from proposing
                        # token 0); XLA's gather clamps the embed lookup
                        toks_host[s, 1:] = -1
                        if cand is not None and cand.size:
                            toks_host[s, 1 : 1 + cand.size] = cand
                            any_found = True
                    if not any_found:
                        # no slot proposed anything: the verify forward
                        # would certify exactly one token per slot at k×
                        # the column cost — a plain step is the same
                        # result cheaper
                        k_round = 1
                if k_round >= 2:
                    args = (
                        jnp.asarray(toks_host), self._pos,
                        jnp.asarray(active_np), self._cache,
                    )
            if k_round < 2:
                # outside self._lock — _plain_step_locked reacquires it
                return self._plain_step_locked(t0)
            logits, cache = self._verify(*args)
            greedy = np.asarray(jnp.argmax(logits, axis=-1))  # [B, k]
            with self._lock:
                self._cache = cache
                emitted: Dict[int, int] = {}
                new_tok = tok_np.copy()
                new_pos = pos_np.copy()
                n_emitted = 0
                accepted = 0
                for s, req in enumerate(self._slots):
                    if req is None or not active_np[s]:
                        continue
                    m = 1
                    while (
                        m < k_round
                        and greedy[s, m - 1] == toks_host[s, m]
                    ):
                        m += 1
                    accepted += m - 1
                    planned = [int(t) for t in toks_host[s, 1:m]]
                    planned.append(int(greedy[s, m - 1]))
                    for t in planned:
                        req.tokens.append(t)
                        emitted[req.rid] = t
                        n_emitted += 1
                        if req.finished():
                            break
                    new_tok[s] = req.tokens[-1]
                    new_pos[s] = pos_np[s] + m
                    if req.finished():
                        self._finish(s)
                self._tok = self._pin(jnp.asarray(new_tok))
                self._pos = self._pin(jnp.asarray(new_pos, jnp.int32))
                self._n_steps += 1
                self._n_tokens += n_emitted
                self._n_spec_rounds += 1
                self._n_spec_accepted += accepted
                self._step_time_s += _time.perf_counter() - t0
                return emitted

    def stats(self) -> Dict[str, float]:
        """Serving counters — the token-world analogue of the filter
        element's latency/throughput props (tensor_filter.c:334-433):
        cumulative steps/tokens, decode rate, and current occupancy."""
        with self._lock:
            occupied = sum(r is not None for r in self._slots)
            return {
                "steps": self._n_steps,
                "tokens_emitted": self._n_tokens,
                "tokens_per_step": (
                    self._n_tokens / self._n_steps if self._n_steps else 0.0
                ),
                "decode_tok_s": (
                    self._n_tokens / self._step_time_s
                    if self._step_time_s > 0 else 0.0
                ),
                "spec_rounds": self._n_spec_rounds,
                "spec_accepted_tokens": self._n_spec_accepted,
                "slots_occupied": occupied,
                "slots_free": self.n_slots - occupied,
                "results_pending_pickup": len(self._done_pool),
                "prefixes_registered": len(self._prefixes),
            }

    def _pin(self, x):
        """Keep per-slot vectors on their mesh sharding after eager
        updates, so the compiled step sees stable input shardings."""
        return jax.device_put(x, self._vec_sh) if self._vec_sh else x

    def _finish(self, slot: int) -> None:
        req = self._slots[slot]
        req.done = True
        self._active[slot] = False
        self._done_pool[req.rid] = req
        while len(self._done_pool) > self._keep_results:
            self._done_pool.popitem(last=False)  # evict oldest uncollected
        self._slots[slot] = None

    def result(self, rid: int) -> Optional[List[int]]:
        """Completed token list for ``rid``, or None if still running."""
        with self._lock:
            if rid in self._done_pool:
                return list(self._done_pool[rid].tokens)
            return None

    def partial(self, rid: int) -> Optional[List[int]]:
        """Tokens emitted SO FAR for ``rid`` (running or finished) — the
        token-streaming read surface. None for unknown/evicted ids."""
        return self.partials([rid]).get(rid)

    def partials(self, rids) -> Dict[int, List[int]]:
        """Batched partial(): {rid: tokens-so-far} for every known rid,
        in ONE lock acquisition and one pass over slots/pending/done —
        the per-token streaming hot path polls every pending request per
        decode step, so the per-rid scan must not multiply."""
        want = set(rids)
        out: Dict[int, List[int]] = {}
        with self._lock:
            for req in self._slots:
                if req is not None and req.rid in want:
                    out[req.rid] = list(req.tokens)
            for p in self._pending:
                if p.req.rid in want:
                    out[p.req.rid] = list(p.req.tokens)
            for rid in want - out.keys():
                if rid in self._done_pool:
                    out[rid] = list(self._done_pool[rid].tokens)
        return out

    @property
    def n_free(self) -> int:
        with self._lock:
            return sum(r is None for r in self._slots)
