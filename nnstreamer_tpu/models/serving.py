"""Continuous-batching LLM serving: slot-based KV-cache decode.

models/decode.py serves one request at a time; real serving multiplexes
many streams of different lengths onto one chip. The TPU-shaped answer is
slot-based continuous batching: a fixed [n_slots] batch of KV-cache slots,
one batched decode program stepping ALL active slots per token, and
requests joining/leaving between steps — shapes never change, so XLA
compiles a fixed handful of programs for the server's lifetime.

This is the genuinely-new analogue of the reference's one-server-many-
clients query path (tensor_query_serversrc client_id demultiplexing,
gst/nnstreamer/tensor_query/tensor_query_serversrc.c:379-427): there the
multiplexed unit is a frame, here it is a decode step.

Correctness invariant (tested): a request served in a busy batch yields
byte-identical greedy tokens to models/decode.generate() run alone —
per-slot positions, per-slot masks, and inactive-slot write gating make
slots fully isolated.

Design notes:
- per-slot RoPE positions (`pos` [B]) — rope() here takes per-batch
  positions, unlike the shared-position prefill path;
- cache writes go through a batched dynamic_update_slice (vmap over the
  slot axis) and are gated by `active`, so idle slots never mutate;
- prompts are right-padded to a fixed prompt bucket; causal masking makes
  the pad positions unreachable (they are never attended and the cache
  beyond the true length is rewritten before the mask can include it);
- ``cache_dtype="int8"`` stores the KV cache quantized (per-token-per-
  head scales, quantize_kv) — 4× less HBM than f32, i.e. 4× the live
  context per chip, dequantized on the attention read (blockwise in VMEM
  when the Pallas kernel runs, so HBM traffic stays at the int8 bytes);
- sampling (temperature / top-k / top-p) runs INSIDE the step program
  with per-slot parameters and per-slot fold_in(seed, position) keys —
  one int32 per slot crosses to host per step, never [B, V] logits;
- admission decouples from decode: submit() prefills outside the state
  lock and queues a pending insert that the next step() applies, so the
  compiled step runs with no lock held and admission never serializes
  behind an in-flight device step.
"""

from __future__ import annotations

import threading
import time as _time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu.models import decode as dec
from nnstreamer_tpu.models import transformer as tfm
from nnstreamer_tpu.models.speculative import ngram_lookup
from nnstreamer_tpu.parallel.mesh import shard_map as _shard_map


def quantize_kv(t):
    """[..., H, Dh] float → (int8 same shape, f32 scale [..., H]).
    Per-token-per-head symmetric scales keep the error tight without
    storing more than 1/Dh extra floats — the cache shrinks 4× vs f32
    (2× vs bf16), which is more live slots or longer contexts per chip."""
    m = jnp.maximum(jnp.max(jnp.abs(t.astype(jnp.float32)), axis=-1), 1e-8)
    scale = m / 127.0
    q = jnp.clip(jnp.round(t.astype(jnp.float32) / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_kv(q, scale):
    return q.astype(jnp.float32) * scale[..., None]


def batched_decode_step(
    params: Dict,
    tok,
    pos,
    active,
    cache: Tuple[jax.Array, jax.Array],
    n_heads: int,
    compute_dtype=jnp.float32,
    attn_fn=None,
    windowed: bool = False,
):
    """One decode step for a whole slot batch.

    tok [B] int32, pos [B] int32 (per-slot fill level), active [B] bool →
    (logits [B, V] f32, cache', pos'). Inactive slots: cache and pos are
    unchanged and their logits are garbage (callers must gate on
    ``active``). ``attn_fn(q, ck, cv, pos) -> [B,1,H,Dh]`` overrides the
    inline masked attention (the Pallas single-pass kernel,
    ops/pallas/decode_attention.py); with an int8 cache the attn_fn
    receives the quantized entries ``(ck8, kscale)`` / ``(cv8, vscale)``
    directly — the kernel dequantizes blockwise in VMEM, which is the
    whole point of quantizing (HBM traffic stays at int8 bytes).

    ``cache`` is either ``(ck, cv)`` (float) or
    ``((ck8, kscale), (cv8, vscale))`` (int8, see quantize_kv).

    ``windowed=True`` treats the cache's length dim as a RING over the
    last max_len tokens (sliding-window attention): writes land at
    ``pos % max_len``, and that is the ONLY change — the ≤pos liveness
    mask saturates to all-live once pos ≥ max_len, which is exactly the
    ring's semantics (every entry then holds one of the last max_len
    tokens). K rows are stored already RoPE-rotated at their absolute
    position, so the softmax needs only the *set* of the last-W keys,
    never their ring order; ``pos`` keeps counting absolute tokens,
    which keeps RoPE exact for as long as f32 can hold the position
    (~16.7M tokens — rope() computes angles in float32).
    The same saturation argument makes windowed compose with attn_fn
    (the Pallas kernel's ``cols ≤ pos`` mask degenerates identically)."""
    quantized = isinstance(cache[0], tuple)
    max_len = (cache[0][0] if quantized else cache[0]).shape[2]
    b = tok.shape[0]
    x = tfm.embed_lookup(params["embed"], tok, compute_dtype)[:, None, :]
    gate = active[:, None, None, None]
    wpos = pos % max_len if windowed else pos

    def write(c, new):
        """c [B,max_len,H,Dh] ← new [B,1,H,Dh] at per-slot pos, if active."""
        written = jax.vmap(
            lambda cb, nb, p: jax.lax.dynamic_update_slice(cb, nb, (p, 0, 0))
        )(c, new.astype(c.dtype), wpos)
        return jnp.where(gate, written, c)

    def write_scale(sc, new):
        """sc [B,max_len,H] ← new [B,1,H] at per-slot pos, if active."""
        written = jax.vmap(
            lambda sb, nb, p: jax.lax.dynamic_update_slice(sb, nb, (p, 0))
        )(sc, new, wpos)
        return jnp.where(gate[..., 0], written, sc)

    def body(carry, layer):
        x = carry
        if quantized:
            blk, ck8, ksc, cv8, vsc = layer
        else:
            blk, ck, cv = layer
        bsz, _, d = x.shape
        # per-slot positions: block_qkv → rope() take [B,T] (here T=1);
        # k/v come back with KV ≤ H heads (GQA) matching the cache
        q, k, v = tfm.block_qkv(x, blk, n_heads, pos[:, None])
        if quantized:
            k8, ks = quantize_kv(k)
            v8, vs = quantize_kv(v)
            ck8 = write(ck8, k8)
            ksc = write_scale(ksc, ks)
            cv8 = write(cv8, v8)
            vsc = write_scale(vsc, vs)
            out_layer = (ck8, ksc, cv8, vsc)
            if attn_fn is None:
                ck = dequantize_kv(ck8, ksc)
                cv = dequantize_kv(cv8, vsc)
        else:
            ck = write(ck, k)
            cv = write(cv, v)
            out_layer = (ck, cv)
        if attn_fn is not None:
            if quantized:
                o = attn_fn(q, (ck8, ksc), (cv8, vsc), pos)
            else:
                o = attn_fn(q, ck, cv, pos)  # [B,1,H,Dh] f32
        else:
            # liveness mask [B, max_len]: the ≤pos prefix — which
            # saturates to all-live past a ring wrap (windowed), exactly
            # the last-W-tokens semantics
            mask = jnp.arange(max_len)[None, :] <= pos[:, None]
            o = tfm.cache_attention(q, ck, cv, mask[:, None, :])
        o = o.astype(x.dtype).reshape(bsz, 1, -1)
        x = x + o @ tfm.wt(blk["wo"], x.dtype)
        x = tfm.block_ffn(x, blk)
        return x, out_layer

    if quantized:
        (ck8, ksc), (cv8, vsc) = cache
        xs = (params["blocks"], ck8, ksc, cv8, vsc)
    else:
        xs = (params["blocks"],) + tuple(cache)
    x, out_layers = jax.lax.scan(body, x, xs)
    if quantized:
        ck8, ksc, cv8, vsc = out_layers
        cache_out = ((ck8, ksc), (cv8, vsc))
    else:
        cache_out = out_layers
    x = tfm.rmsnorm(x, params["ln_f"])
    logits = (x @ tfm.wt(params["head"], x.dtype)).astype(jnp.float32)[:, 0]
    return logits, cache_out, pos + active.astype(jnp.int32)


def batched_verify_step(
    params: Dict,
    toks,
    pos,
    active,
    cache: Tuple[jax.Array, jax.Array],
    n_heads: int,
    compute_dtype=jnp.float32,
):
    """Score per-slot k-token candidate chunks in ONE forward — the
    continuous-batching speculation verify (models/speculative.py's
    _verify generalized to per-slot positions, the same way
    batched_decode_step generalizes decode_step).

    toks [B, k] int32 (row 0 = the slot's pending token, rows 1..k-1 =
    proposals), pos [B] (per-slot fill), active [B] →
    (logits [B, k, V] f32, cache'). Chunk K/V land at per-slot positions
    pos..pos+k-1, gated on ``active``; the caller advances each slot's
    pos by its accepted count — rejected positions are overwritten
    before any mask can reach them (verify_chunk's invariant, held
    per slot). Caller must guarantee pos + k ≤ max_len for every active
    slot (dynamic_update_slice would clamp and corrupt otherwise)."""
    quantized = isinstance(cache[0], tuple)
    max_len = (cache[0][0] if quantized else cache[0]).shape[2]
    b, k = toks.shape
    x = tfm.embed_lookup(params["embed"], toks, compute_dtype)  # [B,k,D]
    positions = pos[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :]
    gate = active[:, None, None, None]

    def write_chunk(c, new):
        """c [B,max_len,H,Dh] ← new [B,k,H,Dh] at per-slot pos."""
        written = jax.vmap(
            lambda cb, nb, p: jax.lax.dynamic_update_slice(cb, nb, (p, 0, 0))
        )(c, new.astype(c.dtype), pos)
        return jnp.where(gate, written, c)

    def write_scale_chunk(sc, new):
        written = jax.vmap(
            lambda sb, nb, p: jax.lax.dynamic_update_slice(sb, nb, (p, 0))
        )(sc, new, pos)
        return jnp.where(gate[..., 0], written, sc)

    # per-slot causal mask over the cache: query i attends ≤ pos_b + i
    mask = (
        jnp.arange(max_len)[None, None, :] <= positions[:, :, None]
    )  # [B, k, max_len]

    def body(carry, layer):
        x = carry
        if quantized:
            blk, ck8, ksc, cv8, vsc = layer
        else:
            blk, ck, cv = layer
        bsz = x.shape[0]
        q, kk, v = tfm.block_qkv(x, blk, n_heads, positions)
        if quantized:
            k8, ks = quantize_kv(kk)
            v8, vs = quantize_kv(v)
            ck8 = write_chunk(ck8, k8)
            ksc = write_scale_chunk(ksc, ks)
            cv8 = write_chunk(cv8, v8)
            vsc = write_scale_chunk(vsc, vs)
            ck = dequantize_kv(ck8, ksc)
            cv = dequantize_kv(cv8, vsc)
            out_layer = (ck8, ksc, cv8, vsc)
        else:
            ck = write_chunk(ck, kk)
            cv = write_chunk(cv, v)
            out_layer = (ck, cv)
        o = tfm.cache_attention(q, ck, cv, mask)
        o = o.astype(x.dtype).reshape(bsz, k, -1)
        x = x + o @ tfm.wt(blk["wo"], x.dtype)
        x = tfm.block_ffn(x, blk)
        return x, out_layer

    if quantized:
        (ck8, ksc), (cv8, vsc) = cache
        xs = (params["blocks"], ck8, ksc, cv8, vsc)
    else:
        xs = (params["blocks"],) + tuple(cache)
    x, out_layers = jax.lax.scan(body, x, xs)
    if quantized:
        ck8, ksc, cv8, vsc = out_layers
        cache_out = ((ck8, ksc), (cv8, vsc))
    else:
        cache_out = out_layers
    x = tfm.rmsnorm(x, params["ln_f"])
    logits = (x @ tfm.wt(params["head"], x.dtype)).astype(jnp.float32)
    return logits, cache_out


def _ring_live_mask(pos, W: int, row):
    """Ring-row liveness for chunk queries on a pre-write W-ring.

    pos [B] absolute fill, row [R] chunk-column indices → [B, R, W]
    bool: ring slot s last held absolute position pos-1-d where
    d = (wp-1-s) mod W (wp = pos % W); it is attendable by the query in
    chunk column r (absolute position pos+r) iff written (d ≤ pos-1)
    and inside the window (d ≤ W-2-r). ONE definition shared by the
    target's verify and the draft's propose — the two masks must never
    drift apart (a divergence only degrades acceptance, silently)."""
    wp = pos % W
    d = (wp[:, None] - 1 - jnp.arange(W, dtype=jnp.int32)[None, :]) % W
    return (
        d[:, None, :]
        <= jnp.minimum(pos[:, None] - 1, W - 2 - row[None, :])[:, :, None]
    )


def batched_windowed_verify(
    params: Dict,
    toks,
    pos,
    active,
    cache,
    n_heads: int,
    compute_dtype=jnp.float32,
):
    """Per-slot k-chunk scoring against a RING cache WITHOUT writing it.

    The windowed sibling of batched_verify_step. In-place chunk writes
    on a ring would clobber live history: column j's row (pos+j) % W
    still holds absolute position pos+j-W, which stays inside the
    attention window of every query before pos+j — so the forward runs
    against the PRE-write ring concatenated with the chunk's own fresh
    K/V (decode.windowed_chunk's formulation, generalized to per-slot
    positions), and returns the chunk K/V for commit_ring_chunk to
    write AFTER acceptance is known (only accepted columns land, so
    rejected proposals never destroy window content).

    toks [B, k], pos [B] (absolute fill), ring cache [L, B, W, KV, Dh]
    (float, or the int8 ((ck8, ksc), (cv8, vsc)) layout) →
    (logits [B, k, V] f32, chunk_ks [L, B, k, KV, Dh],
    chunk_vs [L, B, k, KV, Dh]) — chunk K/V in compute dtype.

    Masking (per slot b, query row i at absolute p = pos_b + i):
    ring row s last held absolute position pos_b - 1 - d where
    d = (wp_b - 1 - s) mod W (wp_b = pos_b % W); it is attendable iff
    written (d ≤ pos_b - 1) and inside the window (d ≤ W - 2 - i).
    Chunk rows are causal (j ≤ i; k ≤ W keeps them all in-window)."""
    quantized = isinstance(cache[0], tuple)
    ring_k = cache[0][0] if quantized else cache[0]
    W = ring_k.shape[2]
    b, k = toks.shape
    x = tfm.embed_lookup(params["embed"], toks, compute_dtype)  # [B,k,D]
    positions = pos[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :]
    row = jnp.arange(k, dtype=jnp.int32)
    ring_mask = _ring_live_mask(pos, W, row)  # [B, k, W]
    chunk_mask = jnp.broadcast_to(
        row[None, None, :] <= row[None, :, None], (b, k, k)
    )
    mask = jnp.concatenate([ring_mask, chunk_mask], axis=2)  # [B, k, W+k]

    def body(carry, layer):
        x = carry
        if quantized:
            blk, ck8, ksc, cv8, vsc = layer
            ck = dequantize_kv(ck8, ksc)
            cv = dequantize_kv(cv8, vsc)
        else:
            blk, ck, cv = layer
        q, kk, v = tfm.block_qkv(x, blk, n_heads, positions)
        if quantized:
            # attend the quantize→dequantize roundtrip of the fresh
            # chunk K/V — exactly what a plain int8 step attends after
            # its pre-attention cache write, so greedy spec rounds stay
            # byte-identical to plain int8 stepping (commit re-quantizes
            # the raw K/V, which lands the same int8 payload)
            ka = dequantize_kv(*quantize_kv(kk)).astype(kk.dtype)
            va = dequantize_kv(*quantize_kv(v)).astype(v.dtype)
        else:
            ka, va = kk, v
        o = tfm.cache_attention(
            q,
            jnp.concatenate([ck.astype(kk.dtype), ka], axis=1),
            jnp.concatenate([cv.astype(v.dtype), va], axis=1),
            mask,
        )
        o = o.astype(x.dtype).reshape(b, k, -1)
        x = x + o @ tfm.wt(blk["wo"], x.dtype)
        x = tfm.block_ffn(x, blk)
        return x, (kk, v)

    if quantized:
        (ck8, ksc), (cv8, vsc) = cache
        xs = (params["blocks"], ck8, ksc, cv8, vsc)
    else:
        xs = (params["blocks"],) + tuple(cache)
    x, (chunk_ks, chunk_vs) = jax.lax.scan(body, x, xs)
    x = tfm.rmsnorm(x, params["ln_f"])
    logits = (x @ tfm.wt(params["head"], x.dtype)).astype(jnp.float32)
    return logits, chunk_ks, chunk_vs


def commit_ring_chunk(cache, chunk_ks, chunk_vs, pos, n_commit, active):
    """Write the first ``n_commit[b]`` chunk columns into the ring at
    rows (pos_b + j) % W, gated on ``active`` — the post-acceptance
    commit paired with batched_windowed_verify (only certified columns
    may overwrite window history). Handles the per-column ring wrap
    (unlike the contiguous prefill write, a decode-time chunk may start
    anywhere in the ring). Quantizes when the cache is int8."""
    quantized = isinstance(cache[0], tuple)
    ring_k = cache[0][0] if quantized else cache[0]
    W = ring_k.shape[2]
    k = chunk_ks.shape[2]

    def write_col(c, col, rows, keep):
        """c [L,B,W,...] ← col [L,B,...] at per-slot ring row, gated."""
        cb = jnp.moveaxis(c, 1, 0)  # [B, L, W, ...]
        nb = jnp.moveaxis(col[:, :, None], 1, 0)  # [B, L, 1, ...]
        start = (0,) * (cb.ndim - 2)
        written = jax.vmap(
            lambda cs, ns, r: jax.lax.dynamic_update_slice(
                cs, ns.astype(cs.dtype), (0, r) + start[1:]
            )
        )(cb, nb, rows)
        gate = keep.reshape((-1,) + (1,) * (cb.ndim - 1))
        return jnp.moveaxis(jnp.where(gate, written, cb), 0, 1)

    for j in range(k):
        rows = (pos + j) % W
        keep = active & (j < n_commit)
        kj = chunk_ks[:, :, j]  # [L, B, KV, Dh]
        vj = chunk_vs[:, :, j]
        if quantized:
            (ck8, ksc), (cv8, vsc) = cache
            k8, ks = quantize_kv(kj)
            v8, vs = quantize_kv(vj)
            cache = (
                (write_col(ck8, k8, rows, keep),
                 write_col(ksc, ks, rows, keep)),
                (write_col(cv8, v8, rows, keep),
                 write_col(vsc, vs, rows, keep)),
            )
        else:
            ck, cv = cache
            cache = (
                write_col(ck, kj, rows, keep),
                write_col(cv, vj, rows, keep),
            )
    return cache


def draft_windowed_propose(
    params: Dict,
    tok,
    pos,
    cache,
    n_heads: int,
    k: int,
    compute_dtype=jnp.float32,
):
    """k-1 greedy draft proposals per slot against a RING cache WITHOUT
    writing it — the draft-side sibling of batched_windowed_verify.

    A draft stepping a ring in place would clobber window history with
    K/V of proposals the target then rejects (the same hazard the
    target's verify avoids). So the whole k-step chain runs in one
    program against the PRE-write ring plus the chain's own fresh chunk
    K/V (column j attends ring rows inside position pos+j's window and
    chunk columns ≤ j), accumulating the chunk in a fixed [L, B, k]
    buffer; commit_ring_chunk later lands only the accepted columns.

    tok [B] (pending tokens, chunk column 0), pos [B] absolute fill →
    (props [B, k-1] int32, chunk_ks, chunk_vs [L, B, k, KV, Dh]).
    Inactive slots are NOT gated here — their proposals are garbage the
    caller ignores, and commit_ring_chunk's ``active`` gate keeps their
    writes out of the ring (the draft ring is always float; a quantized
    target cache never makes the draft's quantized)."""
    ring_k = cache[0]
    L = ring_k.shape[0]
    W = ring_k.shape[2]
    b = tok.shape[0]
    kv = ring_k.shape[3]
    hd = ring_k.shape[4]
    chunk_ks = jnp.zeros((L, b, k, kv, hd), compute_dtype)
    chunk_vs = jnp.zeros((L, b, k, kv, hd), compute_dtype)
    toks0 = jnp.zeros((b, k), jnp.int32).at[:, 0].set(tok)

    def step(carry, j):
        cur, cks, cvs, toks = carry
        x = tfm.embed_lookup(params["embed"], cur, compute_dtype)[:, None, :]
        positions = (pos + j)[:, None]
        ring_mask = _ring_live_mask(pos, W, j[None])  # [B, 1, W]
        chunk_mask = (
            jnp.arange(k, dtype=jnp.int32)[None, None, :] <= j
        )  # [1, 1, k] — columns ≤ j (col j written below before attend)
        mask = jnp.concatenate(
            [ring_mask, jnp.broadcast_to(chunk_mask, (b, 1, k))], axis=2
        )

        def body(xc, layer):
            x = xc
            blk, ck, cv, cks_l, cvs_l = layer
            q, kk, v = tfm.block_qkv(x, blk, n_heads, positions)
            cks_l = jax.lax.dynamic_update_slice(
                cks_l, kk.astype(cks_l.dtype), (0, j, 0, 0)
            )
            cvs_l = jax.lax.dynamic_update_slice(
                cvs_l, v.astype(cvs_l.dtype), (0, j, 0, 0)
            )
            o = tfm.cache_attention(
                q,
                jnp.concatenate([ck.astype(cks_l.dtype), cks_l], axis=1),
                jnp.concatenate([cv.astype(cvs_l.dtype), cvs_l], axis=1),
                mask,
            )
            o = o.astype(x.dtype).reshape(b, 1, -1)
            x = x + o @ tfm.wt(blk["wo"], x.dtype)
            x = tfm.block_ffn(x, blk)
            return x, (cks_l, cvs_l)

        xs = (params["blocks"],) + tuple(cache) + (cks, cvs)
        x, (cks, cvs) = jax.lax.scan(body, x, xs)
        x = tfm.rmsnorm(x, params["ln_f"])
        logits = (x @ tfm.wt(params["head"], x.dtype)).astype(jnp.float32)
        nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
        toks = jnp.where(
            (j + 1 < k), toks.at[:, jnp.minimum(j + 1, k - 1)].set(nxt), toks
        )
        return (nxt, cks, cvs, toks), None

    (_, chunk_ks, chunk_vs, toks), _ = jax.lax.scan(
        step, (tok, chunk_ks, chunk_vs, toks0),
        jnp.arange(k, dtype=jnp.int32),
    )
    return toks[:, 1:], chunk_ks, chunk_vs


def spec_accept(logits, toks, temp, topk, topp, keys, pos, sampling: bool):
    """Device-side acceptance for one speculative round.

    logits [B, k, V] (column j conditioned on toks[:, :j+1]), toks
    [B, k] (column 0 = the pending token, columns 1.. = proposals; -1
    marks a no-proposal column), per-slot sampling params, base keys
    [B, 2], pos [B] → (m [B] int32, final [B] int32). ``m`` is the
    count of committed chunk columns (1 + accepted proposals); the
    round emits toks[:, 1:m] then ``final``.

    Greedy slots (temp ≤ 0) accept while the previous column's argmax
    equals the proposal — byte-identical to plain step()s by
    construction. Sampling slots use point-mass rejection sampling
    (Leviathan et al. with a deterministic draft): accept proposal x
    with probability p̃(x) under the SAME filtered distribution
    sample_tokens draws from, else resample from the renormalized
    remainder (p̃ with x removed) — every emitted token is distributed
    exactly as a plain sampling step's, though the stream is keyed
    per (seed, fill, draw) rather than (seed, fill), so it is
    distribution-exact, not byte-identical, to step() output.
    ``sampling`` is a static flag: the greedy-only program compiles
    without the filtering/PRNG work."""
    b, k, v = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, k]
    if not sampling:
        props = toks[:, 1:]  # [B, k-1]
        match = props == greedy[:, :-1]
        # m-1 = length of the accepted prefix of proposals
        acc_len = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
        m = 1 + acc_len.astype(jnp.int32)
        final = jnp.take_along_axis(greedy, (m - 1)[:, None], axis=1)[:, 0]
        return m, final

    is_sampling = temp > 0  # [B] — mixed batches certify per slot
    logits_t = jnp.moveaxis(logits, 1, 0)  # [k, B, V]
    toks_t = toks.T  # [k, B]

    def col(carry, xs):
        m, done, final = carry
        j, lg, prop = xs  # column j ∈ 1..k-1; lg = logits[:, j-1]
        greedy_col = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        valid = prop >= 0
        kj = jax.vmap(jax.random.fold_in)(keys, pos + j)
        k_acc = jax.vmap(jax.random.fold_in)(kj, jnp.ones((b,), jnp.int32))
        k_res = jax.vmap(jax.random.fold_in)(
            kj, jnp.full((b,), 2, jnp.int32)
        )
        filt = _filtered_logits(lg, temp, topk, topp)
        probs = jax.nn.softmax(filt, axis=-1)
        p_prop = jnp.take_along_axis(
            probs, jnp.clip(prop, 0, v - 1)[:, None], axis=-1
        )[:, 0]
        u = jax.vmap(lambda kk: jax.random.uniform(kk, ()))(k_acc)
        acc = jnp.where(is_sampling, u < p_prop, greedy_col == prop) & valid
        # rejection final: residual distribution (p̃ minus the point
        # mass) for a real proposal; a plain p̃ sample for a
        # no-proposal column (that column IS a plain step)
        residual = jnp.where(
            jax.nn.one_hot(jnp.clip(prop, 0, v - 1), v, dtype=bool)
            & valid[:, None],
            -jnp.inf,
            filt,
        )
        resampled = jax.vmap(jax.random.categorical)(
            k_res, residual
        ).astype(jnp.int32)
        final_rej = jnp.where(is_sampling, resampled, greedy_col)
        rejecting = (~done) & (~acc)
        final = jnp.where(rejecting, final_rej, final)
        m = m + ((~done) & acc).astype(jnp.int32)
        done = done | rejecting
        return (m, done, final), None

    init = (
        jnp.ones((b,), jnp.int32),
        jnp.zeros((b,), bool),
        jnp.zeros((b,), jnp.int32),
    )
    (m, done, final), _ = jax.lax.scan(
        col,
        init,
        (jnp.arange(1, k, dtype=jnp.int32), logits_t[:-1], toks_t[1:]),
    )
    # full acceptance: bonus token from the last column at fill pos+k
    kb = jax.vmap(jax.random.fold_in)(keys, pos + k)
    k_bonus = jax.vmap(jax.random.fold_in)(kb, jnp.ones((b,), jnp.int32))
    bonus = sample_tokens(logits[:, k - 1], temp, topk, topp, k_bonus)
    return m, jnp.where(done, final, bonus)


def _filtered_logits(logits, temp, top_k, top_p):
    """Temperature-scaled, top-k/top-p-filtered logits [B, V] — the
    distribution every sampling decision (plain step, speculative
    acceptance, rejection resample) draws from, factored out so the
    speculative path certifies against EXACTLY what sample_tokens would
    have sampled."""
    v = logits.shape[-1]
    scaled = logits / jnp.maximum(temp, 1e-6)[:, None]
    # top-k: threshold at the k-th largest value per row where enabled
    desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(
        desc, jnp.clip(top_k - 1, 0, v - 1)[:, None], axis=-1
    )
    scaled = jnp.where((top_k > 0)[:, None] & (scaled < kth), -jnp.inf, scaled)
    # top-p over the (possibly top-k-truncated) distribution
    probs = jax.nn.softmax(scaled, axis=-1)
    sp = jnp.sort(probs, axis=-1)[:, ::-1]
    csum = jnp.cumsum(sp, axis=-1)
    n_keep = jnp.sum(csum < top_p[:, None], axis=-1) + 1
    cutoff = jnp.take_along_axis(
        sp, jnp.clip(n_keep - 1, 0, v - 1)[:, None], axis=-1
    )
    return jnp.where(
        (top_p < 1.0)[:, None] & (probs < cutoff), -jnp.inf, scaled
    )


def sample_tokens(logits, temp, top_k, top_p, keys):
    """Per-slot token selection INSIDE the step program.

    logits [B, V] f32; temp [B] f32 (≤ 0 → greedy); top_k [B] int32
    (0 → disabled); top_p [B] f32 (1.0 → disabled; the nucleus keeps the
    smallest most-probable set with mass ≥ top_p, boundary token
    included); keys [B, 2] uint32 per-slot PRNG keys → tok [B] int32.
    Everything is branch-free so one compiled program serves any mix of
    greedy and sampling slots — and only [B] token ids ever cross to the
    host, never the [B, V] logits (at a 32k–128k vocab that transfer is
    megabytes per step)."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = _filtered_logits(logits, temp, top_k, top_p)
    sampled = jax.vmap(jax.random.categorical)(keys, scaled).astype(jnp.int32)
    return jnp.where(temp > 0, sampled, greedy)


def insert_slot(cache, ks, vs, slot):
    """Write one prefilled request's K/V [L,1,P,H,Dh] into cache slot
    ``slot`` (quantizing when the cache is int8). Stale positions beyond
    P from a previous occupant are harmless: the decode mask only ever
    covers positions the new occupant has itself written (each step
    writes position ``pos`` before the mask grows to include it)."""

    def put(c, new):
        # [L, B, max_len, H, Dh]; write [L, 1, P, H, Dh] at (0, slot, 0)
        return jax.lax.dynamic_update_slice(
            c, new.astype(c.dtype), (0, slot, 0, 0, 0)
        )

    def put_scale(sc, new):
        # [L, B, max_len, H] ← [L, 1, P, H]
        return jax.lax.dynamic_update_slice(sc, new, (0, slot, 0, 0))

    if isinstance(cache[0], tuple):
        (ck8, ksc), (cv8, vsc) = cache
        k8, kscale = quantize_kv(ks)
        v8, vscale = quantize_kv(vs)
        return (
            (put(ck8, k8), put_scale(ksc, kscale)),
            (put(cv8, v8), put_scale(vsc, vscale)),
        )
    cache_k, cache_v = cache
    return put(cache_k, ks), put(cache_v, vs)


def hist_write_row(hist, row, start, count, wrap: bool = False):
    """Scatter ``row`` [B, K] into the device token history ``hist``
    [B, H] at per-slot ``start`` [B], keeping only the first ``count``
    [B] columns per slot. ``wrap=True`` treats hist as a RING over the
    last H stream positions (token at absolute position a lives at
    a % H) — the windowed batcher's layout, mirroring its KV ring;
    without it, writes past H-1 clamp onto the last cell (unreachable
    on linear batchers, whose submit validates fill+budget ≤ H)."""
    _, H = hist.shape
    K = row.shape[1]
    raw = start[:, None] + jnp.arange(K)[None, :]
    idx = raw % H if wrap else jnp.clip(raw, 0, H - 1)
    keep = jnp.arange(K)[None, :] < count[:, None]

    def one(h, r, ix, kp):
        return h.at[ix].set(jnp.where(kp, r, h[ix]))

    return jax.vmap(one)(hist, row, idx, keep)


def device_ngram_propose(hist, pos, k: int, g: int, wrap: bool = False):
    """Prompt-lookup proposals ON DEVICE — no host round trip.

    The host n-gram path (ngram_lookup over req.tokens) costs two
    device→host reads per round (pos, tok) plus Python mining; on a
    tunnel-attached TPU each read pays the full RTT, so mining must
    happen where the tokens already are. ``hist`` [B, H] int32 is the
    per-slot token history (-1 padded), ``pos`` [B] the pending token's
    index (invariant: hist[pos] == pending token). Finds the most
    recent earlier occurrence of the suffix g-gram ending at ``pos``
    and proposes the k-1 tokens that followed it; -1 sentinels where
    the lookup finds nothing (sentinels can never be accepted —
    spec_accept's found-nothing discipline, serving.py spec_step).
    Role-match: the device form of the prompt-lookup proposer
    (models/speculative.ngram_lookup, vLLM-style self-drafting)."""
    _, H = hist.shape
    idx = jnp.arange(H)

    def one(h, p):
        if wrap:
            # unroll the ring into stream order: after a wrap the last
            # H tokens live at (p-H+1..p) % H; ordering them makes the
            # pending token the last element, so the same linear
            # matcher applies (before a wrap the ring IS linear)
            start = jnp.where(p >= H, (p + 1) % H, 0)
            h = h[(idx + start) % H]
            p = jnp.minimum(p, H - 1)
        ok = jnp.ones((H,), bool)
        for i in range(g):
            shifted = h[jnp.maximum(idx - i, 0)]
            tgt = h[jnp.maximum(p - i, 0)]
            ok &= (shifted == tgt) & (idx - i >= 0) & (p - i >= 0)
            ok &= shifted >= 0  # pad cells never participate
        ok &= idx < p  # the suffix itself is not a match
        j = jnp.max(jnp.where(ok, idx, -1))
        cols = j + 1 + jnp.arange(k - 1)
        valid = (j >= 0) & (cols <= p)  # only mined, known context
        return jnp.where(valid, h[jnp.clip(cols, 0, H - 1)], -1)

    return jax.vmap(one)(hist, pos)


def spec_emit_hist(toks, m, final, active, hist, pos_, windowed: bool):
    """Emitted row [B, k] for one speculative round — the m-1 accepted
    proposals then the correction/bonus token, -1 beyond — recorded into
    the device history so later rounds mine a complete context. ONE
    implementation shared by the slot and paged spec programs (the
    device-side form of spec_step's host commit loop)."""
    kk = toks.shape[1]
    j = jnp.arange(kk)[None, :]
    prop_part = jnp.concatenate(
        [toks[:, 1:], jnp.full((toks.shape[0], 1), -1, jnp.int32)],
        axis=1,
    )
    emit = jnp.where(
        j < (m - 1)[:, None], prop_part,
        jnp.where(j == (m - 1)[:, None], final[:, None], -1),
    )
    emit = jnp.where(active[:, None], emit, -1)
    hist = hist_write_row(hist, emit, pos_ + 1, m, wrap=windowed)
    return emit, hist


@dataclass
class _Request:
    rid: int
    budget: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    stop_token: Optional[int] = None
    key: Optional[np.ndarray] = None  # base PRNG key [2] uint32
    prompt: Optional[np.ndarray] = None  # spec_step's proposal context
    tokens: List[int] = field(default_factory=list)
    done: bool = False
    fill0: int = 0  # cache fill at admission; pos = fill0+len(tokens)-1
    # latency stamps (perf_counter): submit → first token → done; the
    # serving analogue of the pipeline's wall-stamped p50-e2e cell
    t_submit: float = 0.0
    t_first: float = 0.0
    t_done: float = 0.0

    def finished(self) -> bool:
        """Budget exhausted, or the stop token was emitted (which stays
        in the output, like an EOS id in any serving API)."""
        if len(self.tokens) >= self.budget:
            return True
        return bool(self.tokens) and self.tokens[-1] == self.stop_token


@dataclass
class _PendingInsert:
    """A prefilled request waiting for the next step() to splice its K/V
    into the batch cache (submit never touches device state directly, so
    the compiled step runs lock-free)."""

    slot: int
    ks: Optional[jax.Array]
    vs: Optional[jax.Array]
    first_tok: Any  # device int32 scalar (fetched at apply) or int
    fill: int  # cache fill level (= absolute position count)
    req: _Request
    draft_kv: Optional[Tuple[jax.Array, jax.Array]] = None
    hist_row: Optional[np.ndarray] = None  # device n-gram context seed
    blocks: Optional[List[int]] = None  # paged: the slot's block table
    resumed: bool = False  # paged: re-admission after preemption


class _DraftEngine:
    """Batched draft-model proposer for spec_step: ONE small model
    stepping ALL active slots greedily k-1 times per round, with its own
    slot cache mirroring the target's per-slot positions — draft-model
    speculation at serving scale (the single-stream analogue is
    models/speculative.speculative_generate; the acceptance logic is the
    shared spec_accept, since a greedy draft is a point-mass proposer
    exactly like prompt lookup).

    Rollback is positional, like the target's: after a round the caller
    resumes from the target's accepted pos. On a LINEAR cache the draft
    writes while proposing — accepted positions hold its own proposals,
    rejected ones are overwritten before any mask reaches them. On a
    WINDOWED ring that invariant fails (rejected writes would clobber
    live window history), so the draft uses the same verify-then-commit
    discipline as the target: draft_windowed_propose runs the whole
    chain against the pre-write ring plus its own fresh chunk, and
    commit() lands only the accepted columns after the target rules."""

    def __init__(self, params, n_heads, n_slots, max_len, prompt_len,
                 compute_dtype, windowed: bool = False):
        self.params = params
        self.n_heads = n_heads
        self.prompt_len = prompt_len
        self.max_len = max_len
        self.compute_dtype = compute_dtype
        self.windowed = windowed
        L, d = params["blocks"]["ln1"].shape
        hd = d // n_heads
        kv = tfm.n_kv_heads_of(params["blocks"]["wqkv"], d, n_heads)
        self._cache = (
            jnp.zeros((L, n_slots, max_len, kv, hd), compute_dtype),
            jnp.zeros((L, n_slots, max_len, kv, hd), compute_dtype),
        )
        stage_len = (-(-max_len // prompt_len) + 1) * prompt_len
        self._stage_shape = (L, 1, stage_len, kv, hd)
        self._ring_shape = (L, 1, max_len, kv, hd)
        self._advance = jax.jit(
            lambda toks, cpos, cache: dec.verify_chunk(
                params, toks, cpos, cache, n_heads,
                compute_dtype=compute_dtype, return_logits=False,
            )[1],
            donate_argnums=2,
        )
        self._wadvance = jax.jit(
            lambda toks, cpos, n, cache: dec.windowed_chunk(
                params, toks, cpos, n, cache, n_heads,
                compute_dtype=compute_dtype, return_logits=False,
            )[1],
            donate_argnums=3,
        )
        self._insert = jax.jit(insert_slot, donate_argnums=0)
        self._propose_w = jax.jit(
            lambda tok, pos, cache, k: draft_windowed_propose(
                params, tok, pos, cache, n_heads, k,
                compute_dtype=compute_dtype,
            ),
            static_argnames=("k",),
        )
        self._commit_w = jax.jit(commit_ring_chunk, donate_argnums=0)
        self._pending_chunk = None  # windowed: (cks, cvs) awaiting commit

        def step(tok, pos, active, cache):
            logits, cache, pos2 = batched_decode_step(
                params, tok, pos, active, cache, n_heads, compute_dtype,
                windowed=windowed,
            )
            return jnp.argmax(logits, -1).astype(jnp.int32), cache, pos2

        self._step = jax.jit(step, donate_argnums=3)

    def prefill_tokens(self, tokens: np.ndarray):
        """Draft-prefill a request's FULL context (prefix + prompt) in
        prompt_len buckets → (ks, vs) [L, 1, max_len, KV, Dh] ready for
        insert_slot (a W-ring in windowed mode — same shape). No
        logits: the first pending token is the target's, the draft only
        ever continues from certified tokens."""
        P = self.prompt_len
        t = tokens.shape[0]
        if self.windowed:
            ring = (
                jnp.zeros(self._ring_shape, self.compute_dtype),
                jnp.zeros(self._ring_shape, self.compute_dtype),
            )
            cpos = 0
            while cpos < t:
                n = min(P, t - cpos)
                chunk = np.zeros((1, P), np.int32)
                chunk[0, :n] = tokens[cpos : cpos + n]
                ring = self._wadvance(
                    jnp.asarray(chunk), jnp.asarray(cpos, jnp.int32),
                    jnp.asarray(n, jnp.int32), ring,
                )
                cpos += n
            return ring
        stage = (
            jnp.zeros(self._stage_shape, self.compute_dtype),
            jnp.zeros(self._stage_shape, self.compute_dtype),
        )
        cpos = 0
        while cpos < t:
            n = min(P, t - cpos)
            chunk = np.zeros((1, P), np.int32)
            chunk[0, :n] = tokens[cpos : cpos + n]
            stage = self._advance(
                jnp.asarray(chunk), jnp.asarray(cpos, jnp.int32), stage
            )
            cpos += n
        return stage[0][:, :, : self.max_len], stage[1][:, :, : self.max_len]

    def admit(self, slot: int, draft_kv) -> None:
        self._cache = self._insert(self._cache, *draft_kv, slot)

    def commit(self, pos, m, active) -> None:
        """Windowed only: land the accepted columns of the last
        propose()'s chunk into the draft ring (the draft-side half of
        the verify-then-commit discipline)."""
        if self._pending_chunk is None:
            return
        cks, cvs = self._pending_chunk
        self._pending_chunk = None
        self._cache = self._commit_w(self._cache, cks, cvs, pos, m, active)

    def propose(self, tok, pos, active, k: int) -> np.ndarray:
        """k-1 greedy draft proposals per slot [B, k-1] (np).

        Linear cache: k sequential batched steps writing in place (the
        k-th emission is discarded — that step exists for its WRITE: on
        full acceptance the last proposal's K/V must be in the cache at
        pos+k-1 or the next round would attend an unwritten hole, the
        single-stream _draft_k invariant). Windowed ring: one
        draft_windowed_propose program against the pre-write ring; its
        chunk K/V parks in _pending_chunk until commit()."""
        if self.windowed:
            props, cks, cvs = self._propose_w(tok, pos, self._cache, k=k)
            self._pending_chunk = (cks, cvs)
            return np.asarray(props)
        cache = self._cache
        cur, p = tok, pos
        props = []
        for _ in range(k):
            cur, cache, p = self._step(cur, p, active, cache)
            props.append(cur)
        self._cache = cache
        return np.stack([np.asarray(c) for c in props[: k - 1]], axis=1)

    def advance_one(self, tok, pos, active) -> None:
        """Write the pending tokens' K/V into the draft cache WITHOUT
        proposing — the sync path for rounds the target advances by a
        plain step (no chunk room, nothing proposed, or a direct
        step() call on a draft batcher). Skipping it would leave
        permanent holes at the plain-stepped positions: every later
        propose() would condition on garbage K/V there and acceptance
        would silently collapse for the rest of the generation."""
        _, self._cache, _ = self._step(tok, pos, active, self._cache)


class BatcherFailedError(RuntimeError):
    """The batcher's device state is invalid: a step/pump launch raised
    AFTER dispatch, so the donated ``_cache``/``_hist`` (and draft cache)
    buffers were consumed while the attributes still reference them.
    Every later call would hit a cryptic deleted-buffer error; this typed
    error names the original failure instead. Build a new batcher."""


class ContinuousBatcher:
    """Continuous-batching server over a fixed slot batch (greedy by
    default; per-request temperature/top-k/top-p sampling via submit()).

    submit() may be called at any time (thread-safe); step() advances every
    active slot by one token. Finished requests free their slot for the
    next submit — the batch never drains to admit new work.

    Failure semantics: the step/pump programs donate the KV cache, so a
    raise after dispatch poisons the carried state irreversibly. The
    batcher marks itself failed (``_mark_failed``) and every subsequent
    step/pump/submit raises :class:`BatcherFailedError` chained to the
    original exception — mirroring submit()'s slot-release rollback.
    """

    def __init__(
        self,
        params: Dict,
        n_heads: int,
        n_slots: int = 4,
        max_len: int = 256,
        prompt_len: int = 64,
        compute_dtype=jnp.float32,
        attn_impl: str = "xla",
        keep_results: int = 1024,
        cache_dtype: str = "auto",
        mesh=None,
        slots_axis: str = "dp",
        windowed: bool = False,
        draft_params: Optional[Dict] = None,
        draft_n_heads: Optional[int] = None,
        kv_layout: str = "slot",
        block_size: int = 16,
        kv_blocks: Optional[int] = None,
        prefill_chunks: int = 1,
        kv_attn: str = "auto",
    ):
        """``windowed=True`` makes max_len a sliding attention window
        over a ring-buffer cache: generations AND prompts of any length
        run in the fixed [max_len] cache, each token attending the
        previous max_len (Mistral-style sliding-window attention — the
        time-axis sibling of tensor_aggregator's bounded windows).

        The full feature matrix composes: attn_impl="pallas" works with
        cache_dtype="int8" (the kernel takes the scale operands and
        dequantizes in VMEM), with mesh= (the step program is wrapped in
        shard_map over the slot axis, so each device runs the kernel on
        its local slots), and with windowed=True.

        ``draft_params`` plugs a DRAFT MODEL into spec_step: instead of
        prompt-lookup, a small model proposes k-1 tokens per slot per
        round (k-1 cheap batched forwards), verified by the same chunked
        target forward and accepted by the same point-mass logic — the
        serving-scale form of models/speculative.speculative_generate.
        The draft must share the target's vocabulary. Composes with
        windowed rings: the draft proposes against its pre-write ring
        and commits only accepted columns — the same verify-then-commit
        discipline the target uses (see _DraftEngine).

        ``kv_attn`` selects the PAGED decode formulation
        (docs/llm-serving.md): ``"auto"``/``"block"`` attend the block
        arena directly through the block tables and write each decoded
        token in place into its owning block (kv/block_attn.py — no
        gathered view, the default); ``"gather"`` keeps the
        gather→contiguous-view→scatter oracle (kv/gather.py) for
        debugging/parity at the cost of a transient HBM doubling.
        Both are bitwise identical to the slot layout. Paged composes
        with ``attn_impl="pallas"`` via the block-table kernel
        (ops/pallas/paged_attention.py) — block-native only."""
        if prompt_len > max_len:
            raise ValueError("prompt_len must be ≤ max_len")
        if cache_dtype not in ("auto", "int8"):
            raise ValueError(f"unknown cache_dtype {cache_dtype!r}")
        quantized_cache = cache_dtype == "int8"
        if kv_layout not in ("slot", "paged"):
            raise ValueError(f"unknown kv_layout {kv_layout!r}")
        if kv_attn not in ("auto", "block", "gather"):
            raise ValueError(f"unknown kv_attn {kv_attn!r}")
        self._paged = kv_layout == "paged"
        self._kv_attn = ""
        if self._paged:
            # paged KV (nnstreamer_tpu/kv/, docs/llm-serving.md): the
            # cache is a block arena behind per-slot block tables.
            # kv_attn selects the decode formulation: "block" (the
            # "auto" default) attends DIRECTLY against the arena
            # through the block table and writes the decoded token in
            # place into its single owning block (kv/block_attn.py —
            # no contiguous view in either direction); "gather" keeps
            # the gather→slot-step→scatter oracle (kv/gather.py) for
            # debugging/parity. Both are bitwise identical to the slot
            # layout (tests/test_kv_paged.py, tests/test_kv_block_attn
            # .py). The windowed ring, slot-sharded meshes and draft
            # models keep the slot layout for now.
            self._kv_attn = "block" if kv_attn == "auto" else kv_attn
            for flag, why in (
                (windowed, "windowed (ring) caches"),
                (mesh is not None, "mesh-sharded slots"),
                (draft_params is not None, "draft models"),
                (attn_impl not in ("xla", "pallas"),
                 f"attn_impl={attn_impl!r}"),
                (attn_impl == "pallas" and self._kv_attn == "gather",
                 "attn_impl='pallas' with kv_attn='gather' (the paged "
                 "kernel is block-native — drop kv_attn='gather')"),
            ):
                if flag:
                    raise ValueError(
                        f"kv_layout='paged' does not support {why}; "
                        "use the slot layout"
                    )
            block_size = int(block_size)
            if block_size < 1 or max_len % block_size:
                raise ValueError(
                    f"block_size({block_size}) must divide "
                    f"max_len({max_len})"
                )
            if prompt_len % block_size:
                raise ValueError(
                    f"block_size({block_size}) must divide "
                    f"prompt_len({prompt_len}) so staged prefill chunks "
                    "land on block boundaries"
                )
        elif kv_attn != "auto":
            raise ValueError(
                "kv_attn selects the paged decode formulation; the slot "
                "layout has no block table to attend through"
            )
        paged_attn_fn = None
        from nnstreamer_tpu.ops.dispatch import record as _record_dispatch

        if attn_impl == "pallas":
            # registry dtype/env gate (_compat.pallas_ok): a request the
            # kernels can't serve degrades to the XLA step with a logged
            # reason instead of a trace-time error mid-construction
            from nnstreamer_tpu.ops.pallas._compat import pallas_ok

            kernel = (
                "paged_decode_attention" if self._paged
                else "decode_attention"
            )
            ok, _ = pallas_ok(
                kernel, "int8" if quantized_cache else compute_dtype
            )
            if not ok:
                attn_impl = "xla"
        _record_dispatch(
            "serving_attention",
            "pallas" if attn_impl == "pallas" else "xla",
        )
        if attn_impl == "pallas":
            if self._paged:
                # the block-table kernel: attends the arena through the
                # prefetched tables, one block per grid step, no
                # gathered view (ops/pallas/paged_attention.py); the
                # spec verify keeps inline XLA attention exactly like
                # the slot layout's Pallas batchers
                from nnstreamer_tpu.ops.pallas.paged_attention import (
                    make_paged_attention,
                )

                paged_attn_fn = make_paged_attention()
                attn_fn = None
            else:
                from nnstreamer_tpu.ops.pallas.decode_attention import (
                    make_decode_attention,
                )

                attn_fn = make_decode_attention()
        elif attn_impl == "xla":
            attn_fn = None
        else:
            raise ValueError(f"unknown attn_impl {attn_impl!r}")
        self.params = params
        self.n_heads = n_heads
        self.n_slots = n_slots
        self.max_len = max_len
        self.windowed = windowed
        self._attn_impl = attn_impl
        self.prompt_len = prompt_len
        self.compute_dtype = compute_dtype
        self._lock = threading.Lock()       # host/device state
        self._step_lock = threading.Lock()  # serializes device steps
        # set by _mark_failed when a donated-state launch raised after
        # dispatch; read lock-free (GIL-atomic) by _check_failed
        self._failed: Optional[Exception] = None
        self._next_rid = 0
        self._slots: List[Optional[_Request]] = [None] * n_slots
        self._pending: List[_PendingInsert] = []
        # finished requests await pickup here; bounded FIFO so a caller
        # that never collects cannot grow the host heap without limit
        self._done_pool: "OrderedDict[int, _Request]" = OrderedDict()
        self._keep_results = keep_results

        # nns-obs: the SLO histograms + paged-pool gauges emit through
        # the registry resolved ONCE here (the FaultGate discipline)
        from nnstreamer_tpu.obs import metrics as _obs_metrics

        self._obs_reg = _obs_metrics.get()
        from nnstreamer_tpu.kv.sched import SLOLedger

        self._slo = SLOLedger(keep=keep_results, obs_registry=self._obs_reg)

        L, d = params["blocks"]["ln1"].shape
        hd = d // n_heads
        kv = tfm.n_kv_heads_of(params["blocks"]["wqkv"], d, n_heads)
        shape = (L, n_slots, max_len, kv, hd)
        if self._paged:
            from nnstreamer_tpu.kv import block_attn as _kvb
            from nnstreamer_tpu.kv import gather as _kvg
            from nnstreamer_tpu.kv.blocks import BlockPool

            self._kvg = _kvg
            self._kvb = _kvb
            self.block_size = block_size
            self._blocks_per_slot = max_len // block_size
            if kv_blocks is None:
                # no-saving default: enough blocks for every slot at
                # max_len — memory savings come from setting kv_blocks
                # BELOW this (the bench's fixed-HBM-budget cell)
                kv_blocks = n_slots * self._blocks_per_slot
            if kv_blocks < self._blocks_per_slot:
                raise ValueError(
                    f"kv_blocks({kv_blocks}) cannot hold even one "
                    f"max_len request ({self._blocks_per_slot} blocks)"
                )
            self._pool = BlockPool(
                int(kv_blocks), block_size, obs_registry=self._obs_reg
            )
            # self._cache IS the block arena in paged mode: every
            # donated-launch/commit/failure-latch path stays identical
            self._cache = _kvg.init_arena(
                L, int(kv_blocks), block_size, kv, hd, quantized_cache,
                compute_dtype,
            )
            self._tables = np.zeros(
                (n_slots, self._blocks_per_slot), np.int32
            )
            self._n_alloc = np.zeros((n_slots,), np.int32)
            self._tables_dev = jnp.asarray(self._tables)
            self._tables_dirty = False
            self._write_block, self._read_block, self._copy_block = (
                _kvg.make_paged_ops(quantized_cache, compute_dtype)
            )
            # live migration (kv/migrate.py): raw per-leaf block scatter
            # — donated like every other arena mutator, and bypassing
            # the quantize/dequantize in write_block/read_block so an
            # int8 span lands the exact bytes the source held
            self._adopt_scatter = jax.jit(
                lambda leaf, ids, vals: leaf.at[:, ids].set(vals),
                donate_argnums=0,
            )
            self._quantized = quantized_cache
            self._n_migrations_out = 0
            self._n_migrations_in = 0
            self._n_resumes = 0
            self._n_prefill_chunk_programs = 0
            self._prefill_q: deque = deque()
            self._prefill_chunks = max(1, int(prefill_chunks))
            self._prefixes_paged: Dict[int, Tuple[np.ndarray, List[int]]] = {}
        else:
            self._pool = None
            if quantized_cache:
                sshape = shape[:-1]
                self._cache = (
                    (jnp.zeros(shape, jnp.int8),
                     jnp.ones(sshape, jnp.float32)),
                    (jnp.zeros(shape, jnp.int8),
                     jnp.ones(sshape, jnp.float32)),
                )
            else:
                self._cache = (
                    jnp.zeros(shape, compute_dtype),
                    jnp.zeros(shape, compute_dtype),
                )
        self._tok = jnp.zeros((n_slots,), jnp.int32)
        self._pos = jnp.zeros((n_slots,), jnp.int32)
        self._active = np.zeros((n_slots,), bool)
        # per-slot sampling state lives ON DEVICE so the step program
        # samples in place (host sees one token id per slot per step)
        self._temp = jnp.zeros((n_slots,), jnp.float32)
        self._topk = jnp.zeros((n_slots,), jnp.int32)
        self._topp = jnp.ones((n_slots,), jnp.float32)
        self._keys = jnp.zeros((n_slots, 2), jnp.uint32)
        # per-slot token history ON DEVICE (-1 padded): the n-gram
        # mining context for device-side prompt-lookup speculation and
        # the multi-step pumps' running record — tokens never have to
        # come back to the host just to propose continuations
        self._hist = jnp.full((n_slots, max_len), -1, jnp.int32)
        # device-carried pump state: remaining budgets, stop ids and the
        # active mask live ON DEVICE between pumps (the scan already
        # computes their next values — they used to be recomputed and
        # re-shipped from host EVERY pump even when no slot changed).
        # _pump_state_locked() rebuilds + ships them only when the dirty
        # flag says admission/finish/host-stepping touched a slot; a
        # steady pump-only drain performs ZERO host-state H2D transfers
        # (pinned in tests/test_pumps.py beside the no-new-compiles
        # regression test).
        self._budget_dev = jnp.zeros((n_slots,), jnp.int32)
        self._stop_dev = jnp.full((n_slots,), -1, jnp.int32)
        self._active_dev = jnp.zeros((n_slots,), bool)
        self._pump_state_dirty = True
        self._host_state_builds = 0  # regression-test observable

        if mesh is not None:
            # shard the slot axis over the mesh: the batched step runs
            # SPMD with each device decoding its share of the slots (the
            # data-parallel serving layout; params stay replicated, so
            # the only cross-device traffic is the host-driven admit)
            from jax.sharding import NamedSharding, PartitionSpec as P

            from nnstreamer_tpu.parallel.mesh import batch_sharding

            n_mesh = mesh.shape[slots_axis]
            if n_slots % n_mesh:
                raise ValueError(
                    f"n_slots={n_slots} must divide over mesh axis "
                    f"{slots_axis!r} (size {n_mesh})"
                )
            cache_sh = NamedSharding(mesh, P(None, slots_axis))
            vec_sh = batch_sharding(mesh, slots_axis)
            self._vec_sh = vec_sh
            self._cache = jax.tree_util.tree_map(
                lambda c: jax.device_put(c, cache_sh), self._cache
            )
            self._tok = jax.device_put(self._tok, vec_sh)
            self._pos = jax.device_put(self._pos, vec_sh)
            self._temp = jax.device_put(self._temp, vec_sh)
            self._topk = jax.device_put(self._topk, vec_sh)
            self._topp = jax.device_put(self._topp, vec_sh)
            self._keys = jax.device_put(self._keys, vec_sh)
            self._hist = jax.device_put(self._hist, vec_sh)
        else:
            self._vec_sh = None

        self._prefill = jax.jit(
            lambda toks: dec.prefill(
                params, toks, n_heads, prompt_len,
                compute_dtype=compute_dtype,
            )
        )
        # chunked-prefill programs (prompts longer than the bucket): a
        # staging cache padded to a bucket multiple — plus one spare
        # bucket so chunk starts NOT aligned to the bucket (the prefix-
        # caching path) still fit their full-width writes
        self._stage_len = (-(-max_len // prompt_len) + 1) * prompt_len
        self._stage_shape = (L, 1, self._stage_len, kv, hd)
        if self._paged:
            # coalesced admission staging (kv/gather.make_staging_ops):
            # prefix seeding and block landing as ONE program each —
            # the per-block read/write launches used to dominate paged
            # admission latency on short decode budgets
            self._seed_stage, self._land_stage = (
                self._kvg.make_staging_ops(quantized_cache, compute_dtype)
            )
        self._prefill_chunk = jax.jit(
            lambda toks, cpos, cache: dec.verify_chunk(
                params, toks, cpos, cache, n_heads,
                compute_dtype=compute_dtype,
            ),
            donate_argnums=2,
        )
        self._advance_chunk = jax.jit(
            lambda toks, cpos, cache: dec.verify_chunk(
                params, toks, cpos, cache, n_heads,
                compute_dtype=compute_dtype, return_logits=False,
            )[1],
            donate_argnums=2,
        )
        # windowed (ring) chunked-prefill programs: exact sliding-window
        # prefill for prompts of ANY length in the fixed W ring
        self._ring_shape = (L, 1, max_len, kv, hd)
        self._wchunk = jax.jit(
            lambda toks, cpos, n, cache: dec.windowed_chunk(
                params, toks, cpos, n, cache, n_heads,
                compute_dtype=compute_dtype,
            )[:2],
            donate_argnums=3,
        )
        self._wadvance = jax.jit(
            lambda toks, cpos, n, cache: dec.windowed_chunk(
                params, toks, cpos, n, cache, n_heads,
                compute_dtype=compute_dtype, return_logits=False,
            )[1],
            donate_argnums=3,
        )

        def step_impl(sampling):
            def impl(tok, pos, active, cache, hist, temp, topk, topp,
                     keys):
                logits, cache, pos2 = batched_decode_step(
                    params, tok, pos, active, cache, n_heads,
                    compute_dtype, attn_fn=attn_fn, windowed=windowed,
                )
                if sampling:
                    # per-slot key = fold_in(base, fill level): token
                    # streams are deterministic per (seed, position),
                    # independent of batch composition
                    sub = jax.vmap(jax.random.fold_in)(keys, pos2)
                    new = sample_tokens(logits, temp, topk, topp, sub)
                else:
                    new = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                new = jnp.where(active, new, tok)
                hist = hist_write_row(
                    hist, new[:, None], pos2, active.astype(jnp.int32),
                    wrap=windowed,
                )
                return new, cache, pos2, hist

            return impl

        # the cache (and hist) are DONATED into every step-shaped
        # program: the relay/tunnel runtime moves non-aliased outputs at
        # link bandwidth (~ms per MB) while aliased ones update in
        # place, and on any TPU donation halves the cache's HBM
        # footprint — the carried state never has two live copies
        _don = dict(donate_argnums=(3, 4))
        if self._paged and self._kv_attn == "gather":
            # gather oracle (kv_attn="gather"): gather the block arena
            # into the SAME contiguous per-slot view the slot layout
            # carries, run the IDENTICAL step body on it, then scatter
            # only the written token's block back (inactive lanes route
            # to scratch). Pays a transient [L,B,max_len,...] view
            # beside the arena plus the scatter — kept as the
            # debug/parity reference for the block-native default.
            # tables (arg 4) is NOT donated — it is the cached device
            # copy reused across pumps; arena (3) and hist (5) are.
            _kvg = self._kvg

            def paged_step(sampling):
                inner = step_impl(sampling)

                def impl(tok, pos, active, arena, tables, hist, temp,
                         topk, topp, keys):
                    view = _kvg.gather_cache(arena, tables)
                    new, view, pos2, hist = inner(
                        tok, pos, active, view, hist, temp, topk, topp,
                        keys,
                    )
                    arena = _kvg.scatter_window(
                        arena, tables, view, pos, 1, active
                    )
                    return new, arena, pos2, hist

                return impl

            _pgdon = dict(donate_argnums=(3, 5))
            self._step_greedy = jax.jit(paged_step(False), **_pgdon)
            self._step_sampling = jax.jit(paged_step(True), **_pgdon)
        elif self._paged:
            # block-native (kv_attn="block", the "auto" default): the
            # step attends DIRECTLY against the arena through the block
            # table and lands the decoded token's K/V with one width-1
            # in-place block write under donation — zero gather_cache /
            # scatter_window programs on the decode path (pinned by
            # tests/test_kv_block_attn.py), bitwise identical to the
            # gather oracle and hence the slot layout.
            _kvb = self._kvb
            _pg_attn = paged_attn_fn

            def block_step(sampling):
                def impl(tok, pos, active, arena, tables, hist, temp,
                         topk, topp, keys):
                    logits, arena, pos2 = _kvb.batched_decode_step_block(
                        params, tok, pos, active, arena, tables,
                        n_heads, compute_dtype, attn_fn=_pg_attn,
                    )
                    if sampling:
                        sub = jax.vmap(jax.random.fold_in)(keys, pos2)
                        new = sample_tokens(logits, temp, topk, topp, sub)
                    else:
                        new = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    new = jnp.where(active, new, tok)
                    hist = hist_write_row(
                        hist, new[:, None], pos2, active.astype(jnp.int32)
                    )
                    return new, arena, pos2, hist

                return impl

            _pgdon = dict(donate_argnums=(3, 5))
            self._step_greedy = jax.jit(block_step(False), **_pgdon)
            self._step_sampling = jax.jit(block_step(True), **_pgdon)
        elif mesh is not None and attn_impl == "pallas":
            # GSPMD cannot partition the kernel's custom call over the
            # slot-sharded cache — but the step is slot-parallel by
            # construction, so shard_map IS the partition: each device
            # runs the whole step (kernel included) on its local slots
            from jax.sharding import PartitionSpec as P

            ax = slots_axis
            vec, cac = P(ax), P(None, ax)
            specs = dict(
                in_specs=(vec, vec, vec, cac, vec, vec, vec, vec, vec),
                out_specs=(vec, cac, vec, vec),
                check_vma=False,
            )
            self._step_greedy = jax.jit(
                _shard_map(step_impl(False), mesh=mesh, **specs), **_don
            )
            self._step_sampling = jax.jit(
                _shard_map(step_impl(True), mesh=mesh, **specs), **_don
            )
        else:
            self._step_greedy = jax.jit(step_impl(False), **_don)
            self._step_sampling = jax.jit(step_impl(True), **_don)

        # ---- multi-step pumps: N tokens per program launch ----
        # One dispatch + ONE [B, n] readback per pump instead of a
        # dispatch + readback per token: lax.scan carries
        # (tok, pos, active, cache, hist, budget) on device, deactivates
        # slots at budget/stop-token inside the scan, and emits -1 for
        # idle lanes. On a tunnel-attached chip this amortizes the
        # host↔device RTT over n tokens; on any chip it removes n-1
        # dispatches. Role-match: the per-token step loop of a serving
        # engine collapsed into the compiled program, the token-world
        # analogue of the converter's frames-per-tensor batching.
        def pump_impl(sampling, with_draft):
            def impl(tok, pos, active, cache, hist, budget, stop,
                     temp, topk, topp, keys, dcache, n_steps):
                def body(carry, _):
                    tok, pos, active, cache, hist, budget, dcache = carry
                    if with_draft:
                        # mirror advance_one: the draft ingests the
                        # pending token's K/V in lockstep so later
                        # spec rounds condition on a hole-free cache
                        _, dcache, _ = batched_decode_step(
                            draft_params, tok, pos, active, dcache,
                            draft_n_heads or n_heads, compute_dtype,
                            windowed=windowed,
                        )
                    logits, cache, pos2 = batched_decode_step(
                        params, tok, pos, active, cache, n_heads,
                        compute_dtype, attn_fn=attn_fn, windowed=windowed,
                    )
                    if sampling:
                        sub = jax.vmap(jax.random.fold_in)(keys, pos2)
                        new = sample_tokens(logits, temp, topk, topp, sub)
                    else:
                        new = jnp.argmax(logits, -1).astype(jnp.int32)
                    new = jnp.where(active, new, tok)
                    emit = jnp.where(active, new, -1)
                    hist = hist_write_row(
                        hist, new[:, None], pos2, active.astype(jnp.int32),
                        wrap=windowed,
                    )
                    budget = budget - active.astype(jnp.int32)
                    active = active & (budget > 0) & ~(
                        (new == stop) & (stop >= 0)
                    )
                    return (
                        new, pos2, active, cache, hist, budget, dcache,
                    ), emit

                carry, emits = jax.lax.scan(
                    body, (tok, pos, active, cache, hist, budget, dcache),
                    None, length=n_steps,
                )
                tok, pos, active, cache, hist, budget, dcache = carry
                # budget rides back out so the host can carry it on
                # device across pumps instead of re-shipping host state
                return emits.T, tok, pos, active, cache, hist, budget, dcache

            return impl

        _pdon = dict(
            donate_argnums=(3, 4, 11), static_argnames=("n_steps",)
        )
        _wd = draft_params is not None
        if self._paged:
            # paged pump: the scan steps through the (static-within-a-
            # pump) block table; budget/stop/active are the device-
            # carried pump state like everywhere else. kv_attn="gather"
            # gathers/scatters per step (the oracle); the block-native
            # default reads the arena through the table and writes the
            # token's block in place — a steady pump dispatches ZERO
            # gather/scatter programs.
            _kvg = self._kvg
            _kvb = self._kvb
            _pg_attn = paged_attn_fn
            _gather_pump = self._kv_attn == "gather"

            def paged_pump_impl(sampling):
                def impl(tok, pos, active, arena, tables, hist, budget,
                         stop, temp, topk, topp, keys, n_steps):
                    def body(carry, _):
                        tok, pos, active, arena, hist, budget = carry
                        if _gather_pump:
                            view = _kvg.gather_cache(arena, tables)
                            logits, view, pos2 = batched_decode_step(
                                params, tok, pos, active, view, n_heads,
                                compute_dtype, attn_fn=attn_fn,
                            )
                        else:
                            logits, arena, pos2 = (
                                _kvb.batched_decode_step_block(
                                    params, tok, pos, active, arena,
                                    tables, n_heads, compute_dtype,
                                    attn_fn=_pg_attn,
                                )
                            )
                        if sampling:
                            sub = jax.vmap(jax.random.fold_in)(keys, pos2)
                            new = sample_tokens(
                                logits, temp, topk, topp, sub
                            )
                        else:
                            new = jnp.argmax(logits, -1).astype(jnp.int32)
                        new = jnp.where(active, new, tok)
                        emit = jnp.where(active, new, -1)
                        if _gather_pump:
                            arena = _kvg.scatter_window(
                                arena, tables, view, pos, 1, active
                            )
                        hist = hist_write_row(
                            hist, new[:, None], pos2,
                            active.astype(jnp.int32),
                        )
                        budget = budget - active.astype(jnp.int32)
                        active = active & (budget > 0) & ~(
                            (new == stop) & (stop >= 0)
                        )
                        return (
                            new, pos2, active, arena, hist, budget,
                        ), emit

                    carry, emits = jax.lax.scan(
                        body, (tok, pos, active, arena, hist, budget),
                        None, length=n_steps,
                    )
                    tok, pos, active, arena, hist, budget = carry
                    return emits.T, tok, pos, active, arena, hist, budget

                return impl

            _ppdon = dict(
                donate_argnums=(3, 5), static_argnames=("n_steps",)
            )
            self._pump_greedy = jax.jit(paged_pump_impl(False), **_ppdon)
            self._pump_sampling = jax.jit(paged_pump_impl(True), **_ppdon)
        elif mesh is not None and attn_impl == "pallas":
            # same shard_map partition as the single step: the scan is
            # slot-parallel, each device pumps its local slots with the
            # kernel inline
            import functools as _ft

            from jax.sharding import PartitionSpec as P

            ax = slots_axis
            vec, cac = P(ax), P(None, ax)
            pspecs = dict(
                in_specs=(vec, vec, vec, cac, vec, vec, vec, vec, vec,
                          vec, vec, cac),
                out_specs=(vec, vec, vec, vec, cac, vec, vec, cac),
                check_vma=False,
            )

            def _pump_sm(f):
                def g(tok, pos, active, cache, hist, budget, stop, temp,
                      topk, topp, keys, dcache, n_steps):
                    return _shard_map(
                        _ft.partial(f, n_steps=n_steps), mesh=mesh,
                        **pspecs,
                    )(tok, pos, active, cache, hist, budget, stop, temp,
                      topk, topp, keys, dcache)

                return g

            self._pump_greedy = jax.jit(
                _pump_sm(pump_impl(False, _wd)), **_pdon
            )
            self._pump_sampling = jax.jit(
                _pump_sm(pump_impl(True, _wd)), **_pdon
            )
        else:
            self._pump_greedy = jax.jit(pump_impl(False, _wd), **_pdon)
            self._pump_sampling = jax.jit(pump_impl(True, _wd), **_pdon)
        # first-token pick: same device sampler over the prefill logits
        self._sample1 = jax.jit(
            lambda logits, temp, topk, topp, key: sample_tokens(
                logits[None, :], temp, topk, topp, key[None]
            )[0]
        )
        self._insert = jax.jit(insert_slot, donate_argnums=0)

        # one speculative round = verify + device-side acceptance (+ ring
        # commit of accepted columns when windowed) in ONE program; jit
        # caches one program per distinct chunk width. Only [B] m-counts
        # and [B] final tokens cross to the host — never [B, k, V]
        # logits (sampling acceptance needs the full distributions,
        # which at a 32k+ vocab must not ship per round).
        def spec_round_core(toks, pos_, active, cache, hist, temp, topk,
                            topp, keys, spec_sampling):
            if windowed:
                logits, cks, cvs = batched_windowed_verify(
                    params, toks, pos_, active, cache, n_heads,
                    compute_dtype,
                )
            else:
                logits, cache = batched_verify_step(
                    params, toks, pos_, active, cache, n_heads,
                    compute_dtype,
                )
            m, final = spec_accept(
                logits, toks, temp, topk, topp, keys, pos_, spec_sampling
            )
            m = jnp.where(active, m, 0)
            if windowed:
                cache = commit_ring_chunk(cache, cks, cvs, pos_, m, active)
            emit, hist = spec_emit_hist(
                toks, m, final, active, hist, pos_, windowed
            )
            return m, final, cache, hist, pos_ + m, emit

        def spec_round_impl(spec_sampling):
            def impl(toks, pos_, active, cache, hist, temp, topk, topp,
                     keys):
                m, final, cache, hist, pos2, _ = spec_round_core(
                    toks, pos_, active, cache, hist, temp, topk, topp,
                    keys, spec_sampling,
                )
                return m, final, cache, hist, pos2

            return impl

        self._spec_round_greedy = jax.jit(spec_round_impl(False), **_don)
        self._spec_round_sampling = jax.jit(spec_round_impl(True), **_don)

        # ---- speculative pump: R spec rounds per program launch ----
        # The host spec_step pays two device reads (pos, tok) plus
        # Python n-gram mining per round; this scans R whole
        # propose→verify→accept→commit rounds on device (proposals from
        # device_ngram_propose, or an in-scan draft model stepping k
        # times like _DraftEngine.propose) and ships ONE packed int32
        # vector back: [B·R·k emitted tokens ‖ accepted-count ‖
        # proposal-columns]. Acceptance telemetry therefore costs no
        # extra transfer.
        def spec_pump_impl(spec_sampling, use_draft):
            def impl(tok, pos, active, cache, hist, budget, stop, temp,
                     topk, topp, keys, dcache, rounds, k, g):
                def body(carry, _):
                    (tok, pos, active, cache, hist, budget, dcache,
                     acc, cols) = carry
                    if use_draft:
                        # k greedy draft steps: k-1 proposals + the
                        # k-th write (full-acceptance K/V invariant,
                        # _DraftEngine.propose)
                        cur, p, dc = tok, pos, dcache
                        outs = []
                        for _ in range(k):
                            dlg, dc, p = batched_decode_step(
                                draft_params, cur, p, active, dc,
                                draft_n_heads or n_heads, compute_dtype,
                            )
                            cur = jnp.argmax(dlg, -1).astype(jnp.int32)
                            outs.append(cur)
                        props = jnp.stack(outs[: k - 1], axis=1)
                        dcache = dc
                    else:
                        props = device_ngram_propose(
                            hist, pos, k, g, wrap=windowed
                        )
                    props = jnp.where(active[:, None], props, -1)
                    toks = jnp.concatenate([tok[:, None], props], axis=1)
                    m, final, cache, hist, pos2, emit = spec_round_core(
                        toks, pos, active, cache, hist, temp, topk,
                        topp, keys, spec_sampling,
                    )
                    acc = acc + jnp.sum(jnp.maximum(m - 1, 0))
                    cols = cols + jnp.sum((props >= 0).astype(jnp.int32))
                    budget = budget - m
                    hit_stop = jnp.any(
                        (emit == stop[:, None]) & (stop[:, None] >= 0),
                        axis=1,
                    )
                    active = active & (budget > 0) & ~hit_stop
                    tok = jnp.where(m > 0, final, tok)
                    return (tok, pos2, active, cache, hist, budget,
                            dcache, acc, cols), emit

                zero = jnp.zeros((), jnp.int32)
                (tok, pos, active, cache, hist, budget, dcache, acc,
                 cols), emits = jax.lax.scan(
                    body,
                    (tok, pos, active, cache, hist, budget, dcache,
                     zero, zero),
                    None, length=rounds,
                )
                packed = jnp.concatenate([
                    jnp.transpose(emits, (1, 0, 2)).reshape(-1),
                    jnp.stack([acc, cols]),
                ])
                return packed, tok, pos, active, cache, hist, budget, dcache

            return impl

        _sdon = dict(
            donate_argnums=(3, 4, 11),
            static_argnames=("rounds", "k", "g"),
        )
        _use_draft = draft_params is not None and not windowed
        if self._paged:
            # paged speculative machinery: one verify round (spec_step)
            # and the R-round device pump. The verify chunks ride the
            # SAME formulation as the decode path: block-native reads
            # straight off the arena by default (the k-wide window
            # lands with one in-place multi-column block write), or the
            # gathered-view oracle under kv_attn="gather" — so
            # speculative and prefill-interleaved pumps drop the gather
            # with everything else.

            def paged_spec_round(spec_sampling):
                def impl(toks, pos_, active, arena, tables, hist, temp,
                         topk, topp, keys):
                    if _gather_pump:
                        view = _kvg.gather_cache(arena, tables)
                        logits, view = batched_verify_step(
                            params, toks, pos_, active, view, n_heads,
                            compute_dtype,
                        )
                    else:
                        logits, arena = _kvb.batched_verify_step_block(
                            params, toks, pos_, active, arena, tables,
                            n_heads, compute_dtype,
                        )
                    m, final = spec_accept(
                        logits, toks, temp, topk, topp, keys, pos_,
                        spec_sampling,
                    )
                    m = jnp.where(active, m, 0)
                    if _gather_pump:
                        arena = _kvg.scatter_window(
                            arena, tables, view, pos_, toks.shape[1],
                            active,
                        )
                    _, hist = spec_emit_hist(
                        toks, m, final, active, hist, pos_, False
                    )
                    return m, final, arena, hist, pos_ + m

                return impl

            # overwrite the slot-layout rounds (jit is lazy, nothing
            # was compiled): spec_step builds layout-matched args
            _pgdon = dict(donate_argnums=(3, 5))
            self._spec_round_greedy = jax.jit(
                paged_spec_round(False), **_pgdon
            )
            self._spec_round_sampling = jax.jit(
                paged_spec_round(True), **_pgdon
            )

            def paged_spec_pump_impl(spec_sampling):
                def impl(tok, pos, active, arena, tables, hist, budget,
                         stop, temp, topk, topp, keys, rounds, k, g):
                    def body(carry, _):
                        (tok, pos, active, arena, hist, budget, acc,
                         cols) = carry
                        props = device_ngram_propose(hist, pos, k, g)
                        props = jnp.where(active[:, None], props, -1)
                        toks = jnp.concatenate(
                            [tok[:, None], props], axis=1
                        )
                        if _gather_pump:
                            view = _kvg.gather_cache(arena, tables)
                            logits, view = batched_verify_step(
                                params, toks, pos, active, view,
                                n_heads, compute_dtype,
                            )
                        else:
                            logits, arena = (
                                _kvb.batched_verify_step_block(
                                    params, toks, pos, active, arena,
                                    tables, n_heads, compute_dtype,
                                )
                            )
                        m, final = spec_accept(
                            logits, toks, temp, topk, topp, keys, pos,
                            spec_sampling,
                        )
                        m = jnp.where(active, m, 0)
                        if _gather_pump:
                            arena = _kvg.scatter_window(
                                arena, tables, view, pos, k, active
                            )
                        emit, hist = spec_emit_hist(
                            toks, m, final, active, hist, pos, False
                        )
                        acc = acc + jnp.sum(jnp.maximum(m - 1, 0))
                        cols = cols + jnp.sum(
                            (props >= 0).astype(jnp.int32)
                        )
                        budget = budget - m
                        hit_stop = jnp.any(
                            (emit == stop[:, None]) & (stop[:, None] >= 0),
                            axis=1,
                        )
                        active = active & (budget > 0) & ~hit_stop
                        tok = jnp.where(m > 0, final, tok)
                        return (tok, pos + m, active, arena, hist,
                                budget, acc, cols), emit

                    zero = jnp.zeros((), jnp.int32)
                    (tok, pos, active, arena, hist, budget, acc,
                     cols), emits = jax.lax.scan(
                        body,
                        (tok, pos, active, arena, hist, budget, zero,
                         zero),
                        None, length=rounds,
                    )
                    packed = jnp.concatenate([
                        jnp.transpose(emits, (1, 0, 2)).reshape(-1),
                        jnp.stack([acc, cols]),
                    ])
                    return packed, tok, pos, active, arena, hist, budget

                return impl

            _psdon = dict(
                donate_argnums=(3, 5),
                static_argnames=("rounds", "k", "g"),
            )
            self._spec_pump_greedy = jax.jit(
                paged_spec_pump_impl(False), **_psdon
            )
            self._spec_pump_sampling = jax.jit(
                paged_spec_pump_impl(True), **_psdon
            )
        else:
            self._spec_pump_greedy = jax.jit(
                spec_pump_impl(False, _use_draft), **_sdon
            )
            self._spec_pump_sampling = jax.jit(
                spec_pump_impl(True, _use_draft), **_sdon
            )
        self._draft = (
            _DraftEngine(
                draft_params, draft_n_heads or n_heads, n_slots, max_len,
                prompt_len, compute_dtype, windowed=windowed,
            )
            if draft_params is not None else None
        )
        self._load_prefix = jax.jit(
            lambda stage, ks, vs: (
                jax.lax.dynamic_update_slice(stage[0], ks, (0, 0, 0, 0, 0)),
                jax.lax.dynamic_update_slice(stage[1], vs, (0, 0, 0, 0, 0)),
            ),
            donate_argnums=0,
        )
        # registered shared prefixes:
        # id → ((ck, cv) trimmed to plen, plen, prefix tokens)
        self._prefixes: Dict[
            int, Tuple[Tuple[jax.Array, jax.Array], int, np.ndarray]
        ] = {}
        self._next_prefix = 0
        self._n_steps = 0
        self._n_tokens = 0
        self._n_spec_rounds = 0
        self._n_spec_accepted = 0
        self._n_spec_columns = 0  # proposal columns offered (normalizer)
        # step/pump/spec launches that ran the gather/scatter oracle
        # (kv_attn="gather") instead of the block-native formulation —
        # 0 forever on a block-native batcher (the zero-gather pin in
        # tests/test_kv_block_attn.py); mirrored to the
        # nns_kv_gather_dispatch_total obs counter
        self._n_gather_dispatch = 0
        self._step_time_s = 0.0
        # bounded per-request latency windows (newest 1024): TTFT and
        # full request wall time — stats() reports their p50s
        self._lat_ttft: deque = deque(maxlen=1024)
        self._lat_req: deque = deque(maxlen=1024)
        self._lat_version = 0       # bumped per finished request
        self._lat_cache = (-1, 0.0, 0.0)  # (version, p50_ttft_ms, p50_req_s)

    def _empty_stage(self):
        return (
            jnp.zeros(self._stage_shape, self.compute_dtype),
            jnp.zeros(self._stage_shape, self.compute_dtype),
        )

    def _chunk_step(self, tokens, pos: int, stage, want_logits: bool):
        """ONE prompt_len bucket of chunked prefill at absolute ``pos``.
        Every copy of the chunked-prefill invariant (full-width pad
        writes overwritten before masked; verify_chunk's absolute pos;
        the vocab-head projection only when logits are wanted) lives
        HERE — the slot layout's synchronous _stage_chunks and the
        paged incremental job path (_prefill_chunk_one) both drive it.
        Returns (logits or None, advanced stage, tokens consumed)."""
        P = self.prompt_len
        n = min(P, int(tokens.shape[0]))
        chunk = np.zeros((1, P), np.int32)
        chunk[0, :n] = tokens[:n]
        args = (jnp.asarray(chunk), jnp.asarray(pos, jnp.int32), stage)
        if want_logits:
            logits, stage, _ = self._prefill_chunk(*args)
            return logits, stage, n
        return None, self._advance_chunk(*args), n

    def _stage_chunks(self, tokens, base: int, stage, want_logits: bool):
        """Advance a staging cache with ``tokens`` written at absolute
        positions base..base+t-1, one _chunk_step bucket at a time.
        Returns (final chunk's logits or None, advanced stage)."""
        t = tokens.shape[0]
        cpos = 0
        logits = None
        while cpos < t:
            final = cpos + self.prompt_len >= t
            logits, stage, n = self._chunk_step(
                tokens[cpos:], base + cpos, stage, want_logits and final
            )
            cpos += n
        return logits, stage

    def _stage_ring(self, tokens, base: int = 0, ring=None,
                    want_logits: bool = True):
        """Windowed chunked prefill: advance a W-ring with ``tokens``
        written at absolute positions base..base+t-1, one bucket per
        windowed_chunk call (exact sliding-window attention —
        decode.windowed_chunk). ``ring`` seeds the cache (a registered
        prefix's ring; fresh zeros when None); ``base`` must be a bucket
        multiple (enforced by register_prefix, whose prefix lengths are
        the only nonzero bases) so chunks never wrap mid-write. Returns
        (final chunk's logits or None, ring (ks, vs), last-row index)."""
        # submit()/register_prefix enforce max_len % P == 0 before any
        # chunking reaches here (bucket-sized prefixless prompts never
        # chunk, so unaligned windowed configs stay valid for them)
        P = self.prompt_len
        if ring is None:
            ring = (
                jnp.zeros(self._ring_shape, self.compute_dtype),
                jnp.zeros(self._ring_shape, self.compute_dtype),
            )
        else:
            # the chunk programs DONATE their ring argument — a caller's
            # ring (a registered prefix) must survive this staging run,
            # so advance a fresh copy, never the stored buffers
            ring = (ring[0] + 0, ring[1] + 0)
        t = tokens.shape[0]
        cpos = 0
        logits = None
        while cpos < t:
            n = min(P, t - cpos)
            chunk = np.zeros((1, P), np.int32)
            chunk[0, :n] = tokens[cpos : cpos + n]
            args = (
                jnp.asarray(chunk), jnp.asarray(base + cpos, jnp.int32),
                jnp.asarray(n, jnp.int32), ring,
            )
            if want_logits and cpos + n >= t:
                logits, ring = self._wchunk(*args)
            else:
                ring = self._wadvance(*args)
            cpos += n
        return logits, ring, (t - 1) % P  # last real row of the final chunk

    def register_prefix(self, tokens) -> int:
        """Prefill a shared prompt prefix (e.g. a system prompt) ONCE and
        return its id; submit(prefix=id) starts from its K/V instead of
        re-prefilling it per request — the admission cost of the shared
        part is paid one time. Release with unregister_prefix when no
        longer needed.

        Unwindowed caches store the staged K/V trimmed to the prefix
        length. Windowed caches store the prefix's RING: a prefix always
        starts at absolute position 0, so its ring placement is the same
        for every request — the one alignment requirement is that the
        prefix length be a bucket (prompt_len) multiple, so the
        per-request continuation chunks stay bucket-aligned and never
        wrap the ring mid-write (a windowed prefix may even EXCEED
        max_len: the ring then holds its last W tokens, exactly
        sliding-window semantics)."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        plen = tokens.shape[0]
        if self._paged:
            # paged: prefill ONCE into pool blocks, register them in the
            # prefix index, and PIN them (the registration holds one
            # reference until unregister) — later submits hit the index
            # whether or not they pass prefix=; the stored tokens are
            # prepended for prefix= callers so matching sees one stream
            if not (0 < plen < self.max_len):
                raise ValueError(
                    f"prefix length {plen} not in (0, max_len="
                    f"{self.max_len})"
                )
            # _step_lock: the block writes below donate self._cache —
            # they must serialize with in-flight step/pump launches
            # that donate the same arena (submit() stays lock-free
            # because its writes ride the pending queue; registration
            # is setup-time, so the serialization is fine)
            with self._step_lock:
                _, stage = self._stage_chunks(
                    tokens, 0, self._empty_stage(), False
                )
                bs = self.block_size
                n_blocks = -(-plen // bs)
                with self._lock:
                    blocks = self._pool.alloc(n_blocks)
                ids = np.zeros((self._stage_len // bs,), np.int32)
                valid = np.zeros((self._stage_len // bs,), bool)
                ids[: n_blocks] = blocks
                valid[: n_blocks] = True
                self._cache = self._land_stage(
                    self._cache, stage, jnp.asarray(ids),
                    jnp.asarray(valid),
                )
                with self._lock:
                    self._pool.register(tokens, blocks)
                    pid = self._next_prefix
                    self._next_prefix += 1
                    self._prefixes_paged[pid] = (tokens, blocks)
            return pid
        if self.windowed:
            P = self.prompt_len
            if plen <= 0 or plen % P:
                raise ValueError(
                    f"windowed prefix length {plen} must be a positive "
                    f"multiple of prompt_len({P}) so per-request "
                    "continuation chunks stay bucket-aligned"
                )
            if self.max_len % P:
                raise ValueError(
                    f"windowed prefix caching needs max_len"
                    f"({self.max_len}) to be a multiple of "
                    f"prompt_len({P})"
                )
            _, ring, _ = self._stage_ring(tokens, 0, None, False)
            stored = ring
        else:
            if not (0 < plen < self.max_len):
                raise ValueError(
                    f"prefix length {plen} not in (0, max_len={self.max_len})"
                )
            _, stage = self._stage_chunks(
                tokens, 0, self._empty_stage(), False
            )
            stored = (stage[0][:, :, :plen], stage[1][:, :, :plen])
        with self._lock:
            pid = self._next_prefix
            self._next_prefix += 1
            # tokens ride along so spec_step's prompt-lookup context
            # covers the shared prefix too (proposal quality, not
            # correctness — n-gram matches often live in a system prompt)
            self._prefixes[pid] = (stored, plen, tokens)
        return pid

    def unregister_prefix(self, pid: int) -> bool:
        """Release a registered prefix's device memory (in-flight
        requests are unaffected — their slot cache holds a copy; paged
        sharers hold their own block references, and the blocks stay
        adoptable from the pool's cached tier until reclaimed)."""
        with self._lock:
            if self._paged:
                item = self._prefixes_paged.pop(pid, None)
                if item is None:
                    return False
                self._pool.free(item[1])
                return True
            return self._prefixes.pop(pid, None) is not None

    # -- client API --------------------------------------------------------
    def submit(
        self,
        prompt: np.ndarray,
        max_new_tokens: int,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        seed: Optional[int] = None,
        stop_token: Optional[int] = None,
        prefix: Optional[int] = None,
        deadline_s: Optional[float] = None,
    ) -> Optional[int]:
        """Claim a free slot for ``prompt`` [T]; returns a request id, or
        None when the batch is full (caller queues/retries — the
        admission queue is the caller's policy, not the batcher's).
        ``deadline_s`` is SLO accounting only (surfaced by requests() /
        nns-top --requests), never an eviction trigger.

        Paged batchers (``kv_layout="paged"``) admit through the chunked
        prefill queue instead of prefilling here: submit returns
        immediately and the prompt advances one ``prompt_len`` bucket
        per step/pump, interleaved with decode — a long prompt can no
        longer stall decoding slots for whole prefills
        (docs/llm-serving.md).
        Prompts longer than the prompt_len bucket prefill in bucket-sized
        chunks (decode.verify_chunk; decode.windowed_chunk on a ring when
        windowed), so T is bounded by the cache — or by nothing at all
        when windowed (the ring retains the last max_len tokens, exactly
        sliding-window semantics).

        Sampling is per-request: temperature ≤ 0 is greedy; otherwise
        softmax sampling, optionally top-k truncated and/or top-p
        (nucleus) filtered (0 < top_p < 1; the boundary token is kept),
        with a deterministic per-request stream: every token is keyed by
        fold_in(PRNGKey(seed), fill-level), so the stream depends only on
        (seed, position) — never on batch composition."""
        self._check_failed()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        t = prompt.shape[0]
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be ≥ 1, got {max_new_tokens}")
        if t == 0:
            raise ValueError("empty prompt")
        if self._paged:
            return self._submit_paged(
                prompt, max_new_tokens, temperature, top_k, top_p, seed,
                stop_token, prefix, deadline_s,
            )
        plen = 0
        pfx = None
        pfx_tokens = None
        if prefix is not None:
            with self._lock:
                if prefix not in self._prefixes:
                    raise ValueError(f"unknown prefix id {prefix}")
                pfx, plen, pfx_tokens = self._prefixes[prefix]
        if (
            self.windowed
            and (t > self.prompt_len or pfx is not None)
            and self.max_len % self.prompt_len
        ):
            # checked before any slot is claimed: ring chunked prefill
            # (long prompts, and any prefix continuation — it starts at
            # base=plen) needs bucket-aligned chunks (a mid-chunk ring
            # wrap would corrupt live entries). Bucket-sized prefixless
            # prompts never chunk, so unaligned windowed configs stay
            # valid for them.
            raise ValueError(
                f"windowed long prompts need max_len({self.max_len}) to "
                f"be a multiple of prompt_len({self.prompt_len}) so "
                "prefill chunks never wrap the ring mid-chunk"
            )
        if not self.windowed and plen + t > self.max_len:
            raise ValueError(
                f"prefix({plen}) + prompt({t}) > max_len {self.max_len}"
            )
        if not self.windowed and plen + t + max_new_tokens > self.max_len:
            raise ValueError(
                f"{plen}+{t}+{max_new_tokens} tokens would overflow "
                f"max_len={self.max_len} (windowed=True lifts this: the "
                "cache becomes a sliding ring)"
            )
        with self._lock:
            # claim only — the slot is owned (so no other submit takes it)
            # but inactive, so concurrent step() calls skip it while the
            # prefill below runs outside the lock
            try:
                slot = next(
                    i for i, r in enumerate(self._slots) if r is None
                )
            except StopIteration:
                return None
            rid = self._next_rid
            self._next_rid += 1
            req = _Request(
                rid, max_new_tokens, temperature=temperature, top_k=top_k,
                top_p=top_p, stop_token=stop_token,
                t_submit=_time.perf_counter(),
                key=np.asarray(
                    jax.random.PRNGKey(rid if seed is None else seed)
                ),
                # spec_step's proposal context — the prefix's tokens are
                # part of the stream the n-gram lookup should mine
                prompt=(
                    prompt if pfx_tokens is None
                    else np.concatenate([pfx_tokens, prompt])
                ),
            )
            self._slots[slot] = req
            self._slo.submit(rid, deadline_s)

        try:
            P = self.prompt_len
            if pfx is None and t <= P:
                # single-program fast path for bucket-sized prompts
                padded = np.zeros((1, P), np.int32)
                padded[0, :t] = prompt
                logits, (ks, vs), _ = self._prefill(jnp.asarray(padded))
                logits_row = logits[0, t - 1]
            elif self.windowed:
                # ring chunked prefill: exact sliding-window attention
                # for prompts of any length (the ring keeps the last W);
                # a registered prefix seeds the ring and the prompt
                # continues at absolute position plen (a bucket
                # multiple, so chunks stay wrap-free)
                logits, (ks, vs), last = self._stage_ring(
                    prompt, base=plen, ring=pfx
                )
                logits_row = logits[0, last]
            else:
                # chunked prefill (_stage_chunks): the staging cache
                # starts empty or preloaded with the registered prefix
                if pfx is None:
                    stage = self._empty_stage()
                else:
                    stage = self._load_prefix(self._empty_stage(), *pfx)
                logits, stage = self._stage_chunks(prompt, plen, stage, True)
                last = (t - 1) % P  # true last token's index in the chunk
                logits_row = logits[0, last]
                ks = stage[0][:, :, : self.max_len]
                vs = stage[1][:, :, : self.max_len]
            fill = plen + t
            # the first token stays a DEVICE scalar: materializing it
            # here would cost one device→host read per admission on the
            # submit path; _apply_pending fetches every queued
            # admission's first token in ONE packed transfer instead
            first_dev = self._sample1(
                logits_row,
                jnp.asarray([temperature], jnp.float32),
                jnp.asarray([top_k], jnp.int32),
                jnp.asarray([top_p], jnp.float32),
                jax.random.fold_in(jnp.asarray(req.key), fill),
            )
            if max_new_tokens == 1:
                # a one-token request finishes ON its prefill token:
                # fetch it now so the slot frees immediately (nothing
                # to decode — no hist row, no draft prefill either)
                first = int(first_dev)
                with self._lock:
                    req.fill0 = fill
                    req.t_first = _time.perf_counter()
                    req.tokens.append(first)
                    self._finish(slot)
                return rid
            # draft-prefill the full context (req.prompt already carries
            # prefix + prompt) OUTSIDE the state lock, like the target's
            # prefill — admission must never serialize device steps
            draft_kv = (
                self._draft.prefill_tokens(req.prompt)
                if self._draft is not None else None
            )
        except Exception:
            # release the claimed slot or n_slots failed prefills would
            # brick the server with every slot claimed-but-never-active
            with self._lock:
                self._slots[slot] = None
            raise

        # device n-gram context seed: the full known stream (context +
        # first pending token) as one padded row — staged into
        # self._hist at admission with a single static-shape write.
        # Windowed overruns stage the LAST H tokens in ring layout
        # (a % H, mirroring the KV ring) so post-wrap mining stays
        # exact; the non-windowed else is unreachable (submit validates
        # fill + budget ≤ max_len) and exists as a defensive fallback.
        H = self.max_len
        hist_row = np.full((H,), -1, np.int32)
        ctx = req.prompt
        if fill < H:
            hist_row[:fill] = ctx[:fill]
        elif self.windowed:
            # ring layout: token at absolute position a lives at a % H
            # (mirrors the KV ring), so post-wrap mining stays exact
            span = np.arange(fill - H, fill)
            hist_row[span % H] = ctx[span]
        else:
            hist_row[:] = ctx[:H]
        with self._lock:
            req.fill0 = fill
            # token 0 (and any finished-at-first-token bookkeeping, e.g.
            # a stop token landing on it) materializes at the next
            # _apply_pending, where every queued admission's
            # first token rides one packed read — submit() itself never
            # blocks on the device
            self._pending.append(
                _PendingInsert(slot, ks, vs, first_dev, fill, req,
                               draft_kv=draft_kv, hist_row=hist_row)
            )
        return rid

    def _apply_pending(self) -> None:
        """Splice queued admissions into the device state.

        Caller holds _step_lock ONLY. Every queued admission's first
        token (a device scalar from submit's prefill sampler) is
        fetched in ONE packed transfer — the admission-path analogue
        of the pumps' one-readback rule — and that fetch happens
        OUTSIDE self._lock: it may wait on an in-flight chunked
        prefill, and readers (submit/result/partials/stats) must not
        stall behind it."""
        with self._lock:
            batch = self._pending
            self._pending = []
        if not batch:
            return
        firsts = np.asarray(jnp.stack(
            [jnp.asarray(p.first_tok).reshape(()) for p in batch]
        )).reshape(-1)
        with self._lock:
            self._apply_batch_locked(batch, firsts)

    def _apply_batch_locked(self, batch, firsts) -> None:
        now = _time.perf_counter()
        self._pump_state_dirty = True  # admission changes pump state
        for p, first in zip(batch, firsts):
            if self._slots[p.slot] is not p.req:
                continue  # request vanished (defensive; cannot happen)
            first = int(first)
            if p.blocks is not None:
                # paged: point the slot's block table at its blocks
                # BEFORE any finish path so _finish can free them
                row = np.zeros((self._blocks_per_slot,), np.int32)
                row[: len(p.blocks)] = p.blocks
                self._tables[p.slot] = row
                self._n_alloc[p.slot] = len(p.blocks)
                self._tables_dirty = True
            if not p.resumed:
                p.req.t_first = now
                p.req.tokens.append(first)
                self._slo.admitted(p.req.rid)
                self._slo.first_token(p.req.rid)
                if p.req.finished():
                    # budget 1 or an immediate stop token: the request
                    # ends on its prefill token and never occupies the
                    # batch
                    self._finish(p.slot)
                    continue
            else:
                self._slo.admitted(p.req.rid)
            if p.hist_row is not None:
                Hh = p.hist_row.shape[0]
                if p.fill < Hh:
                    p.hist_row[p.fill] = first
                elif self.windowed:
                    p.hist_row[p.fill % Hh] = first
            if p.blocks is None:
                self._cache = self._insert(self._cache, p.ks, p.vs, p.slot)
            self._tok = self._pin(self._tok.at[p.slot].set(first))
            self._pos = self._pin(self._pos.at[p.slot].set(p.fill))
            self._temp = self._pin(
                self._temp.at[p.slot].set(p.req.temperature)
            )
            self._topk = self._pin(self._topk.at[p.slot].set(p.req.top_k))
            self._topp = self._pin(self._topp.at[p.slot].set(p.req.top_p))
            self._keys = self._pin(
                self._keys.at[p.slot].set(jnp.asarray(p.req.key))
            )
            if p.draft_kv is not None and self._draft is not None:
                self._draft.admit(p.slot, p.draft_kv)
            if p.hist_row is not None:
                self._hist = self._pin(
                    self._hist.at[p.slot].set(jnp.asarray(p.hist_row))
                )
            self._active[p.slot] = True

    # -- paged KV: admission, chunked prefill, blocks, preemption ----------
    def _submit_paged(self, prompt, max_new_tokens, temperature, top_k,
                      top_p, seed, stop_token, prefix, deadline_s
                      ) -> Optional[int]:
        """Paged admission: claim a slot, match the prompt against the
        pool's prefix index (adopting shared blocks NOW so they cannot
        be reclaimed while queued), and enqueue a chunked-prefill job.
        No device work happens here — prefill advances one bucket per
        step/pump, interleaved with decode."""
        from nnstreamer_tpu.kv.sched import PrefillJob

        pfx_tokens = None
        if prefix is not None:
            with self._lock:
                if prefix not in self._prefixes_paged:
                    raise ValueError(f"unknown prefix id {prefix}")
                pfx_tokens = self._prefixes_paged[prefix][0]
        context = (
            prompt if pfx_tokens is None
            else np.concatenate([pfx_tokens, prompt]).astype(np.int32)
        )
        t = int(context.shape[0])
        if t + max_new_tokens > self.max_len:
            raise ValueError(
                f"prefix+prompt({t})+{max_new_tokens} tokens would "
                f"overflow max_len={self.max_len}"
            )
        with self._lock:
            try:
                slot = next(
                    i for i, r in enumerate(self._slots) if r is None
                )
            except StopIteration:
                return None
            rid = self._next_rid
            self._next_rid += 1
            req = _Request(
                rid, max_new_tokens, temperature=temperature,
                top_k=top_k, top_p=top_p, stop_token=stop_token,
                t_submit=_time.perf_counter(),
                key=np.asarray(
                    jax.random.PRNGKey(rid if seed is None else seed)
                ),
                prompt=context,
            )
            self._slots[slot] = req
            self._slo.submit(rid, deadline_s)
            # prefix matching happens lazily when the job starts staging
            # (_prefill_chunk_one): adopted blocks stay pinned only for
            # the short staging→activation window, so queued work never
            # starves the pool
            self._prefill_q.append(PrefillJob(slot, req, context))
        return rid

    def _match_and_adopt_locked(self, job, matchable) -> None:
        m = self._pool.match(matchable)
        for b in m.full:
            self._pool.adopt(b)
        if m.partial_block is not None:
            self._pool.adopt(m.partial_block)
        if m.n_tokens:
            self._pool.record_hit_tokens(m.n_tokens)
        job.matched_full = list(m.full)
        job.matched_partial = m.partial_block
        job.n_partial = m.n_partial
        job.base = m.n_tokens

    def _release_match_locked(self, job) -> None:
        """Drop a job's adopted prefix pins (sharing-degradation path)."""
        self._pool.free(job.matched_full)
        if job.matched_partial is not None:
            self._pool.free([job.matched_partial])
        job.matched_full = []
        job.matched_partial = None
        job.n_partial = 0
        job.base = 0

    def _advance_prefill(self) -> None:
        """Advance the front prefill job by ≤ ``prefill_chunks`` buckets
        and activate it when staged + block-affordable — the chunked-
        prefill interleave: a decoding slot waits at most this many
        chunk programs per pump, whatever someone else's prompt length.

        The throttle exists ONLY to bound decode stalls — while nothing
        is decoding (no active slot, no activation pending), it would
        merely serialize admissions one bucket per pump, so an idle
        decode plane keeps advancing until a job activates or the queue
        drains (the cold-start admission latency fix; the interleave
        bound is unchanged the moment anything is live).
        Caller holds _step_lock; _lock is taken only for bookkeeping."""
        budget = self._prefill_chunks
        while True:
            with self._lock:
                job = self._prefill_q[0] if self._prefill_q else None
                idle = not self._active.any() and not self._pending
            if job is None or (budget <= 0 and not idle):
                return
            self._slo.prefilling(job.req.rid)
            if not job.done_staging():
                self._prefill_chunk_one(job)
                budget -= 1
            if job.done_staging():
                if self._prefill_finalize(job):
                    with self._lock:
                        if self._prefill_q and self._prefill_q[0] is job:
                            self._prefill_q.popleft()
                else:
                    return  # blocks not affordable yet (watermark)

    def _prefill_chunk_one(self, job) -> None:
        """One ``prompt_len`` bucket of chunked prefill for ``job``
        (device work — caller holds _step_lock only)."""
        self._n_prefill_chunk_programs += 1
        P = self.prompt_len
        ctx = job.tokens
        t = job.fill
        if job.stage is None:
            if not job.no_rematch:
                with self._lock:
                    # match context[:-1] for fresh requests: the LAST
                    # token must run through the model even on a full
                    # prefix hit — its logits pick the first generated
                    # token. Resumes (known_first set) may match their
                    # whole context. The sharing-degradation fallback
                    # sets no_rematch: re-adopting the released prefix
                    # here would restore the exact pre-degrade state and
                    # livelock the queue head.
                    self._match_and_adopt_locked(
                        job,
                        ctx if job.known_first is not None else ctx[:-1],
                    )
            if (job.base == 0 and job.matched_partial is None
                    and t <= P and job.known_first is None):
                # bucket-sized fresh prompt: the SAME single fast-path
                # program the slot layout admits through (bitwise parity
                # with contiguous admission)
                padded = np.zeros((1, P), np.int32)
                padded[0, :t] = ctx
                logits, (ks, vs), _ = self._prefill(jnp.asarray(padded))
                job.logits_row = logits[0, t - 1]
                job.stage = (ks, vs)
                job.cpos = t
                return
            stage = self._empty_stage()
            # seed matched prefix K/V into the stage so continuation
            # chunks attend it (fp: bitwise the originally staged
            # values) — all matched blocks in ONE seed_stage launch
            bs = self.block_size
            seeds = list(job.matched_full)
            if job.matched_partial is not None:
                seeds.append(job.matched_partial)
            if seeds:
                ids = np.zeros((self._stage_len // bs,), np.int32)
                ids[: len(seeds)] = seeds
                stage = self._seed_stage(
                    self._cache, stage, jnp.asarray(ids),
                    jnp.asarray(len(seeds), jnp.int32),
                )
            job.stage = stage
        if job.done_staging():
            return
        start = job.base + job.cpos
        final = start + P >= t
        logits, stage, n = self._chunk_step(
            ctx[start:], start, job.stage,
            final and job.known_first is None,
        )
        if logits is not None:
            job.logits_row = logits[0, n - 1]
        job.stage = stage
        job.cpos += n

    def _prefill_finalize(self, job) -> bool:
        """Allocate the job's blocks, land staged K/V, register its
        prefix, and queue the activation. False = not affordable yet
        under the watermark (every live request keeps one decode-growth
        block of headroom), so the job waits — admission can defer but
        never OOM the decode plane."""
        from nnstreamer_tpu.kv.blocks import NoBlocksError

        bs = self.block_size
        t = job.fill
        n_blocks = -(-t // bs)
        n_full = len(job.matched_full)
        fresh_needed = n_blocks - n_full  # includes the CoW copy
        with self._lock:
            n_live = int(self._active.sum())
            if fresh_needed > 0 and (
                self._pool.available() < fresh_needed + n_live
            ):
                if n_live == 0:
                    # nothing is decoding, so waiting cannot help
                    if job.matched_full or job.matched_partial is not None:
                        # give back the adopted prefix pins and restart
                        # staging unshared — degrade sharing to progress
                        self._release_match_locked(job)
                        job.stage = None
                        job.cpos = 0
                        job.no_rematch = True
                        return False
                    raise RuntimeError(
                        "kv pool cannot admit a request with nothing "
                        "decoding: kv_blocks too small for the prompt, "
                        "or registered prefixes pin too much of the pool"
                    )
                return False
            try:
                fresh = (
                    self._pool.alloc(fresh_needed)
                    if fresh_needed > 0 else []
                )
            except NoBlocksError:
                return False
            if job.matched_partial is not None and fresh:
                self._pool.note_cow()  # first fresh block is the copy
        blocks = list(job.matched_full) + fresh
        # land staged K/V into the fresh blocks (adopted full blocks
        # already hold theirs; the CoW block's copied prefix rides the
        # seeded stage, so one write covers copy + continuation) — the
        # whole span in ONE land_stage launch
        if job.stage is not None:
            if n_blocks > n_full:
                # one id slot per stage block — the bucket-wide fast
                # path stage and the full chunked stage each size it
                stage_blocks = job.stage[0].shape[2] // bs
                ids = np.zeros((stage_blocks,), np.int32)
                valid = np.zeros((stage_blocks,), bool)
                for i in range(n_full, n_blocks):
                    ids[i] = blocks[i]
                    valid[i] = True
                self._cache = self._land_stage(
                    self._cache, job.stage, jnp.asarray(ids),
                    jnp.asarray(valid),
                )
        elif job.matched_partial is not None and fresh:
            # fully-matched resume ending in a partial block: pure
            # device-side copy-on-write
            self._cache = self._copy_block(
                self._cache, jnp.asarray(job.matched_partial, jnp.int32),
                jnp.asarray(blocks[n_full], jnp.int32),
            )
        job.stage = None  # release staging memory
        with self._lock:
            if job.matched_partial is not None:
                # the CoW copy replaced the shared partial block
                self._pool.free([job.matched_partial])
            self._pool.register(job.tokens, blocks)
        req = job.req
        if job.known_first is not None:
            first_dev: Any = int(job.known_first)
        else:
            first_dev = self._sample1(
                job.logits_row,
                jnp.asarray([req.temperature], jnp.float32),
                jnp.asarray([req.top_k], jnp.int32),
                jnp.asarray([req.top_p], jnp.float32),
                jax.random.fold_in(jnp.asarray(req.key), t),
            )
        job.logits_row = None
        hist_row = np.full((self.max_len,), -1, np.int32)
        hist_row[:t] = job.tokens[: self.max_len]
        with self._lock:
            if not job.resumed:
                req.fill0 = t
            self._pending.append(
                _PendingInsert(
                    job.slot, None, None, first_dev, t, req,
                    hist_row=hist_row, blocks=blocks,
                    resumed=job.resumed,
                )
            )
        return True

    def _ensure_decode_room_locked(self, n: int) -> None:
        """Watermark decode-growth accounting: every active slot gets
        blocks covering its next ``n`` token writes, preempting the
        youngest other request on exhaustion (its blocks free, shared
        prefix blocks stay cached, and it re-enters the prefill queue
        to resume from whatever prefix still matches) — eviction and
        re-prefill instead of OOM. Caller holds _lock."""
        from nnstreamer_tpu.kv.blocks import NoBlocksError
        from nnstreamer_tpu.kv.sched import choose_victim

        bs = self.block_size
        for s, req in enumerate(self._slots):
            if req is None or not self._active[s]:
                continue
            pos = req.fill0 + len(req.tokens) - 1
            last = min(pos + int(n) - 1, self.max_len - 1)
            need = last // bs + 1
            while self._n_alloc[s] < need:
                try:
                    (b,) = self._pool.alloc(1)
                except NoBlocksError:
                    victim = choose_victim(self._slots, self._active, s)
                    if victim is None:
                        raise RuntimeError(
                            "kv pool exhausted with one active request "
                            "left: kv_blocks cannot cover a single "
                            "stream's growth — raise kv_blocks"
                        ) from None
                    self._preempt_locked(victim)
                    continue
                self._tables[s, self._n_alloc[s]] = b
                self._n_alloc[s] += 1
                self._tables_dirty = True

    def _preempt_locked(self, slot: int) -> None:
        """Evict ``slot``'s request: free its blocks and queue a
        re-prefill job for its full known stream (prompt + generated
        tokens, pending token carried as known_first so the resumed
        stream is exactly the original — greedy AND sampled, since
        sampling keys by (seed, position))."""
        from nnstreamer_tpu.kv.sched import PrefillJob

        req = self._slots[slot]
        self._pool.free(self._tables[slot, : self._n_alloc[slot]].tolist())
        self._tables[slot] = 0
        self._n_alloc[slot] = 0
        self._tables_dirty = True
        self._active[slot] = False
        self._pump_state_dirty = True
        self._slo.preempted(req.rid)
        if len(req.tokens) > 1:
            context = np.concatenate([
                req.prompt, np.asarray(req.tokens[:-1], np.int32)
            ])
        else:
            context = np.asarray(req.prompt, np.int32)
        self._prefill_q.append(PrefillJob(
            slot, req, context, known_first=int(req.tokens[-1]),
            resumed=True,
        ))

    # -- live migration (kv/migrate.py; docs/llm-serving.md) ---------------
    def _span_leaf_template(self):
        """(dtype, per-block shape) per arena leaf, jax leaf order —
        the geometry a span must match to be adoptable here."""
        return [
            (str(np.dtype(leaf.dtype).name),
             (leaf.shape[0],) + tuple(leaf.shape[2:]))
            for leaf in jax.tree_util.tree_leaves(self._cache)
        ]

    def probe_prefix(self, tokens) -> int:
        """Leading tokens of ``tokens`` whose K/V this pool already
        holds in FULL indexed blocks — the migration warm probe.
        Read-only (nothing is adopted); the answer feeds
        ``RequestSpan.strip_shared`` on the sending side so a warm
        migration ships only the unshared suffix."""
        if not self._paged:
            return 0
        toks = np.asarray(tokens, np.int32).reshape(-1)
        with self._lock:
            m = self._pool.match(toks)
        return len(m.full) * self.block_size

    def extract_request(self, rid: int, remove: bool = True):
        """Serialize request ``rid``'s live state into a
        :class:`~nnstreamer_tpu.kv.migrate.RequestSpan`: the request
        row, the rolling-CRC prefix hashes, and every KV block's RAW
        arena bytes (int8 payloads ship quantized + scales verbatim —
        the round trip through ``read_block`` would dequantize and
        break the bitwise guarantee). ``remove=True`` (migration) frees
        the slot and blocks — registered blocks park in the pool's
        cached tier, adoptable by later prompts; ``remove=False`` is
        the non-destructive checkpoint read. Under ``_step_lock`` like
        ``register_prefix``: the arena reads must serialize with
        donated step/pump launches."""
        from nnstreamer_tpu.kv.blocks import roll_hash
        from nnstreamer_tpu.kv.migrate import (
            BlockRecord,
            RequestSpan,
            SpanStateError,
            block_crc,
        )

        if not self._paged:
            raise SpanStateError(
                "request migration needs kv_layout='paged'"
            )
        self._check_failed()
        with self._step_lock:
            self._apply_pending()
            with self._lock:
                slot = None
                for s, r in enumerate(self._slots):
                    if r is not None and r.rid == rid:
                        slot = s
                        break
                if slot is None or not self._active[slot]:
                    raise SpanStateError(
                        f"request {rid} is not extractable: only an "
                        "actively decoding request has a KV span "
                        "(settle the prefill queue first — queued/"
                        "prefilling requests re-submit, they do not "
                        "migrate)"
                    )
                req = self._slots[slot]
                bs = self.block_size
                n_kv = req.fill0 + len(req.tokens) - 1
                n_blocks = -(-n_kv // bs)
                blocks = self._tables[slot, :n_blocks].tolist()
                stream = np.concatenate([
                    np.asarray(req.prompt, np.int32),
                    np.asarray(req.tokens, np.int32),
                ])[:n_kv]
                # one packed gather per arena leaf — the exact resident
                # bytes, fetched through the same lock discipline as
                # snapshot()
                ids = jnp.asarray(np.asarray(blocks, np.int32))
                raw = [
                    np.asarray(leaf[:, ids])
                    for leaf in jax.tree_util.tree_leaves(self._cache)
                ]
                records = []
                hashes = []
                h = 0
                for i in range(n_blocks):
                    n_tok = min(bs, n_kv - i * bs)
                    payload = [
                        np.ascontiguousarray(r[:, i]).tobytes()
                        for r in raw
                    ]
                    records.append(
                        BlockRecord(n_tok, block_crc(payload), payload)
                    )
                    if n_tok == bs:
                        h = roll_hash(h, stream[i * bs: (i + 1) * bs])
                        hashes.append(h)
                rec = self._slo.record(rid)
                deadline = None
                if rec is not None and rec.deadline_s is not None:
                    deadline = rec.deadline_s - (
                        _time.perf_counter() - rec.t_submit
                    )
                span = RequestSpan(
                    block_size=bs,
                    leaves=self._span_leaf_template(),
                    cache_dtype=(
                        "int8" if self._quantized
                        else str(np.dtype(self.compute_dtype).name)
                    ),
                    rid=rid,
                    prompt=np.asarray(req.prompt, np.int32).copy(),
                    tokens=list(req.tokens),
                    fill0=int(req.fill0),
                    budget=int(req.budget),
                    temperature=float(req.temperature),
                    top_k=int(req.top_k),
                    top_p=float(req.top_p),
                    stop_token=req.stop_token,
                    key=np.asarray(req.key, np.uint32).copy(),
                    deadline_s=deadline,
                    preemptions=(
                        rec.preemptions if rec is not None else 0
                    ),
                    prefix_hashes=hashes,
                    blocks=records,
                )
                if remove:
                    self._pool.free(blocks)
                    self._tables[slot] = 0
                    self._n_alloc[slot] = 0
                    self._tables_dirty = True
                    self._active[slot] = False
                    self._pump_state_dirty = True
                    self._slots[slot] = None
                    self._slo.migrated(rid)
                    self._n_migrations_out += 1
                    if self._obs_reg is not None:
                        self._obs_reg.counter(
                            "nns_kv_migrations_total", direction="out"
                        ).inc()
        return span

    def adopt_request(self, span) -> int:
        """Land a peer's :class:`RequestSpan` into THIS batcher and
        continue decoding it: full blocks the prefix index already
        holds are shared by refcount (the warm path — stripped payloads
        must be covered here or :class:`SpanPayloadMissingError`), the
        rest land their raw payloads into freshly allocated blocks, and
        the request re-enters the batch through the resumed-admission
        path (``known_first`` = the pending token, so no re-sampling:
        the continued stream is bitwise the source's). Returns the NEW
        local rid. Raises :class:`SpanCapacityError` (no slot / no
        blocks / budget would overflow ``max_len``) without mutating
        anything."""
        from nnstreamer_tpu.kv.blocks import NoBlocksError
        from nnstreamer_tpu.kv.migrate import (
            SpanCapacityError,
            SpanFormatError,
            SpanPayloadMissingError,
        )

        if not self._paged:
            raise SpanFormatError(
                "request migration needs kv_layout='paged'"
            )
        self._check_failed()
        bs = self.block_size
        if span.block_size != bs:
            raise SpanFormatError(
                f"KV span block_size {span.block_size} != this "
                f"batcher's {bs}"
            )
        if list(span.leaves) != self._span_leaf_template():
            raise SpanFormatError(
                "KV span arena geometry mismatch (layers/heads/dims or "
                "cache dtype differ — migrate between identically "
                "configured batchers)"
            )
        if span.fill0 + span.budget > self.max_len:
            raise SpanCapacityError(
                f"span needs fill0+budget={span.fill0 + span.budget} "
                f"positions but max_len={self.max_len}"
            )
        n_kv = span.n_kv
        n_blocks = -(-n_kv // bs)
        stream = span.kv_tokens
        with self._step_lock:
            self._apply_pending()
            with self._lock:
                try:
                    slot = next(
                        i for i, r in enumerate(self._slots) if r is None
                    )
                except StopIteration:
                    raise SpanCapacityError(
                        f"no free slot ({self.n_slots} occupied)"
                    ) from None
                m = self._pool.match(stream)
                n_shared = min(len(m.full), n_blocks)
                shared = list(m.full[:n_shared])
                for i, rec in enumerate(span.blocks):
                    if rec.payload is None and i >= n_shared:
                        raise SpanPayloadMissingError(
                            f"block {i} was stripped by the sender but "
                            "this pool's prefix index does not cover it"
                        )
                for b in shared:
                    self._pool.adopt(b)
                if n_shared:
                    self._pool.record_hit_tokens(n_shared * bs)
                try:
                    fresh = (
                        self._pool.alloc(n_blocks - n_shared)
                        if n_blocks > n_shared else []
                    )
                except NoBlocksError:
                    self._pool.free(shared)
                    raise SpanCapacityError(
                        f"pool cannot host the span: needs "
                        f"{n_blocks - n_shared} fresh blocks, "
                        f"{self._pool.available()} available"
                    ) from None
                rid = self._next_rid
                self._next_rid += 1
                req = _Request(
                    rid, span.budget, temperature=span.temperature,
                    top_k=span.top_k, top_p=span.top_p,
                    stop_token=span.stop_token,
                    t_submit=_time.perf_counter(),
                    key=np.asarray(span.key, np.uint32),
                    prompt=np.asarray(span.prompt, np.int32),
                )
                req.tokens = list(span.tokens)
                req.fill0 = int(span.fill0)
                self._slots[slot] = req
            blocks = shared + fresh
            if fresh:
                # decode every shipped payload on host BEFORE the first
                # donated device write, so a malformed span can never
                # half-mutate the arena
                per_leaf = []
                for j, (dt, shape) in enumerate(span.leaves):
                    per_leaf.append(np.stack([
                        np.frombuffer(
                            span.blocks[i].payload[j], dtype=np.dtype(dt)
                        ).reshape(shape)
                        for i in range(n_shared, n_blocks)
                    ], axis=1))
                try:
                    treedef = jax.tree_util.tree_structure(self._cache)
                    leaves = jax.tree_util.tree_leaves(self._cache)
                    ids = jnp.asarray(np.asarray(fresh, np.int32))
                    self._cache = jax.tree_util.tree_unflatten(treedef, [
                        self._adopt_scatter(leaf, ids, jnp.asarray(vals))
                        for leaf, vals in zip(leaves, per_leaf)
                    ])
                except Exception as exc:  # donated mid-write: latch
                    self._mark_failed(exc)
                    raise
            with self._lock:
                self._pool.register(stream, blocks)
                rec = self._slo.submit(rid, span.deadline_s)
                rec.preemptions = int(span.preemptions)
                hist_row = np.full((self.max_len,), -1, np.int32)
                hist_row[:n_kv] = stream[: self.max_len]
                self._pending.append(_PendingInsert(
                    slot, None, None, int(span.tokens[-1]), n_kv, req,
                    hist_row=hist_row, blocks=blocks, resumed=True,
                ))
                self._n_migrations_in += 1
            self._apply_pending()
        if self._obs_reg is not None:
            self._obs_reg.counter(
                "nns_kv_migrations_total", direction="in"
            ).inc()
        return rid

    def resume_from_span(self, span) -> int:
        """Deadline-aware re-prefill fallback (the PR-10 eviction-resume
        path): when no peer accepts the span, re-admit the request from
        its token stream — the prefix index supplies whatever KV
        survived in the cached tier, chunked prefill recomputes the
        rest, and ``known_first`` pins the pending token so the
        continued stream is exactly the original. Returns the new rid;
        the span's remaining deadline and preemption count carry over."""
        from nnstreamer_tpu.kv.migrate import (
            SpanCapacityError,
            SpanFormatError,
        )
        from nnstreamer_tpu.kv.sched import PrefillJob

        if not self._paged:
            raise SpanFormatError(
                "request migration needs kv_layout='paged'"
            )
        self._check_failed()
        if span.fill0 + span.budget > self.max_len:
            raise SpanCapacityError(
                f"span needs fill0+budget={span.fill0 + span.budget} "
                f"positions but max_len={self.max_len}"
            )
        with self._lock:
            try:
                slot = next(
                    i for i, r in enumerate(self._slots) if r is None
                )
            except StopIteration:
                raise SpanCapacityError(
                    f"no free slot ({self.n_slots} occupied)"
                ) from None
            rid = self._next_rid
            self._next_rid += 1
            req = _Request(
                rid, span.budget, temperature=span.temperature,
                top_k=span.top_k, top_p=span.top_p,
                stop_token=span.stop_token,
                t_submit=_time.perf_counter(),
                key=np.asarray(span.key, np.uint32),
                prompt=np.asarray(span.prompt, np.int32),
            )
            req.tokens = list(span.tokens)
            req.fill0 = int(span.fill0)
            self._slots[slot] = req
            rec = self._slo.submit(rid, span.deadline_s)
            rec.preemptions = int(span.preemptions)
            if len(span.tokens) > 1:
                context = np.concatenate([
                    np.asarray(span.prompt, np.int32),
                    np.asarray(span.tokens[:-1], np.int32),
                ])
            else:
                context = np.asarray(span.prompt, np.int32)
            self._prefill_q.append(PrefillJob(
                slot, req, context,
                known_first=int(span.tokens[-1]), resumed=True,
            ))
            self._n_resumes += 1
        if self._obs_reg is not None:
            self._obs_reg.counter(
                "nns_request_resumes_total", kind="reprefill"
            ).inc()
        return rid

    # -- failure containment (donated-state launches) ----------------------
    def _mark_failed(self, exc: Exception) -> None:
        """A step/pump program raised after dispatch: the donated cache
        buffers are gone while the attributes still point at them. Latch
        the failure so every later call raises a clear typed error
        instead of a cryptic deleted-buffer one. Lock-free write
        (GIL-atomic; callers may already hold _lock/_step_lock)."""
        if self._failed is None:
            self._failed = exc

    def _check_failed(self) -> None:
        if self._failed is not None:
            raise BatcherFailedError(
                f"batcher is failed: a prior step/pump launch raised "
                f"{type(self._failed).__name__}: {self._failed}; the "
                "donated device state is invalid — build a new batcher"
            ) from self._failed

    def step(self) -> Dict[int, int]:
        """Advance every active slot one token; returns {rid: token}.

        The compiled step runs OUTSIDE the state lock (admission only
        needs the lock for its bookkeeping, so submit() never waits on an
        in-flight device step); _step_lock serializes concurrent
        steppers. Slots admitted while a step is in flight join at the
        next step."""
        self._check_failed()
        t0 = _time.perf_counter()
        with self._step_lock:
            return self._plain_step_locked(t0)

    def _harvest_rows_locked(
        self, active_np, rows
    ) -> Tuple[Dict[int, List[int]], int]:
        """Append per-slot emitted rows (−1-padded, [B, ...] iterable of
        row iterables) into their requests until budget/stop finishes
        them; returns ({rid: tokens}, n_emitted). One implementation of
        the budget/stop truncation discipline for every pump commit
        path (caller holds _lock)."""
        out: Dict[int, List[int]] = {}
        n_em = 0
        for s, req in enumerate(self._slots):
            if req is None or not active_np[s]:
                continue
            got: List[int] = []
            for row in rows(s):
                for t in row:
                    if t < 0:
                        break
                    req.tokens.append(int(t))
                    got.append(int(t))
                    n_em += 1
                    if req.finished():
                        break
                if req.finished():
                    break
            if got:
                out[req.rid] = got
            if req.finished():
                self._finish(s)
        return out, n_em

    def _pump_host_state(self, active_np):
        """Per-slot budget remaining + stop ids for a device pump
        (host-known state; [B] int32 each). Only the dirty-rebuild path
        of :meth:`_pump_state_locked` calls this now."""
        remaining = np.zeros((self.n_slots,), np.int32)
        stop = np.full((self.n_slots,), -1, np.int32)
        for s, req in enumerate(self._slots):
            if req is None or not active_np[s]:
                continue
            remaining[s] = req.budget - len(req.tokens)
            if req.stop_token is not None:
                stop[s] = req.stop_token
        return remaining, stop

    def _pump_state_locked(self):
        """Device-carried pump state: (budget remaining, stop ids,
        active mask) as [B] device arrays.

        The pump scans already compute next-pump values for all three
        (budget decremented, stops latched, lanes idled out) — so the
        arrays are CARRIED on device across pumps and the host rebuild +
        H2D ship happens only when the dirty flag says a slot actually
        changed outside a pump (submit admission, a finished/preempted
        request, or a host-stepped path). A steady pump-only drain ships
        ZERO host state — pinned by the no-new-H2D regression test in
        tests/test_pumps.py. Caller holds _lock."""
        if self._pump_state_dirty:
            remaining, stop = self._pump_host_state(self._active)
            self._budget_dev = self._pin(jnp.asarray(remaining))
            self._stop_dev = self._pin(jnp.asarray(stop))
            self._active_dev = self._pin(jnp.asarray(self._active.copy()))
            self._pump_state_dirty = False
            self._host_state_builds += 1
        return self._budget_dev, self._stop_dev, self._active_dev

    def _tables_device_locked(self):
        """Cached device copy of the block tables (paged), re-shipped
        only when an allocation/preemption/admission changed a row."""
        if self._tables_dirty:
            self._tables_dev = jnp.asarray(self._tables)
            self._tables_dirty = False
        return self._tables_dev

    def _note_gather_dispatch_locked(self) -> None:
        """Count a paged step/pump/spec launch that ran the
        gather→contiguous-view→scatter oracle (``kv_attn="gather"``)
        instead of the block-native formulation. An operator watching
        ``nns_kv_gather_dispatch_total`` (or ``kv_gather_dispatches``
        in stats()) sees exactly when the decode plane is paying the
        materialized-view round trip; a block-native batcher never
        increments it — the zero-gather steady-state regression pin."""
        if self._kv_attn != "gather":
            return
        self._n_gather_dispatch += 1
        if self._obs_reg is not None:
            self._obs_reg.counter("nns_kv_gather_dispatch_total").inc()

    def step_pump(self, n: int = 8) -> Dict[int, List[int]]:
        """Advance every active slot by up to ``n`` tokens in ONE
        compiled program (lax.scan over the batched step) with ONE
        [B, n] device→host read at the end — the serving hot loop
        shaped for the chip, not the host: per-token pumping pays a
        full host↔device round trip per token (ruinous through a
        tunnel-attached device, wasteful anywhere), while a pump
        amortizes it n ways. Slots hit their budget or stop token ON
        DEVICE and idle out (-1 lanes); admissions join at the next
        pump, so admission latency is bounded by one pump — pump small
        when latency-sensitive, large for throughput. Returns
        {rid: [tokens emitted this pump]}. Role-match: the reference's
        single-invoke-per-buffer filter loop
        (gst/nnstreamer/tensor_filter/tensor_filter.c) batched along
        the token axis instead."""
        self._check_failed()
        t0 = _time.perf_counter()
        with self._step_lock:
            if self._paged:
                self._advance_prefill()
            self._apply_pending()
            with self._lock:
                if not self._active.any():
                    return {}
                if self._paged:
                    self._ensure_decode_room_locked(int(n))
                active_np = self._active.copy()
                sampling = any(
                    req is not None and active_np[s] and req.temperature > 0
                    for s, req in enumerate(self._slots)
                )
                budget_dev, stop_dev, active_dev = self._pump_state_locked()
                if self._paged:
                    self._note_gather_dispatch_locked()
                    args = (
                        self._tok, self._pos, active_dev, self._cache,
                        self._tables_device_locked(), self._hist,
                        budget_dev, stop_dev, self._temp, self._topk,
                        self._topp, self._keys,
                    )
                else:
                    args = (
                        self._tok, self._pos, active_dev, self._cache,
                        self._hist, budget_dev, stop_dev, self._temp,
                        self._topk, self._topp, self._keys,
                        self._draft._cache if self._draft is not None
                        else None,
                    )
            fn = self._pump_sampling if sampling else self._pump_greedy
            try:
                if self._paged:
                    emits, tok, pos, act, cache, hist, budget = fn(
                        *args, n_steps=int(n)
                    )
                    dcache = None
                else:
                    emits, tok, pos, act, cache, hist, budget, dcache = fn(
                        *args, n_steps=int(n)
                    )
                emits_np = np.asarray(emits)  # ONE [B, n] transfer
            except Exception as exc:
                # the launch donated _cache/_hist (and the draft cache):
                # a raise here leaves them consumed — latch the failure
                # so later calls get BatcherFailedError, not a cryptic
                # deleted-buffer error (submit()'s rollback analogue)
                self._mark_failed(exc)
                raise
            with self._lock:
                self._cache = cache
                self._hist = self._pin(hist)
                self._tok = self._pin(tok)
                self._pos = self._pin(pos)
                # the scan's carried pump state becomes next pump's input
                self._budget_dev = self._pin(budget)
                self._active_dev = self._pin(act)
                if self._draft is not None:
                    self._draft._cache = dcache
                out, n_em = self._harvest_rows_locked(
                    active_np, lambda s: (emits_np[s],)
                )
                self._n_steps += int(n)
                self._n_tokens += n_em
                self._step_time_s += _time.perf_counter() - t0
                return out

    def spec_pump(
        self, rounds: int = 8, k: int = 4, ngram: int = 2
    ) -> Dict[int, List[int]]:
        """``rounds`` whole speculative rounds per program launch —
        propose → verify → accept → commit scanned ON DEVICE, proposals
        from device_ngram_propose (or an in-scan draft model), one
        packed int32 read back per pump (emitted tokens + acceptance
        telemetry). The host spec_step pays two device reads plus
        Python mining per round; this pays one read per ``rounds``.

        Non-windowed batchers clamp ``rounds`` so the worst-case
        verify writes stay inside max_len (host-side arithmetic — no
        device read: pos = fill0 + len(tokens) - 1); when not even one
        round fits, falls back to spec_step's shrinking k_round. A
        windowed DRAFT batcher also falls back per round: its
        verify-then-commit ring discipline needs each round's
        acceptance before the next propose touches the ring. The
        clamped round count is quantized DOWN to a power of two:
        ``rounds`` is a static scan length, so every distinct value is
        its own XLA program — quantization bounds the program variants
        to log2(rounds) instead of one per tail length."""
        self._check_failed()
        t0 = _time.perf_counter()
        k = max(2, int(k))
        if self._draft is not None and self.windowed:
            return self._spec_fallback_rounds(int(rounds), k, ngram)
        with self._step_lock:
            if self._paged:
                self._advance_prefill()
            self._apply_pending()
            with self._lock:
                if not self._active.any():
                    return {}
                r = int(rounds)
                if not self.windowed:
                    pos_max = max(
                        req.fill0 + len(req.tokens) - 1
                        for s, req in enumerate(self._slots)
                        if req is not None and self._active[s]
                    )
                    r = min(r, (self.max_len - pos_max) // k)
                if r >= 1 and self._paged:
                    # block room BEFORE the active snapshot: allocation
                    # may preempt (deactivate) a victim slot, and the
                    # launch/harvest must both see post-preemption state
                    self._ensure_decode_room_locked(r * k)
                active_np = self._active.copy()
                sampling = any(
                    req is not None and active_np[s] and req.temperature > 0
                    for s, req in enumerate(self._slots)
                )
                # NOT clamped by remaining budget: slots that exhaust
                # their budget mid-scan idle out ON DEVICE (active &=
                # budget > 0), exactly like step_pump's fixed n_steps.
                # Clamping here looked like a harmless economy but made
                # the STATIC scan length a function of live budgets —
                # so a warm-up drain compiled rounds=2/1 programs, the
                # measured drain then built rounds=4 inside the timed
                # region, and every budget tail recompiled its way down
                # a 4→2→1 program ladder: the spec×cb throughput
                # collapse (BENCH_CPU_FULL_r05: 8.0/4.8 vs 25.5 plain).
                # The only static clamp that stays is write-room
                # (cache-bounds correctness), quantized so the window
                # tail costs log2 variants, not one per length.
                if r >= 1:
                    while r & (r - 1):  # power-of-two floor (see above)
                        r &= r - 1
                    budget_dev, stop_dev, active_dev = (
                        self._pump_state_locked()
                    )
                    if self._paged:
                        self._note_gather_dispatch_locked()
                        args = (
                            self._tok, self._pos, active_dev,
                            self._cache, self._tables_device_locked(),
                            self._hist, budget_dev, stop_dev,
                            self._temp, self._topk, self._topp,
                            self._keys,
                        )
                    else:
                        args = (
                            self._tok, self._pos, active_dev,
                            self._cache, self._hist, budget_dev,
                            stop_dev, self._temp, self._topk,
                            self._topp, self._keys,
                            self._draft._cache if self._draft is not None
                            else None,
                        )
                    fn = (
                        self._spec_pump_sampling if sampling
                        else self._spec_pump_greedy
                    )
            if r >= 1:
                try:
                    if self._paged:
                        packed, tok, pos, act, cache, hist, budget = fn(
                            *args, rounds=r, k=k, g=int(ngram)
                        )
                        dcache = None
                    else:
                        (packed, tok, pos, act, cache, hist, budget,
                         dcache) = fn(*args, rounds=r, k=k, g=int(ngram))
                    packed_np = np.asarray(packed)  # ONE transfer
                except Exception as exc:
                    self._mark_failed(exc)  # donated state consumed
                    raise
                acc, cols = int(packed_np[-2]), int(packed_np[-1])
                emits_np = packed_np[:-2].reshape(self.n_slots, r, k)
                with self._lock:
                    self._budget_dev = self._pin(budget)
                    self._active_dev = self._pin(act)
                    return self._spec_pump_commit_locked(
                        t0, active_np, r, acc, cols, emits_np, tok, pos,
                        cache, hist, dcache,
                    )
        # r < 1: no verify room at any width ≥ 2 — the shrinking-k host
        # round handles the tail tokens (takes _step_lock itself)
        return self._spec_fallback_rounds(1, k, ngram)

    def _spec_fallback_rounds(
        self, rounds: int, k: int, ngram: int
    ) -> Dict[int, List[int]]:
        """Drive ``rounds`` host spec_step rounds while preserving
        spec_pump's return contract ({rid: ALL tokens emitted}) —
        spec_step itself reports only the last token per request, so
        the full emission is reconstructed from req.tokens growth.
        Direct _Request references are captured the first time each rid
        is seen: re-resolving rids at the end through the bounded
        _done_pool would silently drop tokens for any request evicted by
        keep_results churn mid-rounds, breaking the ALL-tokens
        contract."""
        before: Dict[int, int] = {}
        with self._lock:
            for req in self._slots:
                if req is not None:
                    # floor 1: token 0 (the prefill's) is appended by
                    # _apply_pending — possibly DURING these
                    # rounds for a deferred admission — and is never
                    # pump output on the device paths either
                    before[req.rid] = max(1, len(req.tokens))
        default_start = 1
        refs: Dict[int, _Request] = {}
        emitted: set = set()
        for _ in range(int(rounds)):
            with self._lock:
                # pre-round snapshot: anything that can emit this round
                # is live in a slot (or pending) RIGHT NOW — grabbing the
                # reference here beats post-round _done_pool lookups,
                # which lose evicted requests
                for r in self._slots:
                    if r is not None and r.rid not in refs:
                        refs[r.rid] = r
                for p in self._pending:
                    if p.req.rid not in refs:
                        refs[p.req.rid] = p.req
            em = self.spec_step(k=k, ngram=ngram)
            if not em:
                break
            emitted |= set(em)
            missing = [rid for rid in em if rid not in refs]
            if missing:
                # admitted DURING the round (the round's own
                # _apply_pending, after our pre-round snapshot): resolve
                # now, while the request is still live or freshly done
                with self._lock:
                    live = {
                        r.rid: r for r in self._slots if r is not None
                    }
                    for rid in missing:
                        req = live.get(rid) or self._done_pool.get(rid)
                        if req is not None:
                            refs[rid] = req
        with self._lock:
            out = {
                rid: list(req.tokens[before.get(rid, default_start):])
                for rid, req in refs.items()
                if rid in emitted
            }
        return {rid: toks for rid, toks in out.items() if toks}

    def _spec_pump_commit_locked(
        self, t0, active_np, r, acc, cols, emits_np, tok, pos, cache,
        hist, dcache,
    ) -> Dict[int, List[int]]:
        """spec_pump bookkeeping; caller holds _step_lock + _lock."""
        self._cache = cache
        self._hist = self._pin(hist)
        self._tok = self._pin(tok)
        self._pos = self._pin(pos)
        if self._draft is not None:
            self._draft._cache = dcache
        out, n_em = self._harvest_rows_locked(
            active_np, lambda s: (emits_np[s, rnd] for rnd in range(r))
        )
        self._n_steps += r
        self._n_tokens += n_em
        self._n_spec_rounds += r
        self._n_spec_accepted += acc
        self._n_spec_columns += cols
        self._step_time_s += _time.perf_counter() - t0
        return out

    def _plain_step_locked(self, t0) -> Dict[int, int]:
        """step() body; caller holds _step_lock."""
        if self._paged:
            self._advance_prefill()
        self._apply_pending()
        with self._lock:
            if not self._active.any():
                return {}
            if self._paged:
                self._ensure_decode_room_locked(1)
            active_np = self._active.copy()
            sampling = any(
                req is not None and active_np[s] and req.temperature > 0
                for s, req in enumerate(self._slots)
            )
            if self._paged:
                self._note_gather_dispatch_locked()
                args = (
                    self._tok, self._pos, jnp.asarray(active_np),
                    self._cache, self._tables_device_locked(),
                    self._hist, self._temp, self._topk, self._topp,
                    self._keys,
                )
            else:
                args = (
                    self._tok, self._pos, jnp.asarray(active_np),
                    self._cache, self._hist, self._temp, self._topk,
                    self._topp, self._keys,
                )
        try:
            if self._draft is not None:
                # keep the draft cache position-synced with the target:
                # this plain step writes the pending token's K/V on the
                # target; the draft must mirror it (see advance_one)
                self._draft.advance_one(args[0], args[1], args[2])
            step_fn = self._step_sampling if sampling else self._step_greedy
            new_tok, cache, pos, hist = step_fn(*args)
            toks = np.asarray(new_tok)  # [B] ids — the only host transfer
        except Exception as exc:
            self._mark_failed(exc)  # donated state consumed
            raise
        with self._lock:
            self._cache = cache
            self._pos = pos
            self._tok = new_tok
            self._hist = hist
            emitted: Dict[int, int] = {}
            for slot, req in enumerate(self._slots):
                if req is None or not active_np[slot]:
                    continue
                tok = int(toks[slot])
                req.tokens.append(tok)
                emitted[req.rid] = tok
                if req.finished():
                    self._finish(slot)
            self._n_steps += 1
            self._n_tokens += len(emitted)
            self._step_time_s += _time.perf_counter() - t0
            # host-stepped path: budgets advanced outside a pump scan,
            # so the device-carried pump state must rebuild next pump
            self._pump_state_dirty = True
            return emitted

    def spec_step(self, k: int = 4, ngram: int = 2) -> Dict[int, int]:
        """One SPECULATIVE round: every active slot verifies k-1 guessed
        continuation tokens in one batched forward and commits its
        accepted prefix plus one correction/bonus token — several tokens
        per program launch when the guesses land. Proposals are
        prompt-lookup (n-gram) from each slot's own context (vLLM-style
        self-drafting: no draft model; models/speculative.py's scheme
        batched over slots).

        Works across the full serving matrix: greedy slots are EXACTLY
        equivalent to step() by construction (verification is the greedy
        model); sampling slots accept by point-mass rejection sampling
        against the same filtered distribution sample_tokens uses, so
        every emitted token is distributed exactly as a plain sampling
        step's (distribution-exact, not byte-identical — see
        spec_accept); windowed ring caches verify against the pre-write
        ring and commit only accepted columns (batched_windowed_verify /
        commit_ring_chunk), so rejected proposals never clobber window
        history; Pallas batchers speculate too — the verify forward uses
        inline XLA attention, so a generation mixing step() and
        spec_step() calls could diverge on near-tied logits (the kernel's
        accumulation order differs), but a server pumping spec_step
        exclusively (speculate=k) is self-consistent: every committed
        token is certified by the same verify program — when ngram
        lookup proposes nothing, a Pallas batcher runs a width-2
        all-sentinel verify (never acceptable, so it emits the plain
        step's token via the verify forward) instead of falling back to
        the kernel-certified plain step. The one remaining plain-step
        fallback on a Pallas batcher is a non-windowed batch whose
        tightest slot has room for <2 columns, i.e. the final token
        before max_len, where no verify chunk fits. XLA batchers fall
        back to a plain step whenever no slot has room for a chunk or no
        slot proposed anything (there the plain step and verify are the
        same inline-attention math). Returns {rid: last emitted token};
        use partials() for the full per-round stream."""
        self._check_failed()
        t0 = _time.perf_counter()
        with self._step_lock:
            if self._paged:
                self._advance_prefill()
            self._apply_pending()
            with self._lock:
                if not self._active.any():
                    return {}
                if self._paged:
                    # before the active snapshot — may preempt a victim
                    self._ensure_decode_room_locked(int(k))
                active_np = self._active.copy()
                sampling = any(
                    req is not None and active_np[s] and req.temperature > 0
                    for s, req in enumerate(self._slots)
                )
                pos_np = np.asarray(self._pos)
                if self.windowed:
                    # a ring has no end: the only bound is the window
                    k_round = max(1, min(k, self.max_len - 1))
                else:
                    room = min(
                        int(self.max_len - pos_np[s])
                        for s in range(self.n_slots) if active_np[s]
                    )
                    k_round = max(1, min(k, room))
                if k_round >= 2:
                    toks_host = np.zeros((self.n_slots, k_round), np.int32)
                    tok_np = np.asarray(self._tok)
                    toks_host[:, 0] = tok_np
                    if self._draft is None:
                        any_found = False
                        for s, req in enumerate(self._slots):
                            if req is None or not active_np[s]:
                                continue
                            ctx = np.concatenate(
                                [req.prompt,
                                 np.asarray(req.tokens, np.int32)]
                            )
                            cand = ngram_lookup(ctx, k_round - 1, ngram)
                            # -1 sentinel for found-nothing columns: a
                            # real greedy token (≥ 0) can never match
                            # it, so the acceptance scan stops at the
                            # pending token instead of crediting
                            # accidental token-0 hits (zero-fill is
                            # indistinguishable from proposing token 0);
                            # XLA's gather clamps the embed lookup
                            toks_host[s, 1:] = -1
                            if cand is not None and cand.size:
                                toks_host[s, 1 : 1 + cand.size] = cand
                                any_found = True
                        if not any_found:
                            if self._attn_impl == "pallas":
                                # a Pallas batcher must NOT mix a
                                # kernel-certified plain step into an
                                # exclusively-speculative generation
                                # (the kernel's accumulation order can
                                # diverge from verify on near-tied
                                # logits): run a width-2 all-sentinel
                                # verify instead — sentinels can never
                                # be accepted, so this emits exactly the
                                # plain step's token, certified by the
                                # same verify program as every other
                                # round
                                k_round = 2
                                toks_host = toks_host[:, :2]
                            else:
                                # no slot proposed anything: the verify
                                # forward would certify exactly one
                                # token per slot at k× the column cost —
                                # a plain step is the same result
                                # cheaper (and on XLA batchers it is
                                # bit-identical to verify)
                                k_round = 1
            if k_round < 2:
                # outside self._lock — _plain_step_locked reacquires it
                return self._plain_step_locked(t0)
            if self._draft is not None:
                # k-1 batched draft forwards propose for every slot at
                # once; a draft always proposes, so there is no
                # found-nothing fallback. Safe outside self._lock: the
                # draft cache and per-slot device vectors are only
                # touched under _step_lock (held here) — submits may
                # queue pending inserts concurrently, but those join at
                # the next round's _apply_pending.
                toks_host[:, 1:] = self._draft.propose(
                    self._tok, self._pos, jnp.asarray(active_np), k_round
                )
            if self._paged:
                with self._lock:
                    self._note_gather_dispatch_locked()
                    tables_dev = self._tables_device_locked()
                args = (
                    jnp.asarray(toks_host), self._pos,
                    jnp.asarray(active_np), self._cache, tables_dev,
                    self._hist, self._temp, self._topk, self._topp,
                    self._keys,
                )
            else:
                args = (
                    jnp.asarray(toks_host), self._pos,
                    jnp.asarray(active_np), self._cache, self._hist,
                    self._temp, self._topk, self._topp, self._keys,
                )
            round_fn = (
                self._spec_round_sampling if sampling
                else self._spec_round_greedy
            )
            try:
                m_dev, final_dev, cache, hist, pos2 = round_fn(*args)
                if self._draft is not None and self._draft.windowed:
                    # draft-side commit of the accepted columns (the ring
                    # discipline: nothing landed during propose)
                    self._draft.commit(args[1], m_dev, args[2])
                # [B] counts + [B] tokens — the only host transfers
                m_np = np.asarray(m_dev)
                final_np = np.asarray(final_dev)
            except Exception as exc:
                self._mark_failed(exc)  # donated state consumed
                raise
            with self._lock:
                self._cache = cache
                self._hist = hist
                self._pos = self._pin(pos2)
                emitted: Dict[int, int] = {}
                new_tok = tok_np.copy()
                n_emitted = 0
                accepted = 0
                for s, req in enumerate(self._slots):
                    if req is None or not active_np[s]:
                        continue
                    m = int(m_np[s])
                    accepted += m - 1
                    planned = [int(t) for t in toks_host[s, 1:m]]
                    planned.append(int(final_np[s]))
                    for t in planned:
                        req.tokens.append(t)
                        emitted[req.rid] = t
                        n_emitted += 1
                        if req.finished():
                            break
                    new_tok[s] = req.tokens[-1]
                    if req.finished():
                        self._finish(s)
                self._tok = self._pin(jnp.asarray(new_tok))
                self._n_steps += 1
                self._n_tokens += n_emitted
                self._n_spec_rounds += 1
                self._n_spec_accepted += accepted
                # count only columns actually holding proposals — -1
                # sentinel columns (ngram found-nothing fill) can never
                # be accepted, so crediting them would bias the
                # per-proposal acceptance rate (and llm_serve's
                # speculate=auto EMA built on it) low
                self._n_spec_columns += int(
                    (toks_host[active_np, 1:] >= 0).sum()
                )
                self._step_time_s += _time.perf_counter() - t0
                self._pump_state_dirty = True  # host-stepped path
                return emitted

    def stats(self) -> Dict[str, float]:
        """Serving counters — the token-world analogue of the filter
        element's latency/throughput props (tensor_filter.c:334-433):
        cumulative steps/tokens, decode rate, and current occupancy."""
        with self._lock:
            occupied = sum(r is not None for r in self._slots)
            st = {
                "steps": self._n_steps,
                "tokens_emitted": self._n_tokens,
                "tokens_per_step": (
                    self._n_tokens / self._n_steps if self._n_steps else 0.0
                ),
                "decode_tok_s": (
                    self._n_tokens / self._step_time_s
                    if self._step_time_s > 0 else 0.0
                ),
                "spec_rounds": self._n_spec_rounds,
                "spec_accepted_tokens": self._n_spec_accepted,
                # accepted/columns is the true per-proposal acceptance
                # rate whatever the slot occupancy or k was per round
                # (sentinel found-nothing columns count in neither)
                "spec_columns": self._n_spec_columns,
                "spec_acceptance_rate": (
                    self._n_spec_accepted / self._n_spec_columns
                    if self._n_spec_columns else 0.0
                ),
                "p50_ttft_ms": self._lat_p50s_locked()[0],
                "p50_request_s": self._lat_p50s_locked()[1],
                "slots_occupied": occupied,
                "slots_free": self.n_slots - occupied,
                "results_pending_pickup": len(self._done_pool),
                "prefixes_registered": len(
                    self._prefixes_paged if self._paged else self._prefixes
                ),
            }
            if self._paged:
                st.update(self._pool.stats())
                st["kv_block_size"] = self.block_size
                st["kv_prefill_queue"] = len(self._prefill_q)
                st["kv_preemptions"] = self._slo.preemptions_total
                # which decode formulation this batcher runs (block =
                # arena attended through the tables, gather = the
                # materialized-view oracle) and how many launches paid
                # the gather round trip — 0 forever under kv_attn=block
                st["kv_attn"] = self._kv_attn
                st["kv_gather_dispatches"] = self._n_gather_dispatch
                st["kv_migrations_out"] = self._n_migrations_out
                st["kv_migrations_in"] = self._n_migrations_in
                st["kv_prefill_chunks"] = self._n_prefill_chunk_programs
                st["request_resumes"] = self._n_resumes
            return st

    def _lat_p50s_locked(self):
        """Cached latency medians (_lock held): the auto-speculation
        controller polls stats() every pump, so the O(n log n) sorts
        run only when a request finished since the last call."""
        if self._lat_cache[0] != self._lat_version:
            ttft = (
                sorted(self._lat_ttft)[len(self._lat_ttft) // 2] * 1000.0
                if self._lat_ttft else 0.0
            )
            req_s = (
                sorted(self._lat_req)[len(self._lat_req) // 2]
                if self._lat_req else 0.0
            )
            self._lat_cache = (self._lat_version, ttft, req_s)
        return self._lat_cache[1], self._lat_cache[2]

    def _pin(self, x):
        """Keep per-slot vectors on their mesh sharding after eager
        updates, so the compiled step sees stable input shardings."""
        return jax.device_put(x, self._vec_sh) if self._vec_sh else x

    def _finish(self, slot: int) -> None:
        req = self._slots[slot]
        req.done = True
        req.t_done = _time.perf_counter()
        if req.t_first and req.t_submit:
            self._lat_ttft.append(req.t_first - req.t_submit)
        if req.t_submit:
            self._lat_req.append(req.t_done - req.t_submit)
        self._lat_version += 1
        self._active[slot] = False
        self._pump_state_dirty = True  # slot left the batch
        if self._paged:
            # release the request's blocks (shared prefix blocks drop a
            # reference and stay adoptable in the pool's cached tier)
            self._pool.free(
                self._tables[slot, : self._n_alloc[slot]].tolist()
            )
            self._tables[slot] = 0
            self._n_alloc[slot] = 0
            self._tables_dirty = True
        self._slo.finished(req.rid, len(req.tokens))
        self._done_pool[req.rid] = req
        while len(self._done_pool) > self._keep_results:
            self._done_pool.popitem(last=False)  # evict oldest uncollected
        self._slots[slot] = None

    def result(self, rid: int) -> Optional[List[int]]:
        """Completed token list for ``rid``, or None if still running."""
        with self._lock:
            if rid in self._done_pool:
                return list(self._done_pool[rid].tokens)
            return None

    def partial(self, rid: int) -> Optional[List[int]]:
        """Tokens emitted SO FAR for ``rid`` (running or finished) — the
        token-streaming read surface. None for unknown/evicted ids."""
        return self.partials([rid]).get(rid)

    def partials(self, rids) -> Dict[int, List[int]]:
        """Batched partial(): {rid: tokens-so-far} for every known rid,
        in ONE lock acquisition and one pass over slots/pending/done —
        the per-token streaming hot path polls every pending request per
        decode step, so the per-rid scan must not multiply."""
        want = set(rids)
        out: Dict[int, List[int]] = {}
        with self._lock:
            for req in self._slots:
                if req is not None and req.rid in want:
                    out[req.rid] = list(req.tokens)
            for p in self._pending:
                if p.req.rid in want:
                    out[p.req.rid] = list(p.req.tokens)
            for rid in want - out.keys():
                if rid in self._done_pool:
                    out[rid] = list(self._done_pool[rid].tokens)
        return out

    def requests(self) -> Dict[int, Dict[str, Any]]:
        """Per-request SLO/state view — the data behind
        ``nns-top --requests``: state (queued/prefilling/decoding/done),
        blocks held (paged), queue/TTFT/TPOT latencies and deadline
        headroom, from the SLO ledger."""
        with self._lock:
            extra: Dict[int, Dict[str, Any]] = {}
            for s, req in enumerate(self._slots):
                if req is None:
                    continue
                row: Dict[str, Any] = {
                    "slot": s, "tokens": len(req.tokens),
                }
                if self._paged:
                    row["blocks"] = int(self._n_alloc[s])
                extra[req.rid] = row
            return self._slo.view(extra)

    # -- warm restart (the PR-7 drain→snapshot→restore discipline) ---------
    def snapshot(self) -> dict:
        """Serializable serving state: every live request, the device
        state (cache or block arena, per-slot vectors, token history)
        and — paged — block tables, pool accounting and the SLO ledger.
        Pending admissions are applied first; a paged batcher must have
        drained its prefill queue (pump until ``kv_prefill_queue`` is 0)
        so no half-staged prompt is lost."""
        self._check_failed()
        with self._step_lock:
            self._apply_pending()
            with self._lock:
                if self._paged and self._prefill_q:
                    raise RuntimeError(
                        "snapshot with queued prefills: pump until the "
                        "prefill queue drains first"
                    )
                reqs = []
                for s, req in enumerate(self._slots):
                    if req is None:
                        continue
                    reqs.append({
                        "slot": s,
                        "rid": req.rid,
                        "budget": req.budget,
                        "temperature": req.temperature,
                        "top_k": req.top_k,
                        "top_p": req.top_p,
                        "stop_token": req.stop_token,
                        "key": np.asarray(req.key).tolist(),
                        "prompt": np.asarray(req.prompt).tolist(),
                        "tokens": list(req.tokens),
                        "fill0": req.fill0,
                        "active": bool(self._active[s]),
                    })
                snap = {
                    "layout": "paged" if self._paged else "slot",
                    "n_slots": self.n_slots,
                    "max_len": self.max_len,
                    "requests": reqs,
                    "device": jax.tree_util.tree_map(np.asarray, {
                        "cache": self._cache,
                        "tok": self._tok,
                        "pos": self._pos,
                        "temp": self._temp,
                        "topk": self._topk,
                        "topp": self._topp,
                        "keys": self._keys,
                        "hist": self._hist,
                    }),
                    "next_rid": self._next_rid,
                    "counters": {
                        "n_steps": self._n_steps,
                        "n_tokens": self._n_tokens,
                        "n_spec_rounds": self._n_spec_rounds,
                        "n_spec_accepted": self._n_spec_accepted,
                        "n_spec_columns": self._n_spec_columns,
                    },
                    "done": {
                        rid: list(r.tokens)
                        for rid, r in self._done_pool.items()
                    },
                    "slo": self._slo.snapshot(),
                }
                if self._paged:
                    snap["tables"] = self._tables.copy()
                    snap["n_alloc"] = self._n_alloc.copy()
                    snap["pool"] = self._pool.snapshot()
                    snap["prefixes"] = {
                        pid: (tok.tolist(), list(blks))
                        for pid, (tok, blks)
                        in self._prefixes_paged.items()
                    }
                else:
                    # slot layout: registered prefixes live as staged
                    # K/V tuples — they must survive the restart too, or
                    # restored callers holding a pid get ValueError (and
                    # a reset _next_prefix would recycle their ids)
                    snap["prefixes"] = {
                        pid: {
                            "kv": jax.tree_util.tree_map(
                                np.asarray, stored
                            ),
                            "plen": int(pl),
                            "tokens": np.asarray(tok).tolist(),
                        }
                        for pid, (stored, pl, tok)
                        in self._prefixes.items()
                    }
                snap["next_prefix"] = self._next_prefix
                return snap

    def restore(self, snap: dict) -> None:
        """Load a :meth:`snapshot` into a freshly built batcher of the
        SAME configuration: decoding continues exactly where the
        snapshot stopped (same streams, same block tables, same prefix
        index — the remembered sharing survives the restart)."""
        want = "paged" if self._paged else "slot"
        if snap.get("layout") != want:
            raise ValueError(
                f"snapshot layout {snap.get('layout')!r} does not match "
                f"this batcher's {want!r}"
            )
        if (snap.get("n_slots") != self.n_slots
                or snap.get("max_len") != self.max_len):
            raise ValueError("snapshot geometry mismatch")
        if self._paged:
            # refuse a shrunk pool BEFORE any device state moves: the
            # first mutation below donates the arena, so discovering the
            # mismatch inside pool.restore() would leave a corrupt half-
            # restored batcher. PoolCapacityError names what the
            # snapshot could shed (cached prefix blocks, registered
            # prefix pins) to fit a smaller kv_blocks on re-snapshot.
            from nnstreamer_tpu.kv.blocks import PoolCapacityError
            psnap = snap.get("pool", {})
            snap_blocks = int(psnap.get("n_blocks", self._pool.n_blocks))
            if snap_blocks > self._pool.n_blocks:
                refcount = list(psnap.get("refcount", []))
                live = sum(1 for rc in refcount[1:] if rc > 0)
                evictable = [
                    ("cached-block", int(b))
                    for b in psnap.get("cached", [])
                ] + [
                    ("prefix", int(pid), len(blks))
                    for pid, (_tok, blks)
                    in snap.get("prefixes", {}).items()
                ]
                raise PoolCapacityError(
                    f"snapshot was taken with kv_blocks={snap_blocks} "
                    f"({live} in use) but this batcher has only "
                    f"{self._pool.n_blocks}: restore refused before any "
                    f"state moved; {len(evictable)} evictable "
                    "candidates (cached prefix blocks / registered "
                    "prefixes) could be shed at the source to fit",
                    needed=snap_blocks, have=self._pool.n_blocks,
                    evictable=evictable,
                )
        with self._step_lock, self._lock:
            dev = snap["device"]
            self._cache = jax.tree_util.tree_map(
                jnp.asarray, dev["cache"]
            )
            self._tok = self._pin(jnp.asarray(dev["tok"]))
            self._pos = self._pin(jnp.asarray(dev["pos"]))
            self._temp = self._pin(jnp.asarray(dev["temp"]))
            self._topk = self._pin(jnp.asarray(dev["topk"]))
            self._topp = self._pin(jnp.asarray(dev["topp"]))
            self._keys = self._pin(jnp.asarray(dev["keys"]))
            self._hist = self._pin(jnp.asarray(dev["hist"]))
            self._slots = [None] * self.n_slots
            self._active = np.zeros((self.n_slots,), bool)
            for d in snap["requests"]:
                req = _Request(
                    d["rid"], d["budget"],
                    temperature=d["temperature"], top_k=d["top_k"],
                    top_p=d["top_p"], stop_token=d["stop_token"],
                    key=np.asarray(d["key"], np.uint32),
                    prompt=np.asarray(d["prompt"], np.int32),
                    t_submit=_time.perf_counter(),
                )
                req.tokens = list(d["tokens"])
                req.fill0 = int(d["fill0"])
                self._slots[d["slot"]] = req
                self._active[d["slot"]] = bool(d["active"])
            self._next_rid = int(snap["next_rid"])
            c = snap.get("counters", {})
            self._n_steps = int(c.get("n_steps", 0))
            self._n_tokens = int(c.get("n_tokens", 0))
            self._n_spec_rounds = int(c.get("n_spec_rounds", 0))
            self._n_spec_accepted = int(c.get("n_spec_accepted", 0))
            self._n_spec_columns = int(c.get("n_spec_columns", 0))
            self._done_pool = OrderedDict()
            for rid, toks in snap.get("done", {}).items():
                stub = _Request(int(rid), 0)
                stub.tokens = list(toks)
                stub.done = True
                self._done_pool[int(rid)] = stub
            self._slo.restore(snap.get("slo", {}))
            if self._paged:
                self._tables = np.asarray(snap["tables"], np.int32).copy()
                self._n_alloc = np.asarray(
                    snap["n_alloc"], np.int32
                ).copy()
                self._tables_dirty = True
                self._pool.restore(snap["pool"])
                self._prefixes_paged = {
                    int(pid): (np.asarray(tok, np.int32), list(blks))
                    for pid, (tok, blks)
                    in snap.get("prefixes", {}).items()
                }
            else:
                self._prefixes = {
                    int(pid): (
                        jax.tree_util.tree_map(jnp.asarray, d["kv"]),
                        int(d["plen"]),
                        np.asarray(d["tokens"], np.int32),
                    )
                    for pid, d in snap.get("prefixes", {}).items()
                }
            self._next_prefix = int(
                snap.get("next_prefix", self._next_prefix)
            )
            self._pump_state_dirty = True

    @property
    def n_free(self) -> int:
        with self._lock:
            return sum(r is None for r in self._slots)
