"""YOLOv5-style single-stage detector — the ``yolov5`` decoder's native
zoo model.

The reference ships a yolov5 bounding-box decoder mode
(ext/nnstreamer/tensor_decoder/tensordec-boundingbox.c:143-159 mode
table; tests/test_models/models/yolov5s-int8.tflite fixtures) whose
input is the flattened [N, 5+C] prediction tensor a YOLOv5 head emits.
This is a from-scratch jnp implementation of that model family shaped
for the TPU, producing exactly the tensor the decoder (and
ops/detection.yolov5_postprocess) consumes — so the zoo has a native
model for every bounding-box decoder mode it claims.

Architecture (CSP-flavored, compact): a strided conv stem, then three
stages of stride-2 conv + a residual bottleneck pair at strides 8/16/32,
and a per-level 1×1 detection head with A=3 anchors per cell. Decode is
the YOLOv5 v4+ formula, in-graph:

    xy = (2σ(t_xy) − 0.5 + grid) · stride / size     (normalized [0,1])
    wh = (2σ(t_wh))² · anchor / size
    obj, cls = σ(t)

All levels concatenate to one [B, Σ(HᵢWᵢA), 5+C] tensor — fixed shape,
fully fused by XLA (grids and anchors are constants baked into the
program; no per-level host loop). The decoder's ``yolov5`` mode then
thresholds + NMSes it, on device in the fused pipeline form.
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu.models import mobilenet_v2, nn

STRIDES = (8, 16, 32)
# anchor (w, h) pixel pairs per level — the familiar v5 P3/P4/P5 priors
ANCHORS = (
    ((10, 13), (16, 30), (33, 23)),
    ((30, 61), (62, 45), (59, 119)),
    ((116, 90), (156, 198), (373, 326)),
)
A = 3  # anchors per cell


def _conv_bn(key, cin, cout, k=3):
    return {"w": nn.init_conv(key, k, k, cin, cout), "bn": nn.init_bn(cout)}


def _apply_conv_bn(x, p, stride=1):
    return nn.relu6(
        nn.batch_norm(nn.conv2d(x, p["w"], stride=stride), p["bn"])
    )


def _bottleneck(key, c):
    k1, k2 = jax.random.split(key)
    return {"c1": _conv_bn(k1, c, c // 2, k=1), "c2": _conv_bn(k2, c // 2, c)}


def _apply_bottleneck(x, p):
    return x + _apply_conv_bn(_apply_conv_bn(x, p["c1"]), p["c2"])


def init_params(key, num_classes: int = 80, width: int = 32) -> Dict:
    """width = channels at stride 4; doubles per stage (stride-32 stage
    at 8×width keeps every matmul MXU-aligned for width ≥ 16)."""
    keys = jax.random.split(key, 12)
    c1, c2, c3, c4 = width, width * 2, width * 4, width * 8
    out_ch = A * (5 + num_classes)
    return {
        "stem": _conv_bn(keys[0], 3, c1),          # stride 4 (two s2 convs
        "stem2": _conv_bn(keys[1], c1, c1),        # folded: s2 then s2)
        "s8": _conv_bn(keys[2], c1, c2),
        "b8": _bottleneck(keys[3], c2),
        "s16": _conv_bn(keys[4], c2, c3),
        "b16": _bottleneck(keys[5], c3),
        "s32": _conv_bn(keys[6], c3, c4),
        "b32": _bottleneck(keys[7], c4),
        "head8": {"w": nn.init_conv(keys[8], 1, 1, c2, out_ch),
                  "b": jnp.zeros((out_ch,), jnp.float32)},
        "head16": {"w": nn.init_conv(keys[9], 1, 1, c3, out_ch),
                   "b": jnp.zeros((out_ch,), jnp.float32)},
        "head32": {"w": nn.init_conv(keys[10], 1, 1, c4, out_ch),
                   "b": jnp.zeros((out_ch,), jnp.float32)},
    }


def n_rows(size: int) -> int:
    """Total prediction rows for a square ``size`` input."""
    return sum((size // s) ** 2 * A for s in STRIDES)


def apply(params: Dict, x, num_classes: int = 80,
          compute_dtype=jnp.float32):
    """[B, S, S, 3] uint8/float → [B, n_rows(S), 5+C] decoded
    predictions (normalized coords, sigmoided scores) — the decoder's
    ``yolov5`` scaled-input layout. ``num_classes`` must agree with the
    head params (guards a mismatched params overlay)."""
    out_ch = params["head8"]["b"].shape[0]
    if out_ch != A * (5 + num_classes):
        raise ValueError(
            f"params head emits {out_ch} channels, expected "
            f"{A * (5 + num_classes)} for num_classes={num_classes}"
        )
    if x.dtype == jnp.uint8:
        x = mobilenet_v2.normalize_uint8(x, compute_dtype)
    else:
        x = x.astype(compute_dtype)
    if compute_dtype != jnp.float32:
        params = nn.cast_params(params, compute_dtype)
    size = x.shape[1]
    y = _apply_conv_bn(x, params["stem"], stride=2)
    y = _apply_conv_bn(y, params["stem2"], stride=2)      # stride 4
    feats = []
    y = _apply_bottleneck(_apply_conv_bn(y, params["s8"], stride=2),
                          params["b8"])
    feats.append(y)                                       # stride 8
    y = _apply_bottleneck(_apply_conv_bn(y, params["s16"], stride=2),
                          params["b16"])
    feats.append(y)                                       # stride 16
    y = _apply_bottleneck(_apply_conv_bn(y, params["s32"], stride=2),
                          params["b32"])
    feats.append(y)                                       # stride 32

    rows: List[jax.Array] = []
    for feat, head_name, stride, anchors in zip(
        feats, ("head8", "head16", "head32"), STRIDES, ANCHORS
    ):
        h = params[head_name]
        t = nn.conv2d(feat, h["w"]) + h["b"]
        b, gh, gw, _ = t.shape
        t = t.reshape(b, gh, gw, A, -1).astype(jnp.float32)
        s = jax.nn.sigmoid(t)
        # grid constants fold into the compiled program
        gy, gx = jnp.meshgrid(
            jnp.arange(gh, dtype=jnp.float32),
            jnp.arange(gw, dtype=jnp.float32),
            indexing="ij",
        )
        grid = jnp.stack([gx, gy], axis=-1)[:, :, None, :]  # [gh,gw,1,2]
        anc = jnp.asarray(np.asarray(anchors, np.float32))  # [A,2] px
        xy = (2.0 * s[..., 0:2] - 0.5 + grid) * (stride / size)
        wh = jnp.square(2.0 * s[..., 2:4]) * (anc / size)
        row = jnp.concatenate([xy, wh, s[..., 4:]], axis=-1)
        rows.append(row.reshape(b, gh * gw * A, -1))
    return jnp.concatenate(rows, axis=1)
