"""Built-in model zoo (pure-jnp models for framework=jax)."""
