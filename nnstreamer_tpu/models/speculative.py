"""Greedy speculative decoding: a draft model proposes, the target verifies.

Autoregressive decode is sequential and memory-bound — the big model reads
all its weights once per token. Speculative decoding breaks the serial
chain: a small draft model runs k cheap steps, then the target scores all
k candidates in ONE chunked forward (decode.verify_chunk) and keeps the
longest prefix that matches its own greedy choice, plus one corrected
token. Per round the target does one weight pass for up to k+1 emitted
tokens; with greedy acceptance the output is EXACTLY the sequence the
target would produce alone (tested invariant — no approximation).

Rollback is free by construction: rejected candidates' K/V stay in the
cache beyond ``pos`` but the ≤ pos attention mask never reaches them, and
they are overwritten before the mask grows past them (the same invariant
models/serving.py relies on for slot reuse).

Two compiled programs per model pair (draft k-step scan, target verify
chunk) regardless of sequence length or acceptance pattern.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu.models import decode as dec


@functools.partial(
    jax.jit, static_argnames=("k", "n_heads", "compute_dtype")
)
def _draft_k(params, cache, pos, tok, k, n_heads, compute_dtype):
    """k greedy draft steps from ``tok``: returns proposals [B, k-1] (the
    chunk tail) and the advanced draft cache.

    Module-level jit: the compile caches on the params/cache shapes, not
    per speculative_generate call. The scan runs k steps, one more than
    the proposals used: the k-th step's emission is discarded but its
    *input* (the last proposal) gets its K/V written — on full acceptance
    the rolled-forward draft position covers that slot, and an unwritten
    hole there would be attended as garbage next round."""

    def step(carry, _):
        cache, pos, tok = carry
        logits, cache, pos = dec.decode_step(
            params, tok, pos, cache, n_heads, compute_dtype=compute_dtype
        )
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (cache, pos, nxt), nxt

    (cache, pos, _), props = jax.lax.scan(
        step, (cache, pos, tok), None, length=k
    )
    return props.T[:, : k - 1], cache, pos  # [B, k-1]


@functools.partial(jax.jit, static_argnames=("n_heads", "compute_dtype"))
def _verify(params, cache, pos, chunk, n_heads, compute_dtype):
    logits, cache, _ = dec.verify_chunk(
        params, chunk, pos, cache, n_heads, compute_dtype=compute_dtype
    )
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache  # [B, k]


def _speculative_loop(
    target_params: Dict,
    prompt,
    n_heads: int,
    max_new_tokens: int,
    k: int,
    compute_dtype,
    propose,
    on_accept=None,
    caller: str = "speculative_generate",
):
    """The one certified verify/accept/rollback loop shared by every
    proposal source. ``propose(cur, context) -> np [k-1]`` supplies the
    candidates (a draft model, an n-gram lookup, ...); ``on_accept(n_acc)``
    lets stateful proposers (the draft cache) roll their state forward.

    Invariants owned HERE: max_len carries k slack for chunk overshoot;
    rejected K/V beyond the rolled-back pos are masked until overwritten;
    the emitted stream is byte-identical to decode.generate on the
    target, whatever the proposals were."""
    prompt = jnp.asarray(prompt, jnp.int32)
    b, t = prompt.shape
    if b != 1:
        raise ValueError(f"{caller} serves one stream (B=1)")
    if k < 2:
        raise ValueError("k must be ≥ 2 (one proposal + one correction)")
    # chunk writes can overshoot the accepted point by up to k-1
    max_len = t + max_new_tokens + k

    t_logits, t_cache, t_pos = dec.prefill(
        target_params, prompt, n_heads, max_len, compute_dtype=compute_dtype
    )
    cur = jnp.argmax(t_logits[:, -1], axis=-1).astype(jnp.int32)  # [B]
    context = list(np.asarray(prompt)[0])

    out = []
    accept_lens = []
    while len(out) < max_new_tokens:
        out.append(int(cur[0]))  # cur is already target-certified
        context.append(int(cur[0]))
        if len(out) >= max_new_tokens:
            break
        props = np.asarray(
            propose(cur, np.asarray(context, np.int32)), np.int32
        ).reshape(-1)
        chunk = jnp.concatenate(
            [cur[:, None], jnp.asarray(props)[None, :]], axis=1
        )  # [B, k]
        preds, t_cache = _verify(
            target_params, t_cache, t_pos, chunk, n_heads, compute_dtype
        )

        # longest prefix of proposals matching the target's own argmax
        pn = np.asarray(preds[0])
        n_acc = 0
        while n_acc < k - 1 and props[n_acc] == pn[n_acc]:
            n_acc += 1
        accept_lens.append(n_acc)
        out.extend(int(x) for x in props[:n_acc])
        context.extend(int(x) for x in props[:n_acc])
        cur = preds[:, n_acc]  # target's correction after the prefix
        # roll back the target cache to the certified length (rejected
        # K/V beyond pos are masked until overwritten)
        t_pos = t_pos + n_acc + 1
        if on_accept is not None:
            on_accept(n_acc)

    toks = jnp.asarray(np.asarray(out[:max_new_tokens], np.int32))[None, :]
    return toks, accept_lens


def speculative_generate(
    target_params: Dict,
    draft_params: Dict,
    prompt,
    n_heads: int,
    max_new_tokens: int,
    draft_n_heads: Optional[int] = None,
    k: int = 4,
    compute_dtype=jnp.float32,
):
    """prompt [B, T] int32 → tokens [B, max_new_tokens] int32 (greedy,
    byte-identical to decode.generate on the target alone).

    ``k`` = draft lookahead per round. Both models must share the vocab.
    B=1 is the intended serving shape (acceptance lengths are per-stream;
    batching streams belongs to the continuous batcher)."""
    if draft_n_heads is None:
        draft_n_heads = n_heads
    prompt = jnp.asarray(prompt, jnp.int32)
    t = prompt.shape[1]
    max_len = t + max_new_tokens + k
    _, d_cache, d_pos = dec.prefill(
        draft_params, prompt, draft_n_heads, max_len,
        compute_dtype=compute_dtype,
    )
    state = {"cache": d_cache, "pos": d_pos}

    def propose(cur, _context):
        props, state["cache"], _ = _draft_k(
            draft_params, state["cache"], state["pos"], cur, k,
            draft_n_heads, compute_dtype,
        )
        return np.asarray(props[0])

    def on_accept(n_acc):
        # roll the draft cache alongside the target's
        state["pos"] = state["pos"] + n_acc + 1

    return _speculative_loop(
        target_params, prompt, n_heads, max_new_tokens, k, compute_dtype,
        propose, on_accept,
    )


def ngram_lookup(
    context: np.ndarray, k: int, ngram: int = 1
) -> Optional[np.ndarray]:
    """Prompt-lookup core: the (up to k) tokens that followed the most
    recent earlier occurrence of the context's final ``ngram`` tokens —
    or None when the tail has no earlier occurrence. Callers that batch
    proposals over slots (ContinuousBatcher.spec_step) use the None to
    skip verify columns for slots with nothing to propose (a zero-fill
    would be indistinguishable from genuinely proposing token 0)."""
    n = context.shape[0]
    if n < ngram + 1:
        return None
    tail = context[n - ngram:]
    # windows over context[:-1]: starts 0..n-1-ngram, which excludes the
    # tail's own start (n-ngram) by construction
    windows = np.lib.stride_tricks.sliding_window_view(context[:-1], ngram)
    hits = np.flatnonzero((windows == tail).all(axis=1))
    if not hits.size:
        return None
    return context[hits[-1] + ngram : hits[-1] + ngram + k]


def ngram_propose(context: np.ndarray, k: int, ngram: int = 1) -> np.ndarray:
    """Prompt-lookup drafting: ngram_lookup zero-padded to a fixed [k]
    (the single-stream generator's chunk shape). Free (no draft model,
    no extra forward); worthless proposals cost only their verify
    columns, which still certify ≥1 token."""
    props = np.zeros((k,), np.int32)
    cand = ngram_lookup(context, k, ngram)
    if cand is not None:
        props[: cand.size] = cand
    return props


_ngram_propose = ngram_propose  # historical name


def ngram_speculative_generate(
    target_params: Dict,
    prompt,
    n_heads: int,
    max_new_tokens: int,
    k: int = 4,
    compute_dtype=jnp.float32,
):
    """Draft-model-free speculative decoding (prompt lookup): candidates
    come from n-gram matches in the generated context instead of a draft
    model. The verify step is the same chunked target forward, so the
    output is still byte-identical to decode.generate on the target —
    the proposal source only changes how many tokens each round
    certifies. Shines on repetitive/structured text; never worse than
    one certified token per round."""
    return _speculative_loop(
        target_params, prompt, n_heads, max_new_tokens, k, compute_dtype,
        lambda cur, context: _ngram_propose(context, k - 1),
        caller="ngram_speculative_generate",
    )


@functools.partial(
    jax.jit,
    static_argnames=("n_heads", "max_new_tokens", "k", "g",
                     "compute_dtype"),
)
def ngram_generate_scanned(
    target_params: Dict,
    prompt,
    n_heads: int,
    max_new_tokens: int,
    k: int = 4,
    g: int = 2,
    compute_dtype=jnp.float32,
):
    """The WHOLE n-gram speculative generation as ONE compiled program.

    ngram_speculative_generate pays a host round trip per round (fetch
    predictions, mine proposals in Python, ship the next chunk) — the
    per-token poison the serving pumps eliminate, here for the
    single-stream ``decode:ngram`` zoo mode. This version runs the
    propose → verify → accept loop in a device while_loop: proposals
    are mined on device from a token-history array
    (serving.device_ngram_propose, B=1), the verify chunk is the same
    target forward, acceptance is the same greedy prefix rule — the
    emitted stream stays byte-identical to decode.generate — and only
    the finished [1, max_new_tokens] token tensor ever crosses to the
    host. Returns (tokens [1, n_new], accepted_proposals [] int32).
    Role-match: tensor_filter's one-invoke-per-buffer contract
    (tensor_filter.c) kept even for a speculative generation loop."""
    from nnstreamer_tpu.models.serving import (
        device_ngram_propose, spec_accept,
    )

    prompt = jnp.asarray(prompt, jnp.int32)
    b, t = prompt.shape
    if b != 1:
        raise ValueError("ngram_generate_scanned serves one stream (B=1)")
    if k < 2:
        raise ValueError("k must be ≥ 2 (one proposal + one correction)")
    n_new = max_new_tokens
    max_len = t + n_new + k  # chunk-overshoot slack (shared invariant)
    H = t + n_new + 1

    logits, cache, pos = dec.prefill(
        target_params, prompt, n_heads, max_len,
        compute_dtype=compute_dtype,
    )
    cur = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)  # [1]
    hist = jnp.full((1, H), -1, jnp.int32)
    hist = jax.lax.dynamic_update_slice(hist, prompt, (0, 0))
    hist = hist.at[0, t].set(cur[0])
    out = jnp.zeros((n_new,), jnp.int32)

    def cond(carry):
        return carry[0] < n_new

    def body(carry):
        n_out, cur, pos, cache, hist, out, acc_total = carry
        # the pending token is target-certified: emit it first (the
        # host loop's `out.append(cur)` ordering)
        out = out.at[jnp.minimum(n_out, n_new - 1)].set(cur[0])
        n_out = n_out + 1
        # budget already spent: skip the speculation entirely (the
        # host loop breaks here too) — running it would pay one dead
        # verify forward and inflate acc_total with acceptances that
        # emit nothing
        return jax.lax.cond(
            n_out < n_new, _spec_round, lambda c: c,
            (n_out, cur, pos, cache, hist, out, acc_total),
        )

    def _spec_round(carry):
        n_out, cur, pos, cache, hist, out, acc_total = carry
        props = device_ngram_propose(
            hist, jnp.full((1,), pos, jnp.int32), k, g
        )  # [1, k-1]; pos = the pending token's absolute index
        chunk = jnp.concatenate([cur[:, None], props], axis=1)  # [1,k]
        vlogits, cache, _ = dec.verify_chunk(
            target_params, chunk, pos, cache, n_heads,
            compute_dtype=compute_dtype,
        )
        # the ONE acceptance rule (serving.spec_accept greedy branch):
        # sentinel discipline and prefix semantics stay shared with the
        # batcher path instead of a second hand-rolled copy
        m, final = spec_accept(
            vlogits, chunk, jnp.zeros((1,), jnp.float32),
            jnp.zeros((1,), jnp.int32), jnp.ones((1,), jnp.float32),
            jnp.zeros((1, 2), jnp.uint32),
            jnp.full((1,), pos, jnp.int32), False,
        )
        n_acc = m[0] - 1
        # masked append of the accepted prefix: dead lanes route to an
        # out-of-bounds index and DROP — clipping them instead would
        # collide with the last live slot, and scatter order between
        # duplicate indices is unspecified (a stale dup can win)
        idx = n_out + jnp.arange(k - 1)
        keep = (jnp.arange(k - 1) < n_acc) & (idx < n_new)
        out = out.at[jnp.where(keep, idx, n_new)].set(
            props[0], mode="drop"
        )
        # hist records the accepted prefix + the next pending token
        hcols = pos + 1 + jnp.arange(k)
        nxt = final[0]
        hrow = jnp.concatenate([props[0], jnp.zeros((1,), jnp.int32)])
        hrow = jnp.where(jnp.arange(k) == n_acc, nxt, hrow)
        hkeep = (jnp.arange(k) <= n_acc) & (hcols < H)
        hist = hist.at[0, jnp.where(hkeep, hcols, H)].set(
            hrow, mode="drop"
        )
        cur = final
        pos = pos + n_acc + 1
        n_out = n_out + n_acc
        return (n_out, cur, pos, cache, hist, out, acc_total + n_acc)

    n0 = jnp.zeros((), jnp.int32)
    (_, _, _, _, _, out, acc_total) = jax.lax.while_loop(
        cond, body, (n0, cur, pos, cache, hist, out, n0)
    )
    return out[None, :], acc_total
