"""Raw-waveform audio classifier (keyword spotting) — the audio model
family for the zoo.

The reference streams audio through the same element chain as video
(audiotestsrc → tensor_converter audio path,
gst/nnstreamer/elements/gsttensor_converter.c media-type dispatch) and
runs whatever model the filter loads; this gives the zoo a native audio
model so that chain is exercised end to end with real inference, the
way mobilenet_v2 does for video.

Architecture: an M5-style deep conv net over the raw waveform (Dai et
al., "Very Deep CNNs for Raw Waveforms" — public): a long-kernel
strided stem (k=80, s=16 ≈ a learned filterbank) then three conv+pool
stages and a global-average head. TPU-first shape choices: the 1-D
convolutions run as NHWC 2-D convs with H=1 (MXU-friendly lowering),
channels are multiples of 8, pooling is a reshape-mean (no windowed
reduce), and int16 PCM normalizes to float inside the program so the
pipeline feeds device-resident S16LE chunks straight in.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from nnstreamer_tpu.models import nn

STEM_K = 80
STEM_S = 16


def _conv1d(x, w, stride: int = 1):
    """[B, T, C] × [1, K, C, Cout] (standard HWIO, H=1) 2-D conv."""
    return nn.conv2d(x[:, None, :, :], w, stride=stride)[:, 0]


def init_params(key, num_classes: int = 12, width: int = 32) -> Dict:
    k = jax.random.split(key, 5)
    c1, c2, c3 = width, width * 2, width * 4
    return {
        "stem": {"w": nn.init_conv(k[0], 1, STEM_K, 1, c1),
                 "bn": nn.init_bn(c1)},
        "c2": {"w": nn.init_conv(k[1], 1, 3, c1, c2),
               "bn": nn.init_bn(c2)},
        "c3": {"w": nn.init_conv(k[2], 1, 3, c2, c3),
               "bn": nn.init_bn(c3)},
        "c4": {"w": nn.init_conv(k[3], 1, 3, c3, c3),
               "bn": nn.init_bn(c3)},
        "head": nn.init_dense(k[4], c3, num_classes),
    }


def _block(x, p, stride=1, pool=4):
    y = nn.relu6(nn.batch_norm(_conv1d(x, p["w"], stride), p["bn"]))
    b, t, c = y.shape
    if t < pool:  # short clips: the global head pools what remains
        return y
    t4 = (t // pool) * pool
    return jnp.mean(y[:, :t4].reshape(b, t4 // pool, pool, c), axis=2)


def apply(params: Dict, x, compute_dtype=jnp.float32):
    """[B, T, C] (or the converter's unbatched [T, C]) int16 PCM or
    float → [B, num_classes] f32 logits. Multi-channel input is
    mono-mixed up front (mean over C)."""
    if x.ndim == 2:
        x = x[None]  # converter audio tensors are [samples, channels]
    if jnp.issubdtype(x.dtype, jnp.integer):
        x = x.astype(compute_dtype) * (1.0 / 32768.0)
    else:
        x = x.astype(compute_dtype)
    x = jnp.mean(x, axis=-1, keepdims=True)  # mono mix
    if compute_dtype != jnp.float32:
        params = nn.cast_params(params, compute_dtype)
    y = _block(x, params["stem"], stride=STEM_S)
    y = _block(y, params["c2"])
    y = _block(y, params["c3"])
    y = _block(y, params["c4"])
    pooled = jnp.mean(y, axis=1)
    return nn.dense(pooled, params["head"]).astype(jnp.float32)
