"""SSD-MobileNet-v2 detector — the bounding-box benchmark model.

The reference's canonical detection fixture is ssd_mobilenet_v2_coco.tflite
(tests/test_models/models/, used by tests/nnstreamer_decoder_boundingbox/ and
the tensor_query object-detection example, tensor_query/README.md). This is a
from-scratch jnp implementation of the same topology: MobileNet-v2 backbone
(300x300 input), 6 SSD feature maps (19/10/5/3/2/1), 1917 prior boxes, and
box/class heads producing the same two output tensors the reference decoder
consumes in ``mobilenet-ssd`` mode (tensordec-boundingbox.c):

    locations [N, 1917, 4]   (ycenter, xcenter, h, w offsets)
    scores    [N, 1917, 91]  raw class logits, class 0 = background

TPU-first notes: heads are 3x3 convs over NHWC maps (MXU-friendly), anchor
decode + NMS for the ``_pp`` variant run **on device** as fixed-shape masked
tensor ops (ops/detection.py) instead of the reference's per-object C loops,
so the whole detect+postprocess graph is one XLA program.
"""

from __future__ import annotations

import math
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu.models import mobilenet_v2, nn
from nnstreamer_tpu.ops import detection as det

# TF object-detection ssd_mobilenet anchor config: 6 layers, scales
# interpolated in [0.2, 0.95], aspect ratios {1, 2, 1/2, 3, 1/3}, the lowest
# layer reduced to 3 boxes, ratio-1 anchors get an extra interpolated scale.
NUM_LAYERS = 6
MIN_SCALE = 0.2
MAX_SCALE = 0.95
FEATURE_MAPS = (19, 10, 5, 3, 2, 1)
ANCHORS_PER_CELL = (3, 6, 6, 6, 6, 6)
NUM_ANCHORS = sum(a * f * f for a, f in zip(ANCHORS_PER_CELL, FEATURE_MAPS))  # 1917
NUM_CLASSES = 91  # COCO + background

# extra feature layers after the backbone: (mid 1x1 channels, out 3x3/s2 channels)
_EXTRAS: Tuple[Tuple[int, int], ...] = ((256, 512), (128, 256), (128, 256), (64, 128))


def generate_anchors() -> np.ndarray:
    """Prior boxes as a [4, NUM_ANCHORS] array of rows (ycenter, xcenter,
    h, w) — the exact layout of the reference's box-priors.txt consumed by
    the bounding-box decoder (tensordec-boundingbox.c box-priors loading)."""
    scales = [
        MIN_SCALE + (MAX_SCALE - MIN_SCALE) * i / (NUM_LAYERS - 1)
        for i in range(NUM_LAYERS)
    ] + [1.0]
    boxes: List[Tuple[float, float, float, float]] = []
    for layer, fm in enumerate(FEATURE_MAPS):
        if layer == 0:
            # reduce_boxes_in_lowest_layer: fixed (scale, ratio) triple
            layer_boxes = [(0.1, 1.0), (scales[0], 2.0), (scales[0], 0.5)]
        else:
            layer_boxes = [
                (scales[layer], 1.0),
                (scales[layer], 2.0),
                (scales[layer], 0.5),
                (scales[layer], 3.0),
                (scales[layer], 1.0 / 3.0),
                # interpolated scale anchor at ratio 1
                (math.sqrt(scales[layer] * scales[layer + 1]), 1.0),
            ]
        for y in range(fm):
            for x in range(fm):
                yc = (y + 0.5) / fm
                xc = (x + 0.5) / fm
                for scale, ratio in layer_boxes:
                    r = math.sqrt(ratio)
                    boxes.append((yc, xc, scale / r, scale * r))
    arr = np.asarray(boxes, np.float32).T  # [4, N]
    assert arr.shape == (4, NUM_ANCHORS), arr.shape
    return arr


def write_box_priors(path: str) -> None:
    """Write anchors in the reference box-priors.txt format: 4 lines
    (ycenter / xcenter / h / w), NUM_ANCHORS space-separated values each."""
    arr = generate_anchors()
    with open(path, "w") as f:
        for row in arr:
            f.write(" ".join(f"{v:.8f}" for v in row) + "\n")


def init_params(key, num_classes: int = NUM_CLASSES) -> Dict:
    keys = iter(jax.random.split(key, 64))
    p: Dict = {"backbone": mobilenet_v2.init_params(next(keys))}
    # backbone taps: block 12 output (19x19x96) and head output (10x10x1280)
    tap_channels = (96, 1280)
    extras = []
    cin = 1280
    for mid, cout in _EXTRAS:
        extras.append(
            {
                "squeeze": {"w": nn.init_conv(next(keys), 1, 1, cin, mid), "bn": nn.init_bn(mid)},
                "expand": {"w": nn.init_conv(next(keys), 3, 3, mid, cout), "bn": nn.init_bn(cout)},
            }
        )
        cin = cout
    p["extras"] = extras
    head_channels = tap_channels + tuple(c for _, c in _EXTRAS)
    loc_heads, cls_heads = [], []
    for c, a in zip(head_channels, ANCHORS_PER_CELL):
        k1, k2 = next(keys), next(keys)
        loc_heads.append(
            {"w": nn.init_conv(k1, 3, 3, c, a * 4), "b": jnp.zeros((a * 4,), jnp.float32)}
        )
        cls_heads.append(
            {
                "w": nn.init_conv(k2, 3, 3, c, a * num_classes),
                "b": jnp.zeros((a * num_classes,), jnp.float32),
            }
        )
    p["loc_heads"] = loc_heads
    p["cls_heads"] = cls_heads
    return p


def _feature_maps(params: Dict, x, train: bool):
    """Run the backbone, tapping the SSD source maps."""
    bb = params["backbone"]
    y = nn.relu6(
        nn.batch_norm(nn.conv2d(x, bb["stem"]["w"], stride=2), bb["stem"]["bn"], train)
    )
    strides = mobilenet_v2._block_strides()
    taps = []
    for i, (blk, stride) in enumerate(zip(bb["blocks"], strides)):
        y = mobilenet_v2._block(y, blk, stride, train)
        if i == 12:  # last 19x19 map (96ch) before the stride-2 160 group
            taps.append(y)
    y = nn.relu6(nn.batch_norm(nn.conv2d(y, bb["head"]["w"]), bb["head"]["bn"], train))
    taps.append(y)  # 10x10x1280
    for ex in params["extras"]:
        y = nn.relu6(nn.batch_norm(nn.conv2d(y, ex["squeeze"]["w"]), ex["squeeze"]["bn"], train))
        y = nn.relu6(
            nn.batch_norm(nn.conv2d(y, ex["expand"]["w"], stride=2), ex["expand"]["bn"], train)
        )
        taps.append(y)
    return taps


def apply(
    params: Dict, x, train: bool = False, compute_dtype=jnp.float32,
    num_classes: int = NUM_CLASSES,
):
    """uint8/float NHWC [N,300,300,3] → (locations [N,1917,4],
    scores [N,1917,num_classes])."""
    if x.dtype == jnp.uint8:
        x = mobilenet_v2.normalize_uint8(x, compute_dtype)
    else:
        x = x.astype(compute_dtype)
    if compute_dtype != jnp.float32:
        params = nn.cast_params(params, compute_dtype)
    maps = _feature_maps(params, x, train)
    n = x.shape[0]
    locs, scores = [], []
    for fmap, lh, ch in zip(maps, params["loc_heads"], params["cls_heads"]):
        l = nn.conv2d(fmap, lh["w"]) + lh["b"]
        c = nn.conv2d(fmap, ch["w"]) + ch["b"]
        locs.append(l.reshape(n, -1, 4))
        scores.append(c.reshape(n, -1, num_classes))
    loc = jnp.concatenate(locs, axis=1).astype(jnp.float32)
    cls = jnp.concatenate(scores, axis=1).astype(jnp.float32)
    return loc, cls


def apply_postprocessed(
    params: Dict,
    x,
    priors,
    max_out: int = 10,
    threshold: float = 0.001,
    iou_threshold: float = det.SSD_IOU_THRESHOLD,
    compute_dtype=jnp.float32,
):
    """Detector + on-device NMS → the 4-tensor TFLite detection-postprocess
    layout the reference's ``mobilenet-ssd-postprocess`` decoder mode
    expects: boxes [max,4] (ymin,xmin,ymax,xmax), classes [max], scores
    [max], num [1]. All fixed-shape jax — one XLA program end to end."""
    loc, cls = apply(params, x, compute_dtype=compute_dtype)
    boxes = det.ssd_decode_boxes(loc[0], priors)  # [N,4] x1y1x2y2
    probs = jax.nn.sigmoid(cls[0])
    probs = probs.at[:, 0].set(0.0)
    best = jnp.argmax(probs, axis=-1)
    best_score = jnp.max(probs, axis=-1)
    score = jnp.where(best_score >= threshold, best_score, 0.0)
    keep_idx, keep_scores = det.nms(boxes, score, iou_threshold, max_out)
    safe = jnp.maximum(keep_idx, 0)
    kept = boxes[safe]  # x1,y1,x2,y2
    valid = (keep_idx >= 0) & (keep_scores > 0)
    out_boxes = jnp.where(
        valid[:, None],
        jnp.stack([kept[:, 1], kept[:, 0], kept[:, 3], kept[:, 2]], axis=-1),
        0.0,
    )
    out_classes = jnp.where(valid, best[safe], 0).astype(jnp.float32)
    out_scores = jnp.where(valid, keep_scores, 0.0)
    num = jnp.sum(valid.astype(jnp.float32)).reshape(1)
    return out_boxes, out_classes, out_scores, num
