"""Vision Transformer (ViT) classifier — the second vision family.

Built from the same stacked encoder blocks as the LM
(models/transformer.py, causal=False): patch-embed conv → [N, P², D]
token grid (+ 2-D sin/cos position encoding in place of RoPE — RoPE is
disabled by passing zero positions), pre-norm encoder stack, mean-pooled
head. TPU notes: the patch conv is one big MXU matmul (P×P×3 → D), tokens
keep D on the lane dimension, and the whole uint8→logits path is a single
XLA program like the CNN zoo models.

fn: uint8 NHWC [N, S, S, 3] → logits [N, num_classes].
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu.models import mobilenet_v2, nn
from nnstreamer_tpu.models import transformer as tfm

INPUT_SIZE = 224
PATCH = 16


def sincos_2d(grid: int, d_model: int) -> jnp.ndarray:
    """Fixed 2-D sin/cos position table [grid*grid, d_model]."""
    assert d_model % 4 == 0, "d_model must be divisible by 4 for 2D sincos"
    d4 = d_model // 4
    omega = 1.0 / (10000 ** (np.arange(d4) / d4))
    pos = np.arange(grid)
    out = np.einsum("p,d->pd", pos, omega)
    emb = [np.sin(out), np.cos(out)]  # [grid, d4] each
    row = np.concatenate(emb, axis=1)  # [grid, d4*2]
    full = np.concatenate(
        [
            np.repeat(row, grid, axis=0),  # y component
            np.tile(row, (grid, 1)),  # x component
        ],
        axis=1,
    )  # [grid*grid, d_model]
    return jnp.asarray(full, jnp.float32)


def init_params(
    key,
    num_classes: int = 1001,
    d_model: int = 384,
    n_heads: int = 6,
    n_layers: int = 12,
    patch: int = PATCH,
    size: int = INPUT_SIZE,
) -> Dict:
    k1, k2, k3 = jax.random.split(key, 3)
    lm = tfm.init_params(
        k1, vocab=1, d_model=d_model, n_heads=n_heads, n_layers=n_layers
    )
    grid = size // patch
    return {
        "patch": {
            "w": nn.init_conv(k2, patch, patch, 3, d_model),
            "b": jnp.zeros((d_model,), jnp.float32),
        },
        "pos": sincos_2d(grid, d_model),
        "blocks": lm["blocks"],
        "ln_f": lm["ln_f"],
        "head": nn.init_dense(k3, d_model, num_classes),
    }


def apply(params: Dict, x, n_heads: int, compute_dtype=jnp.float32):
    if x.dtype == jnp.uint8:
        x = mobilenet_v2.normalize_uint8(x, compute_dtype)
    else:
        x = x.astype(compute_dtype)
    if compute_dtype != jnp.float32:
        params = nn.cast_params(params, compute_dtype)
    patch = params["patch"]["w"].shape[0]
    y = nn.conv2d(x, params["patch"]["w"], stride=patch, padding="VALID")
    y = y + params["patch"]["b"]
    n, gh, gw, d = y.shape
    tokens = y.reshape(n, gh * gw, d) + params["pos"].astype(y.dtype)
    # zero positions disable RoPE's rotation (angle 0 = identity), keeping
    # position information purely in the additive 2-D table
    positions = jnp.zeros((gh * gw,), jnp.int32)
    tokens = tfm.apply_layers(
        params["blocks"], tokens, n_heads, positions, causal=False
    )
    tokens = tfm.rmsnorm(tokens, params["ln_f"])
    pooled = jnp.mean(tokens, axis=1)
    return nn.dense(pooled, params["head"]).astype(jnp.float32)
