"""PoseNet (MobileNet-v1 backbone) — the multi-output pose benchmark model.

The reference's pose fixture is posenet_mobilenet_v1_100_257x257 (tflite,
tests/nnstreamer_decoder_pose/runTest.sh): 257x257 input, four output maps at
stride 32 (9x9 grid) — heatmaps[17], short-range offsets[34], forward and
backward displacement fields[32] for multi-pose grouping. This is the same
topology from scratch in jnp: MobileNet-v1 depthwise-separable backbone + four
1x1 heads; output order matches the reference so the pose decoder's
``mode=pose-estimation`` tensor mapping applies unchanged.

fn: uint8 NHWC [N,257,257,3] → (heatmap [N,9,9,17], offsets [N,9,9,34],
displacement_fwd [N,9,9,32], displacement_bwd [N,9,9,32]).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from nnstreamer_tpu.models import mobilenet_v2, nn

NUM_KEYPOINTS = 17
INPUT_SIZE = 257
OUTPUT_GRID = 9

# MobileNet-v1 plan: (out_channels, stride) per depthwise-separable block
_V1_BLOCKS: Tuple[Tuple[int, int], ...] = (
    (64, 1),
    (128, 2),
    (128, 1),
    (256, 2),
    (256, 1),
    (512, 2),
    (512, 1),
    (512, 1),
    (512, 1),
    (512, 1),
    (512, 1),
    (1024, 2),
    (1024, 1),
)


def init_params(key, num_keypoints: int = NUM_KEYPOINTS) -> Dict:
    keys = iter(jax.random.split(key, 40))
    p: Dict = {"stem": {"w": nn.init_conv(next(keys), 3, 3, 3, 32), "bn": nn.init_bn(32)}}
    cin = 32
    blocks = []
    for cout, _ in _V1_BLOCKS:
        blocks.append(nn.init_sep_conv(next(keys), cin, cout))
        cin = cout
    p["blocks"] = blocks
    for head, c in (
        ("heatmap", num_keypoints),
        ("offsets", 2 * num_keypoints),
        ("disp_fwd", 2 * (num_keypoints - 1)),
        ("disp_bwd", 2 * (num_keypoints - 1)),
    ):
        p[head] = nn.init_dense(next(keys), cin, c)  # used as 1x1 conv
    return p


def _head(y, p: Dict):
    return jnp.einsum("nhwc,cd->nhwd", y, p["w"]) + p["b"]


def apply(params: Dict, x, train: bool = False, compute_dtype=jnp.float32):
    if x.dtype == jnp.uint8:
        x = mobilenet_v2.normalize_uint8(x, compute_dtype)
    else:
        x = x.astype(compute_dtype)
    if compute_dtype != jnp.float32:
        params = nn.cast_params(params, compute_dtype)
    y = nn.relu6(
        nn.batch_norm(nn.conv2d(x, params["stem"]["w"], stride=2), params["stem"]["bn"], train)
    )
    for blk, (_, stride) in zip(params["blocks"], _V1_BLOCKS):
        y = nn.sep_conv(y, blk, stride=stride, train=train)
    return (
        _head(y, params["heatmap"]).astype(jnp.float32),
        _head(y, params["offsets"]).astype(jnp.float32),
        _head(y, params["disp_fwd"]).astype(jnp.float32),
        _head(y, params["disp_bwd"]).astype(jnp.float32),
    )
