"""Post-training int8 quantization for the flagship conv family.

The reference's canonical model is mobilenet_v2_1.0_224_quant.tflite — a
*quantized* network executed by TFLite's int8 kernels
(tensor_filter_tensorflow_lite.cc). The TPU-native equivalent is not a
tflite-flatbuffer interpreter but an int8 compute path on the MXU: v5e/v6e
run s8×s8→s32 matmuls at 2× the bf16 rate, so the win lands exactly where
the FLOPs are.

Design (TPU-first, not a tflite emulation):
- **BN folding**: conv+batchnorm collapse to conv+bias before quantizing
  (standard inference transform; the tflite converter does the same).
- **Weights**: per-output-channel symmetric int8 (scale = maxabs/127).
- **Activations**: per-tensor symmetric int8, scales calibrated by running
  sample batches through the folded fp32 model and recording maxabs at
  every quantization point.
- **What gets int8**: the 1×1 convs (expand/project/head — ~95% of
  MobileNet FLOPs) lowered as ``lax.dot_general`` s8×s8→s32, the form XLA
  maps straight onto the MXU. Depthwise 3×3 and the stem stay float:
  depthwise convs run on the VPU where int8 buys nothing, and keeping them
  float avoids requant noise for <5% of FLOPs. This split is the *point*
  of a TPU redesign — quantize where the systolic array pays, not
  everywhere the wire format demands.

Everything stays one XLA program: quant/requant are elementwise ops fused
into the surrounding convs by the compiler.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp

from nnstreamer_tpu.models import nn
from nnstreamer_tpu.models.mobilenet_v2 import (
    _block_strides,
    normalize_uint8,
)


def fold_bn(w, bn: Dict, eps: float = 1e-3) -> Tuple[jax.Array, jax.Array]:
    """conv(w) → batch_norm(bn) ≡ conv(w·inv) + b  (inference moments)."""
    inv = bn["scale"] * jax.lax.rsqrt(bn["var"] + eps)  # [cout]
    return w * inv, bn["bias"] - bn["mean"] * inv


def fold_mobilenet(params: Dict) -> Dict:
    """Fold every conv+BN pair of a MobileNet-v2 params tree into (w, b)."""
    out: Dict = {}
    out["stem"] = dict(zip(("w", "b"), fold_bn(params["stem"]["w"], params["stem"]["bn"])))
    blocks = []
    for blk in params["blocks"]:
        fb: Dict = {}
        for part in ("expand", "dw", "project"):
            if part in blk:
                fb[part] = dict(zip(("w", "b"), fold_bn(blk[part]["w"], blk[part]["bn"])))
        blocks.append(fb)
    out["blocks"] = blocks
    out["head"] = dict(zip(("w", "b"), fold_bn(params["head"]["w"], params["head"]["bn"])))
    out["classifier"] = params["classifier"]
    return out


def _conv1x1(x, w):
    """1×1 conv as a channel contraction (float path)."""
    return jax.lax.dot_general(x, w[0, 0], (((x.ndim - 1,), (0,)), ((), ())))


def _folded_forward(folded: Dict, x, collect: List):
    """fp32 forward of the folded model, appending the maxabs of every
    quantization-point input to ``collect`` (the calibration taps)."""
    y = nn.relu6(nn.conv2d(x, folded["stem"]["w"], stride=2) + folded["stem"]["b"])
    for blk, stride in zip(folded["blocks"], _block_strides()):
        r = y
        if "expand" in blk:
            collect.append(jnp.max(jnp.abs(y)))
            y = nn.relu6(_conv1x1(y, blk["expand"]["w"]) + blk["expand"]["b"])
        y = nn.relu6(
            nn.conv2d(y, blk["dw"]["w"], stride=stride, groups=y.shape[-1])
            + blk["dw"]["b"]
        )
        collect.append(jnp.max(jnp.abs(y)))
        y = _conv1x1(y, blk["project"]["w"]) + blk["project"]["b"]
        if stride == 1 and y.shape[-1] == r.shape[-1]:
            y = y + r
    collect.append(jnp.max(jnp.abs(y)))
    y = nn.relu6(_conv1x1(y, folded["head"]["w"]) + folded["head"]["b"])
    return y


def calibrate_mobilenet(folded: Dict, batches) -> jax.Array:
    """Run uint8 sample batches through the folded fp32 model; return the
    per-quant-point activation scales [n_points] (maxabs/127)."""

    @jax.jit
    def taps_of(img):
        collect: List = []
        _folded_forward(folded, normalize_uint8(img), collect)
        return jnp.stack(collect)

    maxes = None
    for img in batches:
        t = taps_of(img)
        maxes = t if maxes is None else jnp.maximum(maxes, t)
    if maxes is None:
        raise ValueError("calibrate_mobilenet: need at least one calibration batch")
    return jnp.maximum(maxes, 1e-6) / 127.0


def _quantize_w(w) -> Tuple[jax.Array, jax.Array]:
    """Per-output-channel symmetric int8 for a 1×1 conv kernel [1,1,I,O]."""
    m = jnp.maximum(jnp.max(jnp.abs(w), axis=(0, 1, 2)), 1e-8)  # [O]
    scale = m / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q[0, 0], scale  # [I,O], [O]


def quantize_mobilenet(folded: Dict, act_scales) -> Dict:
    """Folded fp32 tree → int8 serving tree (1×1 convs quantized)."""
    q: Dict = {"stem": folded["stem"], "classifier": folded["classifier"]}
    idx = 0
    blocks = []
    for blk in folded["blocks"]:
        qb: Dict = {"dw": blk["dw"]}
        if "expand" in blk:
            w8, sw = _quantize_w(blk["expand"]["w"])
            qb["expand"] = {
                "w8": w8, "wscale": sw, "b": blk["expand"]["b"],
                "ascale": act_scales[idx],
            }
            idx += 1
        w8, sw = _quantize_w(blk["project"]["w"])
        qb["project"] = {
            "w8": w8, "wscale": sw, "b": blk["project"]["b"],
            "ascale": act_scales[idx],
        }
        idx += 1
        blocks.append(qb)
    q["blocks"] = blocks
    w8, sw = _quantize_w(folded["head"]["w"])
    q["head"] = {
        "w8": w8, "wscale": sw, "b": folded["head"]["b"],
        "ascale": act_scales[idx],
    }
    return q


# -- weight-only int8 for the conv family (fused dequant epilogue) ---------

def quantize_mobilenet_weights(folded: Dict) -> Dict:
    """Folded fp32 tree → weight-only int8 serving tree: the 1×1 conv
    kernels stored int8 with per-output-channel scales, NO activation
    quantization (so no calibration pass). Served by
    :func:`apply_int8w`: the dequant (w8·scale) runs as a fused epilogue
    at the matmul operand inside the XLA segment — int8 weights are the
    HBM-resident form (¼ the weight traffic of f32), float never leaves
    the device, and the per-activation round/clip/cast of
    :func:`apply_int8` disappears. This is the configuration that makes
    int8 *win* on the microbatch cell instead of trailing fp
    (ROADMAP item 4; docs/on-device-ops.md)."""
    q: Dict = {"stem": folded["stem"], "classifier": folded["classifier"]}
    blocks = []
    for blk in folded["blocks"]:
        qb: Dict = {"dw": blk["dw"]}
        for part in ("expand", "project"):
            if part in blk:
                w8, sw = _quantize_w(blk[part]["w"])
                qb[part] = {"w8": w8, "wscale": sw, "b": blk[part]["b"]}
        blocks.append(qb)
    q["blocks"] = blocks
    w8, sw = _quantize_w(folded["head"]["w"])
    q["head"] = {"w8": w8, "wscale": sw, "b": folded["head"]["b"]}
    return q


def dequantize_w(w8, wscale):
    """Host/jnp reference of the fused dequant epilogue: int8 [I, O] ×
    per-channel scale [O] → fp32 [1, 1, I, O] conv kernel. The parity
    test pins apply_int8w against a float forward over these."""
    return (w8.astype(jnp.float32) * wscale)[None, None]


def _wo_conv1x1(x, qc: Dict):
    """1×1 conv over int8 weights: dequantize at the operand read —
    XLA fuses the elementwise ``w8·scale`` into the dot's prologue, so
    the weights stream from HBM as int8 and widen on-chip."""
    w = (qc["w8"].astype(jnp.float32) * qc["wscale"]).astype(x.dtype)
    return jax.lax.dot_general(
        x, w, (((x.ndim - 1,), (0,)), ((), ()))
    ) + qc["b"].astype(x.dtype)


def apply_int8w(qparams: Dict, x, compute_dtype=jnp.float32):
    """uint8 NHWC batch → logits [N, classes]; weight-only int8 with
    the fused on-device dequant epilogue (quantize_mobilenet_weights).
    Float structure identical to the fp forward — the parity bar is
    quantization error only, not path divergence."""
    if x.dtype == jnp.uint8:
        x = normalize_uint8(x, compute_dtype)
    else:
        x = x.astype(compute_dtype)

    def w(a):
        return a.astype(compute_dtype)

    y = nn.relu6(
        nn.conv2d(x, w(qparams["stem"]["w"]), stride=2) + w(qparams["stem"]["b"])
    )
    for blk, stride in zip(qparams["blocks"], _block_strides()):
        r = y
        if "expand" in blk:
            y = nn.relu6(_wo_conv1x1(y, blk["expand"]))
        y = nn.relu6(
            nn.conv2d(y, w(blk["dw"]["w"]), stride=stride, groups=y.shape[-1])
            + w(blk["dw"]["b"])
        )
        y = _wo_conv1x1(y, blk["project"])
        if stride == 1 and y.shape[-1] == r.shape[-1]:
            y = y + r
    y = nn.relu6(_wo_conv1x1(y, qparams["head"]))
    y = jnp.mean(y.astype(jnp.float32), axis=(1, 2))
    return nn.dense(y, qparams["classifier"]).astype(jnp.float32)


# -- weight-only int8 for the transformer family --------------------------

_LM_QUANT_KEYS = ("wqkv", "wo", "w_gate", "w_up", "w_down")


def quantize_weight(w) -> Dict:
    """Per-output-channel symmetric int8 over the contraction axis
    ([…, cin, cout] → scale […, 1, cout]). Consumed by transformer.wt(),
    which dequantizes at the matmul operand."""
    m = jnp.maximum(jnp.max(jnp.abs(w), axis=-2, keepdims=True), 1e-8)
    scale = m / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return {"w8": q, "scale": scale}


def quantize_lm_weights(params: Dict) -> Dict:
    """Weight-only int8 for a transformer LM params tree (models/
    transformer.py layout, stacked [L,…] block leaves). Norms stay f32.

    This is the *decode* lever: autoregressive generation reads every
    weight once per token, so tok/s follows bytes/weight — int8 weights
    are 4× less HBM traffic than f32 (2× vs bf16) with no change to the
    compute path (dequant fuses into the dot's operand read). The
    reference's analogue is serving quantized .tflite models."""
    out = dict(params)
    blocks = dict(params["blocks"])
    for k in _LM_QUANT_KEYS:
        blocks[k] = quantize_weight(blocks[k])
    out["blocks"] = blocks
    out["embed"] = quantize_weight(params["embed"])
    out["head"] = quantize_weight(params["head"])
    return out


def _q_conv1x1(x, qc: Dict):
    """Quantize the activation, contract s8×s8→s32 on the MXU, dequantize.
    The quant/dequant elementwise ops fuse into the dot's prologue/epilogue.
    Quant/dequant math runs in f32 regardless of the carry dtype (scales
    stay exact); the result is cast back to the carry dtype."""
    ascale = qc["ascale"].astype(jnp.float32)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / ascale), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        q, qc["w8"], (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    out = acc.astype(jnp.float32) * (ascale * qc["wscale"]) + qc["b"]
    return out.astype(x.dtype)


def apply_int8(qparams: Dict, x, compute_dtype=jnp.float32):
    """uint8 NHWC batch → logits [N, classes], 1×1 convs in int8.

    ``compute_dtype`` governs the float remainder (stem, depthwise convs,
    pool/classifier); params and quantization scales stay f32 — weights
    are cast at trace time, which XLA constant-folds."""
    if x.dtype == jnp.uint8:
        x = normalize_uint8(x, compute_dtype)
    else:
        x = x.astype(compute_dtype)

    def w(a):
        return a.astype(compute_dtype)

    y = nn.relu6(
        nn.conv2d(x, w(qparams["stem"]["w"]), stride=2) + w(qparams["stem"]["b"])
    )
    for blk, stride in zip(qparams["blocks"], _block_strides()):
        r = y
        if "expand" in blk:
            y = nn.relu6(_q_conv1x1(y, blk["expand"]))
        y = nn.relu6(
            nn.conv2d(y, w(blk["dw"]["w"]), stride=stride, groups=y.shape[-1])
            + w(blk["dw"]["b"])
        )
        y = _q_conv1x1(y, blk["project"])
        if stride == 1 and y.shape[-1] == r.shape[-1]:
            y = y + r
    y = nn.relu6(_q_conv1x1(y, qparams["head"]))
    y = jnp.mean(y.astype(jnp.float32), axis=(1, 2))
    return nn.dense(y, qparams["classifier"]).astype(jnp.float32)
