"""MobileNet-v2 (1001-class, 224x224) — the flagship benchmark model.

The reference treats mobilenet_v2_1.0_224_quant.tflite as its canonical test
model (tests/test_models/models/, used by the image-labeling example and the
BASELINE.md north-star pipeline). This is a from-scratch jnp implementation
of the same architecture (Sandler et al. 2018, arXiv:1801.04381): stem conv
+ 17 inverted-residual bottlenecks (expansion/depthwise/projection) + 1x1
conv to 1280 + global average pool + classifier; ReLU6 activations; NHWC.

Model fn signature: ``fn(image_uint8_nhwc) -> logits[f32 N,1001]`` with
normalization fused in, so a pipeline can feed raw uint8 frames and the
whole pre+model graph compiles to one XLA program.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from nnstreamer_tpu.models import nn

# (expansion t, out channels c, repeats n, first stride s) — table 2 of the
# paper; matches the reference tflite model topology.
_INVERTED_RESIDUAL_CFG: Tuple[Tuple[int, int, int, int], ...] = (
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)


def _make_divisible(v: float, divisor: int = 8) -> int:
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def init_params(
    key, num_classes: int = 1001, width: float = 1.0
) -> Dict:
    keys = iter(jax.random.split(key, 64))
    p: Dict = {}
    c_stem = _make_divisible(32 * width)
    p["stem"] = {"w": nn.init_conv(next(keys), 3, 3, 3, c_stem), "bn": nn.init_bn(c_stem)}
    cin = c_stem
    blocks = []
    for t, c, n, s in _INVERTED_RESIDUAL_CFG:
        cout = _make_divisible(c * width)
        for i in range(n):
            stride = s if i == 0 else 1
            hidden = cin * t
            blk: Dict = {}
            if t != 1:
                blk["expand"] = {
                    "w": nn.init_conv(next(keys), 1, 1, cin, hidden),
                    "bn": nn.init_bn(hidden),
                }
            blk["dw"] = {
                "w": nn.init_conv(next(keys), 3, 3, hidden, hidden, groups=hidden),
                "bn": nn.init_bn(hidden),
            }
            blk["project"] = {
                "w": nn.init_conv(next(keys), 1, 1, hidden, cout),
                "bn": nn.init_bn(cout),
            }
            blocks.append(blk)
            cin = cout
    p["blocks"] = blocks
    c_head = _make_divisible(1280 * width) if width > 1.0 else 1280
    p["head"] = {"w": nn.init_conv(next(keys), 1, 1, cin, c_head), "bn": nn.init_bn(c_head)}
    p["classifier"] = nn.init_dense(next(keys), c_head, num_classes)
    return p


def load_tflite_params(path: str) -> Dict:
    """Import the reference's pretrained weights into THIS from-scratch
    model (VERDICT r4 #2): walk mobilenet_v2_1.0_224_quant.tflite's conv
    ops in graph order (the same canonical order init_params builds),
    dequantize each weight/bias exactly off its integer grid, and fold
    the TFLite-fused biases in as identity batchnorms (scale chosen so
    nn.batch_norm's eps cancels: out = x + bias, exactly). The returned
    pytree drops into apply()/features() unchanged — proving the hand
    topology IS the reference network, not just shaped like it.

    The reference loads the same file through the TFLite interpreter
    (tensor_filter_tensorflow_lite.cc:154-218); here its weights run in
    the jnp model so the whole pre+net graph stays one XLA program."""
    import numpy as np

    from nnstreamer_tpu.tools.tflite_parse import parse

    m = parse(path)
    convs = iter(
        op for op in m.operators
        if op.name in ("CONV_2D", "DEPTHWISE_CONV_2D")
    )
    eps = 1e-3  # nn.batch_norm default

    def identity_bn(bias: np.ndarray) -> Dict:
        c = bias.shape[0]
        return {
            "scale": jnp.full((c,), float(np.sqrt(1.0 + eps)), jnp.float32),
            "bias": jnp.asarray(bias, jnp.float32),
            "mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32),
        }

    def bias_of(op, cout: int) -> np.ndarray:
        # -1 is tflite's missing-optional-input sentinel (python
        # negative indexing would silently grab the LAST tensor)
        if len(op.inputs) > 2 and op.inputs[2] >= 0:
            return m.tensors[op.inputs[2]].dequantized()
        return np.zeros((cout,), np.float32)

    def conv_entry(op, cin: int, cout: int, dw: bool = False) -> Dict:
        w = m.tensors[op.inputs[1]].dequantized()
        # tflite conv weights are [O,KH,KW,I], depthwise [1,KH,KW,C];
        # nn.conv2d wants HWIO (I=1 per group for depthwise)
        w = np.transpose(w, (1, 2, 0, 3) if dw else (1, 2, 3, 0))
        want = (1 if dw else cin, cout)
        if w.shape[-2:] != want:
            raise ValueError(
                f"{path}: conv channels {w.shape[-2:]} != {want} — "
                "not the mobilenet_v2(1.0) topology"
            )
        return {"w": jnp.asarray(w, jnp.float32),
                "bn": identity_bn(bias_of(op, w.shape[-1]))}

    try:
        cin = _make_divisible(32)
        p: Dict = {"stem": conv_entry(next(convs), 3, cin)}
        blocks = []
        for t, c, n, _ in _INVERTED_RESIDUAL_CFG:
            cout = _make_divisible(c)
            for _ in range(n):
                hidden = cin * t
                blk: Dict = {}
                if t != 1:
                    blk["expand"] = conv_entry(next(convs), cin, hidden)
                blk["dw"] = conv_entry(next(convs), hidden, hidden, dw=True)
                blk["project"] = conv_entry(next(convs), hidden, cout)
                blocks.append(blk)
                cin = cout
        p["blocks"] = blocks
        p["head"] = conv_entry(next(convs), cin, 1280)
        cls = next(convs)  # the 1x1 logits conv == our pooled dense
    except StopIteration:
        raise ValueError(
            f"{path}: conv walk ended early — not a mobilenet_v2(1.0) "
            "graph (wrong file or width multiplier)"
        ) from None
    w = m.tensors[cls.inputs[1]].dequantized()  # [1001,1,1,1280]
    p["classifier"] = {
        "w": jnp.asarray(w.reshape(w.shape[0], -1).T, jnp.float32),
        "b": jnp.asarray(bias_of(cls, w.shape[0]), jnp.float32),
    }
    leftover = next(convs, None)
    if leftover is not None:
        raise ValueError(
            f"{path}: {1 + sum(1 for _ in convs)} conv ops beyond the "
            "mobilenet_v2(1.0) topology — refusing a partial import"
        )
    t_in = m.tensors[m.inputs[0]]
    if t_in.quant is not None and t_in.quant.quantized:
        # the graph's own input grid replaces the generic 127.5 norm
        p["input_quant"] = {
            "scale": jnp.float32(t_in.quant.scale[0]),
            "zp": jnp.float32(
                t_in.quant.zero_point[0] if t_in.quant.zero_point.size
                else 0
            ),
        }
    return p


def _block_strides() -> Tuple[int, ...]:
    """Static per-block stride plan from the cfg table (params hold only
    arrays so the pytree is grad-able; the plan is trace-time static)."""
    strides = []
    for _, _, n, s in _INVERTED_RESIDUAL_CFG:
        strides.extend([s if i == 0 else 1 for i in range(n)])
    return tuple(strides)


def _block(x, blk: Dict, stride: int, train: bool):
    y = x
    if "expand" in blk:
        y = nn.relu6(nn.batch_norm(nn.conv2d(y, blk["expand"]["w"]), blk["expand"]["bn"], train))
    groups = y.shape[-1]
    y = nn.relu6(
        nn.batch_norm(
            nn.conv2d(y, blk["dw"]["w"], stride=stride, groups=groups),
            blk["dw"]["bn"],
            train,
        )
    )
    y = nn.batch_norm(nn.conv2d(y, blk["project"]["w"]), blk["project"]["bn"], train)
    # residual iff same spatial + channels (shape check is static at trace)
    if stride == 1 and y.shape[-1] == x.shape[-1]:
        y = y + x
    return y


def features(params: Dict, x, train: bool = False):
    """Backbone: normalized f32/bf16 NHWC → final 7x7x1280 feature map.
    Exposed separately for SSD/DeepLab heads."""
    y = nn.relu6(nn.batch_norm(
        nn.conv2d(x, params["stem"]["w"], stride=2), params["stem"]["bn"], train
    ))
    for blk, stride in zip(params["blocks"], _block_strides()):
        y = _block(y, blk, stride, train)
    y = nn.relu6(nn.batch_norm(nn.conv2d(y, params["head"]["w"]), params["head"]["bn"], train))
    return y


def normalize_uint8(x, compute_dtype=jnp.float32):
    """uint8 [0,255] → [-1,1] (the tflite mobilenet preprocessing; the
    reference pipeline does this in tensor_transform arithmetic mode)."""
    return (x.astype(compute_dtype) - 127.5) / 127.5


def apply(params: Dict, x, train: bool = False, compute_dtype=jnp.float32):
    """uint8/float NHWC image batch → logits [N, num_classes]."""
    if x.dtype == jnp.uint8:
        if "input_quant" in params:
            # imported tflite weights: normalize on the graph's own
            # input grid ((q - zp) * scale), not the generic 127.5
            iq = params["input_quant"]
            x = (x.astype(compute_dtype) - iq["zp"]) * iq["scale"]
        else:
            x = normalize_uint8(x, compute_dtype)
    else:
        x = x.astype(compute_dtype)
    params = nn.cast_params(params, compute_dtype) if compute_dtype != jnp.float32 else params
    y = features(params, x, train)
    y = jnp.mean(y, axis=(1, 2))  # global average pool
    logits = nn.dense(y, params["classifier"])
    return logits.astype(jnp.float32)
