"""KV-cache autoregressive decoding for the transformer LM family.

Streaming token generation is this framework's native ground (the
reference's recurrent analogue is tensor_repo feedback loops holding RNN
state across frames, tests/nnstreamer_repo_{rnn,lstm}): the KV cache is the
in-pipeline state, and both prefill and the per-token step are single XLA
programs with static shapes — the decode loop is a ``lax.scan`` over a
fixed budget, so generation jit-compiles once.

Layout: cache k/v are [L, B, max_len, H, Dh]; a scalar ``pos`` tracks the
fill level. Attention at each step runs over the full max_len with a
``<= pos`` mask (fixed shape; masked positions cost FLOPs but keep XLA
static — the standard TPU serving trade).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu.models import transformer as tfm


def init_cache(
    params: Dict, batch: int, max_len: int, n_heads: int, dtype=jnp.float32
) -> Tuple[jax.Array, jax.Array]:
    """Zeroed (k, v) cache [L, B, max_len, KV, Dh] (KV < H under GQA)."""
    L, d = params["blocks"]["ln1"].shape
    hd = d // n_heads
    kv = tfm.n_kv_heads_of(params["blocks"]["wqkv"], d, n_heads)
    shape = (L, batch, max_len, kv, hd)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def prefill(
    params: Dict,
    tokens,
    n_heads: int,
    max_len: int,
    ffn_fn: Optional[Callable] = None,
    compute_dtype=jnp.float32,
):
    """Run the prompt through the model once, filling the cache.

    tokens [B, T] (T ≤ max_len) → (logits [B, T, V], (cache_k, cache_v),
    pos=T)."""
    b, t = tokens.shape
    if t > max_len:
        raise ValueError(f"prompt length {t} > max_len {max_len}")
    x = tfm.embed_lookup(params["embed"], tokens, compute_dtype)
    positions = jnp.arange(t)
    x, (ks, vs) = tfm.apply_layers(
        params["blocks"], x, n_heads, positions, ffn_fn=ffn_fn, return_kv=True
    )
    x = tfm.rmsnorm(x, params["ln_f"])
    logits = (x @ tfm.wt(params["head"], x.dtype)).astype(jnp.float32)
    pad = max_len - t
    cache_k = jnp.pad(
        ks.astype(compute_dtype), ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0))
    )
    cache_v = jnp.pad(
        vs.astype(compute_dtype), ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0))
    )
    return logits, (cache_k, cache_v), jnp.asarray(t, jnp.int32)


def decode_step(
    params: Dict,
    token,
    pos,
    cache: Tuple[jax.Array, jax.Array],
    n_heads: int,
    ffn_fn: Optional[Callable] = None,
    compute_dtype=jnp.float32,
):
    """One token in, one distribution out.

    token [B] int32, pos scalar (number of tokens already cached) →
    (logits [B, V], cache', pos+1). This is exactly verify_chunk with a
    1-token chunk — one shared body keeps the plain and speculative
    decode paths identical by construction."""
    logits, cache, _ = verify_chunk(
        params, token[:, None], pos, cache, n_heads, ffn_fn, compute_dtype
    )
    return logits[:, 0], cache, pos + 1


def verify_chunk(
    params: Dict,
    tokens,
    pos,
    cache: Tuple[jax.Array, jax.Array],
    n_heads: int,
    ffn_fn: Optional[Callable] = None,
    compute_dtype=jnp.float32,
    return_logits: bool = True,
):
    """Score a k-token candidate chunk in ONE forward against the cache.

    tokens [B, k] int32 (candidates, e.g. a draft model's proposals), pos
    scalar (tokens already cached) → (logits [B, k, V] f32, cache', pos+k).
    Query i sits at absolute position pos+i and attends cache positions
    ≤ pos+i (causal within the chunk). The chunk's K/V are written at
    pos..pos+k-1; the caller rolls back rejected tokens by simply using a
    smaller ``pos`` afterwards — positions beyond the accepted point are
    overwritten before any mask can reach them (the same invariant the
    continuous batcher relies on). This is the speculative-decoding
    verify step (models/speculative.py).

    Precondition: pos + k ≤ max_len — dynamic_update_slice would clamp
    the start index and silently overwrite certified earlier positions.
    Checked here whenever ``pos`` is concrete (outside a trace)."""
    cache_k, cache_v = cache
    max_len = cache_k.shape[2]
    b, kk_len = tokens.shape
    if not isinstance(pos, jax.core.Tracer) and int(pos) + kk_len > max_len:
        raise ValueError(
            f"verify_chunk: pos({int(pos)}) + k({kk_len}) > max_len"
            f"({max_len}); KV cache would clamp and corrupt"
        )
    x = tfm.embed_lookup(params["embed"], tokens, compute_dtype)  # [B,k,D]
    positions = pos + jnp.arange(kk_len, dtype=jnp.int32)

    def body(carry, layer):
        x = carry
        blk, ck, cv = layer
        q, k, v = tfm.block_qkv(x, blk, n_heads, positions)  # k/v [B,k,KV,Dh]
        ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, pos, 0, 0))
        cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, pos, 0, 0))
        mask = (
            jnp.arange(max_len)[None, :] <= positions[:, None]
        )  # [k, max_len]
        o = tfm.cache_attention(q, ck, cv, mask[None])
        o = o.astype(x.dtype).reshape(b, kk_len, -1)
        x = x + o @ tfm.wt(blk["wo"], x.dtype)
        x = tfm.block_ffn(x, blk, ffn_fn)
        return x, (ck, cv)

    x, (cache_k, cache_v) = jax.lax.scan(
        body, x, (params["blocks"], cache_k, cache_v)
    )
    if not return_logits:
        # cache-advance only (chunked prefill's non-final buckets): skip
        # the ln_f + vocab-sized head projection, which dominates a
        # short chunk's FLOPs
        return None, (cache_k, cache_v), pos + kk_len
    x = tfm.rmsnorm(x, params["ln_f"])
    logits = (x @ tfm.wt(params["head"], x.dtype)).astype(jnp.float32)
    return logits, (cache_k, cache_v), pos + kk_len


def windowed_chunk(
    params: Dict,
    tokens,
    pos,
    valid_n,
    cache: Tuple[jax.Array, jax.Array],
    n_heads: int,
    ffn_fn: Optional[Callable] = None,
    compute_dtype=jnp.float32,
    return_logits: bool = True,
):
    """Advance a RING cache by one chunk with EXACT sliding-window
    semantics (windowed chunked prefill; Mistral-style rolling prefill).

    ``cache`` k/v are rings [L, B, W, KV, Dh] over the last W tokens (the
    layout batched_decode_step(windowed=True) consumes). tokens [B, k]
    start at absolute position ``pos``; only the first ``valid_n`` rows
    are real (the tail is pad — its writes are suppressed so live ring
    entries are never clobbered, and causal masking keeps it out of every
    valid query's key set). Returns (logits [B,k,V] or None, cache',
    pos + valid_n).

    Exactness: query i (absolute p = pos+i) must attend the previous
    W-1 tokens and itself — including ring entries the chunk itself is
    about to overwrite. So attention runs against the PRE-write ring
    concatenated with the chunk's fresh K/V, and the ring is updated
    after: ring slot s last held absolute position pos-1-d where
    d = (wp-1-s) mod W (wp = pos % W), attendable by query i iff written
    (d ≤ pos-1) and in-window (d ≤ W-2-i).

    Precondition: wp + k ≤ W — no mid-chunk ring wrap. Callers align
    chunk starts to bucket strides with W % bucket == 0 (checked when
    ``pos`` is concrete)."""
    cache_k, cache_v = cache
    W = cache_k.shape[2]
    b, k_len = tokens.shape
    if not isinstance(pos, jax.core.Tracer) and int(pos) % W + k_len > W:
        raise ValueError(
            f"windowed_chunk: chunk [{int(pos)}, {int(pos) + k_len}) wraps "
            f"the W={W} ring mid-chunk; align chunk starts to a bucket "
            "size that divides the window"
        )
    pos = jnp.asarray(pos, jnp.int32)
    valid_n = jnp.asarray(valid_n, jnp.int32)
    wp = pos % W
    x = tfm.embed_lookup(params["embed"], tokens, compute_dtype)
    positions = pos + jnp.arange(k_len, dtype=jnp.int32)
    row = jnp.arange(k_len, dtype=jnp.int32)
    d = (wp - 1 - jnp.arange(W, dtype=jnp.int32)) % W  # [W] steps behind
    ring_mask = d[None, :] <= jnp.minimum(pos - 1, W - 2 - row)[:, None]
    chunk_mask = row[None, :] <= row[:, None]  # causal (pad rows are
    # later rows, so no valid query ever attends one)
    mask = jnp.concatenate([ring_mask, chunk_mask], axis=1)  # [k, W+k]
    keep = (row < valid_n)[None, :, None, None]

    def body(carry, layer):
        x = carry
        blk, ck, cv = layer
        q, k, v = tfm.block_qkv(x, blk, n_heads, positions)
        o = tfm.cache_attention(
            q,
            jnp.concatenate([ck, k.astype(ck.dtype)], axis=1),
            jnp.concatenate([cv, v.astype(cv.dtype)], axis=1),
            mask[None],
        )
        # write the chunk into the ring (contiguous by precondition),
        # blending so pad rows keep the pre-chunk entries
        tail = ck.shape[2:]
        old_k = jax.lax.dynamic_slice(ck, (0, wp, 0, 0), (b, k_len) + tail)
        old_v = jax.lax.dynamic_slice(cv, (0, wp, 0, 0), (b, k_len) + tail)
        ck = jax.lax.dynamic_update_slice(
            ck, jnp.where(keep, k.astype(ck.dtype), old_k), (0, wp, 0, 0)
        )
        cv = jax.lax.dynamic_update_slice(
            cv, jnp.where(keep, v.astype(cv.dtype), old_v), (0, wp, 0, 0)
        )
        o = o.astype(x.dtype).reshape(b, k_len, -1)
        x = x + o @ tfm.wt(blk["wo"], x.dtype)
        x = tfm.block_ffn(x, blk, ffn_fn)
        return x, (ck, cv)

    x, (cache_k, cache_v) = jax.lax.scan(
        body, x, (params["blocks"], cache_k, cache_v)
    )
    if not return_logits:
        return None, (cache_k, cache_v), pos + valid_n
    x = tfm.rmsnorm(x, params["ln_f"])
    logits = (x @ tfm.wt(params["head"], x.dtype)).astype(jnp.float32)
    return logits, (cache_k, cache_v), pos + valid_n


def beam_search(
    params: Dict,
    prompt,
    n_heads: int,
    max_new_tokens: int,
    beam_width: int = 4,
    ffn_fn: Optional[Callable] = None,
    compute_dtype=jnp.float32,
):
    """Beam search over the KV-cache decode path.

    prompt [1, T] int32 → (tokens [1, max_new_tokens] int32 of the best
    beam, its total log-prob). The beams ARE the cache batch dim: one
    batched decode_step serves all beams per step, and beam reordering is
    a gather on the cache's slot axis — the same fixed-shape machinery as
    everything else, scanned over the token budget so the whole search is
    one compiled program. All beams decode the full budget (no EOS
    stopping), so scores compare directly; beam_width=1 reduces exactly
    to greedy generate()."""
    prompt = jnp.asarray(prompt, jnp.int32)
    b, t = prompt.shape
    if b != 1:
        raise ValueError("beam_search serves one stream (B=1)")
    W = beam_width
    max_len = t + max_new_tokens
    V = params["head"]["scale"].shape[-1] if isinstance(
        params["head"], dict
    ) else params["head"].shape[-1]

    logits, (ck, cv), pos = prefill(
        params, prompt, n_heads, max_len, ffn_fn, compute_dtype
    )
    # replicate the prompt cache across W beams
    ck = jnp.repeat(ck, W, axis=1)
    cv = jnp.repeat(cv, W, axis=1)
    lp0 = jax.nn.log_softmax(logits[0, -1])
    top0 = jax.lax.top_k(lp0, W)
    tok = top0[1].astype(jnp.int32)          # [W]
    scores = top0[0]                         # [W]

    def step(carry, _):
        tok, scores, ck, cv, pos = carry
        logits, (ck, cv), pos = decode_step(
            params, tok, pos, (ck, cv), n_heads, ffn_fn, compute_dtype
        )
        lp = jax.nn.log_softmax(logits, axis=-1)       # [W, V]
        cand = scores[:, None] + lp                    # [W, V]
        flat_scores, flat_idx = jax.lax.top_k(cand.reshape(-1), W)
        beam_idx = (flat_idx // V).astype(jnp.int32)   # parent beam
        tok = (flat_idx % V).astype(jnp.int32)
        # reorder the caches to follow the surviving beams
        ck = jnp.take(ck, beam_idx, axis=1)
        cv = jnp.take(cv, beam_idx, axis=1)
        return (tok, flat_scores, ck, cv, pos), (tok, beam_idx)

    (tok, scores, *_), (toks, parents) = jax.lax.scan(
        step, (tok, scores, ck, cv, pos), None, length=max_new_tokens - 1
    )

    # backtrack the best beam through the parent pointers (host side)
    toks = np.asarray(toks)          # [steps, W]
    parents = np.asarray(parents)    # [steps, W]
    scores = np.asarray(scores)
    beam = int(scores.argmax())
    seq = []
    for i in range(toks.shape[0] - 1, -1, -1):
        seq.append(int(toks[i, beam]))
        beam = int(parents[i, beam])
    seq.append(int(np.asarray(top0[1])[beam]))
    seq.reverse()
    return (
        jnp.asarray(np.asarray(seq, np.int32))[None, :],
        float(scores.max()),
    )


def generate(
    params: Dict,
    prompt,
    n_heads: int,
    max_new_tokens: int,
    max_len: Optional[int] = None,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    ffn_fn: Optional[Callable] = None,
    compute_dtype=jnp.float32,
):
    """Greedy (temperature=0) or sampled generation.

    prompt [B, T] int32 → tokens [B, max_new_tokens] int32. One prefill
    program + one scanned decode program; both compile once per shape."""
    b, t = prompt.shape
    max_len = max_len or (t + max_new_tokens)
    if max_len < t + max_new_tokens:
        # Too-small caches don't error downstream: dynamic_update_slice
        # clamps the write index, silently overwriting the last slot.
        raise ValueError(
            f"max_len={max_len} < prompt_len({t}) + max_new_tokens"
            f"({max_new_tokens}); KV cache would overflow"
        )
    if rng is None:
        rng = jax.random.PRNGKey(0)
    logits, cache, pos = prefill(
        params, prompt, n_heads, max_len, ffn_fn, compute_dtype
    )
    last = logits[:, -1]

    def pick(logits, key):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(key, logits / temperature, axis=-1).astype(
            jnp.int32
        )

    def step(carry, key):
        last_logits, cache, pos = carry
        tok = pick(last_logits, key)
        logits, cache, pos = decode_step(
            params, tok, pos, cache, n_heads, ffn_fn, compute_dtype
        )
        return (logits, cache, pos), tok

    keys = jax.random.split(rng, max_new_tokens)
    _, toks = jax.lax.scan(step, (last, cache, pos), keys)
    return toks.T  # [B, max_new_tokens]
