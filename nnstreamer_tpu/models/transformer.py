"""Decoder-only transformer LM — the long-context flagship family.

The reference has no sequence models (SURVEY.md §5.7); this family exists to
exercise the framework's genuinely-new long-context path: the attention is
pluggable, so the same params run dense (single chip), ring attention
(sequence-parallel over ICI, parallel/ring_attention.py), or Ulysses
(parallel/ulysses.py) — and the block stack is a *stacked* pytree (every
leaf carries a leading [n_layers] dim, consumed by ``lax.scan``), which is
what lets pipeline parallelism shard layers over a mesh axis by slicing one
array (parallel/pipeline_parallel.py).

Architecture: RMSNorm pre-norm, RoPE, multi-head attention, SwiGLU MLP,
tied-free output head. bfloat16 compute / float32 params by default on TPU.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from nnstreamer_tpu.parallel.ring_attention import dense_attention


def wt(w, dtype):
    """Weight fetch honoring weight-only int8 quantization
    (models/quantize.py quantize_lm_weights): a quantized weight is
    ``{"w8": int8 […, cout], "scale": f32 […broadcastable…]}`` and
    dequantizes at the matmul operand — autoregressive decode is
    HBM-bandwidth-bound, so halving/quartering the bytes per weight read
    is a direct tok/s lever on TPU."""
    if isinstance(w, dict) and "w8" in w:
        return w["w8"].astype(dtype) * w["scale"].astype(dtype)
    return w.astype(dtype)


def embed_lookup(embed, tokens, dtype):
    """Embedding row gather, quantization-aware (per-feature scales)."""
    if isinstance(embed, dict) and "w8" in embed:
        return embed["w8"][tokens].astype(dtype) * embed["scale"].astype(dtype)
    return embed[tokens].astype(dtype)


def rmsnorm(x, w, eps: float = 1e-6):
    # Normalize in f32, apply the (f32) weight in f32, THEN cast back —
    # casting before the weight multiply would promote bf16 x back to f32
    # (breaking scan carry dtypes and silently running the block matmuls
    # off the bf16 MXU path).
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * scale * w).astype(x.dtype)


def rope(x, positions, base: float = 10000.0):
    """Rotary embedding over the last dim. x [B,T,H,D]; positions is [T]
    (shared across the batch) or [B,T] (per-batch — the continuous-
    batching decode step, where every slot sits at a different depth)."""
    d = x.shape[-1]
    half = d // 2
    freqs = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    pos = positions.astype(jnp.float32)
    if pos.ndim == 1:
        pos = pos[None, :]  # [1, T] broadcasts over batch
    angles = pos[:, :, None] * freqs  # [B|1, T, half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    ).astype(x.dtype)


def _init_dense(key, cin, cout, scale=None):
    std = scale if scale is not None else math.sqrt(1.0 / cin)
    return jax.random.normal(key, (cin, cout), jnp.float32) * std


def init_params(
    key,
    vocab: int = 1024,
    d_model: int = 256,
    n_heads: int = 8,
    n_layers: int = 4,
    d_ff: Optional[int] = None,
    n_kv_heads: Optional[int] = None,
) -> Dict:
    """``n_kv_heads < n_heads`` = grouped-query attention: K/V project to
    fewer heads, each shared by n_heads/n_kv_heads query heads — the KV
    cache (decode's HBM footprint) shrinks by that factor. The kv head
    count is never passed around: block_qkv derives it from wqkv's width,
    so every consumer (prefill, decode, serving, speculative) supports
    GQA params transparently."""
    d_ff = d_ff or 4 * d_model
    kv = n_kv_heads or n_heads
    if n_heads % kv:
        raise ValueError(f"n_heads {n_heads} not divisible by n_kv_heads {kv}")
    hd = d_model // n_heads
    k = iter(jax.random.split(key, 8))
    L = n_layers

    def stack(init_one):
        keys = jax.random.split(next(k), L)
        return jax.vmap(init_one)(keys)

    blocks = {
        "ln1": jnp.ones((L, d_model), jnp.float32),
        "ln2": jnp.ones((L, d_model), jnp.float32),
        "wqkv": stack(
            lambda kk: _init_dense(kk, d_model, d_model + 2 * kv * hd)
        ),
        "wo": stack(lambda kk: _init_dense(kk, d_model, d_model)),
        "w_gate": stack(lambda kk: _init_dense(kk, d_model, d_ff)),
        "w_up": stack(lambda kk: _init_dense(kk, d_model, d_ff)),
        "w_down": stack(lambda kk: _init_dense(kk, d_ff, d_model)),
    }
    return {
        "embed": jax.random.normal(next(k), (vocab, d_model), jnp.float32) * 0.02,
        "blocks": blocks,
        "ln_f": jnp.ones((d_model,), jnp.float32),
        "head": _init_dense(next(k), d_model, vocab),
        # static metadata kept out of the grad path by being python ints
    }


def block_ffn(x, blk: Dict, ffn_fn: Optional[Callable] = None):
    """Post-attention half of a block: pre-norm + SwiGLU MLP (or MoE)."""
    y = rmsnorm(x, blk["ln2"])
    if ffn_fn is not None:
        return x + ffn_fn(y, blk).astype(x.dtype)
    gate = jax.nn.silu(y @ wt(blk["w_gate"], y.dtype))
    up = y @ wt(blk["w_up"], y.dtype)
    return x + (gate * up) @ wt(blk["w_down"], y.dtype)


def n_kv_heads_of(blk_wqkv, d_model: int, n_heads: int) -> int:
    """Derive the kv head count from the fused projection's width
    (d_model q columns + 2·kv·hd k/v columns)."""
    hd = d_model // n_heads
    total = blk_wqkv["w8"].shape[-1] if isinstance(blk_wqkv, dict) else blk_wqkv.shape[-1]
    return (total - d_model) // (2 * hd)


def repeat_kv(t, n_heads: int):
    """[B,T,KV,Dh] → [B,T,H,Dh]: expand grouped K/V heads for attention
    (each kv head serves n_heads/kv query heads)."""
    kv = t.shape[2]
    if kv == n_heads:
        return t
    return jnp.repeat(t, n_heads // kv, axis=2)


NEG_INF = -1e30


def cache_attention(q, ck, cv, mask):
    """Masked attention against a KV cache, GQA-aware without expansion.

    q [B,T,H,Dh], ck/cv [B,S,KV,Dh] (KV ≤ H), mask [B,T,S] bool (or
    broadcastable) → o [B,T,H,Dh] float32. Query heads fold into
    [KV, H/KV] groups and contract the compact cache directly — the
    decode hot loop streams KV-head-sized tensors, which is the entire
    point of a grouped cache (an explicit repeat_kv here would
    re-materialize the H-head copy every step and layer)."""
    b, t, h, hd = q.shape
    kv = ck.shape[2]
    g = h // kv
    q5 = q.astype(jnp.float32).reshape(b, t, kv, g, hd)
    s = jnp.einsum(
        "btkgd,bskd->bkgts", q5, ck.astype(jnp.float32)
    ) / (hd ** 0.5)
    s = jnp.where(mask[:, None, None, :, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgts,bskd->btkgd", p, cv.astype(jnp.float32))
    return o.reshape(b, t, h, hd)


def block_qkv(x, blk: Dict, n_heads: int, positions):
    """Pre-norm + qkv projection + RoPE → q [B,T,H,Dh], k/v [B,T,KV,Dh]
    (KV ≤ H under grouped-query attention; KV == H otherwise)."""
    b, t, d = x.shape
    h = n_heads
    hd = d // h
    kv = n_kv_heads_of(blk["wqkv"], d, h)
    y = rmsnorm(x, blk["ln1"])
    qkv = y @ wt(blk["wqkv"], y.dtype)
    q = qkv[..., :d]
    kk, v = jnp.split(qkv[..., d:], 2, axis=-1)
    q = rope(q.reshape(b, t, h, hd), positions)
    kk = rope(kk.reshape(b, t, kv, hd), positions)
    return q, kk, v.reshape(b, t, kv, hd)


def block_apply(
    x,
    blk: Dict,
    n_heads: int,
    positions,
    attn_fn: Optional[Callable] = None,
    ffn_fn: Optional[Callable] = None,
    return_kv: bool = False,
    causal: bool = True,
):
    """One transformer block. blk leaves are per-layer (no leading L dim).
    attn_fn(q, k, v, causal) → [B,T,H,D] float32;
    ffn_fn(x_normed, blk) → [B,T,D] overrides the SwiGLU MLP (MoE hook);
    return_kv=True additionally returns this layer's (k, v) — the prefill
    path of the KV-cache decoder (models/decode.py). causal=False turns
    the block into an encoder block (ViT)."""
    attn = attn_fn or dense_attention
    b, t, d = x.shape
    q, kk, v = block_qkv(x, blk, n_heads, positions)
    o = attn(
        q, repeat_kv(kk, n_heads), repeat_kv(v, n_heads), causal=causal
    ).astype(x.dtype)
    x = x + o.reshape(b, t, d) @ wt(blk["wo"], x.dtype)
    x = block_ffn(x, blk, ffn_fn)
    if return_kv:
        return x, (kk, v)
    return x


def apply_layers(
    blocks: Dict,
    x,
    n_heads: int,
    positions,
    attn_fn: Optional[Callable] = None,
    ffn_fn: Optional[Callable] = None,
    return_kv: bool = False,
    causal: bool = True,
):
    """Run a stacked block pytree (leaves [L, ...]) via lax.scan — one
    compiled block body regardless of depth; pipeline stages call this on
    their layer slice. return_kv=True also returns stacked per-layer
    (k, v) [L,B,T,H,Dh] for KV-cache prefill."""

    def body(carry, blk):
        out = block_apply(
            carry, blk, n_heads, positions, attn_fn, ffn_fn, return_kv, causal
        )
        if return_kv:
            return out[0], out[1]
        return out, None

    out, kv = jax.lax.scan(body, x, blocks)
    if return_kv:
        return out, kv
    return out


def apply(
    params: Dict,
    tokens,
    n_heads: int,
    attn_fn: Optional[Callable] = None,
    ffn_fn: Optional[Callable] = None,
    compute_dtype=jnp.float32,
    positions=None,
):
    """tokens [B, T] int32 → logits [B, T, vocab] float32.

    ``positions`` [T] overrides the default arange — REQUIRED when tokens
    are a sequence shard (sequence parallelism): RoPE needs the *global*
    position of each token, so shard i of width Tl passes
    ``i*Tl + arange(Tl)``."""
    x = embed_lookup(params["embed"], tokens, compute_dtype)
    if positions is None:
        positions = jnp.arange(tokens.shape[1])
    x = apply_layers(params["blocks"], x, n_heads, positions, attn_fn, ffn_fn)
    x = rmsnorm(x, params["ln_f"])
    return (x @ wt(params["head"], x.dtype)).astype(jnp.float32)
