"""Logging shim (reference: gst/nnstreamer/nnstreamer_log.{c,h}).

The reference maps ml_log{i,w,e,d,f} onto platform loggers and attaches C
backtraces on fatal paths (nnstreamer_log.c:29-45). Here: stdlib logging
with one framework-wide logger tree and a fatal helper that captures the
Python traceback.
"""

from __future__ import annotations

import logging
import os
import traceback

_ROOT = logging.getLogger("nnstreamer_tpu")
if not _ROOT.handlers:
    _h = logging.StreamHandler()
    _h.setFormatter(
        logging.Formatter("%(asctime)s %(name)s %(levelname).1s: %(message)s")
    )
    _ROOT.addHandler(_h)
    _ROOT.setLevel(os.environ.get("NNS_TPU_LOG_LEVEL", "WARNING").upper())


def get_logger(name: str = "") -> logging.Logger:
    return _ROOT.getChild(name) if name else _ROOT


def logf_stacktrace(logger: logging.Logger, msg: str, *args) -> None:
    """Fatal log with stack trace (ml_logf_stacktrace analogue)."""
    logger.critical(msg, *args)
    logger.critical("".join(traceback.format_stack()))
