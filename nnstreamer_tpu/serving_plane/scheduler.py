"""Weighted-fair cross-stream scheduling for the serving plane.

A :class:`ModelPlane` collects frames from N client streams into one
device batch. Plain drain-in-arrival-order would let one hot stream
(a client flooding its queue) fill every batch while trickle streams
wait unboundedly — the starvation mode PR 6 solved for the query server
with weighted-fair round-robin over clients (edge/admission.py). This
module is the same discipline one layer down, at the device batcher:

- :class:`PlaneStream` — one attached client stream: a FIFO of pending
  requests plus a weight and the DRR deficit counter.
- :class:`StreamScheduler` — deficit-round-robin collection: each
  collection round credits every backlogged stream ``weight`` slots and
  takes frames while credit lasts, rotating the start stream so no
  stream is structurally first. Consequences the tests pin down:

  * per-stream FIFO: a stream's frames enter batches in submission
    order (each queue pops left);
  * starvation bound: a backlogged stream with weight ``w`` receives at
    least ``floor(w)`` of every ``sum(ceil(weights))``-slot collection
    cycle, no matter how deep another stream's backlog is;
  * work conservation: when only one stream is backlogged it gets the
    whole batch (drain-what's-there, the batching.py discipline).

Callers hold the plane lock around :meth:`StreamScheduler.collect`
(single collector, many submitters); the scheduler itself takes no
locks.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Tuple


class PlaneStream:
    """One client stream attached to a plane.

    Counter discipline: ``admitted`` is incremented by the submitting
    executor thread under the plane lock; ``served``/``errors`` only by
    the plane's service thread. Readers (stats, nns-top) get GIL-atomic
    snapshot reads, the BatchStats convention.
    """

    __slots__ = ("sid", "weight", "deficit", "q", "admitted", "served",
                 "errors", "inflight", "_admit_ctr", "_serve_ctr")

    def __init__(self, sid: str, weight: float = 1.0) -> None:
        self.sid = sid
        self.weight = max(0.01, float(weight))
        self.deficit = 0.0
        self.q: deque = deque()
        self.admitted = 0
        self.served = 0
        self.errors = 0
        # async tickets outstanding (submitted, not yet collected by the
        # stream's wait_window) — inc under the plane lock at submit,
        # dec at wait-side resolution; 0 under blocking submits between
        # round trips
        self.inflight = 0
        # nns-obs counter handles, wired by the plane when metrics are on
        self._admit_ctr = None
        self._serve_ctr = None

    @property
    def backlog(self) -> int:
        return len(self.q)

    def snapshot(self) -> Dict[str, Any]:
        return {
            "weight": self.weight,
            "queued": sum(
                len(getattr(r, "frames", (None,))) for r in self.q
            ),
            "admitted": self.admitted,
            "served": self.served,
            "errors": self.errors,
            "inflight": self.inflight,
        }


class StreamScheduler:
    """Deficit round robin over the attached streams (module docstring
    has the contract)."""

    def __init__(self) -> None:
        self._streams: Dict[str, PlaneStream] = {}
        self._rr = 0  # rotation cursor: collection start stream

    # -- membership (plane lock held) --------------------------------------
    def add(self, stream: PlaneStream) -> None:
        if stream.sid in self._streams:
            raise ValueError(f"stream {stream.sid!r} already attached")
        self._streams[stream.sid] = stream

    def remove(self, stream: PlaneStream) -> List[Any]:
        """Detach; returns the stream's still-queued requests so the
        plane can complete them (closed-stream disposal, never silent
        loss)."""
        self._streams.pop(stream.sid, None)
        pending = list(stream.q)
        stream.q.clear()
        return pending

    def streams(self) -> List[PlaneStream]:
        return list(self._streams.values())

    def __len__(self) -> int:
        return len(self._streams)

    @property
    def backlog(self) -> int:
        """Total queued-but-undispatched FRAMES across streams (the
        cross-stream queue depth metric; requests are windows)."""
        return sum(
            len(getattr(r, "frames", (None,)))
            for s in self._streams.values() for r in s.q
        )

    # -- collection (plane lock held) --------------------------------------
    def _rotation(self) -> List[PlaneStream]:
        streams = list(self._streams.values())
        if not streams:
            return streams
        start = self._rr % len(streams)
        self._rr += 1
        return streams[start:] + streams[:start]

    def collect(self, limit: int) -> List[Tuple[PlaneStream, Any]]:
        """Pop requests weighted-fairly across backlogged streams until
        ``limit`` FRAMES are collected; [] when nothing is queued.
        Requests are windows (1..k frames — the submitting executor's
        local micro-batch); a request is atomic, so collection stops
        before a window that would overflow the limit (always taking at
        least one). Fairness is accounted per request — a stream's
        window size reflects its own backlog, its SLOTS are what the
        weights bound. Never blocks."""
        batch: List[Tuple[PlaneStream, Any]] = []
        frames = 0
        if limit <= 0:
            return batch
        rotation = self._rotation()
        full = False
        while not full:
            progressed = False
            for s in rotation:
                if not s.q:
                    continue
                s.deficit += s.weight
                while s.deficit >= 1.0 and s.q:
                    cost = len(getattr(s.q[0], "frames", (None,)))
                    if batch and frames + cost > limit:
                        full = True
                        break
                    batch.append((s, s.q.popleft()))
                    frames += cost
                    s.deficit -= 1.0
                    progressed = True
                    if frames >= limit:
                        full = True
                        break
                if full:
                    break
            if not progressed and not any(s.q for s in rotation):
                break
            # an unprogressed round with backlog means every deficit is
            # still fractional (weights < 1): keep crediting until one
            # crosses 1 — standard DRR cycles rounds until the batch
            # fills or the queues drain, so weights scale RELATIVE
            # share, never absolute pacing (a lone weight-0.1 stream
            # still fills the whole batch). Bounded: each round adds
            # ≥ 0.01 to every backlogged deficit.
        for s in rotation:
            if not s.q:
                # no banked credit: an idle stream must not burst-claim
                # a whole future batch the moment it wakes up
                s.deficit = 0.0
        return batch
