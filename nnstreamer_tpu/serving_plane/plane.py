"""ModelPlane: a process-wide shared device batcher per model.

PR 2 batched frames *within* one stream segment; PR 8 made one stream
fast on one device. A plane batches *across* executors: every
``tensor_filter plane=<name>`` in the process — across pipelines,
across client sessions — attaches as one stream, and a single service
thread continuously collects a weighted-fair cross-stream batch and
dispatches ONE device program for all of them (Hermes/StreamTensor's
shared-accelerator multiplexing, PAPERS.md). What each stream keeps:

- **FIFO order** — requests complete in per-stream submission order
  (the scheduler pops each stream's queue left-to-right, and a stream's
  executor thread submits — and, under async tickets, collects — in
  order).
- **Fault accounting** — a failed batch splits per frame, so only the
  failing frame's stream sees the error; it surfaces in THAT stream's
  executor as an ordinary invoke error, where the PR-3 FaultGate
  (drop/retry/route), PR-6 NACK/release, and PR-7 disposal semantics
  already live.
- **Deadline accounting** — expired frames are shed at the owning
  executor's dequeue (Node.shed_if_expired), before they ever occupy a
  plane slot; per-node ``deadline_shed`` counters stay per stream.

Memory: all sharers ride ONE opened backend (or K replicas /
one mesh-sharded instance) — the ``shared-tensor-filter-key`` weight
dedup, extended with an actual shared dispatch queue. nns-lint
NNS-W114 flags duplicate-model pipelines that use neither.

Lifecycle: the plane registry refcounts by attached filter; the first
:func:`acquire` opens the backend(s) and starts the service thread,
the last :func:`release` drains, closes, and joins it. Planes are
created at negotiation time (before executors start), so the service
thread predates any sanitizer thread-leak baseline.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.obs import metrics as obs_metrics
from nnstreamer_tpu.pipeline.batching import BatchStats
from nnstreamer_tpu.serving_plane.scheduler import PlaneStream, StreamScheduler

_log = get_logger("serving_plane")

PLANE_MODES = ("single", "shard", "replicas")


class PlaneClosedError(RuntimeError):
    """Submit/attach on a closed (or closing) plane."""


class PlaneConfigError(ValueError):
    """Plane name already bound to a different model/config signature."""


@dataclass(frozen=True)
class PlaneConfig:
    """Resolved knobs for one plane (first-attacher wins; the
    config signature guards against sharers disagreeing)."""

    max_batch: int = 8
    timeout_ms: float = 1.0
    mode: str = "single"
    devices: int = 1
    unhealthy_after: int = 3
    probe_every: int = 64
    submit_timeout_s: float = 30.0
    # default per-stream in-flight ring depth for ASYNC submits
    # (docs/serving-plane.md): 1 keeps the blocking submit discipline;
    # an element-level ring-depth= outranks it per stream, so it stays
    # out of signature() — sharers may legitimately differ
    inflight: int = 1

    def signature(self) -> tuple:
        return (
            self.max_batch, self.timeout_ms, self.mode, self.devices,
            self.unhealthy_after, self.probe_every,
        )


def _plane_defaults() -> Dict[str, Any]:
    """``[plane]`` config-section defaults (env ``NNS_TPU_PLANE_*``
    outranks ini, the standard layering). Malformed values fall back
    with a warning — a typo'd ini line must not fail every plane."""
    from nnstreamer_tpu.config import conf

    c = conf()

    def _num(key: str, cast, fallback):
        raw = c.get("plane", key, str(fallback))
        try:
            return cast(raw)
        except ValueError:
            _log.warning("[plane] %s=%r is not a valid %s; using %s",
                         key, raw, cast.__name__, fallback)
            return fallback

    mode = c.get("plane", "mode", "single").strip().lower()
    if mode not in PLANE_MODES:
        _log.warning("[plane] mode=%r unknown; using single", mode)
        mode = "single"
    return {
        "max_batch": _num("max_batch", int, 8),
        "timeout_ms": _num("timeout_ms", float, 1.0),
        "mode": mode,
        "devices": _num("devices", int, 1),
        "unhealthy_after": _num("unhealthy_after", int, 3),
        "probe_every": _num("probe_every", int, 64),
        "submit_timeout_s": _num("submit_timeout_s", float, 30.0),
        "inflight": _num("inflight", int, 1),
    }


def resolve_plane_config(elements) -> PlaneConfig:
    """Merge element-level ``plane-*`` properties over the ``[plane]``
    section defaults (the resolve_batch_config discipline: first
    element that sets a knob explicitly wins; bad values raise with the
    element named)."""
    d = _plane_defaults()

    def _coerce(elem, prop, fn, raw):
        try:
            return fn(raw)
        except (TypeError, ValueError) as exc:
            raise ValueError(
                f"{getattr(elem, 'name', elem)}: bad {prop}={raw!r}: {exc}"
            ) from exc

    for e in elements:
        get = getattr(e, "get_property", None)
        if get is None:
            continue
        raw = get("plane-max-batch")
        if raw is not None:
            d["max_batch"] = _coerce(e, "plane-max-batch", int, raw)
        raw = get("plane-timeout-ms")
        if raw is not None:
            d["timeout_ms"] = _coerce(e, "plane-timeout-ms", float, raw)
        raw = get("plane-mode")
        if raw is not None:
            mode = str(raw).strip().lower()
            if mode not in PLANE_MODES:
                raise ValueError(
                    f"{getattr(e, 'name', e)}: plane-mode={raw!r} not one "
                    f"of {'/'.join(PLANE_MODES)}"
                )
            d["mode"] = mode
        raw = get("plane-devices")
        if raw is not None:
            d["devices"] = _coerce(e, "plane-devices", int, raw)
    return PlaneConfig(
        max_batch=max(1, int(d["max_batch"])),
        timeout_ms=max(0.0, float(d["timeout_ms"])),
        mode=d["mode"],
        devices=max(1, int(d["devices"])),
        unhealthy_after=max(1, int(d["unhealthy_after"])),
        probe_every=max(1, int(d["probe_every"])),
        submit_timeout_s=max(0.1, float(d["submit_timeout_s"])),
        inflight=max(1, min(32, int(d["inflight"]))),
    )


class _Req:
    """One in-flight request: a WINDOW of 1..k same-stream frames plus
    its completion latch. Windows are the submitting executor's local
    micro-batch (TensorOpHostNode's collector), so one round-trip
    through the plane amortizes over the whole window — per-frame
    blocking submits would gate every stream on two thread wakes per
    frame."""

    __slots__ = ("frames", "out", "exc", "done", "abandoned", "ahead")

    def __init__(self, frames) -> None:
        self.frames = frames
        self.out: Optional[List[Tuple[Any, ...]]] = None
        self.exc: Optional[BaseException] = None
        self.done = threading.Event()
        # set by a timed-out submitter that gave up on an IN-FLIGHT
        # window: a recovering service thread must not credit `served`
        # for frames nobody waits on
        self.abandoned = False
        # windows of the SAME stream already in flight when this one was
        # submitted: the wait-side stall grant scales by it (a deep ring
        # legitimately waits several dispatches, but only while the
        # plane keeps making progress)
        self.ahead = 0


class ModelPlane:
    """The shared batcher (module docstring has the contract).

    Counter discipline: ``dispatches``/``frames``/``split_dispatches``
    and the BatchStats mutate only on the service thread; stream
    ``admitted`` mutates under the plane lock in :meth:`submit`.
    Readers snapshot GIL-atomically (the executor stats convention).
    """

    def __init__(
        self,
        name: str,
        cfg: PlaneConfig,
        backends: List[Any],
        program: Optional[Any] = None,
    ) -> None:
        self.name = name
        self.cfg = cfg
        self.backends = backends
        self._sched = StreamScheduler()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._stop_ev = threading.Event()
        # an explicit program (benchmarks, the MULTICHIP scaling cell,
        # tests) bypasses build_plane_program's backend-derived choice
        self._program = program
        self.batch_stats = BatchStats()
        self.dispatches = 0
        self.frames = 0
        self.split_dispatches = 0
        # total windows submitted-but-not-yet-collected across streams
        # (inc under the plane lock at submit, dec at wait-side
        # resolution — the async ring's live depth)
        self._inflight_total = 0
        self._metrics = obs_metrics.get()
        self._occ_hist = None
        self._depth_gauge = None
        self._inflight_gauge = None
        self._wait_hist = None
        if self._metrics is not None:
            self._occ_hist = self._metrics.histogram(
                "nns_plane_batch_occupancy", lo=1.0, growth=2.0 ** 0.5,
                nbuckets=16, plane=name,
            )
            self._depth_gauge = self._metrics.gauge(
                "nns_plane_queue_depth", plane=name
            )
            self._inflight_gauge = self._metrics.gauge(
                "nns_plane_inflight_windows", plane=name
            )
            self._wait_hist = self._metrics.histogram(
                "nns_plane_submit_wait_ms", plane=name
            )
        self._thread = threading.Thread(
            target=self._serve, name=f"nns-plane-{name}", daemon=True
        )
        self._thread.start()

    @property
    def backend(self):
        """Primary backend (negotiation/model-info surface for sharers;
        replica 0 under mode=replicas)."""
        return self.backends[0]

    # -- streams -----------------------------------------------------------
    def attach(self, sid: str, weight: float = 1.0) -> PlaneStream:
        with self._cond:
            if self._closed:
                raise PlaneClosedError(f"plane {self.name!r} is closed")
            s = PlaneStream(sid, weight)
            if self._metrics is not None:
                s._admit_ctr = self._metrics.counter(
                    "nns_plane_stream_admitted_total",
                    plane=self.name, stream=sid,
                )
                s._serve_ctr = self._metrics.counter(
                    "nns_plane_stream_served_total",
                    plane=self.name, stream=sid,
                )
            self._sched.add(s)
            return s

    def detach(self, stream: PlaneStream) -> None:
        with self._cond:
            pending = self._sched.remove(stream)
            # tickets the stream never redeemed (executor torn down
            # with windows parked in its ring) would inflate the
            # plane-wide in-flight counter forever — reconcile them
            # out with the leaving stream
            if stream.inflight > 0:
                self._inflight_total = max(
                    0, self._inflight_total - stream.inflight
                )
                stream.inflight = 0
                if self._inflight_gauge is not None:
                    self._inflight_gauge.set(self._inflight_total)
        for req in pending:
            # a detaching stream's queued frames get a terminal outcome,
            # never a silent hang (the PR-6 disposal discipline)
            req.exc = PlaneClosedError(
                f"stream {stream.sid!r} detached from plane "
                f"{self.name!r} with requests queued"
            )
            req.done.set()

    # -- submission (executor node threads) --------------------------------
    def submit_window_async(
        self, stream: PlaneStream, windows: List[Tuple[Any, ...]]
    ) -> _Req:
        """Enqueue one window of tensor tuples WITHOUT waiting: returns
        a ticket the submitter redeems with :meth:`wait_window` —
        strictly in submission order, which keeps per-stream FIFO
        structural exactly like the blocking path (the stream's
        executor thread is the only submitter AND the only collector).
        The stream's in-flight ring (docs/serving-plane.md) is the
        caller's: it holds up to ``ring-depth``/``[plane] inflight``
        tickets so window N+1 submits while N computes and N−1
        delivers."""
        req = _Req(windows)
        with self._cond:
            if self._closed:
                raise PlaneClosedError(f"plane {self.name!r} is closed")
            req.ahead = stream.inflight
            stream.q.append(req)
            stream.admitted += len(windows)
            stream.inflight += 1
            self._inflight_total += 1
            if stream._admit_ctr is not None:
                stream._admit_ctr.inc(len(windows))
            if self._inflight_gauge is not None:
                self._inflight_gauge.set(self._inflight_total)
            self._cond.notify_all()
        return req

    def wait_window(
        self, stream: PlaneStream, req: _Req
    ) -> List[Tuple[Any, ...]]:
        """Redeem a ticket: block until the plane serves (or fails) the
        window. Returns per-frame output tuples; raises the underlying
        invoke error for THIS window only — batchmates from other
        streams are unaffected.

        Stall discipline: while the window is still QUEUED the wait is
        one ``submit_timeout_s`` (then the request retracts, so a
        timed-out-and-retried window is never ALSO served later by a
        recovering service thread). Once IN FLIGHT the grant scales
        with the windows ahead of it at submit time — a depth-k ring
        legitimately waits k dispatches — but every grant past the
        first requires the plane to have DISPATCHED something since the
        last check: a wedged service thread surfaces after at most
        2×``submit_timeout_s`` regardless of ring depth, instead of the
        depth masking it."""
        t_wait0 = time.perf_counter()
        try:
            deadline = time.monotonic() + self.cfg.submit_timeout_s
            max_ext = 1 + max(0, req.ahead)
            extensions = 0
            last_dispatches = self.dispatches
            while not req.done.wait(0.05):
                if time.monotonic() < deadline:
                    continue
                with self._cond:
                    try:
                        stream.q.remove(req)
                        retracted = True
                    except ValueError:
                        retracted = False  # already collected: in flight
                if retracted:
                    raise PlaneClosedError(
                        f"plane {self.name!r}: no service within "
                        f"{self.cfg.submit_timeout_s}s (service thread "
                        "dead or program wedged)"
                    )
                progressed = self.dispatches != last_dispatches
                last_dispatches = self.dispatches
                if extensions == 0 or (
                    progressed and extensions < max_ext
                ):
                    # in flight: the dispatch may legitimately be slow
                    # (a cold compile, or windows ahead in the ring) —
                    # grant another full window, but past the first
                    # only while dispatches keep landing
                    extensions += 1
                    deadline = time.monotonic() + self.cfg.submit_timeout_s
                    continue
                req.abandoned = True
                raise PlaneClosedError(
                    f"plane {self.name!r}: in-flight window unserved "
                    f"after {(1 + extensions) * self.cfg.submit_timeout_s}"
                    "s without dispatch progress (program wedged)"
                )
        finally:
            with self._lock:
                # conditional: detach() may have already reconciled
                # this stream's tickets out of the totals — a late
                # waiter must not debit another stream's contribution
                if stream.inflight > 0:
                    stream.inflight -= 1
                    self._inflight_total = max(
                        0, self._inflight_total - 1
                    )
                    if self._inflight_gauge is not None:
                        self._inflight_gauge.set(self._inflight_total)
            if self._wait_hist is not None:
                self._wait_hist.observe(
                    (time.perf_counter() - t_wait0) * 1000.0
                )
        if req.exc is not None:
            raise req.exc
        return req.out

    def submit_window(
        self, stream: PlaneStream, windows: List[Tuple[Any, ...]]
    ) -> List[Tuple[Any, ...]]:
        """Blocking submit: one async ticket redeemed immediately (the
        ``inflight=1`` discipline; also the error-policy split's
        re-invoke unit)."""
        return self.wait_window(
            stream, self.submit_window_async(stream, windows)
        )

    def submit(self, stream: PlaneStream, frame):
        """Single-frame convenience over :meth:`submit_window` (the
        per-frame host path; also the error-policy split's re-invoke
        unit)."""
        (out,) = self.submit_window(stream, [frame.tensors])
        return frame.with_tensors(out)

    # -- service thread ----------------------------------------------------
    def _ensure_program(self):
        if self._program is None:
            from nnstreamer_tpu.serving_plane.sharding import (
                build_plane_program,
            )

            self._program = build_plane_program(self.backends, self.cfg)
        return self._program

    def _serve(self) -> None:
        cfg = self.cfg
        cond = self._cond
        while not self._stop_ev.is_set():
            t_wait0 = time.perf_counter()
            with cond:
                batch = self._sched.collect(cfg.max_batch)
                if not batch:
                    cond.wait(0.05)
                    continue
                got = sum(len(req.frames) for _s, req in batch)
                if got < cfg.max_batch and cfg.timeout_ms > 0.0:
                    # trickle-fed: ONE bounded straggler wait, then take
                    # what arrived (the BatchCollector discipline — a
                    # rolling wait would stretch worst-case latency).
                    # Under blocking-submit traffic the other streams'
                    # resubmissions land inside this window, so steady
                    # state dispatches full cross-stream batches.
                    cond.wait(cfg.timeout_ms / 1000.0)
                    batch += self._sched.collect(cfg.max_batch - got)
                depth = self._sched.backlog
            wait_s = time.perf_counter() - t_wait0
            self._dispatch(batch, depth, wait_s)

    def _dispatch(self, batch, depth: int, wait_s: float) -> None:
        # flatten the collected windows into ONE device batch; split
        # results back per request (per-stream order intact: requests
        # complete whole, and each stream's requests were popped FIFO)
        flat: List[Tuple[Any, ...]] = []
        for _s, req in batch:
            flat.extend(req.frames)
        try:
            program = self._ensure_program()
        except Exception as exc:  # noqa: BLE001 — no program at all:
            # the BUILD error is the real verdict for every window (a
            # split would just dereference the still-None program)
            for s, req in batch:
                req.exc = exc
                s.errors += len(req.frames)
                req.done.set()
            return
        try:
            outs = program.invoke(flat)
        except Exception as exc:  # noqa: BLE001 — split below, per window
            self._dispatch_split(batch, exc)
            return
        i = 0
        for s, req in batch:
            k = len(req.frames)
            self._complete(s, req, outs[i:i + k])
            i += k
        n = len(flat)
        self._account_dispatch(n)
        self.batch_stats.record(n, n, wait_s)
        if self._occ_hist is not None:
            self._occ_hist.observe(n)
        if self._depth_gauge is not None:
            self._depth_gauge.set(depth)

    def _account_dispatch(self, n: int) -> None:
        """The ONE place the dispatch counters mutate — the service
        thread is the only caller (single-writer contract; readers
        snapshot GIL-atomically), structural for the nns-san race
        lint."""
        self.dispatches += 1
        self.frames += n

    def _dispatch_split(self, batch, batch_exc: BaseException) -> None:
        """A failed batch re-runs per request (window) so only the
        failing window's stream sees an error — one bad frame must not
        discard (or fail) batchmates from other streams (the PR-3
        batch-split rule at plane granularity). The failing stream's
        executor then splits ITS window per frame through its own
        error-policy gate, which re-submits single-frame windows here —
        the frame-level verdict lands without this thread replaying
        every frame of every innocent stream."""
        _log.warning(
            "plane %s: batched dispatch of %d window(s) failed (%s); "
            "splitting per window", self.name, len(batch), batch_exc,
        )
        self.split_dispatches += 1
        program = self._program
        n = 0
        for s, req in batch:
            n += len(req.frames)
            try:
                outs = program.invoke(list(req.frames))
            except Exception as exc:  # noqa: BLE001 — per-stream verdict
                req.exc = exc
                s.errors += len(req.frames)
                req.done.set()
                continue
            self._complete(s, req, outs)
        self._account_dispatch(n)

    def _complete(self, s: PlaneStream, req: _Req, outs) -> None:
        if req.abandoned:
            # the submitter timed out and (possibly) re-submitted these
            # frames: completing the ghost would double-credit `served`
            req.done.set()
            return
        req.out = [tuple(o) for o in outs]
        s.served += len(req.frames)
        if s._serve_ctr is not None:
            s._serve_ctr.inc(len(req.frames))
        req.done.set()

    # -- observability -----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return self._stats_locked()

    def _stats_locked(self) -> Dict[str, Any]:
        # under the plane lock: backlog/snapshot ITERATE the stream
        # deques, and an unlocked iteration racing the service thread's
        # popleft raises "deque mutated during iteration" — scalar
        # counters are GIL-atomic, deque walks are not
        bs = self.batch_stats
        avg = bs.avg_batch_size
        d: Dict[str, Any] = {
            "name": self.name,
            "mode": self.cfg.mode,
            "devices": self.cfg.devices,
            "max_batch": self.cfg.max_batch,
            "streams": len(self._sched),
            "queue_depth": self._sched.backlog,
            "inflight": self._inflight_total,
            "dispatches": self.dispatches,
            "frames": self.frames,
            "split_dispatches": self.split_dispatches,
            "avg_batch": round(avg, 3),
            "occupancy_pct": round(
                100.0 * avg / self.cfg.max_batch, 1
            ) if self.cfg.max_batch else 0.0,
            "per_stream": {
                s.sid: s.snapshot() for s in self._sched.streams()
            },
        }
        prog = self._program
        if prog is not None:
            d["n_traces"] = getattr(prog, "n_traces", 0)
            rstats = getattr(prog, "replica_stats", None)
            if callable(rstats):
                d["replicas"] = rstats()
        return d

    # -- lifecycle ---------------------------------------------------------
    def close(self, join_timeout: float = 5.0) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._stop_ev.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=join_timeout)
            if t.is_alive():  # pragma: no cover - wedged program
                _log.warning("plane %s service thread did not stop",
                             self.name)
        with self._cond:
            leftovers: List[_Req] = []
            for s in self._sched.streams():
                leftovers.extend(self._sched.remove(s))
        for req in leftovers:
            req.exc = PlaneClosedError(f"plane {self.name!r} closed")
            req.done.set()
        prog, self._program = self._program, None
        if prog is not None:
            close = getattr(prog, "close", None)
            if callable(close):
                close()
        for b in self.backends:
            try:
                b.close()
            except Exception as exc:  # noqa: BLE001 — teardown best-effort
                _log.warning("plane %s: backend close failed: %s",
                             self.name, exc)
        self.backends = []


# -- process-wide plane registry (the shared-backend table's sibling) -------

_registry_lock = threading.Lock()
# name -> {"plane", "sig", "refs", "open_lock"}
_planes: Dict[str, Dict[str, Any]] = {}


def acquire(
    name: str,
    sig: tuple,
    cfg: PlaneConfig,
    opener: Callable[[int], Any],
    cfg_explicit: bool = True,
) -> ModelPlane:
    """Get-or-create the named plane; refcounted like the shared-key
    backend table. The MODEL signature ``sig`` must agree across
    sharers always; the plane config binds with the first attacher —
    a later attacher that set no ``plane-*`` properties
    (``cfg_explicit=False``) INHERITS the bound config, while
    explicitly conflicting knobs fail. ``opener(i, replicated)`` opens
    backend ``i`` (one for single/shard, ``cfg.devices`` for replicas;
    ``replicated`` reflects the BINDING config's mode so the opener
    suffixes ``_replica:<i>`` exactly when the plane replicates)."""
    with _registry_lock:
        entry = _planes.get(name)
        if entry is None:
            entry = {"plane": None, "sig": sig, "cfg": cfg, "refs": 0,
                     "open_lock": threading.Lock()}
            _planes[name] = entry
        else:
            if entry["sig"] != sig:
                raise PlaneConfigError(
                    f"plane {name!r} already bound to {entry['sig']}, "
                    f"cannot rebind to {sig}"
                )
            if cfg_explicit and cfg.signature() != \
                    entry["cfg"].signature():
                raise PlaneConfigError(
                    f"plane {name!r} config already bound to "
                    f"{entry['cfg'].signature()}, cannot rebind to "
                    f"{cfg.signature()} (drop the plane-* properties "
                    "to inherit)"
                )
        cfg = entry["cfg"]  # the binding config governs the open below
        entry["refs"] += 1
    try:
        # per-plane open lock: model opens for DIFFERENT planes must not
        # serialize behind one global lock (the shared-key discipline)
        with entry["open_lock"]:
            if entry["plane"] is None:
                replicated = cfg.mode == "replicas"
                n_backends = cfg.devices if replicated else 1
                backends: List[Any] = []
                try:
                    for i in range(n_backends):
                        backends.append(opener(i, replicated))
                except Exception:
                    for b in backends:
                        try:
                            b.close()
                        except Exception as exc:  # noqa: BLE001
                            _log.warning(
                                "plane %s: backend close failed during "
                                "aborted open: %s", name, exc,
                            )
                    raise
                entry["plane"] = ModelPlane(name, cfg, backends)
        return entry["plane"]
    except Exception:
        with _registry_lock:
            entry["refs"] -= 1
            if entry["refs"] <= 0 and entry["plane"] is None:
                _planes.pop(name, None)
        raise


def release(name: str, plane: ModelPlane) -> bool:
    """Drop one ref; closes (and unregisters) the plane when the last
    sharer leaves. True when this call actually closed it."""
    with _registry_lock:
        entry = _planes.get(name)
        if entry is None or entry["plane"] is not plane:
            plane.close()
            return True
        entry["refs"] -= 1
        if entry["refs"] > 0:
            return False
        del _planes[name]
    plane.close()
    return True


def get(name: str) -> Optional[ModelPlane]:
    """The live plane registered under ``name`` (introspection), or
    None."""
    entry = _planes.get(name)
    return entry["plane"] if entry else None
