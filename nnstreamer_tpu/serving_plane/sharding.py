"""The device programs a ModelPlane dispatches collected batches to.

Three backings, one interface (``invoke(windows) -> outs`` where a
window is one frame's tensor tuple, plus ``invoke_one`` for the
heterogeneous/per-frame fallback):

- :class:`VmapProgram` — ONE device: ``jit(vmap(fn))`` per (signature,
  bucket) with the batching.py bucket ladder, optionally pinned to a
  specific device (placement). The cross-stream generalization of
  ``FusedSegment.process_batch``: same stacking, same padding
  discipline, same bounded trace count — so batched results stay
  bitwise-identical to isolated per-frame invokes.
- :class:`MeshShardedProgram` — N devices, data-parallel: the same
  vmapped program jitted with ``batch_sharding`` over a ``dp`` mesh
  axis (parallel/mesh.py), bucket ladder aligned to multiples of the
  mesh size so every dispatch divides evenly across chips. XLA GSPMD
  inserts the collectives; rows are computed independently, so
  per-frame parity holds exactly like the single-device case.
- :class:`ReplicatedProgram` — K single-device programs behind the
  PR-7 :class:`~nnstreamer_tpu.parallel.replicas.ReplicaSet`: windows
  round-robin over healthy replicas, a device-classified fault fails
  the in-flight window over to the next replica, repeated faults bench
  a replica, probes re-admit it (docs/resilience.md semantics at plane
  granularity).

Thread safety: a plane's service thread is the only invoker; the
programs keep no locks of their own (ReplicaSet locks internally).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Sequence, Tuple

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.pipeline.batching import default_buckets

_log = get_logger("serving_plane.sharding")

Window = Tuple[Any, ...]


def _sig_of(window: Window) -> tuple:
    return tuple((tuple(t.shape), t.dtype) for t in window)


class VmapProgram:
    """``jit(vmap(fn))`` per (signature, bucket) over a bucket ladder.

    ``fn`` is the backend's traceable fn: ``(tensors tuple) -> tensors
    tuple``. ``device`` pins dispatch to one jax device (the placement
    planner's unit); ``in_shardings`` (a per-tensor
    :class:`~jax.sharding.NamedSharding` factory result) data-shards
    the stacked batch instead. ``n_traces`` counts cache fills so tests
    bound retracing at O(log max-batch), the FusedSegment contract.
    """

    mode = "single"

    def __init__(
        self,
        fn: Callable[[Window], Window],
        buckets: Sequence[int],
        device=None,
        in_shardings=None,
    ) -> None:
        self._fn = fn
        self.buckets = tuple(buckets)
        self._device = device
        self._in_shardings = in_shardings
        self._cache: Dict[tuple, Callable] = {}
        self.n_traces = 0

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _jitted(self, sig: tuple, bucket: int) -> Callable:
        import jax

        key = (sig, bucket)
        fn = self._cache.get(key)
        if fn is None:
            base = self._fn
            target = (
                jax.vmap(lambda *ts: tuple(base(ts)))
                if bucket else (lambda *ts: tuple(base(ts)))
            )
            kw = {}
            if self._in_shardings is not None and bucket:
                kw["in_shardings"] = tuple(
                    self._in_shardings for _ in sig
                )
            fn = jax.jit(target, **kw)
            self._cache[key] = fn
            self.n_traces += 1
        return fn

    def _place(self, cols: List[Any]) -> List[Any]:
        if self._device is None:
            return cols
        import jax

        return [jax.device_put(c, self._device) for c in cols]

    def invoke_one(self, window: Window) -> Window:
        tensors = window
        if self._device is not None:
            tensors = tuple(self._place(list(tensors)))
        return tuple(self._jitted(_sig_of(window), 0)(*tensors))

    def invoke(self, windows: List[Window]) -> List[Window]:
        import jax.numpy as jnp

        n = len(windows)
        if n == 1:
            return [self.invoke_one(windows[0])]
        sig = _sig_of(windows[0])
        if any(_sig_of(w) != sig for w in windows[1:]):
            # heterogeneous batch (flexible streams): per-frame
            # programs, identical semantics (FusedSegment parity rule)
            return [self.invoke_one(w) for w in windows]
        cap = self.buckets[-1]
        if n > cap:
            # a batch wider than the top bucket (a caller's explicit
            # max-batch= exceeding the plane's, or a scheduler taking
            # one oversized window) chunks to the ladder instead of
            # computing a NEGATIVE pad — which would silently pad
            # nothing and crash a mesh-sharded jit on the non-divisible
            # size
            out: List[Window] = []
            for i in range(0, n, cap):
                out.extend(self.invoke(windows[i:i + cap]))
            return out
        bucket = self.bucket_for(n)
        pad = bucket - n
        cols = []
        for i in range(len(windows[0])):
            rows = [w[i] for w in windows]
            if pad:
                rows.extend([windows[-1][i]] * pad)
            cols.append(jnp.stack(rows))
        outs = self._jitted(sig, bucket)(*self._place(cols))
        return [tuple(o[j] for o in outs) for j in range(n)]

    def stats(self) -> Dict[str, Any]:
        return {"mode": self.mode, "n_traces": self.n_traces}

    def close(self) -> None:
        self._cache.clear()


class MeshShardedProgram(VmapProgram):
    """Data-sharded plane program over an N-device ``dp`` mesh: bucket
    ladder in multiples of the mesh size (every dispatch divides evenly
    across chips — a 3-frame batch on a 4-chip mesh pads to 4, the
    padding-waste ledger counts the cost exactly like bucket padding)."""

    mode = "shard"

    def __init__(
        self,
        fn: Callable[[Window], Window],
        mesh,
        max_batch: int = 8,
    ) -> None:
        from nnstreamer_tpu.parallel.mesh import batch_sharding

        d = int(mesh.size)
        cap = max(d, ((max(1, int(max_batch)) + d - 1) // d) * d)
        buckets: List[int] = []
        b = d
        while b < cap:
            buckets.append(b)
            b *= 2
        buckets.append(cap)
        super().__init__(
            fn, buckets, in_shardings=batch_sharding(mesh, "dp")
        )
        self.mesh = mesh

    def stats(self) -> Dict[str, Any]:
        return {
            "mode": self.mode, "n_traces": self.n_traces,
            "mesh_devices": int(self.mesh.size),
        }


class HostProgram:
    """Per-frame (or host-batched) dispatch for backends with no
    traceable fn: the plane still shares ONE opened backend across all
    streams — the memory win survives — but device batching degrades
    to the backend's own ``invoke_batched`` (when it declared
    ``batchable``) or a per-frame loop."""

    mode = "host"

    def __init__(self, backend) -> None:
        self._backend = backend
        self.n_traces = 0

    def invoke_one(self, window: Window) -> Window:
        return tuple(self._backend.invoke(window))

    def invoke(self, windows: List[Window]) -> List[Window]:
        b = self._backend
        if getattr(b, "batchable", False) and len(windows) > 1:
            sig = _sig_of(windows[0])
            if all(_sig_of(w) == sig for w in windows[1:]):
                return [tuple(o) for o in b.invoke_batched(windows)]
        return [self.invoke_one(w) for w in windows]

    def stats(self) -> Dict[str, Any]:
        return {"mode": self.mode}

    def close(self) -> None:
        pass


class ReplicatedProgram:
    """K per-replica programs behind a ReplicaSet: load-balanced window
    dispatch with device-fault failover. Failover granularity is one
    collected window (the in-flight unit at this layer): a window on a
    dying replica re-dispatches WHOLE onto the next healthy one, frames
    in order, so per-stream FIFO survives a replica loss."""

    mode = "replicas"

    def __init__(
        self,
        programs: Sequence[Any],
        unhealthy_after: int = 3,
        probe_every: int = 64,
    ) -> None:
        from nnstreamer_tpu.parallel.replicas import ReplicaSet

        self.programs = list(programs)
        self._rs = ReplicaSet(
            [p.invoke for p in self.programs],
            unhealthy_after=unhealthy_after,
            probe_every=probe_every,
        )

    def invoke(self, windows: List[Window]) -> List[Window]:
        return self._rs.dispatch(windows)

    def invoke_one(self, window: Window) -> Window:
        return self._rs.dispatch([window])[0]

    @property
    def n_traces(self) -> int:
        return sum(getattr(p, "n_traces", 0) for p in self.programs)

    def replica_stats(self) -> Dict[str, Any]:
        return self._rs.stats()

    def stats(self) -> Dict[str, Any]:
        return {
            "mode": self.mode, "n_traces": self.n_traces,
            **{f"rep_{k}": v for k, v in self._rs.stats().items()},
        }

    def close(self) -> None:
        for p in self.programs:
            close = getattr(p, "close", None)
            if callable(close):
                close()


def build_plane_program(backends: Sequence[Any], cfg) -> Any:
    """Back a plane with the program its config asks for.

    ``mode=single``: one backend, one device (vmapped when traceable).
    ``mode=shard``: one backend data-sharded over ``cfg.devices`` chips.
    ``mode=replicas``: one program per opened backend (``cfg.devices``
    of them), device-pinned round-robin, behind ReplicaSet failover.
    A non-traceable backend degrades to :class:`HostProgram` (sharing
    without device batching) with a warning — except under ``replicas``,
    where per-replica host programs still fail over correctly.
    """
    import jax

    buckets = default_buckets(cfg.max_batch)
    if cfg.mode == "replicas":
        devs = jax.devices()
        programs = []
        for i, b in enumerate(backends):
            fn = b.traceable_fn()
            if fn is None:
                programs.append(HostProgram(b))
            else:
                programs.append(
                    VmapProgram(fn, buckets, device=devs[i % len(devs)])
                )
        return ReplicatedProgram(
            programs,
            unhealthy_after=cfg.unhealthy_after,
            probe_every=cfg.probe_every,
        )
    primary = backends[0]
    # the plane_fn hook (jax backend) hands out the raw fn even when a
    # device pin made traceable_fn refuse (a pin is a FUSION barrier,
    # not a batching barrier — the plane honors it itself), so
    # plane= device=N batches on chip N instead of silently degrading
    # to a per-frame host loop
    fn = device = None
    hook = getattr(primary, "plane_fn", None)
    if callable(hook):
        fn, device = hook()
    if fn is None:
        fn = primary.traceable_fn()
    if fn is None:
        if cfg.mode == "shard":
            _log.warning(
                "plane mode=shard needs a traceable backend; %s is "
                "host-bound — serving shared-but-unsharded",
                type(primary).__name__,
            )
        return HostProgram(primary)
    if cfg.mode == "shard":
        from nnstreamer_tpu.parallel.mesh import make_mesh

        n = max(1, min(int(cfg.devices), len(jax.devices())))
        if n == 1:
            return VmapProgram(fn, buckets, device=device)
        if device is not None:
            _log.warning(
                "plane mode=shard ignores the stage's device pin: the "
                "dp mesh governs placement"
            )
        mesh = make_mesh(n, axes=("dp",))
        return MeshShardedProgram(fn, mesh, max_batch=cfg.max_batch)
    return VmapProgram(fn, buckets, device=device)
