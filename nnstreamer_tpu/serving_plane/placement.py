"""Hermes-style per-stage device placement under per-chip memory bounds.

A composite pipeline (detector ! crop ! classifier ...) can exceed one
chip's HBM even though every stage fits alone. Hermes (PAPERS.md)
places each stage on a device subject to a per-device memory bound,
keeping the chain's locality; this module is that planner for the
pipeline surface:

- :func:`estimate_backend_bytes` / :func:`estimate_stage_bytes` —
  per-stage resident-memory estimates: the params pytree (weights,
  placed once — docs/streaming.md) plus negotiated input/output
  activation bytes, derived abstractly (``eval_shape``-style spec
  arithmetic, no device allocation).
- :func:`plan_placement` — greedy chain packing: stages stay on the
  current chip while the bound holds — adjacent co-resident stages
  keep the PR-8 device-resident handoff (no host hop, no cross-chip
  transfer) — and spill to the next chip with room when it doesn't.
  Explicit ``device=`` pins are honored as hard constraints.
- :func:`place_pipeline` — apply a plan to a built pipeline: each
  tensor_filter's backend is pinned via ``pin_device`` (jax backend),
  so inter-stage hops become async ``device_put`` transfers (ICI on
  real chips; the staged-transfer path) exactly where the plan put a
  chip boundary.

The per-chip bound defaults to ``[plane] memory_per_device`` (bytes;
``K``/``M``/``G`` suffixes accepted).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

# byte estimators live in the shared cost model (analysis/costmodel.py)
# so placement and nns-xray price stages identically; re-exported here
# because this was their original home
from nnstreamer_tpu.analysis.costmodel import (  # noqa: F401 — re-export
    estimate_backend_bytes,
    estimate_stage_bytes,
    params_bytes,
    parse_bytes,
    spec_bytes,
)
from nnstreamer_tpu.log import get_logger

_log = get_logger("serving_plane.placement")


class PlacementError(RuntimeError):
    """No placement satisfies the memory bound (a stage exceeds one
    chip, or the chips are collectively full)."""


def plan_placement(
    costs: Sequence[int],
    per_device_bytes: int,
    n_devices: int,
    pinned: Optional[Dict[int, int]] = None,
) -> List[int]:
    """Assign each stage (chain order) a device index under the bound.

    Greedy chain packing: stay on the current device while the stage
    fits (co-resident neighbors keep the device-resident handoff);
    otherwise move to the first device with room, preferring the NEXT
    one so the chain keeps flowing forward. ``pinned`` maps stage index
    → device index as hard constraints. Raises :class:`PlacementError`
    when a stage fits nowhere."""
    if n_devices < 1:
        raise PlacementError("need at least one device")
    if per_device_bytes <= 0:
        raise PlacementError(
            f"per-device memory bound must be positive, got "
            f"{per_device_bytes}"
        )
    used = [0] * n_devices
    out: List[int] = []
    d = 0
    for i, cost in enumerate(costs):
        cost = int(cost)
        if cost > per_device_bytes:
            raise PlacementError(
                f"stage {i} needs {cost} bytes, over the per-device "
                f"bound {per_device_bytes}"
            )
        if pinned and i in pinned:
            d = int(pinned[i])
            if not (0 <= d < n_devices):
                raise PlacementError(
                    f"stage {i} pinned to device {d}, have {n_devices}"
                )
            if used[d] + cost > per_device_bytes:
                raise PlacementError(
                    f"stage {i} pinned to device {d} but only "
                    f"{per_device_bytes - used[d]} bytes remain there"
                )
        elif used[d] + cost > per_device_bytes:
            # spill: first device with room, scanning forward from the
            # current chip then wrapping (chain locality first)
            for step in range(1, n_devices + 1):
                cand = (d + step) % n_devices
                if used[cand] + cost <= per_device_bytes:
                    d = cand
                    break
            else:
                raise PlacementError(
                    f"stage {i} ({cost} bytes) fits on no device "
                    f"(per-device bound {per_device_bytes}, used {used})"
                )
        used[d] += cost
        out.append(d)
    return out


def _configured_bound() -> Optional[int]:
    from nnstreamer_tpu.analysis.costmodel import configured_device_bound

    return configured_device_bound()


def place_pipeline(
    pipeline: Any,
    per_device_bytes: Optional[int] = None,
    n_devices: Optional[int] = None,
) -> Dict[str, int]:
    """Plan + apply placement for a pipeline's tensor_filter stages.

    Estimates each stage (opening its backend — the same instance the
    run will use), plans under the bound (default ``[plane]
    memory_per_device``), and pins each stage's backend to its assigned
    device. Stages the plan co-locates on device 0 with no estimated
    cost elsewhere stay untouched (default placement, fully fusable);
    any stage landing off device 0 — or explicitly ``device=``-pinned —
    becomes a placed host node whose inter-stage hops ride staged
    ``device_put`` transfers. Returns {element name: device index}.
    """
    import jax

    from nnstreamer_tpu.elements.filter import TensorFilter

    if per_device_bytes is None:
        per_device_bytes = _configured_bound()
    if per_device_bytes is None:
        raise PlacementError(
            "no memory bound: pass per_device_bytes or set "
            "[plane] memory_per_device"
        )
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    n_devices = max(1, min(int(n_devices), len(devs)))
    order, leftover = pipeline.toposort_partial()
    stages = [e for e in order + leftover if isinstance(e, TensorFilter)]
    if not stages:
        return {}
    pinned: Dict[int, int] = {}
    for i, e in enumerate(stages):
        raw = e.get_property("device")
        if raw is not None and str(raw).strip() != "":
            pinned[i] = int(raw)
    costs = [estimate_stage_bytes(e) for e in stages]
    plan = plan_placement(costs, per_device_bytes, n_devices, pinned)
    out: Dict[str, int] = {}
    for e, d, cost in zip(stages, plan, costs):
        out[e.name] = d
        if d == 0 and e.get_property("device") is None:
            # default device and unpinned: leave the stage fusable (the
            # resident handoff needs no pin to stay on chip 0)
            continue
        e.set_property("device", d)
        pin = getattr(e.backend, "pin_device", None)
        if callable(pin):
            pin(d)
        else:
            _log.warning(
                "%s: backend %s has no pin_device; placement on device "
                "%d is advisory only", e.name, type(e.backend).__name__, d,
            )
    _log.info(
        "placement: %s under %d bytes/device over %d device(s)",
        out, per_device_bytes, n_devices,
    )
    return out
