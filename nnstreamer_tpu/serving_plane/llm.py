"""LlmPlane: continuous batching of the LLM pumps THROUGH a plane.

PR 10/13 built the serving-grade paged ContinuousBatcher, but every
``tensor_llm_serversink`` still owned a private one — N serving
pipelines meant N model copies and N decode planes, exactly the
duplication the tensor plane (plane.py) removed for frame filters. An
LlmPlane is the same discipline at TOKEN granularity: every serversink
naming ``plane=<name>`` attaches as one client stream of ONE shared
paged batcher, and the decode pumps (driven by whichever paired
serversrc thread gets there first) advance every stream's requests in
one slot batch.

What each stream keeps (the plane.py contract, token-shaped):

- **Admission fairness** — queued prompts admit into free batcher
  capacity via the same deficit-round-robin :class:`StreamScheduler`
  the tensor plane uses, so a flooding serversink cannot starve a
  trickle stream out of slots; ``plane-weight`` scales a stream's
  share.
- **Per-stream SLO ledgers** — every request's TTFT/TPOT/deadline row
  (kv/sched.SLOLedger via ``cb.requests()``) reports only through the
  stream that submitted it: sharers never see each other's requests in
  ``nns-top --requests``.
- **Output routing** — completed generations land on the submitting
  stream's own output deque with its meta (client_id!) intact, so each
  pipeline's serversrc emits only its own generations.

The decode path itself is untouched: the shared batcher runs the PR-13
block-native paged attention (``kv_attn="auto"|"block"``) with zero
gather dispatches on steady decode — sharing the plane costs no
materialized view.

Lifecycle mirrors the tensor plane registry: refcounted by attached
serversink, first :func:`acquire` builds the batcher (the opener owns
the model props), last :func:`release` drops it.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.serving_plane.scheduler import (
    PlaneStream,
    StreamScheduler,
)

_log = get_logger("serving_plane.llm")


class LlmPlaneError(RuntimeError):
    """Misuse of a shared LLM plane (config conflict, closed plane)."""


class _PromptReq:
    """One queued-but-unadmitted prompt (cost 1 in the DRR scheduler —
    no ``frames`` attribute, so the shared collect() counts it as one
    slot)."""

    __slots__ = ("prompt", "budget", "kw", "meta")

    def __init__(self, prompt, budget: int, kw: dict, meta: dict) -> None:
        self.prompt = prompt
        self.budget = budget
        self.kw = kw
        self.meta = meta


class LlmStream(PlaneStream):
    """PlaneStream plus the token-serving surfaces: the rid→meta map of
    admitted-but-unfinished requests, the completed-generation output
    deque, and the full rid history (the per-stream SLO ledger
    filter)."""

    __slots__ = ("pending", "out", "rids")

    def __init__(self, sid: str, weight: float = 1.0) -> None:
        super().__init__(sid, weight)
        self.pending: Dict[int, dict] = {}
        from collections import deque

        self.out = deque()
        self.rids: set = set()


class LlmPlane:
    """One shared paged ContinuousBatcher serving N serversink streams.

    Locking: ``_lock`` guards queues/maps/deques (submitters + the
    pumping thread), ``_pump_lock`` serializes batcher stepping — many
    serversrc threads may call :meth:`pump`, one steps at a time, the
    rest return quickly and re-poll (their outputs land via the
    stepper's harvest).
    """

    def __init__(self, name: str, cb, pump_tokens: int = 1) -> None:
        self.name = name
        self.cb = cb
        self.pump_tokens = max(1, int(pump_tokens))
        self._sched = StreamScheduler()
        self._lock = threading.Lock()
        self._pump_lock = threading.Lock()
        self._owner: Dict[int, LlmStream] = {}  # live rid -> stream
        self.closed = False
        self.admit_rounds = 0

    # -- streams -----------------------------------------------------------
    def attach(self, sid: str, weight: float = 1.0) -> LlmStream:
        with self._lock:
            if self.closed:
                raise LlmPlaneError(f"llm plane {self.name!r} is closed")
            s = LlmStream(sid, weight)
            self._sched.add(s)
            return s

    def refuse_migration(self, op: str) -> None:
        """Plane-shared batchers refuse the live-migration surface with
        a typed error (docs/llm-serving.md "Migration & recovery"): the
        KV arena, slot table, and prefix index are shared across N
        serversink streams, so extracting or adopting a span here would
        move one stream's request through state every sharer co-owns.
        Migration needs a PRIVATE kv-layout=paged batcher."""
        raise LlmPlaneError(
            f"llm plane {self.name!r}: {op} refused — plane-shared "
            "batchers cannot migrate or checkpoint requests; serve "
            "with a private kv-layout=paged batcher instead"
        )

    def detach(self, stream: LlmStream) -> None:
        """Drop a stream: its queued prompts are discarded (the owning
        pipeline is stopping — nobody will pop their generations) and
        its admitted requests are orphaned from the routing table so
        the pump never appends to a dead deque. The batcher finishes
        (and frees) the orphans on its own schedule."""
        with self._lock:
            self._sched.remove(stream)
            for rid in list(self._owner):
                if self._owner[rid] is stream:
                    del self._owner[rid]
            stream.pending.clear()

    # -- submission (serversink render threads) ----------------------------
    def submit(
        self, stream: LlmStream, prompt, budget: int, kw: dict,
        meta: dict,
    ) -> None:
        """Queue one prompt for weighted-fair admission. Submission
        itself never blocks on a free slot — admission control is the
        scheduler's job — but a stream deep past its fair backlog pumps
        the plane (backpressure by doing the work, the serversink
        discipline)."""
        with self._lock:
            if self.closed:
                raise LlmPlaneError(f"llm plane {self.name!r} is closed")
            stream.q.append(_PromptReq(prompt, budget, kw, meta))
            stream.admitted += 1
            self._admit_locked()
        # soft backpressure: past 2× the batcher's slot count queued on
        # THIS stream, drive decode until admission drains the excess
        bound = 2 * max(1, getattr(self.cb, "n_slots", 1))
        while len(stream.q) > bound and not self.closed:
            if not self.pump():
                time.sleep(0.002)

    def _admit_locked(self) -> None:
        """Admit queued prompts into the batcher, one DRR pick at a
        time, until the batcher refuses (slot/watermark full) or the
        queues drain. ``_lock`` held; cb.submit is thread-safe but the
        pick→submit→record sequence must be atomic so the refused pick
        goes back to the FRONT of its stream's queue (FIFO intact)."""
        while True:
            picked = self._sched.collect(1)
            if not picked:
                return
            self.admit_rounds += 1
            s, req = picked[0]
            try:
                rid = self.cb.submit(req.prompt, req.budget, **req.kw)
            except Exception:
                # a poisoned prompt fails ITS request; the stream sees
                # the error as a dropped generation (counted), never a
                # wedged admission loop
                s.errors += 1
                _log.warning(
                    "llm plane %s: submit failed for stream %s",
                    self.name, s.sid, exc_info=True,
                )
                continue
            if rid is None:
                # batcher full: refund the pick (front of queue + the
                # consumed DRR slot) and stop admitting this round
                s.q.appendleft(req)
                s.deficit += 1.0
                return
            s.pending[rid] = req.meta
            s.rids.add(rid)
            self._owner[rid] = s

    # -- decode (serversrc pump threads) -----------------------------------
    def pump(self) -> bool:
        """One decode advance of the shared batcher + harvest: finished
        requests route to their owning stream's output deque, then
        freed capacity admits more queued prompts. Many threads may
        call this; one steps at a time (``_pump_lock``), contenders
        skip — their generations arrive via the stepper's harvest, so
        a skipped pump still reports progress when its stream gained
        output."""
        cb = self.cb
        if cb is None:  # closed under a late pumper
            return False
        if not self._pump_lock.acquire(blocking=False):
            # someone else is stepping; don't stack a second device
            # round trip behind theirs
            return False
        try:
            if self.pump_tokens > 1:
                emitted = cb.step_pump(self.pump_tokens)
            else:
                emitted = cb.step()
            harvested = False
            with self._lock:
                for rid in list(self._owner):
                    toks = cb.result(rid)
                    if toks is None:
                        continue
                    s = self._owner.pop(rid)
                    meta = s.pending.pop(rid, {})
                    s.out.append((toks, meta))
                    s.served += 1
                    harvested = True
                self._admit_locked()
            return bool(emitted) or harvested
        finally:
            self._pump_lock.release()

    def pop(self, stream: LlmStream) -> Optional[Tuple[Any, dict]]:
        with self._lock:
            return stream.out.popleft() if stream.out else None

    def idle_for(self, stream: LlmStream) -> bool:
        """True when the stream has nothing queued, admitted, or
        popped-pending — the serversrc's drain condition (its own eos
        flag ANDed by the caller)."""
        with self._lock:
            return (
                not stream.q and not stream.pending and not stream.out
            )

    # -- observability -----------------------------------------------------
    def stats_for(self, stream: LlmStream) -> Dict[str, Any]:
        """Batcher counters + THIS stream's request rows only (sharers
        must not report each other's SLO ledgers) + the plane-wide
        sharing surface."""
        cb = self.cb
        if cb is None:  # closed: only the stream-side counters remain
            st: Dict[str, Any] = {"requests": {}}
        else:
            st = cb.stats()
            st["requests"] = {
                str(rid): row for rid, row in cb.requests().items()
                if rid in stream.rids
            }
        with self._lock:
            st["plane"] = self.name
            st["plane_streams"] = len(self._sched)
            st["plane_queued_prompts"] = sum(
                len(s.q) for s in self._sched.streams()
            )
            st["stream_submitted"] = stream.admitted
            st["stream_served"] = stream.served
            st["stream_errors"] = stream.errors
            st["stream_queued"] = len(stream.q)
            st["stream_active"] = len(stream.pending)
        return st

    def close(self) -> None:
        with self._lock:
            self.closed = True
            self._owner.clear()
        self.cb = None  # drop params + KV arena references


# -- process-wide registry (the ModelPlane registry's sibling) --------------

_registry_lock = threading.Lock()
# name -> {"plane", "sig", "refs", "open_lock"}
_planes: Dict[str, Dict[str, Any]] = {}


def acquire(
    name: str,
    sig: tuple,
    opener: Callable[[], Any],
    pump_tokens: int = 1,
) -> LlmPlane:
    """Get-or-create the named LLM plane; refcounted like the tensor
    plane registry. ``sig`` (model + batcher config) must agree across
    sharers — the batcher is ONE object, so a disagreeing sharer would
    silently serve with someone else's model. ``opener()`` builds the
    ContinuousBatcher (first attacher only)."""
    with _registry_lock:
        entry = _planes.get(name)
        if entry is None:
            entry = {"plane": None, "sig": sig, "refs": 0,
                     "pump_tokens": pump_tokens,
                     "open_lock": threading.Lock()}
            _planes[name] = entry
        else:
            if entry["sig"] != sig:
                raise LlmPlaneError(
                    f"llm plane {name!r} already bound to a different "
                    f"model/batcher config, cannot rebind "
                    f"({entry['sig']} vs {sig})"
                )
        entry["refs"] += 1
    try:
        with entry["open_lock"]:
            if entry["plane"] is None:
                entry["plane"] = LlmPlane(
                    name, opener(), pump_tokens=entry["pump_tokens"]
                )
        return entry["plane"]
    except Exception:
        with _registry_lock:
            entry["refs"] -= 1
            if entry["refs"] <= 0 and entry["plane"] is None:
                _planes.pop(name, None)
        raise


def release(name: str, plane: LlmPlane) -> bool:
    """Drop one ref; closes (and unregisters) the plane when the last
    sharer leaves. True when this call actually closed it."""
    with _registry_lock:
        entry = _planes.get(name)
        if entry is None or entry["plane"] is not plane:
            plane.close()
            return True
        entry["refs"] -= 1
        if entry["refs"] > 0:
            return False
        del _planes[name]
    plane.close()
    return True


def get(name: str) -> Optional[LlmPlane]:
    """The live LLM plane registered under ``name`` (introspection), or
    None."""
    entry = _planes.get(name)
    return entry["plane"] if entry else None
