"""nns-plane: the multi-stream, multi-chip model serving plane.

ROADMAP item 2 ("millions of users"): thousands of concurrent pipelines
multiplexed onto shared accelerators, not one pipeline per process. The
subsystem turns N independent executors into one serving system:

- :mod:`plane` — :class:`ModelPlane`, a process-wide shared device
  batcher per model: N client streams (one per attached tensor_filter,
  across executors) feed ONE continuously-batched device program, with
  per-stream FIFO reassembly and weighted-fair scheduling
  (:mod:`scheduler`), so one hot stream cannot starve the rest.
- :mod:`sharding` — the programs a plane dispatches to: a single-device
  vmapped program, a data-sharded program over an N-device mesh
  (``parallel/mesh.py``), or K device-pinned replicas behind the PR-7
  :class:`~nnstreamer_tpu.parallel.replicas.ReplicaSet` failover core.
- :mod:`placement` — the Hermes-style planner (PAPERS.md): assign a
  composite pipeline's stages to devices under a per-chip memory bound,
  keeping adjacent stages co-resident (PR-8 device handoff) while they
  fit and spilling to the next chip when they don't.

Pipeline surface: ``tensor_filter plane=<name>`` attaches a filter (one
stream) to the named plane; ``device=<idx>`` pins a stage
(docs/serving-plane.md).
"""

from nnstreamer_tpu.serving_plane.placement import (
    PlacementError,
    place_pipeline,
    plan_placement,
)
from nnstreamer_tpu.serving_plane.plane import (
    ModelPlane,
    PlaneClosedError,
    PlaneConfig,
    acquire,
    release,
    resolve_plane_config,
)
from nnstreamer_tpu.serving_plane.scheduler import PlaneStream, StreamScheduler

__all__ = [
    "ModelPlane",
    "PlaneClosedError",
    "PlaneConfig",
    "PlaneStream",
    "PlacementError",
    "StreamScheduler",
    "acquire",
    "place_pipeline",
    "plan_placement",
    "release",
    "resolve_plane_config",
]
