"""Disaggregated prefill/decode LLM serving (docs/llm-serving.md
"Disaggregated serving").

Production serving splits compute-bound prefill from latency-bound
decode onto separate workers (ROADMAP item 2). Everything the split
needs exists after PR 18 — the :class:`~nnstreamer_tpu.kv.migrate.
RequestSpan` codec with ``strip_shared``, ``extract_request`` /
``adopt_request`` with bitwise-identical continued decode, and the
``KIND_CTRL`` migration handshake — this module composes them into
ROLES:

- ``tensor_llm_serversink role=prefill decode-peers=h1:p1,h2:p2`` runs
  chunked prefill only. The moment a request turns extractable (prefill
  finalized, first token pending), :class:`DisaggController` extracts
  its KV span, probes each decode peer (one roundtrip answers both
  "how warm" — shared prefix depth — and "how full" — the pool-headroom
  advert), ``strip_shared``s against the winner's coverage, and ships
  the span over the existing CTRL channel. The decode peer adopts it
  straight into its arena: **zero re-prefill** (its
  ``kv_prefill_chunks`` counter stays flat — the acceptance pin).
- ``role=decode`` advertises pool headroom + prefix depth in its probe
  replies, refuses over capacity with a typed retry-after NACK
  (:class:`~nnstreamer_tpu.kv.blocks.PoolCapacityError` taxonomy on the
  wire), and segregates finished handoff generations for the prefill
  side to collect over ``disagg_fetch`` — the DECODE server never
  delivers to the client, so the PR-15 ``frame_id`` dedup sees exactly
  one DELIVER whatever the client retried mid-handoff.

Failure ladder (tokens are never lost):

1. peer refuses/unreachable at handoff → the span re-enters the LOCAL
   arena via ``adopt_request`` (same bytes, zero re-prefill), decode
   continues in place (outcome ``local``);
2. local adopt refused too (races with capacity) → ``resume_from_span``
   re-prefill (the PR-10 cold fallback);
3. a handed-off generation's peer forgets the rid or stays unreachable
   past the fetch budget → the request re-submits locally from its
   prompt (outcome ``recovered`` — cold, but terminal).

Role placement follows Hermes (PAPERS.md: memory-bounded pipeline
placement across edge devices); span shipping follows StreamTensor
(PAPERS.md: stream tensors between dataflow stages instead of
round-tripping through a host).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.obs import metrics as obs_metrics

_log = get_logger("serving_plane.disagg")


def parse_decode_peers(spec: str,
                       default_llm_id: int = 0) -> List[Tuple[str, int, int]]:
    """``"h1:p1[/llm-id],h2:p2"`` → ``[(host, port, llm_id), ...]`` —
    the ``decode-peers`` property grammar (the ``migrate-to`` target
    grammar, pluralized). Raises ValueError on malformed entries or
    duplicates so the serversink constructor fails loudly."""
    out: List[Tuple[str, int, int]] = []
    seen = set()
    for raw in str(spec).split(","):
        raw = raw.strip()
        if not raw:
            continue
        base, sep, suffix = raw.partition("/")
        llm_id = default_llm_id
        if sep:
            if not suffix.isdigit():
                raise ValueError(
                    f"decode-peers entry {raw!r}: llm-id suffix must be "
                    "an integer"
                )
            llm_id = int(suffix)
        host, _, port_s = base.rpartition(":")
        if not host or not port_s.isdigit() or int(port_s) <= 0:
            raise ValueError(
                f"decode-peers entry {raw!r} is not host:port[/llm-id]"
            )
        key = (host, int(port_s))
        if key in seen:
            raise ValueError(f"decode-peers entry {raw!r} is listed twice")
        seen.add(key)
        out.append((host, int(port_s), llm_id))
    if not out:
        raise ValueError(f"decode-peers={spec!r} names no peers")
    return out


class _Peer:
    """One decode-role target plus its refusal bookkeeping: a peer that
    NACKed or dropped the connection is benched for its retry-after
    hint (or a short default) so a full pool is not hammered every
    pump."""

    __slots__ = ("host", "port", "llm_id", "bench_until", "handoffs",
                 "refusals")

    def __init__(self, host: str, port: int, llm_id: int) -> None:
        self.host = host
        self.port = port
        self.llm_id = llm_id
        self.bench_until = 0.0
        self.handoffs = 0
        self.refusals = 0

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"


class _Handoff:
    """Ledger entry for one generation decoding on a peer: the frame
    meta the prefill server must emit it under (``frame_id``!), plus
    enough of the original request to resubmit locally if the peer
    loses it."""

    __slots__ = ("peer", "remote_rid", "meta", "prompt", "budget",
                 "sample_kw", "next_poll", "fails")

    def __init__(self, peer: _Peer, remote_rid: int, meta: dict,
                 prompt, budget: int, sample_kw: dict) -> None:
        self.peer = peer
        self.remote_rid = remote_rid
        self.meta = meta
        self.prompt = prompt
        self.budget = budget
        self.sample_kw = sample_kw
        self.next_poll = 0.0
        self.fails = 0


class DisaggController:
    """The prefill role's handoff engine, ticked from the owning
    ``_LlmServer.pump()``.

    Each tick: (1) retry any queued local resubmits, (2) OFFLOAD —
    extract every freshly-extractable request, pick the decode peer
    with the deepest shared prefix (pool headroom breaks ties), ship
    the stripped span, (3) RELAY — poll outstanding handoffs over
    ``disagg_fetch`` and append finished generations to the server's
    out queue under their original meta, so the prefill server's OWN
    serversrc delivers them (at-most-once rides the unchanged
    ``frame_id``). Reentrant ticks are skipped (pump runs from both the
    src thread and the sink's backpressure loop)."""

    def __init__(self, peers_spec: str, llm_id: int = 0,
                 poll_s: float = 0.02, probe_timeout_s: float = 2.0,
                 max_fetch_fails: int = 25,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.peers = [
            _Peer(h, p, i) for h, p, i in
            parse_decode_peers(peers_spec, default_llm_id=llm_id)
        ]
        self.poll_s = float(poll_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.max_fetch_fails = max(1, int(max_fetch_fails))
        self.clock = clock
        self._handoffs: Dict[Tuple[str, int], _Handoff] = {}
        self._resubmit_q: List[_Handoff] = []
        self._tick_lock = threading.Lock()
        self.counts: Dict[str, int] = {}
        self._reg = obs_metrics.get()
        self._ctrs: Dict[str, object] = {}

    # -- accounting --------------------------------------------------------
    def _count(self, outcome: str) -> None:
        self.counts[outcome] = self.counts.get(outcome, 0) + 1
        reg = self._reg
        if reg is None:
            return
        c = self._ctrs.get(outcome)
        if c is None:
            c = self._ctrs[outcome] = reg.counter(
                "nns_disagg_handoffs_total", outcome=outcome
            )
        c.inc()

    def outstanding(self) -> int:
        return len(self._handoffs) + len(self._resubmit_q)

    def idle(self) -> bool:
        return not self._handoffs and not self._resubmit_q

    def stats(self) -> Dict[str, object]:
        return {
            "outstanding": len(self._handoffs),
            "counts": dict(self.counts),
            "peers": {
                p.addr: {"handoffs": p.handoffs, "refusals": p.refusals}
                for p in self.peers
            },
        }

    # -- the pump hook -----------------------------------------------------
    def tick(self, server) -> bool:
        """One offload+relay pass; True when anything moved. Non-
        blocking reentrancy guard: pump() runs concurrently from the
        src thread and the sink's backpressure loop, and a second
        overlapping tick would double-extract."""
        if not self._tick_lock.acquire(blocking=False):
            return False
        try:
            did = self._drain_resubmits(server)
            if not server.draining:
                did |= self._offload(server)
            did |= self._relay(server)
            return did
        finally:
            self._tick_lock.release()

    # -- offload -----------------------------------------------------------
    def _offload(self, server) -> bool:
        with server._lock:
            rids = list(server._pending)
        if not rids:
            return False
        if not any(self.clock() >= p.bench_until for p in self.peers):
            return False  # whole fleet benched: decode locally meanwhile
        parts = server.cb.partials(rids)
        did = False
        for rid in rids:
            toks = parts.get(rid)
            if toks is None or not toks:
                continue  # still prefilling (no first token yet)
            did |= self._handoff_one(server, rid)
        return did

    def _handoff_one(self, server, rid: int) -> bool:
        from nnstreamer_tpu.edge import query as _equery
        from nnstreamer_tpu.edge.transport import TransportError
        from nnstreamer_tpu.kv import migrate as _migrate

        try:
            span = server.cb.extract_request(rid)
        except _migrate.SpanError:
            return False  # finished (or re-queued) this instant
        with server._lock:
            meta = dict(server._pending.get(rid) or {})
        span.meta.update(server.span_meta(meta))
        # the decode server segregates this generation for fetch
        # instead of emitting it — the prefill side owns DELIVER
        span.meta["_nns_disagg"] = 1
        now = self.clock()
        best = None  # ((shared, free_blocks), peer, shared)
        for p in self.peers:
            if now < p.bench_until:
                continue
            try:
                shared, advert = _equery.probe_migration_full(
                    p.host, p.port, span.kv_tokens, llm_id=p.llm_id,
                    timeout=self.probe_timeout_s,
                )
            except _equery.MigrationRefused as exc:
                self._bench(p, exc.retry_after_ms)
                continue
            except (TransportError, OSError, ValueError):
                self._bench(p, 250.0)
                continue
            key = (int(shared), int(advert.get("free_blocks", 0) or 0))
            if best is None or key > best[0]:
                best = (key, p, int(shared))
        remote_rid = -1
        peer = None
        if best is not None:
            _key, peer, shared = best
            try:
                wire = _migrate.encode_span(span.strip_shared(shared))
                remote_rid = _equery.send_migration(
                    peer.host, peer.port, wire, llm_id=peer.llm_id,
                    timeout=self.probe_timeout_s,
                )
            except _equery.MigrationRefused as exc:
                self._bench(peer, exc.retry_after_ms)
                remote_rid = -1
            except (TransportError, OSError, ValueError,
                    _migrate.SpanError):
                self._bench(peer, 250.0)
                remote_rid = -1
        if remote_rid < 0 or peer is None:
            # rung 1/2 of the failure ladder: the span re-enters the
            # LOCAL arena (same bytes, zero re-prefill); cold re-prefill
            # only if even that is refused. Tokens never lost.
            self._readopt(server, rid, span, meta)
            return True
        with server._lock:
            server._pending.pop(rid, None)
            server._sent.pop(rid, None)
        peer.handoffs += 1
        self._handoffs[(peer.addr, remote_rid)] = _Handoff(
            peer, remote_rid, meta,
            np.asarray(span.prompt, np.int32), int(span.budget),
            dict(temperature=float(span.temperature),
                 top_k=int(span.top_k), top_p=float(span.top_p)),
        )
        self._count("handoff")
        return True

    def _bench(self, p: _Peer, retry_after_ms: float) -> None:
        p.refusals += 1
        p.bench_until = self.clock() + max(
            float(retry_after_ms or 0.0), 50.0
        ) / 1000.0

    def _readopt(self, server, rid: int, span, meta: dict) -> None:
        try:
            new_rid = server.cb.adopt_request(span)
        except Exception:
            new_rid = server.cb.resume_from_span(span)
        with server._lock:
            server._pending.pop(rid, None)
            server._pending[new_rid] = meta
        self._count("local")

    # -- relay -------------------------------------------------------------
    def _relay(self, server) -> bool:
        if not self._handoffs:
            return False
        from nnstreamer_tpu.edge import query as _equery
        from nnstreamer_tpu.edge.transport import TransportError

        did = False
        for key, h in list(self._handoffs.items()):
            now = self.clock()
            if now < h.next_poll:
                continue
            h.next_poll = now + self.poll_s
            try:
                toks = _equery.fetch_handoff(
                    h.peer.host, h.peer.port, h.remote_rid,
                    llm_id=h.peer.llm_id, timeout=self.probe_timeout_s,
                )
            except _equery.MigrationRefused as exc:
                if "draining" in exc.reason:
                    # a draining peer still finishes its in-flight
                    # before quiescing — keep polling
                    continue
                # the peer no longer knows the rid: that copy is gone;
                # rung 3 — resubmit locally from the prompt
                _log.warning(
                    "disagg: peer %s lost rid %d (%s); resubmitting "
                    "locally", h.peer.addr, h.remote_rid, exc.reason,
                )
                self._handoffs.pop(key, None)
                self._resubmit_q.append(h)
                did = True
                continue
            except (TransportError, OSError, ValueError):
                h.fails += 1
                if h.fails >= self.max_fetch_fails:
                    _log.warning(
                        "disagg: peer %s unreachable for rid %d after "
                        "%d polls; resubmitting locally",
                        h.peer.addr, h.remote_rid, h.fails,
                    )
                    self._handoffs.pop(key, None)
                    self._resubmit_q.append(h)
                    did = True
                continue
            h.fails = 0
            if toks is None:
                continue  # still decoding on the peer
            self._handoffs.pop(key, None)
            meta = dict(h.meta)
            if server.stream:
                # streaming servers hand off whole generations; the
                # done frame still carries the full token list
                meta = {**meta, "stream": True, "done": True}
            with server._lock:
                server._out.append((list(toks), meta))
            self._count("relayed")
            did = True
        return did

    # -- local resubmit (rung 3) -------------------------------------------
    def _drain_resubmits(self, server) -> bool:
        if not self._resubmit_q:
            return False
        did = False
        kept: List[_Handoff] = []
        for h in self._resubmit_q:
            rid = server.cb.submit(h.prompt, h.budget, **h.sample_kw)
            if rid is None:
                kept.append(h)  # batch full: retry next tick
                continue
            with server._lock:
                server._pending[rid] = dict(h.meta)
            self._count("recovered")
            did = True
        self._resubmit_q = kept
        return did
