"""protobuf converter subplugin: serialized Tensors message → tensors.

Reference: ext/nnstreamer/tensor_converter/tensor_converter_protobuf.cc with
the nnstreamer.proto schema — our schema (proto/nns_tensors.proto) is
wire-compatible (same field numbers/enum values).
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import DType, TensorFormat, TensorsSpec

# enum value ↔ dtype (proto Tensor_type, mirroring the reference's order)
PB_TO_DTYPE = {
    0: DType.INT32, 1: DType.UINT32, 2: DType.INT16, 3: DType.UINT16,
    4: DType.INT8, 5: DType.UINT8, 6: DType.FLOAT64, 7: DType.FLOAT32,
    8: DType.INT64, 9: DType.UINT64, 10: DType.FLOAT16, 11: DType.BFLOAT16,
}
DTYPE_TO_PB = {v: k for k, v in PB_TO_DTYPE.items()}


def frame_to_message(
    frame: Frame, fmt: TensorFormat = TensorFormat.STATIC, rate=None
):
    from nnstreamer_tpu.proto import nns_tensors_pb2 as pb

    msg = pb.Tensors()
    msg.num_tensor = frame.num_tensors
    rate = rate or frame.meta.get("rate")
    if rate:
        msg.fr.rate_n = rate.numerator
        msg.fr.rate_d = rate.denominator
    msg.format = {
        TensorFormat.STATIC: 0, TensorFormat.FLEXIBLE: 1, TensorFormat.SPARSE: 2
    }[fmt]
    for i, t in enumerate(frame.tensors):
        arr = np.asarray(t)
        entry = msg.tensor.add()
        entry.name = str(frame.meta.get("names", {}).get(i, ""))
        entry.type = DTYPE_TO_PB[DType.from_any(arr.dtype)]
        # reference dimension order: innermost-first uint32s
        entry.dimension.extend(int(d) for d in reversed(arr.shape))
        entry.data = np.ascontiguousarray(arr).tobytes()
    return msg


def message_to_tensors(msg) -> tuple:
    out = []
    for entry in msg.tensor:
        dtype = PB_TO_DTYPE.get(entry.type, DType.UINT8)
        shape = tuple(reversed([int(d) for d in entry.dimension]))
        arr = np.frombuffer(entry.data, dtype=dtype.np_dtype)
        if shape and int(np.prod(shape)) == arr.size:
            arr = arr.reshape(shape)
        out.append(arr)
    return tuple(out)


@registry.converter_plugin("protobuf")
class ProtobufConverter:
    def negotiate(self, in_spec, props: dict) -> TensorsSpec:
        return TensorsSpec(format=TensorFormat.FLEXIBLE)

    def convert(self, frame: Frame, props: dict) -> Frame:
        from fractions import Fraction

        from nnstreamer_tpu.proto import nns_tensors_pb2 as pb

        data = np.asarray(frame.tensors[0], dtype=np.uint8).tobytes()
        msg = pb.Tensors.FromString(data)
        tensors = message_to_tensors(msg)
        if not tensors:
            raise ValueError("protobuf: empty Tensors message")
        out = frame.with_tensors(tensors)
        if msg.fr.rate_n and msg.fr.rate_d:  # cadence survives the hop
            out = out.with_meta(rate=Fraction(msg.fr.rate_n, msg.fr.rate_d))
        return out
