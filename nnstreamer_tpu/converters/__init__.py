"""Converter subplugins (reference ext/nnstreamer/tensor_converter/).

Protocol: negotiate(in_spec, props) -> TensorsSpec; convert(frame, props)
-> Frame. Registered under registry kind "converter"; used by
tensor_converter mode=NAME. Built-ins: flexbuf (see wire codec in
tensors/meta.py used directly by the edge layer).
"""

from nnstreamer_tpu.converters import flatbuf  # noqa: F401,E402
from nnstreamer_tpu.converters import flexbuf  # noqa: F401,E402
from nnstreamer_tpu.converters import protobuf  # noqa: F401,E402
from nnstreamer_tpu.converters import python_script  # noqa: F401,E402
