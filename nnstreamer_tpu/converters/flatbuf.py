"""flatbuf converter subplugin + the flatbuffer tensor-frame codec.

Reference: ext/nnstreamer/tensor_converter/tensor_converter_flatbuf.cc with
the nnstreamer.fbs schema (Tensors{num_tensor, fr, tensor[], format},
Tensor{name, type, dimension, data}). The image has no ``flatc``, so the
codec is written directly against the flatbuffers runtime Builder/Table API
with the same table layout (slot order + enum values as the reference
schema), keeping the wire format interoperable.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import DType, TensorFormat, TensorsSpec

# enum Tensor_type (nnstreamer.fbs order); NNS_END = 10 is the default slot
FB_TO_DTYPE = {
    0: DType.INT32, 1: DType.UINT32, 2: DType.INT16, 3: DType.UINT16,
    4: DType.INT8, 5: DType.UINT8, 6: DType.FLOAT64, 7: DType.FLOAT32,
    8: DType.INT64, 9: DType.UINT64,
}
DTYPE_TO_FB = {v: k for k, v in FB_TO_DTYPE.items()}
FB_TYPE_END = 10

_FORMAT_TO_FB = {
    TensorFormat.STATIC: 0, TensorFormat.FLEXIBLE: 1, TensorFormat.SPARSE: 2
}


def encode_flatbuf(
    tensors: Sequence[np.ndarray],
    rate: Optional[Tuple[int, int]] = None,
    fmt: TensorFormat = TensorFormat.STATIC,
) -> bytes:
    import flatbuffers

    b = flatbuffers.Builder(1024)
    tensor_offs = []
    for arr in tensors:
        arr = np.ascontiguousarray(np.asarray(arr))
        dtype = DType.from_any(arr.dtype)
        if dtype not in DTYPE_TO_FB:
            raise ValueError(f"flatbuf: dtype {dtype} not representable")
        data_off = b.CreateByteVector(arr.tobytes())
        # dimension: innermost-first uint32s, reference convention
        dims = list(reversed(arr.shape))
        b.StartVector(4, len(dims), 4)
        for d in reversed(dims):
            b.PrependUint32(int(d))
        dim_off = b.EndVector()
        name_off = b.CreateString("")
        b.StartObject(4)
        b.PrependUOffsetTRelativeSlot(0, name_off, 0)
        b.PrependInt32Slot(1, DTYPE_TO_FB[dtype], FB_TYPE_END)
        b.PrependUOffsetTRelativeSlot(2, dim_off, 0)
        b.PrependUOffsetTRelativeSlot(3, data_off, 0)
        tensor_offs.append(b.EndObject())
    b.StartVector(4, len(tensor_offs), 4)
    for off in reversed(tensor_offs):
        b.PrependUOffsetTRelative(off)
    vec_off = b.EndVector()
    b.StartObject(4)
    b.PrependInt32Slot(0, len(tensor_offs), 0)
    rn, rd = rate if rate else (0, 0)
    # inline struct frame_rate{rate_n, rate_d}
    b.Prep(4, 8)
    b.PrependInt32(int(rd))
    b.PrependInt32(int(rn))
    b.PrependStructSlot(1, b.Offset(), 0)
    b.PrependUOffsetTRelativeSlot(2, vec_off, 0)
    b.PrependInt32Slot(3, _FORMAT_TO_FB[fmt], 0)
    b.Finish(b.EndObject())
    return bytes(b.Output())


def decode_flatbuf(data: bytes):
    """→ (tensors tuple, (rate_n, rate_d))."""
    import flatbuffers
    from flatbuffers import encode as fb_encode
    from flatbuffers import number_types as NT
    from flatbuffers.table import Table

    buf = bytearray(data)
    root = fb_encode.Get(NT.UOffsetTFlags.packer_type, buf, 0)
    tab = Table(buf, root)

    rate = (0, 0)
    o = tab.Offset(6)  # fr struct, slot 1
    if o:
        pos = o + tab.Pos
        rate = (
            fb_encode.Get(NT.Int32Flags.packer_type, buf, pos),
            fb_encode.Get(NT.Int32Flags.packer_type, buf, pos + 4),
        )
    tensors = []
    o = tab.Offset(8)  # tensor vector, slot 2
    if o:
        n = tab.VectorLen(o)
        base = tab.Vector(o)
        for j in range(n):
            t = Table(buf, tab.Indirect(base + j * 4))
            to = t.Offset(6)
            ftype = (
                t.Get(NT.Int32Flags, to + t.Pos) if to else FB_TYPE_END
            )
            dtype = FB_TO_DTYPE.get(int(ftype), DType.UINT8)
            dims = []
            do = t.Offset(8)
            if do:
                dbase = t.Vector(do)
                for k in range(t.VectorLen(do)):
                    dims.append(t.Get(NT.Uint32Flags, dbase + k * 4))
            vo = t.Offset(10)
            raw = b""
            if vo:
                vbase = t.Vector(vo)
                raw = bytes(buf[vbase : vbase + t.VectorLen(vo)])
            arr = np.frombuffer(raw, dtype=dtype.np_dtype)
            shape = tuple(reversed([int(d) for d in dims]))
            if shape and int(np.prod(shape)) == arr.size:
                arr = arr.reshape(shape)
            tensors.append(arr)
    return tuple(tensors), rate


@registry.converter_plugin("flatbuf")
class FlatbufConverter:
    def negotiate(self, in_spec, props: dict) -> TensorsSpec:
        return TensorsSpec(format=TensorFormat.FLEXIBLE)

    def convert(self, frame: Frame, props: dict) -> Frame:
        from fractions import Fraction

        data = np.asarray(frame.tensors[0], dtype=np.uint8).tobytes()
        tensors, (rn, rd) = decode_flatbuf(data)
        if not tensors:
            raise ValueError("flatbuf: empty Tensors frame")
        out = frame.with_tensors(tensors)
        if rn and rd:  # stream cadence survives the serialize hop
            out = out.with_meta(rate=Fraction(rn, rd))
        return out
