"""python3 converter subplugin: user script converts media → tensors.

Reference: ext/nnstreamer/tensor_converter/tensor_converter_python3.cc —
the script defines ``CustomConverter`` with ``convert(tensors) -> tensors``
and optionally ``negotiate(in_spec) -> TensorsSpec``. Script path comes
from the element's ``script`` (or ``option1``) property.
"""

from __future__ import annotations

from nnstreamer_tpu import registry
from nnstreamer_tpu.script import load_script_object
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec


@registry.converter_plugin("python3")
class PythonScriptConverter:
    def __init__(self) -> None:
        self._obj = None

    def _load(self, props: dict):
        if self._obj is None:
            path = props.get("script") or props.get("option1")
            if not path:
                raise ValueError("python3 converter: script=/path/to.py required")
            self._obj = load_script_object(
                path, ("CustomConverter", "converter_class")
            )
            if not hasattr(self._obj, "convert"):
                raise ValueError("python3 converter: script has no convert()")
        return self._obj

    def negotiate(self, in_spec, props: dict) -> TensorsSpec:
        obj = self._load(props)
        if hasattr(obj, "negotiate"):
            return obj.negotiate(in_spec)
        return TensorsSpec(format=TensorFormat.FLEXIBLE)

    def convert(self, frame: Frame, props: dict) -> Frame:
        out = self._load(props).convert(frame.tensors)
        return frame.with_tensors(tuple(out))
