"""flexbuf converter subplugin: serialized flex-tensor bytes → tensors.

Reference: ext/nnstreamer/tensor_converter/tensor_converter_flexbuf.cc —
turns a self-describing binary buffer into other/tensors. The wire format
here is the framework's own flex-tensor header codec (tensors/meta.py),
which is also the edge layer's network format, so
``filesrc ! tensor_converter mode=flexbuf`` round-trips anything
``tensor_decoder mode=flexbuf`` (or the edge sender) produced.
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import NegotiationError
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.meta import decode_frame_tensors
from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec


@registry.converter_plugin("flexbuf")
class FlexbufConverter:
    def negotiate(self, in_spec, props: dict) -> TensorsSpec:
        # input is an opaque byte stream; per-frame headers carry shapes, so
        # the output is format=flexible (self-describing frames)
        return TensorsSpec(format=TensorFormat.FLEXIBLE)

    def convert(self, frame: Frame, props: dict) -> Frame:
        data = np.asarray(frame.tensors[0], dtype=np.uint8).tobytes()
        try:
            tensors = decode_frame_tensors(data)
        except ValueError as exc:
            raise ValueError(f"flexbuf: undecodable frame: {exc}") from exc
        if not tensors:
            raise ValueError("flexbuf: empty frame")
        return frame.with_tensors(tensors)
