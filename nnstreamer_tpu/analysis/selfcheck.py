"""nns-lint --self-check: the PROPERTIES schemas must cover the code.

Every registered builtin element reads its configuration through
``get_property("...")`` / ``props.pop("...")``; this check scans each
element class's source for those literals and fails if any read property
is missing from the class's merged ``PROPERTIES`` schema. The style gate
(tools/check_style.py, tests/test_style.py) runs it, so a new element (or
a new property on an old one) cannot land without schema coverage — the
same role as the reference's gst-inspect property introspection staying
in sync with the GObject param specs by construction.
"""

from __future__ import annotations

import inspect
import re
from typing import Dict, List, Set

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import PROPS_ANY

_PROP_READ = re.compile(r"""(?:get_property|props\.pop)\(\s*["']([^"']+)["']""")

# Properties consumed positionally/indirectly that the scan cannot see but
# the schema intentionally documents anyway — nothing to do for these.


def scan_class_properties(cls: type) -> Set[str]:
    """Property names the class source reads (dash-normalized). Walks the
    MRO so inherited reads (base Element, Sink) are attributed too."""
    names: Set[str] = set()
    for klass in cls.__mro__:
        if klass is object:
            continue
        try:
            src = inspect.getsource(klass)
        except (OSError, TypeError):  # pragma: no cover - builtins only
            continue
        for m in _PROP_READ.finditer(src):
            names.add(m.group(1).replace("_", "-"))
    return names


def self_check() -> List[str]:
    """Return a list of problems (empty = all schemas cover their code)."""
    problems: List[str] = []
    seen: Dict[type, str] = {}
    for name in registry.available(registry.KIND_ELEMENT):
        try:
            cls = registry.get(registry.KIND_ELEMENT, name)
        except KeyError:  # restricted by runtime config
            continue
        if cls in seen:  # aliases (videotestsrc/testsrc) check once
            continue
        seen[cls] = name
        schema = cls.property_schema()
        if PROPS_ANY in schema:
            continue
        for prop in sorted(scan_class_properties(cls)):
            if prop not in schema:
                problems.append(
                    f"{name} ({cls.__module__}.{cls.__name__}): property "
                    f"{prop!r} is read by the code but missing from "
                    "PROPERTIES"
                )
    return problems


# -- nns-san --self-check: the diagnostic catalog must cover the code -------

_CODE_REF = re.compile(r"""["'](NNS-[EWRS]\d{3})["']""")


def _emitted_codes() -> Set[str]:
    """Every diagnostic code referenced by an analyzer/sanitizer module
    (the emitters; the catalog module itself doesn't count)."""
    import importlib

    out: Set[str] = set()
    for name in (
        # importlib (not `import a.b as m`): analysis.__init__ re-binds
        # `lint` to the function, and the as-import would grab that
        "nnstreamer_tpu.analysis.kernels",
        "nnstreamer_tpu.analysis.lint",
        "nnstreamer_tpu.analysis.racecheck",
        "nnstreamer_tpu.analysis.xray",
        "nnstreamer_tpu.pipeline.sanitize",
    ):
        mod = importlib.import_module(name)
        out |= set(_CODE_REF.findall(inspect.getsource(mod)))
    return out


def san_self_check() -> List[str]:
    """Validate the diagnostic catalog against the code (the nns-san
    mirror of the element-schema self-check): every code an analyzer can
    emit exists in the catalog, every catalog code has an emitter, slugs
    are unique, severities match the E/W prefix convention, and the
    sanitizer doc covers the nns-san codes."""
    import os

    from nnstreamer_tpu.analysis.diagnostics import CATALOG, Severity

    problems: List[str] = []
    emitted = _emitted_codes()
    for code in sorted(emitted - set(CATALOG)):
        problems.append(f"code {code} is emitted but not in the catalog")
    for code in sorted(set(CATALOG) - emitted):
        problems.append(f"catalog code {code} has no emitter in the code")
    slugs: Dict[str, str] = {}
    for code, (sev, slug, _desc) in CATALOG.items():
        if slug in slugs:
            problems.append(
                f"slug {slug!r} used by both {slugs[slug]} and {code}"
            )
        slugs[slug] = code
        if code.startswith("NNS-E") and sev is not Severity.ERROR:
            problems.append(f"{code} has an E prefix but severity {sev}")
        if code.startswith("NNS-W") and sev is not Severity.WARNING:
            problems.append(f"{code} has a W prefix but severity {sev}")
    doc = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))),
        "docs", "sanitizer.md",
    )
    if os.path.isfile(doc):  # repo checkouts only; wheels ship no docs
        with open(doc, encoding="utf-8") as f:
            text = f.read()
        for code in sorted(CATALOG):
            if code.startswith(("NNS-R", "NNS-S")) and code not in text:
                problems.append(
                    f"{code} is not documented in docs/sanitizer.md"
                )
    return problems


# -- nns-obs self-check: the metric catalog must cover the code -------------

_METRIC_EMIT = re.compile(
    r"""(?:counter|gauge|histogram)\(\s*\n?\s*["'](nns_[a-z0-9_]+)["']"""
)


def _repo_root() -> str:
    import os

    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))


def obs_self_check() -> List[str]:
    """Validate the nns-obs metric catalog against the code and the docs
    (the metrics mirror of san_self_check): every metric name the
    package emits through a registry call exists in METRIC_CATALOG,
    every cataloged metric has an emitter, and docs/observability.md
    documents every cataloged name."""
    import os

    from nnstreamer_tpu.obs.metrics import METRIC_CATALOG

    problems: List[str] = []
    pkg_root = os.path.join(_repo_root(), "nnstreamer_tpu")
    catalog_file = os.path.join(pkg_root, "obs", "metrics.py")
    emitted: Set[str] = set()
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            if os.path.samefile(path, catalog_file):
                continue  # the catalog module itself doesn't count
            with open(path, encoding="utf-8") as f:
                emitted |= set(_METRIC_EMIT.findall(f.read()))
    for name in sorted(emitted - set(METRIC_CATALOG)):
        problems.append(
            f"metric {name} is emitted but not in METRIC_CATALOG"
        )
    for name in sorted(set(METRIC_CATALOG) - emitted):
        problems.append(
            f"catalog metric {name} has no emitter in the package"
        )
    doc = os.path.join(_repo_root(), "docs", "observability.md")
    if os.path.isfile(doc):  # repo checkouts only; wheels ship no docs
        with open(doc, encoding="utf-8") as f:
            text = f.read()
        for name in sorted(METRIC_CATALOG):
            if name not in text:
                problems.append(
                    f"metric {name} is not documented in "
                    "docs/observability.md"
                )
    return problems


# -- nns-xray self-check: chain codes wired emitters<->catalog<->docs -------

_XRAY_CODES = (
    "NNS-W120", "NNS-W121", "NNS-W122", "NNS-W123", "NNS-W124",
    "NNS-W125",
)


def xray_self_check() -> List[str]:
    """Validate the chain-analysis diagnostics both ways: every
    W120-W125 code is in the catalog, has an emitter in
    analysis/xray.py, and is documented in docs/chain-analysis.md AND
    docs/linting.md; conversely every NNS code docs/chain-analysis.md
    mentions exists in the catalog (no doc drift either direction)."""
    import importlib
    import os

    from nnstreamer_tpu.analysis.diagnostics import CATALOG

    problems: List[str] = []
    mod = importlib.import_module("nnstreamer_tpu.analysis.xray")
    emitted = set(_CODE_REF.findall(inspect.getsource(mod)))
    for code in _XRAY_CODES:
        if code not in CATALOG:
            problems.append(f"chain code {code} missing from the catalog")
        if code not in emitted:
            problems.append(
                f"chain code {code} has no emitter in analysis/xray.py"
            )
    for doc_name in ("chain-analysis.md", "linting.md"):
        doc = os.path.join(_repo_root(), "docs", doc_name)
        if not os.path.isfile(doc):  # repo checkouts only
            continue
        with open(doc, encoding="utf-8") as f:
            text = f.read()
        for code in _XRAY_CODES:
            if code not in text:
                problems.append(
                    f"{code} is not documented in docs/{doc_name}"
                )
        if doc_name == "chain-analysis.md":
            for code in sorted(set(_CODE_REF.findall(text))):
                if code not in CATALOG:
                    problems.append(
                        f"docs/chain-analysis.md mentions unknown code "
                        f"{code}"
                    )
    return problems


# -- nns-kscope self-check: kernel codes + registry wired both ways ---------

_KSCOPE_CODES = ("NNS-W127", "NNS-W128", "NNS-W129")


def kscope_self_check() -> List[str]:
    """Validate the kernel-analysis wiring both ways: every W127-W129
    code is in the catalog, has an emitter in analysis/kernels.py, and
    is documented in docs/kernel-analysis.md AND docs/linting.md;
    every NNS code docs/kernel-analysis.md mentions exists in the
    catalog; every public kernel entry point in ops/pallas has a
    registered KernelSpec of the same name (and vice versa); and the
    union of registered dispatch ops equals ops/dispatch.KNOWN_OPS (a
    dispatch site cannot appear without --engage coverage)."""
    import importlib
    import os

    from nnstreamer_tpu.analysis.diagnostics import CATALOG

    problems: List[str] = []
    mod = importlib.import_module("nnstreamer_tpu.analysis.kernels")
    emitted = set(_CODE_REF.findall(inspect.getsource(mod)))
    for code in _KSCOPE_CODES:
        if code not in CATALOG:
            problems.append(f"kernel code {code} missing from the catalog")
        if code not in emitted:
            problems.append(
                f"kernel code {code} has no emitter in analysis/kernels.py"
            )
    for doc_name in ("kernel-analysis.md", "linting.md"):
        doc = os.path.join(_repo_root(), "docs", doc_name)
        if not os.path.isfile(doc):  # repo checkouts only
            continue
        with open(doc, encoding="utf-8") as f:
            text = f.read()
        for code in _KSCOPE_CODES:
            if code not in text:
                problems.append(
                    f"{code} is not documented in docs/{doc_name}"
                )
        if doc_name == "kernel-analysis.md":
            for code in sorted(set(_CODE_REF.findall(text))):
                if code not in CATALOG:
                    problems.append(
                        f"docs/kernel-analysis.md mentions unknown code "
                        f"{code}"
                    )
    # registry completeness: public kernel entry points <-> KernelSpecs
    import nnstreamer_tpu.ops.pallas as pallas_pkg
    from nnstreamer_tpu.ops import dispatch
    from nnstreamer_tpu.ops.pallas import registry as kreg

    public = {
        name for name, obj in vars(pallas_pkg).items()
        # callable, not isfunction: the entry points are jax.jit-wrapped
        if not name.startswith("_") and callable(obj)
        and not inspect.ismodule(obj)
        and getattr(obj, "__module__", "").startswith(
            "nnstreamer_tpu.ops.pallas.")
        and not getattr(obj, "__name__", "").endswith("_ref")
    }
    registered = set(kreg.names())
    for name in sorted(public - registered):
        problems.append(
            f"ops/pallas exports kernel {name!r} with no registered "
            "KernelSpec (nns-kscope cannot analyze it)"
        )
    for name in sorted(registered - public):
        problems.append(
            f"KernelSpec {name!r} is registered but ops/pallas exports "
            "no kernel of that name"
        )
    covered = set()
    for spec in kreg.all_specs():
        covered |= set(spec.ops)
    for op in sorted(set(dispatch.KNOWN_OPS) - covered):
        problems.append(
            f"dispatch op {op!r} is in KNOWN_OPS but no KernelSpec "
            "covers it (--engage cannot prove it)"
        )
    for op in sorted(covered - set(dispatch.KNOWN_OPS)):
        problems.append(
            f"KernelSpec op {op!r} is not in ops/dispatch.KNOWN_OPS"
        )
    return problems


# -- nns-disagg self-check: disagg codes + metrics wired both ways ----------

_DISAGG_CODES = ("NNS-W130",)


def disagg_self_check() -> List[str]:
    """Validate the disaggregated-serving wiring both ways: every
    disagg lint code is in the catalog, has an emitter in
    analysis/lint.py, and is documented in docs/linting.md AND
    docs/llm-serving.md; and both disagg metrics
    (``nns_disagg_handoffs_total``, ``nns_route_prefix_hits_total``)
    are in the METRIC_CATALOG with a live emitter in the serving/edge
    code — a renamed counter cannot silently fall out of the docs."""
    import importlib
    import os

    from nnstreamer_tpu.analysis.diagnostics import CATALOG

    problems: List[str] = []
    mod = importlib.import_module("nnstreamer_tpu.analysis.lint")
    emitted = set(_CODE_REF.findall(inspect.getsource(mod)))
    for code in _DISAGG_CODES:
        if code not in CATALOG:
            problems.append(f"disagg code {code} missing from the catalog")
        if code not in emitted:
            problems.append(
                f"disagg code {code} has no emitter in analysis/lint.py"
            )
    for doc_name in ("linting.md", "llm-serving.md"):
        doc = os.path.join(_repo_root(), "docs", doc_name)
        if not os.path.isfile(doc):  # repo checkouts only
            continue
        with open(doc, encoding="utf-8") as f:
            text = f.read()
        for code in _DISAGG_CODES:
            if code not in text:
                problems.append(
                    f"{code} is not documented in docs/{doc_name}"
                )
    from nnstreamer_tpu.obs.metrics import METRIC_CATALOG

    wanted = {
        "nns_disagg_handoffs_total": "nnstreamer_tpu.serving_plane.disagg",
        "nns_route_prefix_hits_total": "nnstreamer_tpu.edge.query",
    }
    for metric, mod_name in wanted.items():
        if metric not in METRIC_CATALOG:
            problems.append(
                f"disagg metric {metric} missing from METRIC_CATALOG"
            )
        src = inspect.getsource(importlib.import_module(mod_name))
        if f'"{metric}"' not in src and f"'{metric}'" not in src:
            problems.append(
                f"disagg metric {metric} has no emitter in {mod_name}"
            )
    return problems


def main(argv=None) -> int:  # pragma: no cover - thin wrapper
    problems = self_check()
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} schema gap(s)")
        return 1
    print("all element PROPERTIES schemas cover their code")
    return 0
