"""nns-xray: whole-chain compile-unit inference and jaxpr lint.

``nns-lint`` answers "is this pipeline wired right"; this module
answers "what will XLA actually compile, and what will it cost". From
a launch string (or constructed Pipeline) it compiles the plan the
executor would run and reports at CHAIN granularity
(:meth:`ExecPlan.chains` — maximal runs of fused segments joined by
device-resident handoffs, the span ROADMAP item 1 would compile into
one resident program):

- **compile units** — which elements land in which chain, and what
  severs the chains (docs/chain-analysis.md);
- **jaxpr lint** — each segment's composed program traced abstractly
  (``jax.make_jaxpr``, no device work) and walked for silent f64/dtype
  promotion (NNS-W122), host callbacks inside a would-be-resident
  chain (NNS-W120), donation-defeating outputs (NNS-W123 via the same
  ``_aliasable_argnums`` the executor donates with), and jit-cache-key
  cardinality hazards from the bucket ladder (NNS-W121);
- **cost model** — per-chain params/activation/transient-HBM bytes and
  predicted per-frame host-transfer bytes at every boundary
  (analysis/costmodel.py), checked against the declared device bound
  (NNS-W124) and verifiable at runtime against ``TransferTally``
  (``Executor.transfer_crosscheck``, ``NNS_XRAY_CROSSCHECK``);
- **kernel dispatch** — :func:`dispatch_table` proves which Pallas/jnp
  implementation each dual-path op engages (ops/dispatch.py).

The shared static predicates (``device_capable`` & co.) moved here
from lint's resident-handoff pass, which now imports them — the two
analyzers can never disagree about what splits a chain.

Pipelines are never started. Stateful serving elements
(``LINT_SKIP_NEGOTIATE``) and pipelines whose negotiation fails (e.g.
doc snippets naming absent model files) degrade to notes-only results
with zero diagnostics.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

from nnstreamer_tpu.analysis.costmodel import (
    ChainCost,
    TransferBoundary,
    chain_cost,
    configured_device_bound,
    plan_transfer_boundaries,
    predict_frame_transfers,
)
from nnstreamer_tpu.analysis.diagnostics import Diagnostic, LintReport
from nnstreamer_tpu.log import get_logger

_log = get_logger("xray")

# past this many jit-cache keys for ONE segment, steady state is still
# compiling (bucket ladders are O(log max-batch), so a healthy segment
# sits far below)
_CACHE_KEY_BOUND = 32
# donated-but-unreusable buffers below this are noise, not a finding
_DONATION_MIN_BYTES = 1 << 20
_HOST_CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "callback", "outside_call",
    "host_callback_call", "debug_callback",
})


# -- shared static predicates ------------------------------------------------
# Used by BOTH lint's resident-handoff pass (analysis/lint.py) and the
# chain passes below. Everything reads element/backend CLASSES — no
# backend open, no model load, no negotiation.

def device_capable(e: Any) -> bool:
    """A tensor_filter that will trace into a fused device segment:
    explicit registered framework whose backend class overrides
    ``traceable_fn``, no fallback-framework, no replica fan-out."""
    from nnstreamer_tpu import registry
    from nnstreamer_tpu.backends.base import Backend
    from nnstreamer_tpu.elements.filter import TensorFilter

    if not isinstance(e, TensorFilter):
        return False
    fw = e.get_property("framework")
    if not fw or str(fw) == "auto":
        return False
    if e.get_property("fallback-framework"):
        return False  # deliberate per-frame fusion barrier
    try:
        if int(e.get_property("replicas") or 0) > 1:
            return False  # idem
    except (TypeError, ValueError):
        pass
    try:
        cls = registry.get(registry.KIND_FILTER, str(fw))
    except KeyError:
        return False  # unknown framework has its own diagnostic
    return cls.traceable_fn is not Backend.traceable_fn


def transparent(e: Any) -> bool:
    """Plumbing a device array rides through untouched: thread/buffer
    boundaries and fan-out that never read tensor bytes."""
    from nnstreamer_tpu.elements.flow import CapsFilter, Queue, Tee

    return isinstance(e, (Queue, CapsFilter, Tee))


def host_bound(e: Any) -> bool:
    """Elements that read/produce tensor bytes on host. Routing
    (mux/demux/split/join) regroups frames without touching bytes, so
    it passes device arrays through; traceable TensorOps
    (tensor_transform, device filters) FUSE into the chain — no split
    to warn about."""
    from nnstreamer_tpu import registry
    from nnstreamer_tpu.backends.base import Backend
    from nnstreamer_tpu.elements.base import Routing, TensorOp
    from nnstreamer_tpu.elements.filter import TensorFilter

    if transparent(e) or isinstance(e, Routing):
        return False
    if isinstance(e, TensorFilter):
        fw = e.get_property("framework")
        if not fw or str(fw) == "auto":
            return False  # can't tell statically; never open here
        try:
            cls = registry.get(registry.KIND_FILTER, str(fw))
        except KeyError:
            return False
        return cls.traceable_fn is Backend.traceable_fn
    if isinstance(e, TensorOp):
        try:
            return not e.is_traceable()
        except Exception:  # noqa: BLE001 — can't tell without opening
            return False
    return hasattr(e, "host_process")


def host_postproc_with_device_path(e: Any) -> bool:
    """NNS-W116's static capability read (no negotiation, no
    model/labels load): a tensor_decoder that will RUN host
    (postproc=host, or postproc=auto with a subplugin that offers no
    auto-fuse make_fn) while its subplugin declares a device decode
    path for these options."""
    from nnstreamer_tpu import registry
    from nnstreamer_tpu.elements.decoder import TensorDecoder

    if not isinstance(e, TensorDecoder):
        return False
    if e.postproc == "device" or e.mode == "custom-code":
        return False
    try:
        cls = registry.get(registry.KIND_DECODER, e.mode)
    except KeyError:
        return False  # unknown mode has its own diagnostic
    probe = getattr(cls, "device_capable", None)
    if probe is None or not probe(e.options):
        return False
    if e.postproc == "auto" and getattr(cls, "make_fn", None) is not None:
        return False  # auto already fuses this subplugin
    return True


def decoder_will_fuse(e: Any) -> bool:
    """Decoders whose is_traceable() is False only because lint never
    negotiates: postproc=device always fuses (or fails negotiation
    loudly), and auto fuses subplugins that offer a make_fn for these
    options (image_labeling without labels)."""
    from nnstreamer_tpu import registry
    from nnstreamer_tpu.elements.decoder import TensorDecoder

    if not isinstance(e, TensorDecoder) or e.mode == "custom-code":
        return False
    if e.postproc == "device":
        return True
    if e.postproc != "auto":
        return False
    try:
        cls = registry.get(registry.KIND_DECODER, e.mode)
    except KeyError:
        return False
    if getattr(cls, "make_fn", None) is None:
        return False
    probe = getattr(cls, "device_capable", None)
    return probe is None or bool(probe(e.options))


def reaches_capable(e: Any, links: Callable[[Any], List[Any]]) -> bool:
    """A device-capable filter is reachable from ``e`` across only
    transparent plumbing (the resident handoff's span)."""
    seen = {e}
    frontier = [n for n in links(e) if n not in seen]
    while frontier:
        n = frontier.pop()
        if n in seen:
            continue
        seen.add(n)
        if device_capable(n):
            return True
        if transparent(n):
            frontier.extend(links(n))
    return False


# -- result types ------------------------------------------------------------

@dataclass
class ChainReport:
    """One compile unit's analysis row.

    ``compiled`` is the executor's OWN verdict for the whole-chain
    resident program (pipeline/chain_program.py ``decide_chain`` — the
    same function ``Executor._build`` calls, so the report can never
    disagree with what actually runs): ``yes (unroll K)``, or ``no:``
    followed by the blocking hazard/config."""

    name: str
    segments: List[str]
    n_ops: int
    cost: ChainCost
    notes: List[str] = field(default_factory=list)
    compiled: str = ""


@dataclass
class XrayResult:
    """Chain analysis outcome: compile units + costs + diagnostics.
    ``degraded`` means the pipeline could not be compiled here
    (stateful serving elements, absent model files) and only notes are
    available — by design zero W120–W124."""

    report: LintReport
    pipeline: Optional[Any] = None
    plan: Optional[Any] = None
    chains: List[ChainReport] = field(default_factory=list)
    boundaries: List[TransferBoundary] = field(default_factory=list)
    predicted: Dict[str, int] = field(default_factory=dict)
    predicted_tpu: Dict[str, int] = field(default_factory=dict)
    dispatch: List[Dict[str, Any]] = field(default_factory=list)
    notes: List[str] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)
    degraded: bool = False

    @property
    def diagnostics(self) -> List[Diagnostic]:
        return self.report.diagnostics

    @property
    def codes(self) -> List[str]:
        return self.report.codes

    @property
    def exit_code(self) -> int:
        if self.errors:
            return 2
        return self.report.exit_code

    def render(self) -> str:
        lines: List[str] = []
        for err in self.errors:
            lines.append(f"error: {err}")
        for note in self.notes:
            lines.append(f"note: {note}")
        lines.append(f"compile units: {len(self.chains)}")
        for ch in self.chains:
            lines.append(
                f"  chain [{ch.name}]: {ch.n_ops} op(s) in "
                f"{len(ch.segments)} segment(s)"
            )
            c = ch.cost
            lines.append(
                f"    params {_fmt_bytes(c.params_bytes)}, activations "
                f"{_fmt_bytes(c.activation_bytes)}, peak transient "
                f"{_fmt_bytes(c.transient_bytes)}, boundary in/out "
                f"{_fmt_bytes(c.boundary_in_bytes)}/"
                f"{_fmt_bytes(c.boundary_out_bytes)} per frame"
            )
            if ch.compiled:
                lines.append(f"    compiled: {ch.compiled}")
            for note in ch.notes:
                lines.append(f"    note: {note}")
        for b in self.boundaries:
            lines.append(
                f"  boundary {b.direction} {b.producer} -> {b.consumer} "
                f"({b.reason}): {_fmt_bytes(b.bytes_per_frame)}/frame"
            )
        if self.predicted:
            lines.append(
                f"predicted per-frame transfer here: "
                f"h2d={self.predicted['h2d']} d2h={self.predicted['d2h']}"
                f"  (on tpu: h2d={self.predicted_tpu['h2d']} "
                f"d2h={self.predicted_tpu['d2h']})"
            )
        for d in self.diagnostics:
            lines.append(str(d))
        if self.dispatch:
            lines.append("kernel dispatch (impl=auto):")
            for row in self.dispatch:
                measured = ",".join(row["measured"]) or "-"
                lines.append(
                    f"  {row['op']}: on-tpu={row['auto_on_tpu']} "
                    f"here={row['auto_here']} measured={measured}"
                    + (f" ({row['error']})" if row.get("error") else "")
                )
        return "\n".join(lines)


def _fmt_bytes(n: int) -> str:
    size = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if size < 1024 or unit == "GiB":
            return (
                f"{int(size)} {unit}" if unit == "B"
                else f"{size:.1f} {unit}"
            )
        size /= 1024
    return f"{n} B"


# -- jaxpr lint --------------------------------------------------------------

def _sub_jaxprs(v: Any) -> List[Any]:
    out = []
    vals = v if isinstance(v, (list, tuple)) else [v]
    for x in vals:
        x = getattr(x, "jaxpr", x)  # ClosedJaxpr → Jaxpr
        if hasattr(x, "eqns"):
            out.append(x)
    return out


def _iter_eqns(jaxpr: Any):
    """Every equation, recursing into sub-jaxprs (scan/cond/pjit
    bodies)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from _iter_eqns(sub)


def segment_jaxpr(seg: Any) -> Optional[Any]:
    """The segment's composed program traced abstractly at its
    negotiated per-frame signature (``jax.make_jaxpr`` over
    ShapeDtypeStructs — no device work). None when the input spec is
    flexible."""
    import jax

    sig = seg._negotiated_sig()
    if sig is None:
        return None
    shapes = [jax.ShapeDtypeStruct(shape, dtype) for shape, dtype in sig]
    return jax.make_jaxpr(seg._compose())(*shapes)


def _is_wide(dtype: Any) -> bool:
    d = np.dtype(dtype)
    return d.kind in "fc" and d.itemsize >= 8


def dtype_findings(
    jaxpr: Any, declared_out: Optional[Tuple] = None
) -> List[str]:
    """NNS-W122 walker: silent f64/complex128 promotion (a wide value
    appears with no wide input) and traced-vs-negotiated output dtype
    drift. Pure jaxpr arithmetic — callable directly in tests under
    ``jax.experimental.enable_x64``."""
    msgs: List[str] = []
    if not any(_is_wide(a.dtype) for a in jaxpr.in_avals):
        for eqn in _iter_eqns(jaxpr.jaxpr):
            wide = [
                np.dtype(v.aval.dtype).name
                for v in eqn.outvars
                if getattr(getattr(v, "aval", None), "dtype", None)
                is not None and _is_wide(v.aval.dtype)
            ]
            if wide:
                msgs.append(
                    f"`{eqn.primitive.name}` produces {wide[0]} with no "
                    f"64-bit input"
                )
                break  # one promotion site is enough evidence
    if declared_out:
        for i, (aval, want) in enumerate(zip(jaxpr.out_avals, declared_out)):
            if np.dtype(aval.dtype) != np.dtype(want):
                msgs.append(
                    f"output {i} traces as {np.dtype(aval.dtype).name} "
                    f"but negotiated {np.dtype(want).name}"
                )
    return msgs


def host_callback_prims(jaxpr: Any) -> List[str]:
    """NNS-W120 walker: host-callback primitives inside a device
    program (each invocation round-trips through Python + host
    memory)."""
    found = []
    for eqn in _iter_eqns(jaxpr.jaxpr):
        name = eqn.primitive.name
        if name in _HOST_CALLBACK_PRIMS and name not in found:
            found.append(name)
    return found


def cache_key_finding(seg: Any) -> Optional[str]:
    """NNS-W121: unbounded or exploding jit-cache key space for one
    segment."""
    sig = seg._negotiated_sig()
    cfg = seg.batch_config
    active = bool(
        cfg is not None and getattr(cfg, "active", False)
        and getattr(cfg, "buckets", ())
    )
    if sig is None and active:
        return (
            "flexible per-frame input spec under micro-batching: every "
            "distinct arriving shape multiplies the bucket ladder "
            f"({len(cfg.buckets)} buckets) into fresh XLA compiles — "
            "the cache key space is unbounded"
        )
    if sig is not None and active:
        n_keys = (len(cfg.buckets) + 1) * (2 if seg.donate else 1)
        if n_keys > _CACHE_KEY_BOUND:
            return (
                f"{n_keys} jit-cache keys for one segment (buckets x "
                "donation variants): steady state keeps compiling"
            )
    return None


def donation_finding(seg: Any) -> Optional[str]:
    """NNS-W123: the segment streams with donated buffers but XLA can
    reuse none of them (no output shape/dtype-matches any input).
    Checked on the path that actually donates at runtime: the batched
    stacked-window program when micro-batching is active, else the
    per-frame staging program (which only donates off-CPU —
    pipeline/graph.py ``build``), so a CPU-only run without batching
    never false-positives."""
    from nnstreamer_tpu.pipeline.transfer import default_backend_is_cpu

    sig = seg._negotiated_sig()
    if sig is None or not seg.donate or (seg.ring_depth or 1) <= 1:
        return None
    cfg = seg.batch_config
    batched = bool(
        cfg is not None and getattr(cfg, "active", False)
        and getattr(cfg, "buckets", ())
    )
    if not batched and default_backend_is_cpu():
        return None  # the per-frame path never donates on local CPU
    bucket = int(cfg.buckets[-1]) if batched else 0
    in_bytes = sum(
        int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
        for shape, dtype in sig
    ) * max(1, bucket)
    if in_bytes < _DONATION_MIN_BYTES:
        return None
    try:
        import jax

        composed = seg._compose()
        target = jax.vmap(composed) if bucket else composed
        argnums = seg._aliasable_argnums(target, sig, bucket)
    except Exception:  # noqa: BLE001 — untraceable here: no verdict
        return None
    if argnums:
        return None
    return (
        f"donate is on (ring-depth {seg.ring_depth}) but no output "
        f"matches any input's shape/dtype: {_fmt_bytes(in_bytes)} donated "
        "per dispatch with nothing reused — every frame pays a fresh "
        "output allocation"
    )


# -- chain passes ------------------------------------------------------------

def _nearest_segment(plan: Any, e: Any, links: Callable) -> Optional[Any]:
    seen: set = set()
    frontier = list(links(e))
    while frontier:
        n = frontier.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        seg = plan.seg_of.get(n)
        if seg is not None:
            return seg
        if transparent(n):
            frontier.extend(links(n))
    return None


def _host_split_pass(plan: Any, chains: List[Any], report: LintReport) -> None:
    """NNS-W120 (structural): a host-path tensor op with a fused
    segment on BOTH sides — the two chains around it would be one
    compile unit if the op had a device path. Decoders that W116
    already pinpoints (device path exists, one property away) are
    excluded: one code per boundary."""
    from nnstreamer_tpu.elements.base import Routing, TensorOp

    pipeline = plan.pipeline
    chain_of = {
        id(seg): ch for ch in chains for seg in ch.segments
    }

    def ups(e):
        return [ln.src for ln in pipeline.in_links(e)]

    def downs(e):
        return [ln.dst for ln in pipeline.out_links(e)]

    for e in pipeline.elements:
        if not isinstance(e, TensorOp) or e in plan.seg_of:
            continue
        if transparent(e) or isinstance(e, Routing):
            continue
        if decoder_will_fuse(e) or host_postproc_with_device_path(e):
            continue  # W116 territory (one-property fix; lint emits it)
        up_seg = _nearest_segment(plan, e, ups)
        down_seg = _nearest_segment(plan, e, downs)
        if up_seg is None or down_seg is None:
            continue
        up_name = chain_of[id(up_seg)].name
        down_name = chain_of[id(down_seg)].name
        report.add(
            "NNS-W120", e.name,
            f"host-path op severs the chain: [{up_name}] and "
            f"[{down_name}] would be ONE compile unit, but every frame "
            "materializes to host and re-stages to device here",
            "give this op a device-capable framework/traceable path, or "
            "move it outside the device span (docs/chain-analysis.md)",
        )


def _segment_pass(
    seg: Any, report: LintReport, notes: List[str]
) -> None:
    jaxpr = None
    try:
        jaxpr = segment_jaxpr(seg)
    except Exception as exc:  # noqa: BLE001 — trace is best-effort
        notes.append(f"{seg.name}: trace unavailable ({exc})")
    if jaxpr is not None:
        for prim in host_callback_prims(jaxpr):
            report.add(
                "NNS-W120", seg.first.name,
                f"host callback `{prim}` inside device segment "
                f"{seg.name}: every invocation round-trips through "
                "Python and host memory, and the chain can never become "
                "one resident program",
                "compute in-graph, or split the callback into an "
                "explicit host element (docs/chain-analysis.md)",
            )
        declared = None
        out_spec = seg.last.out_specs[0] if seg.last.out_specs else None
        if out_spec is not None and getattr(out_spec, "is_static", False):
            declared = tuple(t.dtype.np_dtype for t in out_spec)
        for msg in dtype_findings(jaxpr, declared):
            report.add(
                "NNS-W122", seg.first.name,
                f"segment {seg.name}: {msg}",
                "pin dtypes explicitly (astype at the boundary) — on "
                "TPU 64-bit math is emulated and doubles activation "
                "bytes (docs/chain-analysis.md)",
            )
        for i, v in enumerate(jaxpr.jaxpr.outvars):
            if any(v is iv for iv in jaxpr.jaxpr.invars):
                notes.append(
                    f"{seg.name}: output {i} is an untouched passthrough "
                    "of an input (dead compute path?)"
                )
    msg = cache_key_finding(seg)
    if msg is not None:
        report.add(
            "NNS-W121", seg.first.name,
            f"segment {seg.name}: {msg}",
            "declare static dimensions upstream (capsfilter / source "
            "dimensions=) or disable batching on this segment "
            "(docs/chain-analysis.md)",
        )
    msg = donation_finding(seg)
    if msg is not None:
        report.add(
            "NNS-W123", seg.first.name,
            f"segment {seg.name}: {msg}",
            "match an output to an input shape/dtype (in-place-style "
            "update) or set donate=false for this segment "
            "(docs/chain-analysis.md)",
        )


def _bound_pass(chain: Any, cost: ChainCost, report: LintReport) -> None:
    bound = configured_device_bound()
    if bound is None or cost.resident_bytes <= bound:
        return
    report.add(
        "NNS-W124", chain.first.name,
        f"chain [{chain.name}]: resident "
        f"{_fmt_bytes(cost.resident_bytes)} (params "
        f"{_fmt_bytes(cost.params_bytes)} + peak transient "
        f"{_fmt_bytes(cost.transient_bytes)} at the max micro-batch "
        f"bucket) exceeds [plane] memory_per_device {_fmt_bytes(bound)}",
        "shrink the max batch bucket, split the chain across devices "
        "(serving_plane placement), or raise the bound "
        "(docs/chain-analysis.md)",
    )


def _compiled_pass(
    plan: Any, chain: Any, cr: "ChainReport", report: LintReport
) -> None:
    """Fill the chain report's ``compiled`` column from the executor's
    own verdict (pipeline/chain_program.py ``decide_chain``) and emit
    NNS-W125 for the one configuration the lint exists for: a
    hazard-free multi-segment chain someone switched OFF — leaving a
    per-node-per-frame dispatch cost the compiled path would remove."""
    from nnstreamer_tpu.pipeline.chain_program import decide_chain

    try:
        d = decide_chain(plan, chain)
    except Exception as exc:  # noqa: BLE001 — verdict is best-effort here
        cr.compiled = f"no: verdict unavailable ({exc})"
        return
    if d.compiles:
        cr.compiled = f"yes (unroll {d.unroll})"
        return
    if d.eligible:  # and therefore mode == "off"
        cr.compiled = "no: chain_mode=off"
        report.add(
            "NNS-W125", chain.first.name,
            f"chain [{chain.name}]: {len(chain.segments)} hazard-free "
            "segments are running with chain_mode=off — every frame "
            "crosses one service thread per node where ONE resident "
            "program (dispatched once per unrolled window) would serve "
            "it",
            "set [executor] chain_mode=auto (or drop the chain-mode=off "
            "property) to compile this chain; keep off only while "
            "debugging against the per-node parity oracle "
            "(docs/chain-analysis.md)",
        )
        return
    cr.compiled = f"no: {d.reason}"


# -- entry point -------------------------------------------------------------

def xray(
    target: Union[str, Any], open_backends: bool = True
) -> XrayResult:
    """Analyze a launch string or constructed Pipeline at chain
    granularity. Compiles the plan (negotiation runs — tensor_filter
    backends open exactly as the executor would open them; nothing is
    started). ``open_backends=False`` skips params estimation in the
    cost model."""
    report = LintReport()
    res = XrayResult(report=report)
    if isinstance(target, str):
        from nnstreamer_tpu.pipeline.parse import parse_pipeline

        try:
            pipeline = parse_pipeline(target)
        except Exception as exc:  # noqa: BLE001 — surfaced as the result
            res.errors.append(f"parse failed: {exc}")
            return res
    else:
        pipeline = target
    res.pipeline = pipeline
    skip = [
        e.name for e in pipeline.elements if type(e).LINT_SKIP_NEGOTIATE
    ]
    if skip:
        res.degraded = True
        res.notes.append(
            "negotiation skipped (stateful serving elements: "
            f"{', '.join(skip)}); chain analysis unavailable"
        )
        return res
    try:
        plan = pipeline.compile_plan()
    except Exception as exc:  # noqa: BLE001 — degrade, lint owns the error
        res.degraded = True
        res.notes.append(
            f"compile_plan failed ({exc}); chain analysis unavailable"
        )
        return res
    res.plan = plan
    chains = plan.chains()
    res.boundaries = plan_transfer_boundaries(plan)
    res.predicted = predict_frame_transfers(plan)
    res.predicted_tpu = predict_frame_transfers(plan, assume_tpu=True)
    _host_split_pass(plan, chains, report)
    for chain in chains:
        cost = chain_cost(chain, open_backends=open_backends)
        cr = ChainReport(
            name=chain.name,
            segments=[s.name for s in chain.segments],
            n_ops=len(chain.ops),
            cost=cost,
        )
        for seg in chain.segments:
            _segment_pass(seg, report, cr.notes)
        _bound_pass(chain, cost, report)
        _compiled_pass(plan, chain, cr, report)
        res.chains.append(cr)
    return res


# -- kernel dispatch table ---------------------------------------------------

def _probe_crop() -> None:
    import jax.numpy as jnp

    from nnstreamer_tpu.ops.image import crop_and_resize

    crop_and_resize(
        jnp.zeros((8, 8, 3), jnp.float32),
        jnp.asarray([[0.0, 0.0, 4.0, 4.0]], jnp.float32), 4, 4,
    )


def _probe_resize() -> None:
    import jax.numpy as jnp

    from nnstreamer_tpu.ops.image import resize_bilinear

    resize_bilinear(jnp.zeros((8, 8, 3), jnp.float32), 4, 4)


def _probe_nms() -> None:
    import jax.numpy as jnp

    from nnstreamer_tpu.ops.detection import nms

    nms(
        jnp.zeros((4, 4), jnp.float32), jnp.zeros((4,), jnp.float32),
        0.5, 2,
    )


def _probe_block_attn() -> None:
    import jax.numpy as jnp

    from nnstreamer_tpu.kv.block_attn import block_attention

    b, h, hd, bs = 1, 2, 4, 2
    block_attention(
        jnp.zeros((b, 1, h, hd), jnp.float32),
        jnp.zeros((4, bs, h, hd), jnp.float32),
        jnp.zeros((4, bs, h, hd), jnp.float32),
        jnp.zeros((b, 2), jnp.int32),
        jnp.zeros((b,), jnp.int32),
        (
            jnp.zeros((b, 1, h, hd), jnp.float32),
            jnp.zeros((b, 1, h, hd), jnp.float32),
        ),
    )


_DISPATCH_PROBES: List[Tuple[str, Optional[Callable[[], None]]]] = [
    ("crop_and_resize", _probe_crop),
    ("resize_bilinear", _probe_resize),
    ("nms", _probe_nms),
    ("block_attention", _probe_block_attn),
    ("serving_attention", None),  # construction-time dispatch: static row
]


def dispatch_table(run: bool = True) -> List[Dict[str, Any]]:
    """Which implementation each dual-path op engages under
    ``impl="auto"``: the static decision for TPU and for THIS backend,
    plus — with ``run=True`` — the impls actually measured by invoking
    each op on tiny inputs and diffing the dispatch tally
    (ops/dispatch.py). The dispatch record lands at the branch point
    before any math, so even a probe that fails numerically still
    proves its dispatch."""
    import jax

    from nnstreamer_tpu.ops import dispatch as disp

    on_tpu = jax.default_backend() == "tpu"
    rows: List[Dict[str, Any]] = []
    for op, probe in _DISPATCH_PROBES:
        fallback = "xla" if op == "serving_attention" else "jnp"
        before = disp.tally.snapshot()
        err = None
        if run and probe is not None:
            try:
                probe()
            except Exception as exc:  # noqa: BLE001 — probe is best-effort
                err = f"probe failed: {exc}"
        rows.append({
            "op": op,
            "auto_on_tpu": "pallas",
            "auto_here": "pallas" if on_tpu else fallback,
            "measured": (
                disp.engaged_impls(op, before)
                if run and probe is not None else []
            ),
            "error": err,
        })
    return rows
