"""Static pipeline analysis (nns-lint): pre-flight validation of launch
strings and constructed Pipelines without ever starting them.

Public surface:

    from nnstreamer_tpu.analysis import lint
    result = lint("videotestsrc ! tensor_converter ! tensor_sink")
    for d in result.diagnostics:
        print(d)            # NNS-E003 error [tensor_filter0]: ...
    sys.exit(result.exit_code)   # 0 clean / 1 warnings / 2 errors

See docs/linting.md for the diagnostic-code catalog.
"""

from nnstreamer_tpu.analysis.diagnostics import (  # noqa: F401
    CATALOG,
    Diagnostic,
    LintReport,
    Severity,
)
from nnstreamer_tpu.analysis.lint import (  # noqa: F401
    DEADLOCK_CODES,
    LintResult,
    annotated_dot,
    check_properties,
    coerce_property,
    lint,
)
from nnstreamer_tpu.analysis.racecheck import run_race_lint  # noqa: F401
from nnstreamer_tpu.analysis.xray import (  # noqa: F401
    XrayResult,
    dispatch_table,
    xray,
)
