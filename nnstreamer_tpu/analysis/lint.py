"""nns-lint: static pipeline analysis — report EVERY problem, start nothing.

The reference front-loads failure detection with gst-validate, confchk and
the launch parser's semantic checks because launch-string pipelines fail
late and cryptically at runtime. This module gives the reproduction the
same pre-flight: take a launch string (or a constructed Pipeline) and,
WITHOUT starting it, run four passes that each append structured
:class:`~nnstreamer_tpu.analysis.diagnostics.Diagnostic` findings:

1. graph structure — unlinked pads, cycles (with the member list),
   unreachable elements, mux fan-in branches sharing a tee ancestor with
   no intervening queue (the classic deadlock topology);
2. dry-run spec flow — each element's own ``negotiate()`` runs on a CLONE
   in topological order, so every caps mismatch in the graph is reported,
   not just the first, and the user's pipeline object is never mutated;
3. property validation — launch-string properties are checked against the
   elements' ``PROPERTIES`` schemas (unknown names, un-coercible values);
4. resource checks — tensor_filter model paths that don't exist,
   ``framework=`` naming an unregistered backend, decoder/converter modes
   missing from the registry.

Pipelines are never executed: no ``start()``, no executor, no sockets.
"""

from __future__ import annotations

import copy
import difflib
import os
from dataclasses import dataclass
from fractions import Fraction
from typing import Any, Dict, List, Optional, Set, Tuple, Union

from nnstreamer_tpu import registry
from nnstreamer_tpu.analysis.diagnostics import Diagnostic, LintReport
from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.elements.base import (
    Element,
    PropSpec,
    PROPS_ANY,
    Routing,
    Sink,
    Source,
)
from nnstreamer_tpu.pipeline.graph import Pipeline
from nnstreamer_tpu.pipeline.parse import (
    ParseError,
    _make_caps_element,
    _parse_caps,
    scan_description,
)

_log = get_logger("lint")


class _Placeholder(Element):
    """Stand-in for an element that could not be resolved/constructed, so
    the rest of the graph still wires up and gets checked."""

    FACTORY_NAME = "~unresolved"
    N_SINKS = 1
    N_SRCS = 1

    def negotiate(self, in_specs):
        return [None]


@dataclass
class LintResult:
    """LintReport + the (possibly partially constructed) pipeline and the
    dry-run negotiated specs (element name → out specs) for annotation."""

    report: LintReport
    pipeline: Optional[Pipeline]
    negotiated_specs: Dict[str, List[Any]] = None  # type: ignore[assignment]

    @property
    def diagnostics(self) -> List[Diagnostic]:
        return self.report.diagnostics

    @property
    def exit_code(self) -> int:
        return self.report.exit_code

    @property
    def codes(self) -> List[str]:
        return self.report.codes

    def render(self) -> str:
        return self.report.render()


# -- property validation ----------------------------------------------------

_TRUE = ("1", "true", "yes", "on")
_FALSE = ("0", "false", "no", "off")


def coerce_property(ps: PropSpec, value: Any) -> Any:
    """Coerce a raw (usually string) property value per its schema; raise
    ValueError when the value cannot possibly be what the element needs."""
    if ps.type == "str":
        return str(value)
    s = str(value).strip()
    if ps.type == "int":
        return int(s)
    if ps.type == "float":
        return float(s)
    if ps.type == "fraction":
        return Fraction(s)
    if ps.type == "bool":
        if s.lower() in _TRUE:
            return True
        if s.lower() in _FALSE:
            return False
        raise ValueError(f"not a boolean: {value!r}")
    if ps.type == "enum":
        if s.lower() in tuple(c.lower() for c in ps.choices):
            return s
        raise ValueError(
            f"{value!r} not one of {', '.join(ps.choices)}"
        )
    return value  # unknown schema type: accept


def check_properties(
    cls: type, props: Dict[str, Any], elem_label: str, report: LintReport
) -> None:
    """Schema-validate one element's property dict (NNS-W101 / NNS-E005)."""
    schema = cls.property_schema()
    open_schema = PROPS_ANY in schema
    for key, value in props.items():
        norm = key.replace("_", "-")
        ps = schema.get(norm)
        if ps is None:
            if open_schema:
                continue
            known = sorted(k for k in schema if k != PROPS_ANY)
            close = difflib.get_close_matches(norm, known, n=1)
            hint = f"did you mean {close[0]!r}?" if close else (
                f"known properties: {', '.join(known)}"
            )
            report.add(
                "NNS-W101", elem_label,
                f"unknown property {key!r} for {cls.FACTORY_NAME}", hint,
            )
            continue
        try:
            coerce_property(ps, value)
        except (ValueError, ZeroDivisionError) as exc:
            hint = (
                f"default is {ps.default!r}" if ps.default is not None else ""
            )
            if ps.type == "bool":
                # runtime _parse_bool never raises — any unrecognized
                # string silently becomes False, so this is a suspicion,
                # not a predicted failure
                report.add(
                    "NNS-W106", elem_label,
                    f"property {key}={value!r} is not a recognized boolean "
                    "and will silently read as false",
                    hint,
                )
            else:
                report.add(
                    "NNS-E005", elem_label,
                    f"property {key}={value!r} is not a valid {ps.type}: "
                    f"{exc}",
                    hint,
                )


# -- fault-tolerant launch-string build -------------------------------------

def _build_tolerant(
    description: str, report: LintReport, placeholders: Set[str]
) -> Optional[Pipeline]:
    """parse.parse_pipeline's two passes, but every failure becomes a
    diagnostic and a placeholder so later passes still see the graph."""
    try:
        items = scan_description(description)
    except ParseError as exc:
        report.add("NNS-E009", None, str(exc))
        return None
    # constructing lint elements must not shift the gst-style default
    # numbering (tensor_sink0, ...) of pipelines parsed afterwards — the
    # whole point of lint is to run BEFORE the real parse
    counters_snapshot = dict(Element._instance_counters)
    try:
        return _build_items(items, report, placeholders)
    finally:
        Element._instance_counters.clear()
        Element._instance_counters.update(counters_snapshot)


def _build_items(
    items: List[Any],
    report: LintReport,
    placeholders: Set[str],
) -> Optional[Pipeline]:
    pipeline = Pipeline()
    instances: List[Optional[Element]] = []
    n_anon = 0

    def placeholder(label: Optional[str], factory: str = "unresolved") -> Element:
        nonlocal n_anon
        # '~' cannot appear in parsed names, so this never collides
        p = _Placeholder(name=label or f"{factory}~{n_anon}")
        n_anon += 1
        placeholders.add(p.name)
        return p

    for item in items:
        if item[0] == "element":
            _, factory, props = item
            cls: Optional[type] = None
            lookup_err: Optional[Tuple[str, str, str]] = None
            try:
                cls = registry.get(registry.KIND_ELEMENT, factory)
            except KeyError:
                # builtin_only: a restricted name must never trigger
                # plugin-file execution just to classify the diagnostic
                if registry.is_restricted(
                    registry.KIND_ELEMENT, factory
                ) and registry.exists(
                    registry.KIND_ELEMENT, factory, builtin_only=True
                ):
                    lookup_err = (
                        "NNS-E010",
                        f"element {factory!r} is restricted by configuration",
                        "[common] restricted_elements blocks it",
                    )
                else:
                    known = registry.available(registry.KIND_ELEMENT)
                    close = difflib.get_close_matches(factory, known, n=1)
                    lookup_err = (
                        "NNS-E004",
                        f"no element factory named {factory!r}",
                        f"did you mean {close[0]!r}?" if close else "",
                    )
            # construct FIRST so diagnostics anchor to the node's actual
            # (possibly auto-generated) name and dot annotation matches
            elem: Optional[Element] = None
            ctor_exc: Optional[Exception] = None
            ctor = dict(props)
            elem_name = ctor.pop("name", None)
            if cls is not None:
                try:
                    elem = cls(name=elem_name, **ctor)
                except Exception as exc:  # ctor rejected the properties
                    ctor_exc = exc
            if elem is None:
                elem = placeholder(elem_name, factory)
            label = elem.name
            if lookup_err is not None:
                report.add(lookup_err[0], label, lookup_err[1], lookup_err[2])
            if cls is not None:
                n_before = len(report.diagnostics)
                check_properties(cls, props, label, report)
                schema_flagged = any(
                    d.code == "NNS-E005"
                    for d in report.diagnostics[n_before:]
                )
                if ctor_exc is not None and not schema_flagged:
                    # a ctor failure the schema didn't predict: missing
                    # required property, unopenable resource, ... — its
                    # own code, NOT bad-property-value (scripts match on
                    # codes)
                    report.add(
                        "NNS-E011", label,
                        f"{factory} could not be constructed: {ctor_exc}",
                    )
            try:
                pipeline.add(elem)
            except ValueError as exc:  # duplicate name
                report.add("NNS-E009", elem.name, str(exc))
                elem = placeholder(None)
                pipeline.add(elem)
            instances.append(elem)
        elif item[0] == "caps":
            try:
                media, fields = _parse_caps(item[1])
                elem = _make_caps_element(media, fields)
            except (ParseError, ValueError) as exc:
                report.add("NNS-E009", None, f"bad caps {item[1]!r}: {exc}")
                elem = placeholder(None)
            pipeline.add(elem)
            instances.append(elem)
        else:
            instances.append(None)

    # pass 2: wire links, tolerating per-link failures
    prev: Optional[Element] = None
    prev_src_pad: Optional[int] = None
    expect_link = False
    for item, inst in zip(items, instances):
        if item[0] == "bang":
            if prev is None:
                report.add("NNS-E009", None, "'!' with nothing to link from")
            elif expect_link:
                report.add("NNS-E009", None, "duplicate '!'")
            else:
                expect_link = True
        elif item[0] == "ref":
            _, name, kind, pad = item
            try:
                target = pipeline[name]
            except KeyError:
                report.add(
                    "NNS-E009", None,
                    f"reference to unknown element {name!r}",
                )
                prev, prev_src_pad, expect_link = None, None, False
                continue
            if expect_link:
                dst_pad = pad if kind in (None, "sink") else None
                try:
                    pipeline.link(prev, target, src_pad=prev_src_pad,
                                  dst_pad=dst_pad)
                except ValueError as exc:
                    report.add("NNS-E009", target.name, str(exc))
                prev, prev_src_pad, expect_link = None, None, False
            else:
                prev = target
                prev_src_pad = pad if kind in (None, "src") else None
        else:
            if expect_link:
                try:
                    pipeline.link(prev, inst, src_pad=prev_src_pad)
                except ValueError as exc:
                    report.add("NNS-E009", inst.name, str(exc))
                expect_link = False
            prev, prev_src_pad = inst, None
    if expect_link:
        report.add("NNS-E009", None, "pipeline ends with '!'")
    return pipeline


# -- pass 1: graph structure -------------------------------------------------

def _structure_pass(
    pipeline: Pipeline, report: LintReport, placeholders: Set[str]
) -> List[Element]:
    """NNS-E001/W105 unlinked pads, NNS-E002 cycles, NNS-W104 reachability.
    Returns the cycle members (non-empty means the spec pass must skip)."""
    for e in pipeline.elements:
        if e.name in placeholders:
            continue
        ins = len(pipeline.in_links(e))
        outs = len(pipeline.out_links(e))
        if e.N_SINKS is not None and ins < e.N_SINKS:
            report.add(
                "NNS-E001", e.name,
                f"{ins}/{e.N_SINKS} sink pads linked",
                "link an upstream element into it",
            )
        elif e.N_SINKS is None and ins == 0 and not isinstance(e, Source):
            report.add(
                "NNS-E001", e.name,
                f"{e.FACTORY_NAME} has no inputs linked",
                "fan-in elements need at least one linked sink pad",
            )
        err_pad = getattr(e, "error_pad", None)
        out_pads = {l.src_pad for l in pipeline.out_links(e)}
        if err_pad is not None:
            # the dead-letter pad gets its own diagnostic (NNS-W107), and
            # is excluded from the generic unlinked-src count below: an
            # unlinked error pad is a ROUTING mistake (silent drop), not
            # a dangling data output. Only on-error=route REQUIRES the
            # pad; a retry element's pad is optional exhaustion overflow
            if getattr(e, "error_pad_required", False) \
                    and err_pad not in out_pads:
                report.add(
                    "NNS-W107", e.name,
                    "on-error=route but the error pad "
                    f"(src_{err_pad}) is unlinked; dead-lettered frames "
                    "are silently dropped",
                    f"link '{e.name}.src_{err_pad}' to a sink "
                    "(the dead-letter queue)",
                )
            n_data_srcs = e.N_SRCS - 1
            data_outs = len(out_pads - {err_pad})
        else:
            n_data_srcs = e.N_SRCS
            data_outs = outs
        if n_data_srcs is not None and n_data_srcs > 0 \
                and data_outs < n_data_srcs:
            report.add(
                "NNS-W105", e.name,
                f"{data_outs}/{n_data_srcs} src pads linked; unlinked "
                "output is dropped",
                "terminate it into a sink (or fakesink)",
            )
        # explicit pad indices beyond the allocated pad count (e.g.
        # 'mux.sink_5' with one branch linked): pad numbering must be
        # dense, or negotiation indexes out of range at runtime
        n_sinks = pipeline.n_sinks(e)
        for l in pipeline.in_links(e):
            if l.dst_pad >= n_sinks:
                report.add(
                    "NNS-E001", e.name,
                    f"sink pad {l.dst_pad} linked but only pads "
                    f"0..{n_sinks - 1} exist; lower-numbered pads are "
                    "unlinked",
                    "pad numbering must be dense from 0",
                )
        n_srcs = pipeline.n_srcs(e)
        for l in pipeline.out_links(e):
            if l.src_pad >= n_srcs:
                report.add(
                    "NNS-W105", e.name,
                    f"src pad {l.src_pad} linked but only pads "
                    f"0..{n_srcs - 1} exist; lower-numbered pads are "
                    "unlinked",
                    "pad numbering must be dense from 0",
                )
    _, leftover = pipeline.toposort_partial()
    if leftover:
        names = sorted(e.name for e in leftover)
        report.add(
            "NNS-E002", None,
            f"pipeline has a cycle through {names}",
            "use tensor_reposink/tensor_reposrc for feedback loops",
        )
    # placeholders with no inputs may well BE sources (unknown name in
    # the source position): treat them as reachability seeds and never
    # claim "no source" on their account
    seeds = [
        e for e in pipeline.elements
        if isinstance(e, Source)
        or (e.name in placeholders and not pipeline.in_links(e))
    ]
    if not seeds:
        if pipeline.elements:
            report.add(
                "NNS-W104", None,
                "pipeline has no source element; nothing will flow",
            )
    else:
        reached: Set[Element] = set()
        stack = list(seeds)
        while stack:
            e = stack.pop()
            if e in reached:
                continue
            reached.add(e)
            stack.extend(l.dst for l in pipeline.out_links(e))
        in_cycle = set(leftover)
        for e in pipeline.elements:
            if e not in reached and e not in in_cycle \
                    and e.name not in placeholders:
                report.add(
                    "NNS-W104", e.name,
                    f"{e.FACTORY_NAME} is not reachable from any source",
                )
    return leftover


def _queue_free_reach(pipeline: Pipeline, start: Element, goal: Element) -> bool:
    """True if `goal` is reachable from `start` without crossing a queue."""
    from nnstreamer_tpu.elements.flow import Queue

    if isinstance(goal, Queue):
        return False
    seen: Set[Element] = set()
    stack = [start]
    while stack:
        e = stack.pop()
        if e in seen:
            continue
        seen.add(e)
        if e is goal:
            return True
        if isinstance(e, Queue) and e is not start:
            continue  # a queue on the path buffers it: stop this walk
        stack.extend(l.dst for l in pipeline.out_links(e))
    return False


def _branch_ancestors(pipeline: Pipeline, ins) -> List[Set[Element]]:
    """Per-in-link ancestor sets of a fan-in element (one upstream walk,
    shared by the W103/W109/W110 join passes)."""
    out: List[Set[Element]] = []
    for l in ins:
        anc: Set[Element] = set()
        stack = [l.src]
        while stack:
            e = stack.pop()
            if e in anc:
                continue
            anc.add(e)
            stack.extend(ll.src for ll in pipeline.in_links(e))
        out.append(anc)
    return out


def _unqueued_join_scan(
    pipeline: Pipeline, report: LintReport, code: str,
    ancestor_pred, noun, hint: str,
) -> None:
    """The shared blocking-join shape: a fan-in whose branch pair shares
    an ancestor selected by `ancestor_pred`, with at least one branch
    carrying no queue between the ancestor and the fan-in. `noun` labels
    the ancestor in the message (e.g. 'tee')."""
    for m in pipeline.elements:
        ins = pipeline.in_links(m)
        if len(ins) < 2:
            continue
        branch_anc = _branch_ancestors(pipeline, ins)
        flagged: Set[Element] = set()
        for i in range(len(ins)):
            for j in range(i + 1, len(ins)):
                shared = [
                    f for f in branch_anc[i] & branch_anc[j]
                    if ancestor_pred(f) and f not in flagged
                ]
                for fo in shared:
                    bad = [
                        ins[k].dst_pad for k in (i, j)
                        if _queue_free_reach(pipeline, fo, ins[k].src)
                        or ins[k].src is fo
                    ]
                    if bad:
                        flagged.add(fo)
                        pads = ", ".join(f"sink_{p}" for p in bad)
                        report.add(
                            code, m.name,
                            f"branches from {noun(fo)} {fo.name!r} reach "
                            f"{m.name} ({pads}) without an intervening "
                            "queue",
                            hint,
                        )


def _tee_pass(pipeline: Pipeline, report: LintReport) -> None:
    """NNS-W103: fan-in element whose branches share a tee ancestor with at
    least one branch carrying no queue between the tee and the fan-in —
    the tee blocks on the unqueued branch while the fan-in waits for the
    other, the textbook launch-string deadlock."""
    from nnstreamer_tpu.elements.flow import Tee

    _unqueued_join_scan(
        pipeline, report, "NNS-W103",
        lambda f: isinstance(f, Tee),
        lambda f: "tee",
        "insert 'queue' after each tee branch",
    )


# -- nns-san deadlock/capacity pass (graph side of the sanitizer) -----------

#: Codes the graph-level deadlock/capacity analysis can produce
#: (`nns-san --deadlock` filters a full lint run down to these).
DEADLOCK_CODES = frozenset(
    {"NNS-E002", "NNS-W103", "NNS-W108", "NNS-W109", "NNS-W110"}
)


def _effective_input_depth(pipeline: Pipeline, e: Element) -> Optional[int]:
    """The channel depth the EXECUTOR will give e's input: an eliminated
    upstream queue chain overrides e's own queue-size (tighter bound
    wins across the chain — executor._build's rewrite rule)."""
    from nnstreamer_tpu.elements.flow import Queue

    override: Optional[int] = None
    cur: Element = e
    while True:
        ins = pipeline.in_links(cur)
        if len(ins) != 1:
            break
        up = ins[0].src
        # only 1-in/1-out queues are eliminated into a depth override
        if not isinstance(up, Queue) or len(pipeline.out_links(up)) != 1:
            break
        override = (
            up.queue_size if override is None
            else min(override, up.queue_size)
        )
        cur = up
    if override is not None:
        return override
    return getattr(e, "queue_size", None)


def _capacity_pass(pipeline: Pipeline, report: LintReport) -> None:
    """NNS-W108: bounded channels sized so they cannot do their job."""
    from nnstreamer_tpu.elements.base import _parse_bool

    for e in pipeline.elements:
        qs = getattr(e, "queue_size", None)
        if qs is not None and qs <= 0:
            report.add(
                "NNS-W108", e.name,
                f"queue-size={qs} is non-positive; the executor clamps it "
                "to 1, so every put parks the producer",
                "size the channel for the expected burst",
            )
            continue
        raw = e.get_property("batching")
        if raw is None or not _parse_bool(raw):
            continue
        try:
            mb = int(e.get_property("max-batch", 8))
        except (TypeError, ValueError):
            continue  # NNS-E005 already covers the bad value
        depth = _effective_input_depth(pipeline, e)
        if depth is not None and mb > depth:
            report.add(
                "NNS-W108", e.name,
                f"max-batch={mb} exceeds the input channel depth "
                f"({depth}); a full batch can never assemble",
                "deepen the input channel (queue-size / the upstream "
                "queue's max-size-buffers) above max-batch",
            )


def _fanout_join_pass(pipeline: Pipeline, report: LintReport) -> None:
    """NNS-W109: the NNS-W103 blocking topology generalized to non-tee
    fan-outs (demux/split/crop): a fan-in whose branches share a
    multi-src-pad ancestor with no intervening queue on some branch."""
    from nnstreamer_tpu.elements.flow import Tee

    _unqueued_join_scan(
        pipeline, report, "NNS-W109",
        lambda f: len(pipeline.out_links(f)) >= 2
        and not isinstance(f, Tee),  # tee: NNS-W103's case
        lambda f: f.FACTORY_NAME,
        "insert 'queue' after each fan-out branch",
    )


def _may_drop_frames(e: Element, pipeline: Pipeline) -> Optional[str]:
    """Reason string when `e` drops frames data-dependently, else None."""
    from nnstreamer_tpu.elements.control import TensorIf

    if isinstance(e, TensorIf):
        if "SKIP" in (e.then_action, e.else_action):
            return "tensor_if with a SKIP action"
        return None
    raw = e.get_property("on-error")
    if raw is None:
        return None
    mode = str(raw).strip().lower()
    if mode == "drop":
        return "on-error=drop"
    if mode == "retry":
        err_pad = getattr(e, "error_pad", None)
        routed = err_pad is not None and any(
            l.src_pad == err_pad for l in pipeline.out_links(e)
        )
        if not routed:
            return "on-error=retry with no dead-letter pad linked"
    return None


def _skewed_join_pass(pipeline: Pipeline, report: LintReport) -> None:
    """NNS-W110: a synchronizing fan-in (mux/merge, sync-mode != nosync)
    with a data-dependent frame dropper on a strict subset of branches —
    the join waits forever for counterparts of skipped frames."""
    from nnstreamer_tpu.elements.routing import TensorMerge, TensorMux

    for m in pipeline.elements:
        if not isinstance(m, (TensorMux, TensorMerge)):
            continue
        if str(m.get_property("sync-mode", "slowest")).lower() == "nosync":
            continue
        ins = pipeline.in_links(m)
        if len(ins) < 2:
            continue
        droppers: Dict[int, str] = {}
        for l, anc in zip(ins, _branch_ancestors(pipeline, ins)):
            for e in anc:
                reason = _may_drop_frames(e, pipeline)
                if reason is not None:
                    droppers[l.dst_pad] = f"{e.name} ({reason})"
                    break
        if droppers and len(droppers) < len(ins):
            detail = "; ".join(
                f"sink_{pad}: {who}" for pad, who in sorted(droppers.items())
            )
            report.add(
                "NNS-W110", m.name,
                "synchronizing fan-in has data-dependent droppers on a "
                f"subset of its branches ({detail}); pads fill at "
                "different rates and the sync policy can starve",
                "drop on every branch symmetrically, use sync-mode=nosync,"
                " or dead-letter failures instead of dropping",
            )


def _admission_pass(pipeline: Pipeline, report: LintReport) -> None:
    """NNS-W111: a query server launched without any admission bound —
    every client is accepted and every request queued forever, so
    overload shows up as latency collapse instead of structured NACKs
    (docs/edge-serving.md)."""
    from nnstreamer_tpu.edge.query import TensorQueryServerSrc

    bounds = ("max-clients", "max-inflight", "per-client-inflight", "rate")
    for e in pipeline.elements:
        if not isinstance(e, TensorQueryServerSrc):
            continue
        bounded = False
        for key in bounds:
            raw = e.get_property(key)
            if raw is None:
                continue
            try:
                if float(raw) > 0:
                    bounded = True
                    break
            except (TypeError, ValueError):
                bounded = True  # NNS-E005 already covers the bad value
                break
        if not bounded:
            report.add(
                "NNS-W111", e.name,
                "no admission bound set; overload degrades as unbounded "
                "queueing and silent latency collapse",
                "set max-clients / max-inflight / per-client-inflight / "
                "rate (docs/edge-serving.md)",
            )


def _fleet_failover_pass(pipeline: Pipeline, report: LintReport) -> None:
    """NNS-W119: single-endpoint-no-failover — a tensor_query_client
    that stamps a per-request SLO (``deadline-ms``) cares about
    tail latency, yet binds exactly ONE endpoint with ``retry-max=0``:
    a dead or draining server is then a terminal error per frame, with
    no reconnect, no failover target, and no hedge
    (docs/edge-serving.md "Running a fleet")."""
    from nnstreamer_tpu.edge.fleet import parse_hosts
    from nnstreamer_tpu.edge.query import TensorQueryClient

    for e in pipeline.elements:
        if not isinstance(e, TensorQueryClient):
            continue
        hosts = e.get_property("hosts")
        if hosts:
            try:
                if len(parse_hosts(hosts)) > 1:
                    continue  # a real fleet: failover targets exist
            except ValueError:
                continue  # NNS-E011 already covers the bad value
        try:
            deadline = float(e.get_property("deadline-ms") or 0.0)
            retry_max = int(e.get_property("retry-max") or 0)
        except (TypeError, ValueError):
            continue  # NNS-E005 already covers the bad value
        if deadline > 0 and retry_max <= 0:
            report.add(
                "NNS-W119", e.name,
                f"deadline-ms={deadline:.0f} with one endpoint and "
                "retry-max=0: an endpoint hiccup is a terminal error "
                "with no failover",
                "bind a fleet (hosts=h1:p1,h2:p2,...) or set retry-max "
                "(docs/edge-serving.md)",
            )


def _llm_drain_loss_pass(pipeline: Pipeline, report: LintReport) -> None:
    """NNS-W126: llm-drain-loses-generations — an explicitly tuned
    ``retry-after-ms`` on a query serversrc is the fleet-drain
    contract's fingerprint: the operator expects clients to re-route
    on ``draining`` NACKs during rolling restarts. An LLM serversink
    behind such a serversrc with NO migrate-to peer and NO
    checkpoint-dir turns every one of those drains into lost work —
    the in-flight generations' KV and decoded tokens are abandoned and
    the re-routed requests re-prefill from token zero
    (docs/llm-serving.md "Migration & recovery"). The explicit-set
    check matters: retry-after-ms DEFAULTS to 50, so only an operator
    who wrote it down has promised drain semantics."""
    from nnstreamer_tpu.edge.query import TensorQueryServerSrc
    from nnstreamer_tpu.elements.llm_serve import LlmServerSink

    if not any(
        isinstance(e, TensorQueryServerSrc)
        and e.get_property("retry-after-ms") is not None
        for e in pipeline.elements
    ):
        return
    for e in pipeline.elements:
        if not isinstance(e, LlmServerSink):
            continue
        if e.get_property("plane"):
            continue  # plane-shared batchers refuse migration by design
        if e.get_property("migrate-to") or e.get_property("checkpoint-dir"):
            continue
        report.add(
            "NNS-W126", e.name,
            "fleet drain is tuned (serversrc retry-after-ms) but this "
            "LLM server can neither migrate nor recover its in-flight "
            "generations: a drain abandons their KV and decoded "
            "tokens, and re-routed clients pay full re-prefill",
            "set migrate-to=host:port (live KV-span migration) and/or "
            "checkpoint-dir (crash recovery); both need "
            "kv-layout=paged (docs/llm-serving.md)",
        )


def _disagg_role_pass(pipeline: Pipeline, report: LintReport) -> None:
    """NNS-W130: prefill-role-no-decode-peer — role=prefill is a
    promise that prefilled requests LEAVE: their KV spans ship to a
    decode peer and this server's pool churns through prompt
    processing only (docs/llm-serving.md "Disaggregated serving"). A
    prefill server with no decode-peers keeps every generation local —
    the colocated behavior the operator explicitly opted out of — and
    with no checkpoint-dir a drain of that unexpected decode load
    abandons it."""
    from nnstreamer_tpu.elements.llm_serve import LlmServerSink

    for e in pipeline.elements:
        if not isinstance(e, LlmServerSink):
            continue
        if str(e.get_property("role") or "") != "prefill":
            continue
        if str(e.get_property("decode-peers") or "").strip():
            continue
        if e.get_property("checkpoint-dir"):
            continue
        report.add(
            "NNS-W130", e.name,
            "role=prefill with no decode-peers: every prefilled "
            "request decodes locally, so the configured "
            "disaggregation never happens and drains abandon the "
            "unexpected local decode load",
            "set decode-peers=host:port[/llm-id],... (KV-span "
            "handoff to the decode tier) or drop role=prefill; "
            "checkpoint-dir at least recovers drains "
            "(docs/llm-serving.md \"Disaggregated serving\")",
        )


def _replica_failover_pass(pipeline: Pipeline, report: LintReport) -> None:
    """NNS-W112: replicas=N promises the stream survives a dying
    replica, but with the default on-error=stop the day EVERY replica is
    down (ReplicaExhaustedError) the whole pipeline dies with it — and
    in a serving pipeline the admitted clients hang instead of getting
    terminal NACKs. A failover deployment needs a disposal policy
    (docs/resilience.md)."""
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.pipeline.faults import resolve_fault_policy

    for e in pipeline.elements:
        if not isinstance(e, TensorFilter):
            continue
        try:
            n = int(e.get_property("replicas") or 0)
        except (TypeError, ValueError):
            continue  # NNS-E005 already covers the bad value
        if n <= 1:
            continue
        try:
            policy = resolve_fault_policy([e])
        except Exception:  # noqa: BLE001 — bad policy props have their
            continue       # own diagnostics
        if not policy.active:
            report.add(
                "NNS-W112", e.name,
                f"replicas={n} with on-error=stop: replica exhaustion "
                "kills the pipeline instead of disposing frames "
                "(drop/route/retry + NACK for admitted requests)",
                "set on-error=drop|route|retry on the replicated filter "
                "(docs/resilience.md)",
            )


def _model_sharing_pass(pipeline: Pipeline, report: LintReport) -> None:
    """NNS-W114: duplicate model, no sharing — two+ tensor_filter
    instances naming the same model/framework without a
    ``shared-tensor-filter-key`` or a serving ``plane`` each open their
    own backend: N copies of the weights resident on device where one
    would serve (docs/serving-plane.md). Replicated filters
    (``replicas=N``) duplicate on purpose and are exempt."""
    from nnstreamer_tpu.elements.filter import TensorFilter

    groups: Dict[tuple, List] = {}
    for e in pipeline.elements:
        if not isinstance(e, TensorFilter):
            continue
        model = str(e.get_property("model") or "").strip()
        if not model:
            continue  # model-less fakes: nothing resident to duplicate
        if str(e.get_property("shared-tensor-filter-key") or "").strip():
            continue
        if str(e.get_property("plane") or "").strip():
            continue
        try:
            if int(e.get_property("replicas") or 0) > 1:
                continue  # deliberate copies (failover)
        except (TypeError, ValueError):
            pass  # NNS-E005 already covers the bad value
        fw = str(e.get_property("framework") or "auto").strip()
        groups.setdefault((fw, model), []).append(e)
    for (fw, model), elems in groups.items():
        if len(elems) < 2:
            continue
        names = ", ".join(e.name for e in elems)
        for e in elems:
            report.add(
                "NNS-W114", e.name,
                f"model {model!r} ({fw}) is opened {len(elems)}x "
                f"without sharing ({names}): {len(elems)} weight "
                "copies resident where one would serve",
                "set one shared-tensor-filter-key on the group, or "
                "serve them through a plane=<name> "
                "(docs/serving-plane.md)",
            )


def _plane_async_pass(pipeline: Pipeline, report: LintReport) -> None:
    """NNS-W118: blocking plane submits under a ring
    (docs/serving-plane.md). Two shapes, both static property reads:

    - a plane filter with ``ring-depth>1`` but ``batching=false``: the
      async ticket ring rides the host WINDOW loop, so disabling the
      local collector forces per-frame blocking submits and the ring
      never engages;
    - two or more streams of the same plane in this pipeline with every
      in-flight depth left at 1 (no ``ring-depth`` and ``[plane]
      inflight = 1``): each stream blocks a full plane round trip per
      window — exactly the multi-stream shape async submits exist for.
    """
    from nnstreamer_tpu.elements.filter import TensorFilter

    def _depth(e) -> Optional[int]:
        raw = e.get_property("ring-depth")
        if raw is None:
            return None
        try:
            return max(1, int(raw))
        except (TypeError, ValueError):
            return None  # NNS-W101/E005 already covers the bad value

    cfg_inflight = 1
    try:
        from nnstreamer_tpu.serving_plane.plane import _plane_defaults

        cfg_inflight = max(1, int(_plane_defaults()["inflight"]))
    except Exception:  # noqa: BLE001 — a broken ini has its own warning
        pass
    groups: Dict[str, List] = {}
    for e in pipeline.elements:
        if not isinstance(e, TensorFilter):
            continue
        if not str(e.get_property("plane") or "").strip():
            continue
        groups.setdefault(str(e.get_property("plane")).strip(), []).append(e)
        depth = _depth(e)
        raw_batching = e.get_property("batching")
        batching_off = (
            raw_batching is not None
            and str(raw_batching).strip().lower() in ("false", "0", "no")
        )
        if depth is not None and depth > 1 and batching_off:
            report.add(
                "NNS-W118", e.name,
                f"ring-depth={depth} with batching=false: the async "
                "in-flight ring rides the window collector, so this "
                "stream still submits per frame, blocking a full plane "
                "round trip each time",
                "drop batching=false (plane filters default the "
                "collector on, window-matched to the plane) — "
                "docs/serving-plane.md",
            )
    for pname, elems in groups.items():
        if len(elems) < 2:
            continue
        depths = [(_depth(e) or cfg_inflight) for e in elems]
        if any(d > 1 for d in depths):
            continue
        names = ", ".join(e.name for e in elems)
        report.add(
            "NNS-W118", elems[0].name,
            f"{len(elems)} streams share plane {pname!r} with every "
            f"in-flight depth at 1 ({names}): each blocks a full plane "
            "round trip per window instead of overlapping submits",
            "set ring-depth=2..3 on the plane filters (or [plane] "
            "inflight = 2) to pipeline submit/compute/delivery — "
            "docs/serving-plane.md",
        )


def _kv_cache_pass(pipeline: Pipeline, report: LintReport) -> None:
    """NNS-W115 + NNS-W117: KV caches that cannot fit their declared
    memory bound (``kv-memory-bound`` prop, or ``[llm] memory_bound``).

    - W115: a slot-layout cache (2 · L · n-slots · max-len · KV · Dh,
      every slot sized for the worst case) exceeds the bound while
      ``kv-layout=paged`` is available.
    - W117: a PAGED element pinned to ``kv-attn=gather``, whose step
      programs materialize the full contiguous per-slot view (slot-
      cache-sized) BESIDE the block arena — the transient footprint
      arena + view exceeds the bound. The block-native default has no
      gathered view, so the fix is simply dropping the pin.

    Static estimates from the element's props and custom model options
    — no model is loaded (the sink is LINT_SKIP_NEGOTIATE for exactly
    that reason)."""
    from nnstreamer_tpu.backends.base import FilterProps
    from nnstreamer_tpu.config import conf
    from nnstreamer_tpu.elements.llm_serve import LlmServerSink
    from nnstreamer_tpu.serving_plane.placement import parse_bytes

    for e in pipeline.elements:
        if not isinstance(e, LlmServerSink):
            continue
        layout = str(e.get_property("kv-layout") or "").strip() or (
            conf().get("llm", "kv_layout", "slot")
        )
        bound_raw = str(e.get_property("kv-memory-bound") or "").strip()
        if not bound_raw:
            bound_raw = conf().get("llm", "memory_bound", "").strip()
        if not bound_raw:
            continue  # no declared bound: nothing to check against
        try:
            bound = parse_bytes(bound_raw)
        except (TypeError, ValueError):
            continue  # NNS-E005-shaped value; not this pass's finding
        opts = FilterProps(
            custom=str(e.get_property("custom") or "")
        ).custom_dict()
        # zoo:transformer_lm defaults (models/zoo.py)
        d_model = int(opts.get("d_model", 256))
        n_layers = int(opts.get("n_layers", 4))
        n_heads = int(opts.get("n_heads", 8)) or 1
        n_kv = int(opts.get("n_kv_heads", n_heads))
        hd = d_model // n_heads
        cache_dtype = str(e.get_property("cache-dtype") or "auto")
        if cache_dtype == "int8":
            per_elem = 1.0 + 4.0 / max(hd, 1)  # int8 payload + scales
        else:
            dt = str(opts.get("compute_dtype", "float32"))
            per_elem = 2.0 if dt == "bfloat16" else 4.0
        n_slots = int(e.get_property("n-slots") or 4)
        max_len = int(e.get_property("max-len") or 256)
        # the slot cache — which is ALSO the gathered view's shape
        view = int(2 * n_layers * n_slots * max_len * n_kv * hd * per_elem)
        if layout == "paged":
            attn = str(e.get_property("kv-attn") or "").strip() or (
                conf().get("llm", "kv_attn", "auto")
            )
            if attn != "gather":
                continue  # block-native: no gathered view to flag
            bs = int(e.get_property("block-size") or 0) or (
                conf().get_int("llm", "block_size", 16)
            ) or 16
            kv_blocks = int(e.get_property("kv-blocks") or 0) or (
                conf().get_int("llm", "kv_blocks", 0)
            )
            if kv_blocks <= 0:  # no-saving auto default (serving.py)
                kv_blocks = n_slots * (-(-max_len // bs))
            arena = int(
                2 * n_layers * (kv_blocks + 1) * bs * n_kv * hd * per_elem
            )
            est = arena + view
            if est <= bound:
                continue
            report.add(
                "NNS-W117", e.name,
                f"kv-attn=gather materializes the contiguous view ≈ "
                f"{view / (1 << 20):.0f} MiB beside the "
                f"{arena / (1 << 20):.0f} MiB block arena every step — "
                f"transient ≈ {est / (1 << 20):.0f} MiB exceeds the "
                f"declared bound {bound_raw}",
                "drop kv-attn=gather (the block-native default attends "
                "the arena directly through the block tables, no "
                "gathered view — docs/llm-serving.md); keep the gather "
                "oracle for parity debugging only",
            )
            continue
        if view <= bound:
            continue
        report.add(
            "NNS-W115", e.name,
            f"slot-layout KV cache ≈ {view / (1 << 20):.0f} MiB "
            f"(2·L{n_layers}·slots{n_slots}·len{max_len}·kv{n_kv}·"
            f"hd{hd}) exceeds the declared bound {bound_raw} — every "
            "slot is sized for the worst-case request",
            "set kv-layout=paged (block-table arena sized by kv-blocks "
            "to the bound; prefix sharing and chunked prefill come "
            "with it — docs/llm-serving.md)",
        )


def _resident_handoff_pass(pipeline: Pipeline, report: LintReport) -> None:
    """NNS-W113/W116/W120: a host-bound element between two
    device-capable (traceable) filters forces every frame through host
    memory and back mid-stream — the resident device-to-device segment
    handoff (docs/streaming.md) only works across contiguous device
    segments and pure plumbing (queue/capsfilter/tee carry device
    arrays untouched). The predicates live in analysis/xray.py (shared
    with the chain analyzer so the two can never disagree about what
    splits a chain); capability is read STATICALLY from the backend
    class — no backend open, no model load. ONE code per boundary:
    W116 when the split is a decoder with an unused device path (a
    one-property fix), W120 when a host-path tensor op severs a
    compileable chain (docs/chain-analysis.md), W113 for host elements
    outside the tensor-op surface (a structural restructure)."""
    from nnstreamer_tpu.analysis.xray import (
        decoder_will_fuse,
        host_bound,
        host_postproc_with_device_path,
        reaches_capable,
    )
    from nnstreamer_tpu.elements.base import TensorOp

    def ups(e):
        return [ln.src for ln in pipeline.in_links(e)]

    def downs(e):
        return [ln.dst for ln in pipeline.out_links(e)]

    for e in pipeline.elements:
        if not host_bound(e) or decoder_will_fuse(e):
            continue
        if not (reaches_capable(e, ups) and reaches_capable(e, downs)):
            continue
        if host_postproc_with_device_path(e):
            # the specific diagnostic wins: there IS a device path, so
            # the fix is one property, not a pipeline restructure
            report.add(
                "NNS-W116", e.name,
                "fusable decoder runs as a host node between two "
                "device segments: its (large) inputs materialize to "
                "host every frame although the decode has a device "
                "path",
                "set postproc=device to fold the decode into the "
                "adjacent fused segment (docs/on-device-ops.md)",
            )
            continue
        if isinstance(e, TensorOp):
            # host-path tensor op (host-backend filter, non-traceable
            # op, device-path-less decoder) severing a chain: the
            # chain-granular diagnostic (nns-xray reports the same
            # boundary with the chains it severs)
            report.add(
                "NNS-W120", e.name,
                "host-path op severs an otherwise compileable chain "
                "of fused segments: frames materialize to host and "
                "re-stage to device here every frame",
                "give this op a device-capable framework/traceable "
                "path, or move it outside the device span "
                "(docs/chain-analysis.md)",
            )
            continue
        report.add(
            "NNS-W113", e.name,
            "host-bound element between two device-capable filters: "
            "frames materialize to host and back mid-stream, "
            "defeating the resident segment handoff",
            "move the host step before/after the device chain, or "
            "give it a traceable equivalent (docs/streaming.md)",
        )


# -- pass 4: resources -------------------------------------------------------

def _resource_pass(
    pipeline: Pipeline, report: LintReport
) -> Set[str]:
    """NNS-E006/E007/E008/W102. Returns names whose negotiate() would fail
    for an already-reported reason (the spec pass skips them)."""
    from nnstreamer_tpu.elements.converter import TensorConverter
    from nnstreamer_tpu.elements.decoder import TensorDecoder
    from nnstreamer_tpu.elements.filter import TensorFilter

    skip: Set[str] = set()
    for e in pipeline.elements:
        if isinstance(e, TensorFilter):
            fw = e.fprops.framework
            if not registry.exists(registry.KIND_FILTER, fw):
                known = registry.available(registry.KIND_FILTER)
                report.add(
                    "NNS-E006", e.name,
                    f"framework={fw!r} names no registered backend",
                    f"available: {', '.join(known)}",
                )
                skip.add(e.name)
            for model in e.fprops.model:
                if model.startswith("zoo:"):
                    continue  # resolved from the in-package model zoo
                if not os.path.exists(model):
                    report.add(
                        "NNS-W102", e.name,
                        f"model file {model!r} does not exist",
                        "the path is resolved at open time, relative to "
                        "the working directory",
                    )
                    skip.add(e.name)
        elif isinstance(e, TensorDecoder):
            if e.mode and e.mode != "custom-code" \
                    and not registry.exists(registry.KIND_DECODER, e.mode):
                known = registry.available(registry.KIND_DECODER)
                report.add(
                    "NNS-E007", e.name,
                    f"mode={e.mode!r} names no registered decoder",
                    f"available: {', '.join(known)}",
                )
                skip.add(e.name)
        elif isinstance(e, TensorConverter):
            mode = e.mode
            if mode and not str(mode).startswith("custom-") \
                    and not registry.exists(registry.KIND_CONVERTER, str(mode)):
                known = registry.available(registry.KIND_CONVERTER)
                report.add(
                    "NNS-E008", e.name,
                    f"mode={mode!r} names no registered converter",
                    f"available: {', '.join(known)}",
                )
                skip.add(e.name)
    return skip


# -- pass 2: dry-run spec flow -----------------------------------------------

def _spec_pass(
    pipeline: Pipeline,
    report: LintReport,
    placeholders: Set[str],
    skip: Set[str],
) -> Dict[str, List[Any]]:
    """Run every element's negotiate() on a shallow CLONE in topological
    order, collecting ALL NegotiationErrors. Returns name → out_specs of
    the clones (for dot annotation). The user's pipeline is untouched and
    nothing is started."""
    order, _ = pipeline.toposort_partial()
    clones: Dict[Element, Element] = {}
    for e in order:
        c = copy.copy(e)
        c.in_specs = []
        c.out_specs = []
        clones[e] = c
    specs_out: Dict[str, List[Any]] = {}
    try:
        for e in order:
            clone = clones[e]
            n_sinks = pipeline.n_sinks(e)
            n_srcs = pipeline.n_srcs(e)
            in_specs: List[Any] = [None] * n_sinks
            for l in pipeline.in_links(e):
                if not (0 <= l.dst_pad < n_sinks):
                    continue  # sparse pad numbering: NNS-E001 already filed
                up = clones.get(l.src)
                if up is not None and l.src_pad < len(up.out_specs):
                    in_specs[l.dst_pad] = up.out_specs[l.src_pad]
            unknown_inputs = n_sinks > 0 and any(s is None for s in in_specs)
            not_linked = len(pipeline.in_links(e)) < n_sinks
            if (
                e.name in placeholders
                or e.name in skip
                or unknown_inputs
                or not_linked
                or type(e).LINT_SKIP_NEGOTIATE
            ):
                clone.out_specs = [None] * n_srcs
                continue
            if isinstance(e, Routing):
                clone.set_pad_counts(n_sinks, n_srcs)
            try:
                clone.fix_negotiation(in_specs)
                if len(clone.out_specs) != n_srcs:
                    raise ValueError(
                        f"negotiated {len(clone.out_specs)} specs for "
                        f"{n_srcs} src pads"
                    )
            except Exception as exc:
                report.add(
                    "NNS-E003", e.name,
                    f"negotiation would fail: {exc}",
                    "check upstream dimensions/types against what this "
                    "element accepts",
                )
                clone.out_specs = [None] * n_srcs
                continue
            specs_out[e.name] = list(clone.out_specs)
    finally:
        for e, clone in clones.items():
            # The only resource negotiate() opens is a tensor_filter
            # backend. Release it IF the clone opened its own; never call
            # a generic clone.stop() — shallow copies share the original's
            # live files/sockets, and stopping them would close resources
            # of a started user pipeline.
            opened = getattr(clone, "backend", None)
            if opened is not None and opened is not getattr(e, "backend", None):
                try:
                    clone.stop()
                except Exception as exc:
                    _log.debug("clone cleanup for %s failed: %s",
                               e.name, exc)
    return specs_out


# -- entry point -------------------------------------------------------------

def lint(target: Union[str, Pipeline]) -> LintResult:
    """Statically analyze a launch string or a constructed Pipeline.

    Returns a :class:`LintResult`; ``result.exit_code`` follows the
    0/1/2 = clean/warnings/errors contract. The pipeline is never started.
    """
    report = LintReport()
    placeholders: Set[str] = set()
    if isinstance(target, str):
        pipeline = _build_tolerant(target, report, placeholders)
        if pipeline is None:
            return LintResult(report, None, {})
    else:
        pipeline = target
        for e in pipeline.elements:
            check_properties(type(e), e.props, e.name, report)
    skip = _resource_pass(pipeline, report)
    cyclic = _structure_pass(pipeline, report, placeholders)
    _tee_pass(pipeline, report)
    _capacity_pass(pipeline, report)
    _fanout_join_pass(pipeline, report)
    _skewed_join_pass(pipeline, report)
    _admission_pass(pipeline, report)
    _fleet_failover_pass(pipeline, report)
    _llm_drain_loss_pass(pipeline, report)
    _disagg_role_pass(pipeline, report)
    _replica_failover_pass(pipeline, report)
    _resident_handoff_pass(pipeline, report)
    _model_sharing_pass(pipeline, report)
    _plane_async_pass(pipeline, report)
    _kv_cache_pass(pipeline, report)
    specs: Dict[str, List[Any]] = {}
    if not cyclic:
        specs = _spec_pass(pipeline, report, placeholders, skip)
        # NNS-W129 (nns-kscope): an explicit impl=pallas request the
        # kernel registry says would degrade to the jnp/xla path —
        # needs the negotiated specs for the input dtypes
        from nnstreamer_tpu.analysis.kernels import pallas_request_pass

        pallas_request_pass(pipeline, report, specs)
    return LintResult(report, pipeline, specs)


def annotated_dot(result: LintResult) -> str:
    """Graphviz dump with diagnostics painted onto the offending nodes and
    the dry-run negotiated specs on the clean ones."""
    if result.pipeline is None:
        return 'digraph "unparseable" {}'
    return result.pipeline.dump_dot(
        diagnostics=result.diagnostics,
        specs=result.negotiated_specs,
    )
